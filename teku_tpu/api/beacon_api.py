"""Beacon REST API: the standard eth2 node HTTP surface.

Equivalent of the reference's beacon REST API (reference: data/
beaconrestapi/src/main/java/tech/pegasys/teku/beaconrestapi/
JsonTypeDefinitionBeaconRestApi.java and handlers/v1/{node,beacon,
validator,config}/): node identity/health/syncing, chain queries
(genesis, headers, blocks, finality checkpoints, validators), pool
submission, duty queries, spec config, plus the Prometheus /metrics
exposition (infrastructure/metrics MetricsEndpoint analogue).
"""

import logging
from typing import Optional

from ..infra import tracing
from ..infra.metrics import GLOBAL_REGISTRY
from ..infra.restapi import HttpError, RestApi
from ..spec import helpers as H

_LOG = logging.getLogger(__name__)

VERSION = "teku-tpu/0.3.0"


def _hex(b: bytes) -> str:
    return "0x" + b.hex()


# schema-driven SSZ<->JSON (shared with the Web3Signer client)
from ..ssz.json import ssz_from_json as _ssz_from_json  # noqa: E402
from ..ssz.json import ssz_to_json as _ssz_to_json  # noqa: E402


class BeaconRestApi(RestApi):
    """Routes bound to one BeaconNode (and optionally its p2p net)."""

    def __init__(self, node, networked=None, host: str = "127.0.0.1",
                 port: int = 0, validator_api=None, database=None):
        super().__init__(host, port)
        self.node = node
        self.networked = networked
        self.validator_api = validator_api
        # archive database: serves historical blocks/states the hot
        # store has moved past (regenerating states from snapshots)
        self.database = database
        g = self.get
        p = self.post
        g("/eth/v1/node/health", self._health)
        g("/eth/v1/node/version", self._version)
        g("/eth/v1/node/identity", self._identity)
        g("/eth/v1/node/syncing", self._syncing)
        g("/eth/v1/node/peers", self._peers)
        g("/eth/v1/beacon/genesis", self._genesis)
        g("/eth/v1/beacon/headers/{block_id}", self._header)
        g("/eth/v2/beacon/blocks/{block_id}", self._block)
        g("/eth/v1/beacon/states/{state_id}/root", self._state_root)
        g("/eth/v1/beacon/states/{state_id}/finality_checkpoints",
          self._finality)
        g("/eth/v1/beacon/states/{state_id}/validators", self._validators)
        g("/eth/v1/config/spec", self._spec_config)
        g("/eth/v1/validator/duties/proposer/{epoch}", self._proposer_duties)
        p("/eth/v1/validator/duties/attester/{epoch}", self._attester_duties)
        p("/eth/v1/validator/duties/sync/{epoch}", self._sync_duties)
        p("/eth/v1/validator/liveness/{epoch}", self._liveness)
        g("/eth/v1/beacon/states/{state_id}/committees", self._committees)
        g("/eth/v1/beacon/states/{state_id}/sync_committees",
          self._state_sync_committees)
        g("/eth/v1/config/fork_schedule", self._fork_schedule)
        g("/eth/v1/beacon/rewards/blocks/{block_id}",
          self._block_rewards)
        p("/eth/v1/beacon/rewards/attestations/{epoch}",
          self._attestation_rewards)
        p("/eth/v1/beacon/rewards/sync_committee/{block_id}",
          self._sync_committee_rewards)
        p("/eth/v1/validator/beacon_committee_subscriptions",
          self._committee_subscriptions)
        p("/eth/v1/validator/sync_committee_subscriptions",
          self._sync_subscriptions)
        p("/eth/v1/validator/prepare_beacon_proposer",
          self._prepare_proposer)
        p("/eth/v1/validator/register_validator",
          self._register_validator)
        p("/eth/v1/beacon/pool/attestations", self._submit_attestations)
        p("/eth/v1/beacon/pool/voluntary_exits", self._submit_exit)
        p("/eth/v1/beacon/pool/sync_committees", self._submit_sync_messages)
        # op-pool family (reference data/beaconrestapi handlers/v1/
        # beacon: Get/PostAttesterSlashings, Get/PostProposerSlashings,
        # Get/PostBlsToExecutionChanges)
        g("/eth/v1/beacon/pool/voluntary_exits", self._get_pool_exits)
        g("/eth/v1/beacon/pool/attester_slashings",
          self._get_attester_slashings)
        p("/eth/v1/beacon/pool/attester_slashings",
          self._post_attester_slashing)
        g("/eth/v1/beacon/pool/proposer_slashings",
          self._get_proposer_slashings)
        p("/eth/v1/beacon/pool/proposer_slashings",
          self._post_proposer_slashing)
        g("/eth/v1/beacon/pool/bls_to_execution_changes",
          self._get_bls_changes)
        p("/eth/v1/beacon/pool/bls_to_execution_changes",
          self._post_bls_changes)
        # v2 pool family: electra-era versioned envelope (reference
        # handlers/v2/beacon/GetAttesterSlashingsV2.java etc.)
        g("/eth/v2/beacon/pool/attester_slashings",
          self._get_attester_slashings_v2)
        p("/eth/v2/beacon/pool/attester_slashings",
          self._post_attester_slashing)
        g("/eth/v2/beacon/pool/proposer_slashings",
          self._get_proposer_slashings_v2)
        p("/eth/v2/beacon/pool/proposer_slashings",
          self._post_proposer_slashing)
        g("/eth/v1/beacon/states/{state_id}/validator_balances",
          self._validator_balances)
        p("/eth/v1/beacon/states/{state_id}/validator_balances",
          self._validator_balances_post)
        g("/eth/v1/beacon/blocks/{block_id}/root", self._block_root)
        g("/eth/v1/beacon/blocks/{block_id}/attestations",
          self._block_attestations)
        g("/eth/v1/node/peer_count", self._peer_count)
        g("/eth/v1/beacon/states/{state_id}/expected_withdrawals",
          self._expected_withdrawals)
        g("/eth/v1/beacon/blob_sidecars/{block_id}", self._blob_sidecars)
        # the remote-VC surface (reference: handlers/v1/validator/* and
        # the debug state endpoint checkpoint sync reads)
        g("/eth/v2/debug/beacon/states/{state_id}", self._state_ssz)
        g("/eth/v1/validator/attestation_data", self._attestation_data)
        g("/eth/v1/validator/aggregate_attestation",
          self._aggregate_attestation)
        g("/eth/v3/validator/blocks/{slot}", self._produce_block)
        p("/eth/v2/beacon/blocks", self._publish_block_ssz)
        p("/eth/v1/validator/aggregate_and_proofs",
          self._submit_aggregate_ssz)
        g("/eth/v1/validator/sync_committee_contribution",
          self._sync_contribution)
        p("/eth/v1/validator/contribution_and_proofs",
          self._submit_contribution_ssz)
        g("/eth/v1/events", self._events)
        g("/eth/v1/beacon/light_client/bootstrap/{block_id}",
          self._lc_bootstrap)
        g("/eth/v1/beacon/light_client/finality_update",
          self._lc_finality_update)
        g("/eth/v1/beacon/light_client/updates", self._lc_updates)
        g("/eth/v1/node/peers/{peer_id}", self._peer_by_id)
        g("/eth/v1/debug/fork_choice", self._debug_fork_choice)
        # slow-trace dump (per-stage breakdowns of the slowest
        # verifies) — teku-namespaced like the reference's /teku/v1
        # operator endpoints
        g("/teku/v1/admin/traces", self._admin_traces)
        g("/teku/v1/admin/readiness", self._admin_readiness)
        g("/teku/v1/admin/flight_recorder", self._admin_flight_recorder)
        g("/teku/v1/admin/capacity", self._admin_capacity)
        g("/teku/v1/admin/dispatches", self._admin_dispatches)
        g("/teku/v1/admin/admission", self._admin_admission)
        g("/teku/v1/admin/profile", self._admin_profile)
        g("/teku/v1/admin/timeline", self._admin_timeline)
        g("/metrics", self._metrics)

    # -- resolution helpers -------------------------------------------
    def _resolve_block_root(self, block_id: str) -> bytes:
        chain = self.node.chain
        if block_id == "head":
            return chain.head_root
        if block_id == "finalized":
            return chain.finalized_checkpoint.root
        if block_id == "justified":
            return chain.justified_checkpoint.root
        if block_id.startswith("0x"):
            try:
                root = bytes.fromhex(block_id[2:])
            except ValueError:
                raise HttpError(400, f"invalid root {block_id!r}")
            if len(root) != 32:
                raise HttpError(400, "root must be 32 bytes")
            if chain.contains_block(root):
                return root
            if self.database is not None \
                    and self.database.has_block(root):
                return root
            raise HttpError(404, "block not found")
        try:
            slot = int(block_id)
        except ValueError:
            raise HttpError(400, f"invalid block id {block_id!r}")
        if slot < 0:
            raise HttpError(400, "slot must be non-negative")
        root = self.node.store.proto.ancestor_at_slot(chain.head_root, slot)
        if root is None or self.node.store.blocks[root].slot != slot:
            # historical: the finalized slot index in the archive
            if self.database is not None:
                db_root = self.database.canonical_root_at_slot(slot)
                if db_root is not None:
                    return db_root
            raise HttpError(404, "no canonical block at slot")
        return root

    async def _state_by_root_async(self, root: bytes):
        """Hot store, else archive regeneration in an executor (the
        replay can be ~snapshot_interval state transitions — it must
        not stall duty queries on the event loop); None if unknown."""
        state = self.node.chain.get_state(root)
        if state is None and self.database is not None:
            import asyncio
            state = await asyncio.get_running_loop().run_in_executor(
                None, self.database.get_or_regenerate_state, root)
        return state

    async def _resolve_state_async(self, state_id: str):
        root = self._resolve_block_root(
            "head" if state_id == "head" else state_id)
        state = await self._state_by_root_async(root)
        if state is None:
            raise HttpError(404, "state not available")
        return state

    # -- node ----------------------------------------------------------
    def _is_syncing(self) -> bool:
        return bool(self.networked and self.networked.sync.syncing)

    async def _health(self, query=None):
        """Spec-correct node health (reference handlers/v1/node/
        GetHealth.java): 200 ready, 206 syncing or DEGRADED (serving,
        but impaired), 503 DOWN — driven by the live HealthRegistry,
        not a stub.  The optional ``syncing_status`` query param
        substitutes the 206 (per the Beacon API spec: any valid HTTP
        code; invalid values are a 400)."""
        from ..infra.health import HealthStatus
        health = getattr(self.node, "health", None)
        status = health.evaluate() if health is not None \
            else HealthStatus.UP
        syncing_code = 206
        if query and "syncing_status" in query:
            try:
                syncing_code = int(query["syncing_status"])
            except ValueError:
                raise HttpError(400, "syncing_status must be an "
                                     "integer status code")
            if not 100 <= syncing_code < 600:
                raise HttpError(400, "syncing_status out of range "
                                     "(100-599)")
        if status is HealthStatus.DOWN:
            return {}, None, 503
        # the override substitutes ONLY the syncing response (its spec
        # contract) — a ?syncing_status=200 probe keeping syncing nodes
        # in rotation must not also mask genuine degradation
        if self._is_syncing():
            return {}, None, syncing_code
        if status is HealthStatus.DEGRADED:
            return {}, None, 206
        return {}, None, 200

    async def _admin_readiness(self):
        """Detailed operator/autoscaler readiness: every health check's
        verdict + detail, the SLO burn rates, and sync state — the
        'WHICH subsystem is hurting' companion to /eth/v1/node/health's
        one status code."""
        health = getattr(self.node, "health", None)
        slo = getattr(self.node, "slo", None)
        if health is None:
            raise HttpError(503, "health registry not wired")
        health.evaluate()
        out = health.snapshot()
        out["syncing"] = self._is_syncing()
        if slo is not None:
            out["slo"] = slo.snapshot()
        sup = getattr(self.node, "supervisor", None)
        if sup is not None:
            out["backend"] = sup.snapshot()
        # brownout state rides the readiness body: an autoscaler or
        # load balancer deciding where to send traffic needs "this
        # node is deliberately shedding OPTIMISTIC/GOSSIP" next to
        # the per-check verdicts, not on a separate endpoint
        admission = getattr(self.node, "admission", None)
        if admission is not None:
            snap = admission.snapshot()
            out["admission"] = {"brownout": snap["brownout"],
                                "plan": snap["plan"],
                                "inputs": snap["inputs"]}
        return out

    async def _admin_flight_recorder(self, query=None):
        """The flight-recorder ring as JSON, oldest first: backend
        state transitions, breaker trips, SLO breaches, queue sheds,
        health flips — each with its originating trace id.  `?last=N`
        tails, `?clear=1` empties after the read, `?dump=1` also
        writes the JSONL file an incident report wants."""
        recorder = getattr(self.node, "flight_recorder", None)
        if recorder is None:
            raise HttpError(503, "flight recorder not wired")
        last = None
        if query and query.get("last"):
            try:
                last = max(1, int(query["last"]))
            except ValueError:
                raise HttpError(400, "last must be an integer")
        out = {"data": recorder.snapshot(last=last)}
        if query and query.get("dump") in ("1", "true"):
            out["dumped_to"] = recorder.dump("operator request")
        if query and query.get("clear") in ("1", "true"):
            recorder.clear()
        return out

    async def _admin_capacity(self):
        """The node's self-measurement (infra/capacity.py): per-shape
        device-latency model, arrival rates per source, queue-depth
        series, shed rate, true device occupancy, and the derived
        sustainable-sigs/sec + utilization/headroom signals the
        adaptive batcher (ROADMAP 3) will consume.  refresh() also
        fires the edge-triggered headroom-exhausted flight-recorder
        event, so polling this endpoint keeps the evidence current
        even between node health ticks."""
        from ..infra import capacity
        return {"data": capacity.refresh()}

    async def _admin_dispatches(self, query=None):
        """The dispatch decision ledger (infra/dispatchledger.py):
        bounded structured per-dispatch records — batch plan mode and
        brownout level, real vs padded lanes and unique counts (waste
        split by stage bucket), H(m) cache hits/misses, resolved msm
        path + why, mesh shard plan + makespan ratio, compile outcome
        with duration, device sync/busy spans, verdict — each stamped
        with its originating trace ids.  ``?last=N`` tails,
        ``?trace_id=X`` filters to the record serving that trace (the
        slow-trace ring's join key), ``?slow=1`` filters to records
        linked to the current slow-trace ring."""
        from ..infra import dispatchledger
        last = None
        if query and query.get("last"):
            try:
                last = max(1, int(query["last"]))
            except ValueError:
                raise HttpError(400, "last must be an integer")
        trace_id = (query or {}).get("trace_id") or None
        slow = (query or {}).get("slow") in ("1", "true")
        ledger = dispatchledger.LEDGER
        records = ledger.snapshot(last=last, trace_id=trace_id,
                                  slow=slow)
        return {"data": {
            "records": records,
            "summary": dispatchledger.summarize(records),
            "capacity": ledger.capacity,
            "recorded_total": ledger.recorded_total}}

    async def _admin_timeline(self, query=None):
        """The unified causal timeline (infra/timeline.py): every
        observability ring joined on the shared clock spine.  With
        ``?trace_id=X`` returns the full joined view for that trace —
        gap-free span tree, the ledger record that served it, its
        flight-recorder entries and timeline ring events — as a
        schema-versioned envelope.  Without a trace id returns the
        anchor, the slow-trace ring and the timeline ring (the raw
        material ``cli timeline`` turns into a Perfetto trace)."""
        from ..infra import dispatchledger, schema, timeline
        trace_id = (query or {}).get("trace_id") or None
        recorder = getattr(self.node, "flight_recorder", None)
        flight = recorder.snapshot() if recorder is not None else []
        if trace_id:
            return timeline.join(
                trace_id,
                tracing.slow_traces(),
                dispatchledger.LEDGER.snapshot(trace_id=trace_id),
                [e for e in flight
                 if e.get("trace_id") == trace_id],
                timeline.RING.snapshot(trace_id=trace_id))
        last = None
        if query and query.get("last"):
            try:
                last = max(1, int(query["last"]))
            except ValueError:
                raise HttpError(400, "last must be an integer")
        from ..infra import clock
        return schema.envelope("timeline", {
            "anchor": clock.anchor_dict(),
            "enabled": timeline.enabled(),
            "traces": tracing.slow_traces(),
            "ring": timeline.RING.snapshot(last=last),
        })

    async def _admin_admission(self):
        """The overload controller's state (services/admission.py):
        the current BatchPlan (adaptive pow-2 batch size + flush
        deadline and the modeled device time behind them), the
        brownout state machine (level, shed classes, hysteresis
        counters, edge counts), the driving inputs (utilization, p50
        burn rate, queue depth), the full knob config, and the
        per-class queue depths/ages from the signature service."""
        ctl = getattr(self.node, "admission", None)
        if ctl is None:
            raise HttpError(503, "admission controller not wired "
                                 "(overload control off)")
        out = {"controller": ctl.snapshot()}
        svc = getattr(self.node, "sig_service", None)
        if svc is not None:
            out["queues"] = svc.queue_snapshot()
        return {"data": out}

    async def _admin_profile(self, query=None):
        """On-demand jax.profiler capture (infra/profiling.py):
        ``?start=1`` begins a capture (optional ``&duration_s=N`` arms
        the auto-stop the health tick enforces), ``?stop=1`` ends it
        and names the trace directory, no params = status (active
        capture, last capture, cooldown/trigger config).  Start/stop
        are also recorded to the flight recorder with the originating
        trace id."""
        from ..infra import profiling
        ctl = profiling.CONTROLLER
        if query and query.get("start") in ("1", "true"):
            duration = None
            if query.get("duration_s"):
                try:
                    duration = max(0.1, float(query["duration_s"]))
                except ValueError:
                    raise HttpError(400, "duration_s must be a number")
            return {"data": ctl.start(trigger="manual",
                                      duration_s=duration)}
        if query and query.get("stop") in ("1", "true"):
            return {"data": ctl.stop()}
        return {"data": ctl.status()}

    async def _version(self):
        return {"data": {"version": VERSION}}

    async def _identity(self):
        node_id = (self.networked.net.node_id.hex()
                   if self.networked else "00" * 32)
        attnets = bytearray(8)
        manager = getattr(self.networked, "subnets", None) \
            if self.networked else None
        if manager is not None:
            for subnet in manager.active_subnets():
                attnets[subnet // 8] |= 1 << (subnet % 8)
        enr = getattr(self.networked, "enr", None) \
            if self.networked else None
        return {"data": {"peer_id": node_id,
                         "enr": enr.to_text() if enr else "",
                         "p2p_addresses": [], "metadata": {
                             "seq_number": "0",
                             "attnets": "0x" + bytes(attnets).hex()}}}

    async def _syncing(self):
        syncing = bool(self.networked and self.networked.sync.syncing)
        head = self.node.chain.head_slot()
        current = self.node.chain.current_slot()
        return {"data": {"head_slot": str(head),
                         "sync_distance": str(max(0, current - head)),
                         "is_syncing": syncing,
                         "is_optimistic": False, "el_offline": False}}

    @staticmethod
    def _peer_json(peer) -> dict:
        return {"peer_id": peer.node_id.hex(),
                "state": "connected" if peer.connected
                else "disconnected",
                "direction": "outbound" if peer.outbound
                else "inbound",
                "last_seen_p2p_address": ""}

    async def _peers(self):
        peers = []
        if self.networked:
            for peer in self.networked.net.peers:
                peers.append(self._peer_json(peer))
        return {"data": peers,
                "meta": {"count": len(peers)}}

    # -- beacon --------------------------------------------------------
    async def _genesis(self):
        # every state carries the same genesis fields
        state = self.node.chain.head_state()
        return {"data": {
            "genesis_time": str(state.genesis_time),
            "genesis_validators_root": _hex(state.genesis_validators_root),
            "genesis_fork_version": _hex(
                self.node.spec.config.GENESIS_FORK_VERSION)}}

    def _block_by_root(self, root: bytes):
        """Hot store first, then the archive (the resolver may return
        roots only the database holds)."""
        block = self.node.store.blocks.get(root)
        if block is None and self.database is not None:
            signed = self.database.get_block(root)
            block = signed.message if signed is not None else None
        if block is None:
            raise HttpError(404, "block not found")
        return block

    async def _header(self, block_id: str):
        root = self._resolve_block_root(block_id)
        block = self._block_by_root(root)
        return {"data": {
            "root": _hex(root),
            "canonical": True,
            "header": {"message": {
                "slot": str(block.slot),
                "proposer_index": str(block.proposer_index),
                "parent_root": _hex(block.parent_root),
                "state_root": _hex(block.state_root),
                "body_root": _hex(block.body.htr())}}},
            "execution_optimistic": False, "finalized": False}

    async def _blob_sidecars(self, block_id: str):
        """Deneb blob sidecars for one block (reference: handlers/v1/
        beacon/GetBlobSidecars.java), served from the tracking pool."""
        root = self._resolve_block_root(block_id)
        pool = getattr(self.node, "blob_pool", None)
        sidecars = pool.wire_sidecars_for(root) if pool is not None else []
        out = []
        for sc in sidecars:
            hdr = sc.signed_block_header.message
            out.append({
                "index": str(sc.index),
                "blob": _hex(bytes(sc.blob)),
                "kzg_commitment": _hex(sc.kzg_commitment),
                "kzg_proof": _hex(sc.kzg_proof),
                "signed_block_header": {
                    "message": {
                        "slot": str(hdr.slot),
                        "proposer_index": str(hdr.proposer_index),
                        "parent_root": _hex(hdr.parent_root),
                        "state_root": _hex(hdr.state_root),
                        "body_root": _hex(hdr.body_root),
                    },
                    "signature": _hex(sc.signed_block_header.signature),
                },
                "kzg_commitment_inclusion_proof": [
                    _hex(h) for h in sc.kzg_commitment_inclusion_proof],
            })
        return {"data": out}

    async def _block(self, block_id: str, query=None, headers=None):
        root = self._resolve_block_root(block_id)
        signed = self.node.store.signed_blocks.get(root)
        if signed is None and self.database is not None:
            signed = self.database.get_block(root)
        if signed is None:
            raise HttpError(404, "signed block not retained")
        wants_ssz = ("application/octet-stream"
                     in (headers or {}).get("accept", "")
                     or (query or {}).get("format") == "ssz")
        if wants_ssz:
            # octet-stream variant per the standard Accept negotiation
            # — checkpoint sync's block fetch
            return type(signed).serialize(signed), \
                "application/octet-stream"
        block = signed.message
        version = self.node.spec.milestone_at_slot(block.slot).name.lower()
        return {"version": version, "data": {
            "message": {
                "slot": str(block.slot),
                "proposer_index": str(block.proposer_index),
                "parent_root": _hex(block.parent_root),
                "state_root": _hex(block.state_root),
                "body": {
                    "randao_reveal": _hex(block.body.randao_reveal),
                    "graffiti": _hex(block.body.graffiti),
                    "attestations_count": len(block.body.attestations)},
            },
            "signature": _hex(signed.signature)}}

    async def _state_ssz(self, state_id: str):
        """Full state as SSZ (reference GetState debug handler) — the
        fetch behind checkpoint sync and the remote VC's duty states."""
        state = await self._resolve_state_async(state_id)
        return type(state).serialize(state), "application/octet-stream"

    async def _attestation_data(self, query=None):
        if self.validator_api is None:
            raise HttpError(503, "validator api not wired")
        try:
            slot = int((query or {})["slot"])
            ci = int((query or {})["committee_index"])
        except (KeyError, ValueError):
            raise HttpError(400, "slot and committee_index required")
        data = self.validator_api.get_attestation_data(slot, ci)
        return {"data": {
            "slot": str(data.slot), "index": str(data.index),
            "beacon_block_root": _hex(data.beacon_block_root),
            "source": {"epoch": str(data.source.epoch),
                       "root": _hex(data.source.root)},
            "target": {"epoch": str(data.target.epoch),
                       "root": _hex(data.target.root)}}}

    async def _aggregate_attestation(self, query=None):
        try:
            root = bytes.fromhex(
                (query or {})["attestation_data_root"][2:])
        except (KeyError, ValueError):
            raise HttpError(400, "attestation_data_root required")
        ci = None
        if query and "committee_index" in query:
            try:
                ci = int(query["committee_index"])
            except ValueError:
                raise HttpError(400, "invalid committee_index")
        aggregate = self.node.pool.get_aggregate_by_root(root, ci)
        if aggregate is None:
            raise HttpError(404, "no aggregate for this data")
        return type(aggregate).serialize(aggregate), \
            "application/octet-stream"

    async def _produce_block(self, slot: str, query=None):
        """Unsigned block production for the remote VC (reference
        produceBlockV3) — SSZ response; the VC signs and POSTs back."""
        if self.validator_api is None:
            raise HttpError(503, "validator api not wired")
        try:
            reveal = bytes.fromhex((query or {})["randao_reveal"][2:])
        except (KeyError, ValueError):
            raise HttpError(400, "randao_reveal required")
        graffiti = bytes(32)
        if query and "graffiti" in query:
            graffiti = bytes.fromhex(query["graffiti"][2:]).ljust(32,
                                                                  b"\x00")
        try:
            block, _pre = await self.validator_api.produce_unsigned_block(
                int(slot), reveal, graffiti)
        except Exception as exc:
            raise HttpError(500, f"block production failed: {exc}")
        return type(block).serialize(block), "application/octet-stream"

    async def _publish_block_ssz(self, raw_body=None):
        if not raw_body:
            raise HttpError(400, "SSZ SignedBeaconBlock body required")
        from ..spec.codec import deserialize_signed_block
        try:
            signed = deserialize_signed_block(self.node.spec.config,
                                              raw_body)
        except Exception as exc:
            raise HttpError(400, f"malformed block: {exc}")
        if self.validator_api is not None:
            await self.validator_api.publish_signed_block(signed)
        else:
            self.node.block_manager.import_block(signed)
        return {}

    async def _sync_contribution(self, query=None):
        """Produce a sync-committee contribution (reference
        GetSyncCommitteeContribution) — SSZ response."""
        if self.validator_api is None:
            raise HttpError(503, "validator api not wired")
        try:
            slot = int((query or {})["slot"])
            sub = int((query or {})["subcommittee_index"])
            root = bytes.fromhex(
                (query or {})["beacon_block_root"][2:])
        except (KeyError, ValueError):
            raise HttpError(
                400, "slot, subcommittee_index, beacon_block_root "
                     "required")
        build = getattr(self.validator_api, "build_sync_contribution",
                        None)
        if build is None:
            raise HttpError(503, "contributions not supported")
        contribution = build(slot, root, sub)
        if contribution is None:
            raise HttpError(404, "no messages pooled for this root")
        return type(contribution).serialize(contribution), \
            "application/octet-stream"

    async def _submit_contribution_ssz(self, raw_body=None):
        if not raw_body:
            raise HttpError(400, "SSZ SignedContributionAndProof "
                                 "required")
        signed = self._decode_versioned("SignedContributionAndProof",
                                        raw_body)
        publish = getattr(self.validator_api,
                          "publish_contribution_and_proof", None)
        if publish is None:
            raise HttpError(503, "contributions not supported")
        await publish(signed)
        return {}

    async def _submit_aggregate_ssz(self, raw_body=None):
        if not raw_body:
            raise HttpError(400, "SSZ SignedAggregateAndProof required")
        signed = self._decode_versioned("SignedAggregateAndProof",
                                        raw_body)
        if self.validator_api is None:
            raise HttpError(503, "validator api not wired")
        await self.validator_api.publish_aggregate_and_proof(signed)
        return {}

    async def _state_root(self, state_id: str):
        state = await self._resolve_state_async(state_id)
        return {"data": {"root": _hex(state.htr())}}

    async def _finality(self, state_id: str):
        state = await self._resolve_state_async(state_id)
        def cp(c):
            return {"epoch": str(c.epoch), "root": _hex(c.root)}
        return {"data": {
            "previous_justified": cp(state.previous_justified_checkpoint),
            "current_justified": cp(state.current_justified_checkpoint),
            "finalized": cp(state.finalized_checkpoint)}}

    async def _validators(self, state_id: str, query=None):
        state = await self._resolve_state_async(state_id)
        cfg = self.node.spec.config
        epoch = H.get_current_epoch(cfg, state)
        from ..spec.config import FAR_FUTURE_EPOCH
        out = []
        for i, v in enumerate(state.validators):
            if H.is_active_validator(v, epoch):
                status = ("active_slashed" if v.slashed
                          else "active_exiting"
                          if v.exit_epoch != FAR_FUTURE_EPOCH
                          else "active_ongoing")
            elif epoch >= v.exit_epoch:
                status = ("withdrawal_possible"
                          if epoch >= v.withdrawable_epoch
                          else "exited_slashed" if v.slashed
                          else "exited_unslashed")
            else:
                status = ("pending_queued"
                          if v.activation_eligibility_epoch
                          != FAR_FUTURE_EPOCH else "pending_initialized")
            out.append({"index": str(i),
                        "balance": str(state.balances[i]),
                        "status": status,
                        "validator": {
                            "pubkey": _hex(v.pubkey),
                            "effective_balance": str(v.effective_balance),
                            "slashed": v.slashed,
                            "activation_epoch": str(v.activation_epoch),
                            "exit_epoch": str(v.exit_epoch)}})
        return {"data": out}

    async def _spec_config(self):
        cfg = self.node.spec.config
        out = {}
        for name in cfg.__dataclass_fields__:
            v = getattr(cfg, name)
            out[name] = _hex(v) if isinstance(v, bytes) else str(v)
        return {"data": out}

    # -- validator -----------------------------------------------------
    async def _proposer_duties(self, epoch: str):
        if self.validator_api is None:
            raise HttpError(503, "validator api not wired")
        duties = self.validator_api.get_proposer_duties(int(epoch))
        state = self.node.chain.head_state()
        return {"data": [
            {"pubkey": _hex(
                state.validators[d.validator_index].pubkey),
             "validator_index": str(d.validator_index),
             "slot": str(d.slot)} for d in duties]}

    async def _attester_duties(self, epoch: str, body=None):
        if self.validator_api is None:
            raise HttpError(503, "validator api not wired")
        indices = [int(i) for i in (body or [])]
        duties = self.validator_api.get_attester_duties(int(epoch), indices)
        state = self.node.chain.head_state()
        return {"data": [
            {"pubkey": _hex(state.validators[d.validator_index].pubkey),
             "validator_index": str(d.validator_index),
             "committee_index": str(d.committee_index),
             "committee_length": str(d.committee_size),
             "committees_at_slot": str(d.committees_at_slot),
             "validator_committee_index": str(d.committee_position),
             "slot": str(d.slot)} for d in duties]}

    async def _sync_duties(self, epoch: str, body=None):
        """Sync-committee duties (reference handlers/v1/validator/
        PostSyncDuties.java:43) — what lets the remote VC run sync
        duties without downloading states."""
        if self.validator_api is None:
            raise HttpError(503, "validator api not wired")
        indices = [int(i) for i in (body or [])]
        duties = self.validator_api.get_sync_duties(int(epoch), indices)
        return {"execution_optimistic": False, "data": [
            {"pubkey": _hex(d.pubkey),
             "validator_index": str(d.validator_index),
             "validator_sync_committee_indices":
                 [str(p) for p in d.positions]}
            for d in duties]}

    async def _liveness(self, epoch: str, body=None):
        """Per-validator liveness from the epoch's participation flags
        (reference handlers/v1/validator/PostValidatorLiveness.java —
        there from a seen-attestation cache; here the participation
        registry IS that record for current/previous epoch)."""
        epoch = int(epoch)
        state = self.node.chain.head_state()
        cfg = self.node.spec.config
        current = H.get_current_epoch(cfg, state)
        if epoch == current:
            participation = getattr(state, "current_epoch_participation",
                                    None)
        elif epoch == current - 1:
            participation = getattr(state, "previous_epoch_participation",
                                    None)
        else:
            raise HttpError(400, "liveness only for current/previous "
                                 "epoch")
        if participation is None:
            raise HttpError(501, "pre-altair state has no participation "
                                 "registry")
        out = []
        for i in (body or []):
            vi = int(i)
            live = (vi < len(participation)
                    and participation[vi] != 0)
            out.append({"index": str(vi), "is_live": live})
        return {"data": out}

    async def _committees(self, state_id: str, query=None):
        """Beacon committees (reference handlers/v1/beacon/
        GetStateCommittees.java): all committees for an epoch, or
        filtered by slot/index."""
        query = query or {}
        state = await self._resolve_state_async(state_id)
        cfg = self.node.spec.config
        epoch = (int(query["epoch"]) if "epoch" in query
                 else H.get_current_epoch(cfg, state))
        want_slot = int(query["slot"]) if "slot" in query else None
        want_index = int(query["index"]) if "index" in query else None
        committees = H.get_committee_count_per_slot(cfg, state, epoch)
        first = H.compute_start_slot_at_epoch(cfg, epoch)
        out = []
        for slot in range(first, first + cfg.SLOTS_PER_EPOCH):
            if want_slot is not None and slot != want_slot:
                continue
            for ci in range(committees):
                if want_index is not None and ci != want_index:
                    continue
                try:
                    members = H.get_beacon_committee(cfg, state, slot, ci)
                except Exception:
                    raise HttpError(400, "epoch out of shuffling range")
                out.append({"index": str(ci), "slot": str(slot),
                            "validators": [str(v) for v in members]})
        return {"execution_optimistic": False, "data": out}

    async def _state_sync_committees(self, state_id: str, query=None):
        """Current sync committee of a state as validator indices
        (reference handlers/v1/beacon/GetStateSyncCommittees.java)."""
        state = await self._resolve_state_async(state_id)
        if not hasattr(state, "current_sync_committee"):
            raise HttpError(400, "pre-altair state")
        by_pubkey = {v.pubkey: i for i, v in enumerate(state.validators)}
        indices = [by_pubkey.get(pk)
                   for pk in state.current_sync_committee.pubkeys]
        if any(i is None for i in indices):
            raise HttpError(500, "committee pubkey not in registry")
        from ..spec.altair.helpers import sync_subcommittee_size
        sub = sync_subcommittee_size(self.node.spec.config)
        return {"execution_optimistic": False, "data": {
            "validators": [str(i) for i in indices],
            "validator_aggregates": [
                [str(i) for i in indices[off:off + sub]]
                for off in range(0, len(indices), sub)]}}

    async def _fork_schedule(self):
        """All scheduled forks (reference handlers/v1/config/
        GetForkSchedule.java) — lets a remote VC build signing domains
        for any epoch without a state."""
        from ..spec.milestones import build_fork_schedule
        schedule = build_fork_schedule(self.node.spec.config)
        out = []
        for i, v in enumerate(schedule.versions):
            prev = schedule.versions[i - 1] if i > 0 else v
            out.append({
                "previous_version": _hex(prev.fork_version),
                "current_version": _hex(v.fork_version),
                "epoch": str(v.fork_epoch)})
        return {"data": out}

    async def _pre_post_states(self, root: bytes):
        """(pre_state_at_block_slot, post_state, block) for a block —
        the reward endpoints' shared setup."""
        from ..spec.transition import process_slots
        block = self._block_by_root(root)
        post = await self._state_by_root_async(root)
        parent_state = await self._state_by_root_async(
            block.parent_root)
        if post is None or parent_state is None:
            raise HttpError(404, "states not available for rewards")
        pre = parent_state
        if pre.slot < block.slot:
            pre = process_slots(self.node.spec.config, pre, block.slot)
        return pre, post, block

    def _validator_indices(self, state, body) -> list:
        """The beacon-API 'validator index or pubkey' body shape."""
        by_pubkey = None
        out = []
        for item in (body or []):
            item = str(item)
            if item.startswith("0x"):
                if by_pubkey is None:
                    by_pubkey = {v.pubkey: i
                                 for i, v in enumerate(state.validators)}
                try:
                    index = by_pubkey.get(bytes.fromhex(item[2:]))
                except ValueError:
                    raise HttpError(400, f"bad pubkey {item!r}")
                if index is None:
                    raise HttpError(404, f"unknown validator {item!r}")
                out.append(index)
            else:
                try:
                    out.append(int(item))
                except ValueError:
                    raise HttpError(400, f"bad validator id {item!r}")
        return out

    async def _block_rewards(self, block_id: str):
        """reference handlers/v1/rewards/GetBlockRewards.java."""
        from . import rewards as R
        root = self._resolve_block_root(block_id)
        pre, post, block = await self._pre_post_states(root)
        out = R.block_rewards(self.node.spec.config, pre, post, block)
        return {"execution_optimistic": False, "finalized": False,
                "data": {k: str(v) for k, v in out.items()}}

    async def _attestation_rewards(self, epoch: str, body=None):
        """reference handlers/v1/rewards/PostAttestationRewards.java —
        rewards for `epoch` read from a state one epoch later (whose
        previous-epoch participation covers it)."""
        from . import rewards as R
        cfg = self.node.spec.config
        epoch = int(epoch)
        head_state = self.node.chain.head_state()
        current = H.get_current_epoch(cfg, head_state)
        if epoch + 2 > current:
            # attestations for `epoch` are includable through ALL of
            # epoch+1 — rewards only settle once epoch+1 closes
            raise HttpError(400, "rewards settle after epoch+1 closes")
        # the LAST canonical block of epoch+1: its post-state holds the
        # final participation for `epoch` (rotated away at the next
        # boundary)
        start = H.compute_start_slot_at_epoch(cfg, epoch + 1)
        state = None
        for slot in range(start + cfg.SLOTS_PER_EPOCH - 1, start - 1,
                          -1):
            try:
                root = self._resolve_block_root(str(slot))
            except HttpError:
                continue
            state = await self._state_by_root_async(root)
            break
        if state is None:
            raise HttpError(404, "no state covering that epoch")
        indices = self._validator_indices(state, body) or None
        out = R.attestation_rewards(cfg, state, indices)
        return {"execution_optimistic": False, "finalized": False,
                "data": {
                    "ideal_rewards": [
                        {k: str(v) for k, v in row.items()}
                        for row in out["ideal_rewards"]],
                    "total_rewards": [
                        {k: str(v) for k, v in row.items()}
                        for row in out["total_rewards"]]}}

    async def _sync_committee_rewards(self, block_id: str, body=None):
        """reference handlers/v1/rewards/PostSyncCommitteeRewards."""
        from . import rewards as R
        root = self._resolve_block_root(block_id)
        pre, post, block = await self._pre_post_states(root)
        if not hasattr(block.body, "sync_aggregate") \
                or not hasattr(pre, "current_sync_committee"):
            raise HttpError(400, "pre-altair block has no sync rewards")
        _, _, deltas = R.sync_aggregate_rewards(
            self.node.spec.config, pre, block.body.sync_aggregate)
        wanted = set(self._validator_indices(pre, body)) or None
        return {"execution_optimistic": False, "finalized": False,
                "data": [
                    {"validator_index": str(i), "reward": str(d)}
                    for i, d in deltas
                    if wanted is None or i in wanted]}

    async def _committee_subscriptions(self, body=None):
        """reference handlers/v1/validator/PostSubscribeToBeaconCommittee
        Subnet.java: duty-driven subnet subscriptions from the VC.
        This node carries every attestation subnet (devnet-correct);
        the manager tracks the duty windows for expiry and for the
        attnets advertised by /eth/v1/node/identity.  Validation runs
        over the WHOLE body before any state changes."""
        if body is not None and not isinstance(body, list):
            raise HttpError(400, "body must be a list")
        from ..node.node import compute_subnet_for_attestation
        cfg = self.node.spec.config
        manager = getattr(self.networked, "subnets", None) \
            if self.networked else None
        parsed = []
        for sub in (body or []):
            try:
                parsed.append((int(sub["slot"]),
                               int(sub["committee_index"]),
                               int(sub["committees_at_slot"])))
            except (KeyError, ValueError, TypeError):
                raise HttpError(400, "malformed subscription")
        for slot, committee_index, committees in parsed:
            if manager is not None:
                subnet = compute_subnet_for_attestation(
                    cfg, committees, slot, committee_index)
                manager.subscribe_for_duty(subnet, slot + 1)
        return {"data": {"accepted": str(len(parsed))}}

    async def _sync_subscriptions(self, body=None):
        """reference PostSyncCommitteeSubscriptions — sync-committee
        topics are node-global in this stack, so acceptance is the
        whole contract."""
        if body is not None and not isinstance(body, list):
            raise HttpError(400, "body must be a list")
        for sub in (body or []):
            if not isinstance(sub, dict) or "validator_index" not in sub:
                raise HttpError(400, "malformed subscription")
        return {}

    async def _prepare_proposer(self, body=None):
        """reference PostPrepareBeaconProposer: fee recipients per
        proposer, consumed by block production (the devnet payload
        builder stamps them into execution_payload.fee_recipient)."""
        if body is not None and not isinstance(body, list):
            raise HttpError(400, "body must be a list")
        parsed = []
        for item in (body or []):
            try:
                index = int(item["validator_index"])
                recipient = bytes.fromhex(
                    item["fee_recipient"].removeprefix("0x"))
                if len(recipient) != 20:
                    raise ValueError("fee recipient must be 20 bytes")
            except (KeyError, ValueError, TypeError, AttributeError):
                raise HttpError(400, "malformed preparation")
            parsed.append((index, recipient))
        # all-or-nothing: nothing commits if any item was malformed
        prepared = getattr(self.node, "proposer_preparations", None)
        if prepared is None:
            prepared = {}
            self.node.proposer_preparations = prepared
        prepared.update(parsed)
        return {}

    async def _register_validator(self, body=None):
        """reference PostRegisterValidator: signed builder
        registrations, verified and forwarded to the builder when one
        is wired (otherwise retained for when it is)."""
        from ..builderapi import (SignedValidatorRegistration,
                                  ValidatorRegistration,
                                  verify_registration)
        if body is not None and not isinstance(body, list):
            raise HttpError(400, "body must be a list")
        cfg = self.node.spec.config
        registrations = []
        for item in (body or []):
            try:
                msg = item["message"]
                signed = SignedValidatorRegistration(
                    message=ValidatorRegistration(
                        fee_recipient=bytes.fromhex(
                            msg["fee_recipient"].removeprefix("0x")),
                        gas_limit=int(msg["gas_limit"]),
                        timestamp=int(msg["timestamp"]),
                        pubkey=bytes.fromhex(
                            msg["pubkey"].removeprefix("0x"))),
                    signature=bytes.fromhex(
                        item["signature"].removeprefix("0x")))
            except (KeyError, ValueError, TypeError,
                    AttributeError) as exc:
                raise HttpError(400, f"malformed registration: {exc}")
            registrations.append(signed)
        # signature checks off the event loop (a VC registers its
        # whole keyset at once; pairings would stall every endpoint)
        import asyncio

        def _verify_all():
            for signed in registrations:
                try:
                    if not verify_registration(cfg, signed):
                        return False
                except Exception:
                    return False       # SSZ length/range errors = 400
            return True
        if registrations and not await asyncio.get_running_loop() \
                .run_in_executor(None, _verify_all):
            raise HttpError(400, "bad registration signature")
        store = getattr(self.node, "validator_registrations", None)
        if store is None:
            store = {}
            self.node.validator_registrations = store
        for signed in registrations:
            store[signed.message.pubkey] = signed
        # forwarded when a builder relay is wired on the node (the
        # builder flow consumes the same SignedValidatorRegistration
        # shape); otherwise retained for the flow to pick up
        builder = getattr(self.node, "builder", None)
        if builder is not None and registrations:
            await builder.register_validators(registrations)
        return {}

    def _decode_versioned(self, attr: str, raw: bytes):
        """Decode raw SSZ against each scheduled milestone's schema,
        newest first — strict decoding makes cross-family false
        positives fail, so the wire shape picks its own fork."""
        from ..spec.milestones import build_fork_schedule
        last = None
        for version in reversed(
                build_fork_schedule(self.node.spec.config).versions):
            try:
                return getattr(version.schemas, attr).deserialize(raw)
            except Exception as exc:
                last = exc
        raise HttpError(400, f"malformed {attr}: {last}")

    async def _submit_attestations(self, body=None, raw_body=None):
        if body is None and raw_body:
            # SSZ alternative (application/octet-stream): ONE
            # attestation per request, the remote VC's submit shape
            # (electra wire = SingleAttestation); the shared codec
            # policy disambiguates by slot
            from ..spec.codec import deserialize_attestation_wire
            try:
                att = deserialize_attestation_wire(
                    self.node.spec.config, raw_body,
                    self.node.chain.current_slot())
            except Exception as exc:
                raise HttpError(400, f"malformed attestation: {exc}")
            if self.validator_api is not None:
                await self.validator_api.publish_attestation(att)
                return {}
            if hasattr(att, "attester_index"):
                from ..node.validators import normalize_attestation
                try:
                    # same advanced state the gossip path uses: the
                    # committee shuffle needs the slot's epoch applied
                    state = self.node.advanced_head_state(
                        min(att.data.slot,
                            self.node.chain.current_slot()))
                except Exception:
                    raise HttpError(503, "no state for this slot yet")
                att = normalize_attestation(self.node.spec, state, att)
                if att is None:
                    raise HttpError(400, "attester not in committee")
            from ..node.gossip import ValidationResult
            result = await self.node.attestation_validator.validate(att)
            if result is ValidationResult.REJECT:
                raise HttpError(400, "attestation rejected")
            self.node.attestation_manager.add_attestation(att)
            return {}
        if not isinstance(body, list):
            raise HttpError(400, "expected a list of attestations")
        S = self.node.spec.schemas
        from ..spec.datastructures import AttestationData, Checkpoint
        accepted = 0
        for a in body:
            try:
                data = a["data"]
                att = S.Attestation(
                    aggregation_bits=S.Attestation._ssz_fields[
                        "aggregation_bits"].deserialize(
                        bytes.fromhex(a["aggregation_bits"][2:])),
                    data=AttestationData(
                        slot=int(data["slot"]),
                        index=int(data["index"]),
                        beacon_block_root=bytes.fromhex(
                            data["beacon_block_root"][2:]),
                        source=Checkpoint(
                            epoch=int(data["source"]["epoch"]),
                            root=bytes.fromhex(data["source"]["root"][2:])),
                        target=Checkpoint(
                            epoch=int(data["target"]["epoch"]),
                            root=bytes.fromhex(data["target"]["root"][2:]))),
                    signature=bytes.fromhex(a["signature"][2:]))
            except (KeyError, ValueError, TypeError, AttributeError) as exc:
                raise HttpError(400, f"malformed attestation: {exc}")
            result = await self.node.attestation_validator.validate(att)
            from ..node.gossip import ValidationResult
            if result is ValidationResult.ACCEPT:
                self.node.attestation_manager.add_attestation(att)
                accepted += 1
        return {"data": {"accepted": accepted}}

    async def _submit_exit(self, body=None):
        from ..spec.datastructures import (SignedVoluntaryExit,
                                           VoluntaryExit)
        try:
            msg = body["message"]
            exit_op = SignedVoluntaryExit(
                message=VoluntaryExit(
                    epoch=int(msg["epoch"]),
                    validator_index=int(msg["validator_index"])),
                signature=bytes.fromhex(
                    body["signature"].removeprefix("0x")))
        except (KeyError, ValueError, TypeError, AttributeError) as exc:
            raise HttpError(400, f"malformed exit: {exc}")
        pool = self.node.operation_pools["voluntary_exits"]
        if not pool.add(self.node.chain.head_state(), exit_op):
            raise HttpError(400, "exit invalid or duplicate")
        from ..node.gossip import VOLUNTARY_EXIT_TOPIC
        from ..spec.datastructures import SignedVoluntaryExit as SVE
        await self.node.gossip.publish(
            VOLUNTARY_EXIT_TOPIC, SVE.serialize(exit_op))
        return {}

    # -- op-pool family (generic SSZ<->JSON via the schema walk) -------
    def _pool_json(self, pool_name: str):
        return {"data": [
            _ssz_to_json(type(op), op)
            for op in self.node.operation_pools[pool_name].get_for_block(
                10 ** 9)]}

    async def _get_pool_exits(self):
        return self._pool_json("voluntary_exits")

    async def _get_attester_slashings(self):
        return self._pool_json("attester_slashings")

    async def _get_proposer_slashings(self):
        return self._pool_json("proposer_slashings")

    async def _get_bls_changes(self):
        return self._pool_json("bls_to_execution_changes")

    def _head_version_name(self) -> str:
        from ..spec.milestones import build_fork_schedule
        v = build_fork_schedule(self.node.spec.config).version_at_slot(
            self.node.chain.head_slot())
        return v.milestone.name.lower()

    async def _get_attester_slashings_v2(self):
        return {"version": self._head_version_name(),
                **self._pool_json("attester_slashings")}

    async def _get_proposer_slashings_v2(self):
        return {"version": self._head_version_name(),
                **self._pool_json("proposer_slashings")}

    async def _submit_op(self, pool_name: str, schema, topic, body):
        """Shared POST path: parse via the schema walk, validate by
        pool entry (the apply rule), gossip on accept (reference
        statetransition/OperationPool.java add + publish)."""
        try:
            op = _ssz_from_json(schema, body)
        except (KeyError, ValueError, TypeError, AttributeError) as exc:
            raise HttpError(400, f"malformed {pool_name[:-1]}: {exc}")
        pool = self.node.operation_pools[pool_name]
        if not pool.add(self.node.chain.head_state(), op):
            raise HttpError(400,
                            f"{pool_name[:-1]} invalid or duplicate")
        await self.node.gossip.publish(topic, type(op).serialize(op))
        return {}

    async def _post_attester_slashing(self, body=None):
        from ..node.gossip import ATTESTER_SLASHING_TOPIC
        S = self.node.spec.at_slot(self.node.chain.head_slot()).schemas
        return await self._submit_op(
            "attester_slashings", S.AttesterSlashing,
            ATTESTER_SLASHING_TOPIC, body)

    async def _post_proposer_slashing(self, body=None):
        from ..node.gossip import PROPOSER_SLASHING_TOPIC
        S = self.node.spec.at_slot(self.node.chain.head_slot()).schemas
        return await self._submit_op(
            "proposer_slashings", S.ProposerSlashing,
            PROPOSER_SLASHING_TOPIC, body)

    async def _post_bls_changes(self, body=None):
        """Per-item semantics (standard API): every valid change is
        pooled + broadcast; failures are reported per index, and one
        duplicate must not abort the rest of the batch."""
        from ..node.gossip import BLS_TO_EXECUTION_CHANGE_TOPIC
        from ..spec.milestones import build_fork_schedule, SpecMilestone
        try:
            version = build_fork_schedule(
                self.node.spec.config).version_for(SpecMilestone.CAPELLA)
        except KeyError:
            raise HttpError(400, "capella not scheduled on this network")
        ops = body if isinstance(body, list) else [body]
        failures = []
        for i, op in enumerate(ops):
            try:
                await self._submit_op(
                    "bls_to_execution_changes",
                    version.schemas.SignedBLSToExecutionChange,
                    BLS_TO_EXECUTION_CHANGE_TOPIC, op)
            except HttpError as exc:
                failures.append({"index": i, "message": exc.message})
        if failures:
            raise HttpError(400, f"failures: {failures}")
        return {}

    # -- balances / roots / withdrawals --------------------------------
    async def _validator_balances(self, state_id: str, query=None):
        state = await self._resolve_state_async(state_id)
        ids = None
        if query and query.get("id"):
            # the standard API allows index OR pubkey ids
            ids = self._validator_indices(state,
                                          query["id"].split(","))
        return self._balances_json(state, ids)

    async def _validator_balances_post(self, state_id: str, body=None):
        state = await self._resolve_state_async(state_id)
        ids = self._validator_indices(state, body) \
            if isinstance(body, list) else None
        return self._balances_json(state, ids)

    def _balances_json(self, state, ids):
        n = len(state.balances)
        idx = range(n) if ids is None else ids
        out = []
        for i in idx:
            if not 0 <= i < n:
                raise HttpError(400, f"unknown validator index {i}")
            out.append({"index": str(i),
                        "balance": str(state.balances[i])})
        return {"data": out}

    async def _block_root(self, block_id: str):
        return {"data": {"root": _hex(self._resolve_block_root(
            block_id))}}

    async def _block_attestations(self, block_id: str):
        block = self._block_by_root(self._resolve_block_root(block_id))
        if block is None:
            raise HttpError(404, "block not found")
        body = block.message.body if hasattr(block, "message") else \
            block.body
        return {"data": [_ssz_to_json(type(a), a)
                         for a in body.attestations]}

    async def _peer_count(self):
        connected = 0
        if self.networked:
            connected = sum(1 for p in self.networked.net.peers
                            if p.connected)
        return {"data": {"disconnected": "0", "connecting": "0",
                         "connected": str(connected),
                         "disconnecting": "0"}}

    async def _expected_withdrawals(self, state_id: str, query=None):
        state = await self._resolve_state_async(state_id)
        if not hasattr(state, "next_withdrawal_index"):
            raise HttpError(400, "pre-capella state has no withdrawals")
        cfg = self.node.spec.config
        try:
            slot = int(query["proposal_slot"]) if query \
                and query.get("proposal_slot") else state.slot + 1
        except (ValueError, TypeError):
            raise HttpError(400, "invalid proposal_slot")
        # the advance is client-controlled work on the event loop:
        # bound it to one epoch ahead (the reference's handler serves
        # proposal lookahead, not arbitrary time travel)
        if not (state.slot <= slot
                <= state.slot + cfg.SLOTS_PER_EPOCH):
            raise HttpError(400, "proposal_slot out of range "
                                 "(within one epoch of the state)")
        from ..spec.transition import process_slots
        if state.slot < slot:
            state = process_slots(cfg, state, slot)
        if hasattr(state, "pending_partial_withdrawals"):
            from ..spec.electra.block import get_expected_withdrawals
            withdrawals = get_expected_withdrawals(cfg, state)[0]
        else:
            from ..spec.capella.block import get_expected_withdrawals
            withdrawals = get_expected_withdrawals(cfg, state)
        return {"data": [{
            "index": str(w.index),
            "validator_index": str(w.validator_index),
            "address": _hex(w.address),
            "amount": str(w.amount)} for w in withdrawals]}

    # -- metrics -------------------------------------------------------
    async def _submit_sync_messages(self, body=None):
        """Sync-committee messages (reference handlers/v1/beacon/
        PostSyncCommittees) — the remote VC's sync-duty submission."""
        if not isinstance(body, list):
            raise HttpError(400, "expected a list of sync messages")
        from ..spec.milestones import build_fork_schedule, SpecMilestone
        try:
            version = build_fork_schedule(
                self.node.spec.config).version_for(SpecMilestone.ALTAIR)
        except KeyError:
            raise HttpError(400, "altair not scheduled on this network")
        # parse the WHOLE batch before publishing anything: a 400 must
        # not leave earlier messages already gossiped
        msgs = []
        for m in body:
            try:
                msgs.append(version.schemas.SyncCommitteeMessage(
                    slot=int(m["slot"]),
                    beacon_block_root=bytes.fromhex(
                        m["beacon_block_root"][2:]),
                    validator_index=int(m["validator_index"]),
                    signature=bytes.fromhex(m["signature"][2:])))
            except (KeyError, ValueError, TypeError) as exc:
                raise HttpError(400, f"malformed sync message: {exc}")
        for msg in msgs:
            if self.validator_api is not None:
                await self.validator_api.publish_sync_committee_message(
                    msg)
            else:
                await self.node._process_sync_message(msg)
        return {"accepted": len(msgs)}

    async def _events(self, query=None):
        """SSE events stream (reference: handlers/v1/events/GetEvents +
        EventSubscriptionManager): head / block / finalized_checkpoint
        topics, one subscriber per connection, detached on close."""
        import asyncio as _asyncio
        from ..infra.events import (BlockImportChannel, ChainHeadChannel,
                                    FinalizedCheckpointChannel)
        from ..infra.restapi import SseStream
        topics = set((query or {}).get(
            "topics", "head,block,finalized_checkpoint").split(","))
        known = {"head", "block", "finalized_checkpoint"}
        if not topics <= known:
            raise HttpError(400, f"unknown topics {topics - known}")
        queue: _asyncio.Queue = _asyncio.Queue(maxsize=256)

        def _offer(item):
            try:
                queue.put_nowait(item)
            except _asyncio.QueueFull:
                pass    # slow client: drop rather than grow unbounded

        api = self

        class _Sink:
            def on_block_imported(self, signed_block, post_state):
                if "block" not in topics:
                    return
                block = signed_block.message
                _offer(("block", {
                    "slot": str(block.slot),
                    "block": _hex(block.htr()),
                    "execution_optimistic": False}))

            def on_chain_head_updated(self, slot, root, reorg=False):
                # FORK-CHOICE head changes only — an imported
                # non-canonical block must not masquerade as head
                if "head" not in topics:
                    return
                block = api.node.store.blocks.get(root)
                cfg = api.node.spec.config
                # duty dependent roots: last block before the epoch's
                # (and previous epoch's) first slot — consumers refetch
                # duties when these change across a reorg
                prev_dep = cur_dep = bytes(32)
                try:
                    from ..spec import helpers as _H
                    state = api.node.chain.head_state()
                    epoch = slot // cfg.SLOTS_PER_EPOCH
                    cur_start = epoch * cfg.SLOTS_PER_EPOCH
                    prev_start = max(epoch - 1, 0) * cfg.SLOTS_PER_EPOCH
                    if cur_start > 0:
                        cur_dep = _H.get_block_root_at_slot(
                            cfg, state, cur_start - 1)
                    if prev_start > 0:
                        prev_dep = _H.get_block_root_at_slot(
                            cfg, state, prev_start - 1)
                except Exception:
                    pass
                _offer(("head", {
                    "slot": str(slot), "block": _hex(root),
                    "state": _hex(block.state_root)
                    if block is not None else _hex(bytes(32)),
                    "epoch_transition": slot
                    % cfg.SLOTS_PER_EPOCH == 0,
                    "previous_duty_dependent_root": _hex(prev_dep),
                    "current_duty_dependent_root": _hex(cur_dep),
                    "execution_optimistic": False}))

            def on_new_finalized_checkpoint(self, checkpoint,
                                            from_optimistic_api=False):
                if "finalized_checkpoint" in topics:
                    _offer(("finalized_checkpoint", {
                        "block": _hex(checkpoint.root),
                        "epoch": str(checkpoint.epoch),
                        "execution_optimistic": False}))

        channels = self.node.channels

        async def gen():
            # subscribe INSIDE the generator so attach/detach are
            # symmetric: a stream torn down before its first event
            # (or never started at all) leaves no dead sink behind
            sink = _Sink()
            channels.subscribe(BlockImportChannel, sink)
            channels.subscribe(ChainHeadChannel, sink)
            channels.subscribe(FinalizedCheckpointChannel, sink)
            try:
                while True:
                    yield await queue.get()
            finally:
                channels.unsubscribe(BlockImportChannel, sink)
                channels.unsubscribe(ChainHeadChannel, sink)
                channels.unsubscribe(FinalizedCheckpointChannel, sink)

        return SseStream(gen())

    # -- light client (reference: handlers/v1/beacon/lightclient/) -----
    @staticmethod
    def _lc_header_json(header):
        return {"beacon": {
            "slot": str(header.slot),
            "proposer_index": str(header.proposer_index),
            "parent_root": _hex(header.parent_root),
            "state_root": _hex(header.state_root),
            "body_root": _hex(header.body_root)}}

    @staticmethod
    def _lc_committee_json(committee):
        return {"pubkeys": [_hex(pk) for pk in committee.pubkeys],
                "aggregate_pubkey": _hex(committee.aggregate_pubkey)}

    async def _lc_bootstrap(self, block_id: str):
        from ..spec.altair.light_client import create_bootstrap
        root = self._resolve_block_root(block_id)
        block = self.node.store.blocks.get(root)
        state = self.node.store.block_states.get(root)
        if block is None or state is None:
            raise HttpError(404, "block/state not retained")
        if not hasattr(state, "current_sync_committee"):
            raise HttpError(400, "pre-altair state has no light client")
        b = create_bootstrap(self.node.spec.config, state, block)
        return {"data": {
            "header": self._lc_header_json(b.header),
            "current_sync_committee": self._lc_committee_json(
                b.current_sync_committee),
            "current_sync_committee_branch": [
                _hex(h) for h in b.current_sync_committee_branch]}}

    async def _lc_finality_update(self):
        """Latest finality-bearing update derivable from the hot chain:
        newest (attested, child-with-aggregate) pair whose attested
        state names a known finalized block."""
        from ..spec.altair.light_client import (block_to_header,
                                                create_update)
        store = self.node.store
        cfg = self.node.spec.config
        root = self.node.chain.head_root
        for _ in range(2 * cfg.SLOTS_PER_EPOCH):
            blk = store.blocks.get(root)
            if blk is None or not hasattr(blk.body, "sync_aggregate"):
                break
            parent = blk.parent_root
            pblk = store.blocks.get(parent)
            pstate = store.block_states.get(parent)
            agg = blk.body.sync_aggregate
            if (pblk is not None and pstate is not None
                    and pblk.slot == blk.slot - 1
                    and sum(agg.sync_committee_bits) > 0):
                fin_root = pstate.finalized_checkpoint.root
                fin_blk = store.blocks.get(fin_root)
                if fin_blk is not None:
                    u = create_update(
                        cfg, pstate, pblk, block_to_header(fin_blk),
                        agg, blk.slot, include_next_committee=False)
                    return {"data": {
                        "attested_header": self._lc_header_json(
                            u.attested_header),
                        "finalized_header": self._lc_header_json(
                            u.finalized_header),
                        "finality_branch": [
                            _hex(h) for h in u.finality_branch],
                        "sync_aggregate": {
                            # packed SSZ bitvector hex, per the API spec
                            "sync_committee_bits": _hex(
                                type(agg)._ssz_fields[
                                    "sync_committee_bits"].serialize(
                                    agg.sync_committee_bits)),
                            "sync_committee_signature": _hex(
                                agg.sync_committee_signature)},
                        "signature_slot": str(u.signature_slot)}}
            root = parent
        raise HttpError(404, "no finality update available")

    async def _lc_updates(self, query=None):
        """GetLightClientUpdatesByRange: best retained update per sync
        committee period (reference handlers/v1/beacon/
        GetLightClientUpdatesByRange) — served from the hot chain, so
        only recently-retained periods resolve."""
        from ..spec.altair.light_client import (block_to_header,
                                                create_update)
        try:
            start = int(query.get("start_period", 0)) if query else 0
            count = min(int(query.get("count", 1)) if query else 1, 128)
        except (ValueError, TypeError, KeyError):
            raise HttpError(400, "invalid start_period/count")
        store = self.node.store
        cfg = self.node.spec.config
        period_slots = (cfg.EPOCHS_PER_SYNC_COMMITTEE_PERIOD
                        * cfg.SLOTS_PER_EPOCH)
        best_by_period: dict = {}
        root = self.node.chain.head_root
        for _ in range(4 * cfg.SLOTS_PER_EPOCH):
            blk = store.blocks.get(root)
            if blk is None or not hasattr(blk.body, "sync_aggregate"):
                break
            parent = blk.parent_root
            pblk = store.blocks.get(parent)
            pstate = store.block_states.get(parent)
            agg = blk.body.sync_aggregate
            if (pblk is not None and pstate is not None
                    and sum(agg.sync_committee_bits) > 0):
                period = pblk.slot // period_slots
                fin_blk = store.blocks.get(
                    pstate.finalized_checkpoint.root)
                prev = best_by_period.get(period)
                # "best" per the spec's is_better_update ordering
                # proxy: finality-bearing beats not, then highest
                # sync-committee participation
                rank = (fin_blk is not None,
                        sum(agg.sync_committee_bits))
                if (start <= period < start + count
                        and (prev is None or rank > prev[2])):
                    u = create_update(
                        cfg, pstate, pblk,
                        block_to_header(fin_blk)
                        if fin_blk is not None else None,
                        agg, blk.slot)
                    best_by_period[period] = (u, agg, rank)
            root = parent
        # the API schema requires these fields populated; a zeroed
        # header marks "no finality proof in this update"
        zero_header = {"beacon": {
            "slot": "0", "proposer_index": "0",
            "parent_root": _hex(bytes(32)),
            "state_root": _hex(bytes(32)),
            "body_root": _hex(bytes(32))}}
        out = []
        for period in sorted(best_by_period):
            u, agg, _rank = best_by_period[period]
            out.append({"data": {
                "attested_header": self._lc_header_json(
                    u.attested_header),
                "next_sync_committee": self._lc_committee_json(
                    u.next_sync_committee)
                if u.next_sync_committee is not None else None,
                "next_sync_committee_branch": [
                    _hex(h) for h in u.next_sync_committee_branch],
                "finalized_header": self._lc_header_json(
                    u.finalized_header)
                if u.finalized_header is not None else zero_header,
                "finality_branch": [_hex(h)
                                    for h in u.finality_branch],
                "sync_aggregate": {
                    "sync_committee_bits": _hex(
                        type(agg)._ssz_fields[
                            "sync_committee_bits"].serialize(
                            agg.sync_committee_bits)),
                    "sync_committee_signature": _hex(
                        agg.sync_committee_signature)},
                "signature_slot": str(u.signature_slot)}})
        return out

    async def _peer_by_id(self, peer_id: str):
        """reference handlers/v1/node/GetPeerById."""
        if self.networked:
            for peer in self.networked.net.peers:
                if peer.node_id.hex() == peer_id.removeprefix("0x"):
                    return {"data": self._peer_json(peer)}
        raise HttpError(404, "peer not found")

    async def _debug_fork_choice(self):
        """reference handlers/v1/debug/GetForkChoice: the proto-array
        dump fork-choice debugging tools consume."""
        store = self.node.store
        nodes = []
        for n in store.proto.nodes:
            nodes.append({
                "slot": str(n.slot),
                "block_root": _hex(n.root),
                "parent_root": _hex(store.proto.nodes[n.parent].root)
                if n.parent is not None else _hex(bytes(32)),
                "justified_epoch": str(n.justified_epoch),
                "finalized_epoch": str(n.finalized_epoch),
                # RAW weight: this endpoint exists to expose
                # vote-accounting state, including corrupt (negative)
                # values a clamp would hide
                "weight": str(n.weight),
                "validity": "valid",
                "execution_block_hash": _hex(bytes(32)),
            })
        return {
            "justified_checkpoint": {
                "epoch": str(store.justified_checkpoint.epoch),
                "root": _hex(store.justified_checkpoint.root)},
            "finalized_checkpoint": {
                "epoch": str(store.finalized_checkpoint.epoch),
                "root": _hex(store.finalized_checkpoint.root)},
            "fork_choice_nodes": nodes,
            "extra_data": {},
        }

    async def _admin_traces(self, query=None):
        """The slow-trace ring as JSON: the N slowest complete verifies
        with their per-stage latency breakdowns (ms), slowest first.
        `?clear=1` empties the ring after the read — useful for
        isolating one incident's traces from boot-time compiles."""
        out = {"tracing_enabled": tracing.enabled(),
               "data": tracing.slow_traces()}
        if query and query.get("clear") in ("1", "true"):
            tracing.clear_slow_traces()
        return out

    async def _metrics(self):
        return GLOBAL_REGISTRY.expose(), "text/plain; version=0.0.4"
