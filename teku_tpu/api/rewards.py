"""Reward calculation for the REST rewards endpoints.

Equivalent of the reference's rewards providers (reference: data/
beaconrestapi/.../handlers/v1/rewards/ GetBlockRewards /
PostAttestationRewards / PostSyncCommitteeRewards backed by
validator/coordinator/RewardCalculator.java): block proposer reward
decomposition, per-validator attestation rewards for an epoch, and
per-participant sync-committee rewards for a block.

All math reuses the spec modules' own formulas; the proposer's
attestation component is derived exactly as
(post - pre balance delta) - sync component - slashing components,
which is the identity the transition guarantees.
"""

from typing import Dict, List, Optional, Tuple

from ..spec import helpers as H
from ..spec.config import (PARTICIPATION_FLAG_WEIGHTS, PROPOSER_WEIGHT,
                           SpecConfig, SYNC_REWARD_WEIGHT,
                           TIMELY_HEAD_FLAG_INDEX,
                           TIMELY_SOURCE_FLAG_INDEX,
                           TIMELY_TARGET_FLAG_INDEX, WEIGHT_DENOMINATOR)


def sync_aggregate_rewards(cfg: SpecConfig, pre_state,
                           sync_aggregate
                           ) -> Tuple[int, int, List[Tuple[int, int]]]:
    """(proposer_total, participant_reward, [(validator_index, delta)])
    for one block's sync aggregate, from the block's PRE-state (same
    math as altair process_sync_aggregate)."""
    from ..spec.altair import helpers as AH
    total_active_increments = (H.get_total_active_balance(cfg, pre_state)
                               // cfg.EFFECTIVE_BALANCE_INCREMENT)
    base_per_inc = AH.get_base_reward_per_increment(cfg, pre_state)
    total_base_rewards = base_per_inc * total_active_increments
    max_participant_rewards = (total_base_rewards * SYNC_REWARD_WEIGHT
                               // WEIGHT_DENOMINATOR
                               // cfg.SLOTS_PER_EPOCH)
    participant_reward = (max_participant_rewards
                          // cfg.SYNC_COMMITTEE_SIZE)
    proposer_per = (participant_reward * PROPOSER_WEIGHT
                    // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT))
    pubkey_to_index = {v.pubkey: i
                       for i, v in enumerate(pre_state.validators)}
    deltas = []
    proposer_total = 0
    for pk, participated in zip(
            pre_state.current_sync_committee.pubkeys,
            sync_aggregate.sync_committee_bits):
        index = pubkey_to_index[pk]
        if participated:
            deltas.append((index, participant_reward))
            proposer_total += proposer_per
        else:
            deltas.append((index, -participant_reward))
    return proposer_total, participant_reward, deltas


def slashing_rewards(cfg: SpecConfig, pre_state, body
                     ) -> Tuple[int, int]:
    """(proposer_slashing_reward, attester_slashing_reward) the block's
    proposer earns for included slashings.  In-protocol slashings pass
    whistleblower_index=None, so the proposer collects the FULL
    whistleblower reward (spec slash_validator: proposer_reward plus
    the whistleblower remainder both land on the proposer)."""
    epoch = H.get_current_epoch(cfg, pre_state)
    electra = hasattr(pre_state, "deposit_requests_start_index")
    quotient = (cfg.WHISTLEBLOWER_REWARD_QUOTIENT_ELECTRA if electra
                else cfg.WHISTLEBLOWER_REWARD_QUOTIENT)

    def full_whistleblower(validator_index: int) -> int:
        v = pre_state.validators[validator_index]
        # only slashable validators are slashed (and rewarded for)
        if v.slashed or not (v.activation_epoch <= epoch
                             < v.withdrawable_epoch):
            return 0
        return v.effective_balance // quotient

    proposer_total = 0
    for slashing in body.proposer_slashings:
        proposer_total += full_whistleblower(
            slashing.signed_header_1.message.proposer_index)
    attester_total = 0
    for slashing in body.attester_slashings:
        a = set(slashing.attestation_1.attesting_indices)
        b = set(slashing.attestation_2.attesting_indices)
        for index in sorted(a & b):
            attester_total += full_whistleblower(index)
    return proposer_total, attester_total


def block_rewards(cfg: SpecConfig, pre_state, post_state, block
                  ) -> Dict[str, int]:
    """The GetBlockRewards decomposition.  `pre_state` must already be
    advanced to block.slot (pre-block), `post_state` is the block's
    post-state."""
    proposer = block.proposer_index
    total = int(post_state.balances[proposer]) \
        - int(pre_state.balances[proposer])
    body = block.body
    # the raw delta includes non-reward balance movement: withdrawals
    # debiting the proposer (capella+ sweep) and deposits crediting it
    # — normalize them out so the decomposition reports REWARDS only
    payload = getattr(body, "execution_payload", None)
    for w in getattr(payload, "withdrawals", ()) or ():
        if w.validator_index == proposer:
            total += int(w.amount)
    # electra (EIP-6110/7251) deposits credit the pending-deposit queue
    # during block processing, NOT balances — normalizing there would
    # understate the attestations component by the deposit amount
    if not hasattr(post_state, "pending_deposits"):
        proposer_pubkey = pre_state.validators[proposer].pubkey
        for deposit in getattr(body, "deposits", ()) or ():
            if deposit.data.pubkey == proposer_pubkey:
                total -= int(deposit.data.amount)
    sync_total = 0
    if hasattr(body, "sync_aggregate") \
            and hasattr(pre_state, "current_sync_committee"):
        sync_total, _, deltas = sync_aggregate_rewards(
            cfg, pre_state, body.sync_aggregate)
        # the proposer may itself sit in the committee: its own
        # participant delta lands in `total` but is not proposer income
        # from PROPOSING — the endpoint counts it under sync_aggregate
        # per the reference's calculator
        sync_total += sum(d for i, d in deltas if i == proposer)
    prop_slash, att_slash = slashing_rewards(cfg, pre_state, body)
    attestations = total - sync_total - prop_slash - att_slash
    return {
        "proposer_index": proposer,
        "total": total,
        "attestations": attestations,
        "sync_aggregate": sync_total,
        "proposer_slashings": prop_slash,
        "attester_slashings": att_slash,
    }


def phase0_attestation_rewards(cfg: SpecConfig, state,
                               indices: Optional[List[int]] = None
                               ) -> Dict:
    """Phase0 shape of the rewards decomposition (pending-attestation
    component deltas + inclusion delay + leak penalties — the same
    parts get_attestation_deltas sums)."""
    from ..spec import epoch as E0

    n = len(state.validators)
    wanted = set(indices) if indices else None
    total_balance = H.get_total_active_balance(cfg, state)
    eligible = E0.get_eligible_validator_indices(cfg, state)
    prev = H.get_previous_epoch(cfg, state)
    src = E0.get_matching_source_attestations(cfg, state, prev)
    tgt = E0.get_matching_target_attestations(cfg, state, prev)
    head = E0.get_matching_head_attestations(cfg, state, prev)
    parts = {}
    for name, atts in (("source", src), ("target", tgt),
                       ("head", head)):
        r, p = E0._component_deltas(cfg, state, atts, n, total_balance,
                                    eligible)
        parts[name] = [r[i] - p[i] for i in range(n)]
    # inclusion delay (attester part only; the proposer part is block
    # income, reported by the block-rewards endpoint)
    incl = [0] * n
    att_cache = {}
    for a in src:
        for i in H.get_attesting_indices(cfg, state, a.data,
                                         a.aggregation_bits):
            cached = att_cache.get(i)
            if cached is None or a.inclusion_delay < \
                    cached.inclusion_delay:
                att_cache[i] = a
    for index in E0.get_unslashed_attesting_indices(cfg, state, src):
        a = att_cache[index]
        base = E0.get_base_reward(cfg, state, index, total_balance)
        proposer_reward = base // cfg.PROPOSER_REWARD_QUOTIENT
        incl[index] += (base - proposer_reward) // a.inclusion_delay
    inactivity = [0] * n
    if E0.is_in_inactivity_leak(cfg, state):
        tgt_unslashed = E0.get_unslashed_attesting_indices(cfg, state,
                                                           tgt)
        delay = E0.get_finality_delay(cfg, state)
        for index in eligible:
            base = E0.get_base_reward(cfg, state, index, total_balance)
            inactivity[index] -= (E0.BASE_REWARDS_PER_EPOCH * base
                                  - base // cfg.PROPOSER_REWARD_QUOTIENT)
            if index not in tgt_unslashed:
                eff = state.validators[index].effective_balance
                inactivity[index] -= (eff * delay
                                      // cfg.INACTIVITY_PENALTY_QUOTIENT)
    totals = []
    for i in range(n):
        if wanted is not None and i not in wanted:
            continue
        totals.append({"validator_index": i,
                       "head": parts["head"][i],
                       "target": parts["target"][i],
                       "source": parts["source"][i],
                       "inclusion_delay": incl[i],
                       "inactivity": inactivity[i]})
    return {"ideal_rewards": [], "total_rewards": totals}


def attestation_rewards(cfg: SpecConfig, state,
                        indices: Optional[List[int]] = None) -> Dict:
    """Per-validator attestation rewards for the epoch the state's
    PREVIOUS participation covers (call with a state in epoch+1, as the
    reference's PostAttestationRewards does): actual head/target/source
    rewards-or-penalties plus the ideal table per effective balance."""
    from ..spec import epoch as E0
    from ..spec.altair import epoch as AE
    from ..spec.altair import helpers as AH

    if not hasattr(state, "previous_epoch_participation"):
        return phase0_attestation_rewards(cfg, state, indices)

    n = len(state.validators)
    wanted = set(indices) if indices else None
    flag_names = {TIMELY_SOURCE_FLAG_INDEX: "source",
                  TIMELY_TARGET_FLAG_INDEX: "target",
                  TIMELY_HEAD_FLAG_INDEX: "head"}
    totals = {i: {"head": 0, "target": 0, "source": 0, "inactivity": 0}
              for i in range(n)
              if wanted is None or i in wanted}
    for flag_index in range(len(PARTICIPATION_FLAG_WEIGHTS)):
        rewards, penalties = AE.get_flag_index_deltas(cfg, state,
                                                      flag_index)
        name = flag_names[flag_index]
        for i in totals:
            totals[i][name] = rewards[i] - penalties[i]
    _, inactivity = AE.get_inactivity_penalty_deltas(cfg, state)
    for i in totals:
        totals[i]["inactivity"] = -inactivity[i]

    # ideal rewards per effective-balance increment tier (a perfect
    # attester with every timely flag, not leaking)
    inc = cfg.EFFECTIVE_BALANCE_INCREMENT
    active_increments = H.get_total_active_balance(cfg, state) // inc
    base_per_inc = AH.get_base_reward_per_increment(cfg, state)
    leaking = E0.is_in_inactivity_leak(cfg, state)
    unslashed_incs = {}
    for flag_index in range(len(PARTICIPATION_FLAG_WEIGHTS)):
        participating = AH.get_unslashed_participating_indices(
            cfg, state, flag_index, H.get_previous_epoch(cfg, state))
        unslashed_incs[flag_index] = H.get_total_balance(
            cfg, state, participating) // inc
    max_eb = max((v.effective_balance for v in state.validators),
                 default=cfg.MAX_EFFECTIVE_BALANCE)
    ideal = []
    for tiers in range(1, max_eb // inc + 1):
        eb = tiers * inc
        base_reward = tiers * base_per_inc
        row = {"effective_balance": eb, "head": 0, "target": 0,
               "source": 0, "inactivity": 0}
        if not leaking:
            for flag_index, weight in enumerate(
                    PARTICIPATION_FLAG_WEIGHTS):
                row[flag_names[flag_index]] = (
                    base_reward * weight * unslashed_incs[flag_index]
                    // (active_increments * WEIGHT_DENOMINATOR))
        ideal.append(row)
    return {"ideal_rewards": ideal,
            "total_rewards": [dict(validator_index=i, **vals)
                              for i, vals in sorted(totals.items())]}
