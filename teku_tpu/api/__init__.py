"""REST API surface (reference: data/beaconrestapi)."""

from .beacon_api import BeaconRestApi
