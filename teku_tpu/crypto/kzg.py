"""KZG polynomial commitments (EIP-4844 blob verification).

Equivalent of the reference's KZG module (reference: infrastructure/
kzg/src/main/java/tech/pegasys/teku/kzg/KZG.java interface and
CKZG4844.java:58-145 JNI wrapper over c-kzg-4844) — here implemented on
this repo's own BLS12-381 base (crypto/bls): barycentric evaluation in
the scalar field, Pippenger MSM over the Lagrange setup, and the
two-pairing proof check.  The math follows the public EIP-4844 /
polynomial-commitments consensus spec.

Trusted setups load from the standard ceremony text format
(4096 G1-Lagrange points, 65 G2-monomial points — the same public
artifact every client ships); `insecure_setup(tau)` builds a dev/test
setup with KNOWN tau, which also unlocks O(1) commitment/proof
construction for tests (never use outside tests).
"""

import hashlib
from dataclasses import dataclass
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

from .bls import constants as K
from .bls import curve as C
from .bls import fields as F
from .bls import pairing as PAIR

R = K.R                                    # BLS scalar field modulus
FIELD_ELEMENTS_PER_BLOB = 4096
BYTES_PER_FIELD_ELEMENT = 32
BYTES_PER_BLOB = FIELD_ELEMENTS_PER_BLOB * BYTES_PER_FIELD_ELEMENT
PRIMITIVE_ROOT = 7
FIAT_SHAMIR_PROTOCOL_DOMAIN = b"FSBLOBVERIFY_V1_"
RANDOM_CHALLENGE_DOMAIN = b"RCKZGBATCH___V1_"

G1 = C.G1_GENERATOR
G2 = C.G2_GENERATOR


class KzgError(ValueError):
    """Malformed blob/commitment/proof input."""


class BackendUnavailable(RuntimeError):
    """The accelerated backend cannot serve this dispatch (circuit
    open, deadline overrun, device fault).  The facade falls through to
    the host path: a sick device costs latency, never a verdict."""


# --------------------------------------------------------------------------
# Roots of unity (bit-reversed order, matching c-kzg's Lagrange layout)
# --------------------------------------------------------------------------

def _bit_reversed_roots() -> List[int]:
    order = FIELD_ELEMENTS_PER_BLOB
    w = pow(PRIMITIVE_ROOT, (R - 1) // order, R)
    roots = [1] * order
    for i in range(1, order):
        roots[i] = roots[i - 1] * w % R
    width = order.bit_length() - 1
    return [roots[int(format(i, f"0{width}b")[::-1], 2)]
            for i in range(order)]


_ROOTS: Optional[List[int]] = None


def roots_of_unity() -> List[int]:
    global _ROOTS
    if _ROOTS is None:
        _ROOTS = _bit_reversed_roots()
    return _ROOTS


# --------------------------------------------------------------------------
# Field / bytes helpers
# --------------------------------------------------------------------------

def bytes_to_bls_field(b: bytes) -> int:
    if len(b) != BYTES_PER_FIELD_ELEMENT:
        raise KzgError("field element must be 32 bytes")
    v = int.from_bytes(b, "big")
    if v >= R:
        raise KzgError("field element out of range")
    return v


def blob_to_polynomial(blob: bytes) -> List[int]:
    if len(blob) != BYTES_PER_BLOB:
        raise KzgError(f"blob must be {BYTES_PER_BLOB} bytes")
    return [bytes_to_bls_field(blob[i * 32:(i + 1) * 32])
            for i in range(FIELD_ELEMENTS_PER_BLOB)]


def evaluate_polynomial_in_evaluation_form(poly: Sequence[int],
                                           z: int) -> int:
    """Barycentric: p(z) = (z^n - 1)/n * sum_i p_i * w_i / (z - w_i)."""
    n = FIELD_ELEMENTS_PER_BLOB
    roots = roots_of_unity()
    for i, w in enumerate(roots):
        if z == w:
            return poly[i] % R
    # batch-invert the (z - w_i) denominators with one Fermat pass
    denoms = [(z - w) % R for w in roots]
    invs = _batch_inverse(denoms)
    acc = 0
    for p_i, w, inv in zip(poly, roots, invs):
        acc = (acc + p_i * w % R * inv) % R
    acc = acc * (pow(z, n, R) - 1) % R
    acc = acc * pow(n, R - 2, R) % R
    return acc


# --------------------------------------------------------------------------
# Trusted setup
# --------------------------------------------------------------------------

@dataclass
class TrustedSetup:
    g1_lagrange: Optional[List[Tuple]]     # None for insecure setups
    g2_monomial: List[Tuple]               # at least [G2, [s]G2]
    g1_monomial: Optional[List[Tuple]] = None
    tau: Optional[int] = None              # ONLY for insecure dev setups

    @property
    def s_g2(self):
        return self.g2_monomial[1]


def load_trusted_setup(path) -> TrustedSetup:
    """Parse the standard ceremony text format: counts, G1-Lagrange
    points (bit-reversed), G2 monomial points, and (extended format)
    G1 monomial points (reference: TrustedSetup.java /
    CKZG4844.loadTrustedSetup)."""
    lines = Path(path).read_text().split()
    n_g1, n_g2 = int(lines[0]), int(lines[1])
    if n_g1 != FIELD_ELEMENTS_PER_BLOB:
        raise KzgError(f"expected {FIELD_ELEMENTS_PER_BLOB} G1 points")
    hexes = lines[2:]
    if len(hexes) not in (n_g1 + n_g2, 2 * n_g1 + n_g2):
        raise KzgError("trusted setup length mismatch")
    g1 = [C.g1_decompress(bytes.fromhex(h)) for h in hexes[:n_g1]]
    # the file stores Lagrange points in natural order; the library
    # works in bit-reversed order throughout (c-kzg applies the same
    # permutation in its load_trusted_setup)
    width = n_g1.bit_length() - 1
    g1 = [g1[int(format(i, f"0{width}b")[::-1], 2)] for i in range(n_g1)]
    g2 = [C.g2_decompress(bytes.fromhex(h))
          for h in hexes[n_g1:n_g1 + n_g2]]
    g1_mono = None
    if len(hexes) == 2 * n_g1 + n_g2:
        g1_mono = [C.g1_decompress(bytes.fromhex(h))
                   for h in hexes[n_g1 + n_g2:]]
        gen = C.to_affine(C.FQ_OPS, g1_mono[0])
        if gen != (K.G1_X, K.G1_Y):
            raise KzgError("monomial[0] is not the G1 generator")
    return TrustedSetup(g1_lagrange=g1, g2_monomial=g2,
                        g1_monomial=g1_mono)


def insecure_setup(tau: int = 0x107) -> TrustedSetup:
    """Dev setup with known tau — commitments become a single scalar
    multiplication.  Tests only."""
    s_g2 = C.point_mul(C.FQ2_OPS, tau, G2)
    return TrustedSetup(g1_lagrange=None,
                        g2_monomial=[G2, s_g2], tau=tau)


_SETUP: Optional[TrustedSetup] = None
# the public KZG-ceremony output (the exact artifact every consensus
# client ships; vendored under teku_tpu/resources with provenance)
REFERENCE_SETUP_PATH = str(
    Path(__file__).resolve().parents[1]
    / "resources" / "mainnet-trusted-setup.txt")


def get_setup() -> TrustedSetup:
    global _SETUP
    if _SETUP is None:
        if not Path(REFERENCE_SETUP_PATH).is_file():
            # NEVER degrade to the known-tau dev setup implicitly —
            # that would make default-path proofs forgeable
            raise KzgError(
                "trusted setup missing; call set_setup() explicitly "
                f"(looked at {REFERENCE_SETUP_PATH})")
        _SETUP = load_trusted_setup(REFERENCE_SETUP_PATH)
    return _SETUP


def set_setup(setup: Optional[TrustedSetup]) -> None:
    global _SETUP
    _SETUP = setup


# --------------------------------------------------------------------------
# MSM (host Pippenger; the device path reuses ops/points batching)
# --------------------------------------------------------------------------

def g1_msm(points: Sequence[Tuple], scalars: Sequence[int],
           window: int = 8) -> Tuple:
    """Pippenger bucket MSM over G1 (the role blst's mult_pippenger
    plays for c-kzg; reference consumes it via JNI)."""
    ops = C.FQ_OPS
    acc = C.infinity(ops)
    n_windows = (255 + window - 1) // window
    for w in range(n_windows - 1, -1, -1):
        for _ in range(window):
            acc = C.point_double(ops, acc)
        buckets = [None] * (1 << window)
        shift = w * window
        mask = (1 << window) - 1
        for p, s in zip(points, scalars):
            b = (s >> shift) & mask
            if b:
                buckets[b] = p if buckets[b] is None else C.point_add(
                    ops, buckets[b], p)
        running = C.infinity(ops)
        total = C.infinity(ops)
        for b in range(len(buckets) - 1, 0, -1):
            if buckets[b] is not None:
                running = C.point_add(ops, running, buckets[b])
            total = C.point_add(ops, total, running)
        acc = C.point_add(ops, acc, total)
    return acc


# --------------------------------------------------------------------------
# Commitments and proofs
# --------------------------------------------------------------------------

def blob_to_kzg_commitment(blob: bytes,
                           setup: Optional[TrustedSetup] = None) -> bytes:
    setup = setup or get_setup()
    poly = blob_to_polynomial(blob)
    if setup.tau is not None:
        # known tau: p(tau) in the field, then ONE scalar mul
        y = evaluate_polynomial_in_evaluation_form(poly, setup.tau)
        return C.g1_compress(C.point_mul(C.FQ_OPS, y, G1))
    if _BACKEND is not None:
        try:
            # device ladder MSM over the Lagrange basis (ops/kzg.py)
            return _BACKEND.g1_lincomb(setup, poly)
        except BackendUnavailable:
            pass                 # host Pippenger serves this call
    pt = g1_msm(setup.g1_lagrange, poly)
    return C.g1_compress(pt)


def compute_kzg_proof_impl(poly: List[int], z: int,
                           setup: Optional[TrustedSetup] = None
                           ) -> Tuple[bytes, int]:
    """(proof, y): quotient witness for p(z) = y."""
    setup = setup or get_setup()
    y = evaluate_polynomial_in_evaluation_form(poly, z)
    roots = roots_of_unity()
    n = FIELD_ELEMENTS_PER_BLOB
    # quotient in evaluation form: q_i = (p_i - y) / (w_i - z)
    denoms = [(w - z) % R for w in roots]
    if any(d == 0 for d in denoms):
        # z hits a root: use the standard special-case formula
        m = denoms.index(0)
        q = [0] * n
        for i in range(n):
            if i == m:
                continue
            q[i] = (poly[i] - y) * pow(denoms[i], R - 2, R) % R
            q[m] = (q[m] - q[i] * roots[i] % R
                    * pow(roots[m], R - 2, R)) % R
        quotient = q
    else:
        invs = _batch_inverse(denoms)
        quotient = [(p - y) * inv % R for p, inv in zip(poly, invs)]
    if setup.tau is not None:
        q_tau = evaluate_polynomial_in_evaluation_form(quotient, setup.tau)
        return C.g1_compress(C.point_mul(C.FQ_OPS, q_tau, G1)), y
    if _BACKEND is not None:
        try:
            return _BACKEND.g1_lincomb(setup, quotient), y
        except BackendUnavailable:
            pass
    return C.g1_compress(g1_msm(setup.g1_lagrange, quotient)), y


def _batch_inverse(xs: List[int]) -> List[int]:
    n = len(xs)
    prefix = [1] * (n + 1)
    for i, x in enumerate(xs):
        prefix[i + 1] = prefix[i] * x % R
    inv_all = pow(prefix[n], R - 2, R)
    out = [0] * n
    for i in range(n - 1, -1, -1):
        out[i] = prefix[i] * inv_all % R
        inv_all = inv_all * xs[i] % R
    return out


def compute_blob_kzg_proof(blob: bytes, commitment: bytes,
                           setup: Optional[TrustedSetup] = None) -> bytes:
    poly = blob_to_polynomial(blob)
    z = compute_challenge(blob, commitment)
    proof, _ = compute_kzg_proof_impl(poly, z, setup)
    return proof


# --------------------------------------------------------------------------
# Verification
# --------------------------------------------------------------------------

def _decompress_g1_checked(b: bytes, what: str):
    try:
        p = C.g1_decompress(b)
    except Exception as exc:
        raise KzgError(f"bad {what}: {exc}") from exc
    if not C.is_infinity(C.FQ_OPS, p) and not C.g1_in_subgroup(p):
        raise KzgError(f"{what} not in subgroup")
    return p


def verify_kzg_proof_impl(commitment_pt, z: int, y: int, proof_pt,
                          setup: Optional[TrustedSetup] = None) -> bool:
    """e(C - [y]G1, G2) == e(proof, [s-z]G2), via one 2-term multi
    pairing (reference: c-kzg verify_kzg_proof)."""
    setup = setup or get_setup()
    ops1, ops2 = C.FQ_OPS, C.FQ2_OPS
    p_min_y = C.point_add(ops1, commitment_pt,
                          C.point_neg(ops1, C.point_mul(ops1, y, G1)))
    s_min_z = C.point_add(ops2, setup.s_g2,
                          C.point_neg(ops2, C.point_mul(ops2, z, G2)))
    a1 = C.to_affine(ops1, C.point_neg(ops1, p_min_y))
    a2 = C.to_affine(ops2, G2)
    b1 = C.to_affine(ops1, proof_pt)
    b2 = C.to_affine(ops2, s_min_z)
    out = PAIR.multi_pairing([(a1, a2), (b1, b2)])
    return out == F.FQ12_ONE


def _verify_blob_kzg_proof_host(blob: bytes, commitment: bytes,
                                proof: bytes,
                                setup: Optional[TrustedSetup] = None
                                ) -> bool:
    """Host-only pairing path — shared by the no-backend case and the
    BackendUnavailable fallbacks (which must NOT re-enter the device)."""
    try:
        c_pt = _decompress_g1_checked(commitment, "commitment")
        p_pt = _decompress_g1_checked(proof, "proof")
        poly = blob_to_polynomial(blob)
    except KzgError:
        return False
    z = compute_challenge(blob, commitment)
    y = evaluate_polynomial_in_evaluation_form(poly, z)
    return verify_kzg_proof_impl(c_pt, z, y, p_pt, setup)


def verify_blob_kzg_proof(blob: bytes, commitment: bytes, proof: bytes,
                          setup: Optional[TrustedSetup] = None) -> bool:
    """reference KZG.verifyBlobKzgProof (CKZG4844.java:104-113)."""
    _record_kzg_arrival(1)
    if _BACKEND is not None and len(blob) == BYTES_PER_BLOB:
        try:
            return _BACKEND.verify_blob_kzg_proof(
                blob, commitment, proof, setup or get_setup())
        except KzgError:
            return False
        except BackendUnavailable:
            pass                 # host pairing path serves this call
    return _verify_blob_kzg_proof_host(blob, commitment, proof, setup)


# Pluggable accelerated backend (the KZG analogue of the BLS facade's
# set_implementation seam): installed by the loader alongside the JAX
# BLS provider, mirroring the reference's initKzg wiring
# (BeaconChainController.java:557-572 -> CKZG4844 JNI singleton).
_BACKEND = None


def set_backend(backend) -> None:
    global _BACKEND
    _BACKEND = backend


def get_backend():
    return _BACKEND


def backend_name() -> str:
    return getattr(_BACKEND, "name", "host-pure") if _BACKEND else \
        "host-pure"


# Blob verification is a DA prerequisite for import/sync: its demand
# stream competes with signature verification for the same device, so
# arrivals are accounted under their own capacity source and the
# sync-critical class (never sheddable — a shed blob check stalls the
# chain, not a gossip opinion).
KZG_ARRIVAL_SOURCE = "kzg"


def kzg_verify_class():
    """The VerifyClass blob verification is accounted under
    (SYNC_CRITICAL).  Lazy import: crypto must stay importable without
    the services layer."""
    from ..services.admission import VerifyClass
    return VerifyClass.SYNC_CRITICAL


def _record_kzg_arrival(n: int) -> None:
    """Blob-batch demand into the capacity model (source="kzg"), so
    utilization and brownout see blob storms.  Accounting must never
    fail a verification."""
    try:
        from ..infra import capacity
        capacity.record_arrival(KZG_ARRIVAL_SOURCE, n)
    except Exception:
        pass


def verify_blob_kzg_proof_batch(blobs: Sequence[bytes],
                                commitments: Sequence[bytes],
                                proofs: Sequence[bytes],
                                setup: Optional[TrustedSetup] = None
                                ) -> bool:
    """reference KZG.verifyBlobKzgProofBatch (CKZG4844.java:115-122):
    one random-linear-combination fold -> 2 pairings for the whole
    batch, dispatched to the device backend when installed."""
    if not (len(blobs) == len(commitments) == len(proofs)):
        return False
    if not blobs:
        return True
    _record_kzg_arrival(len(blobs))
    if _BACKEND is not None:
        try:
            return _BACKEND.verify_blob_kzg_proof_batch(
                blobs, commitments, proofs, setup or get_setup())
        except KzgError:
            return False
        except BackendUnavailable:
            # the device just failed this batch: serve it entirely
            # from the host path rather than paying a fresh device
            # deadline per blob on a backend we know is sick
            return _verify_batch_host(blobs, commitments, proofs,
                                      setup)
    # no backend installed: the host path directly — per-blob re-entry
    # through verify_blob_kzg_proof would double-count the demand
    return _verify_batch_host(blobs, commitments, proofs, setup)


def _verify_batch_host(blobs, commitments, proofs, setup) -> bool:
    """Per-blob host verification with an explicit first-failure exit:
    once one blob fails the batch verdict is False, and each remaining
    blob would cost a 4096-point barycentric pass + a 2-pairing check
    on a host that is already degraded."""
    for b, c, p in zip(blobs, commitments, proofs):
        if not _verify_blob_kzg_proof_host(b, c, p, setup):
            return False
    return True


def compute_challenge(blob: bytes, commitment: bytes) -> int:
    """Fiat-Shamir challenge: sha256(domain || uint128_be(degree) ||
    blob || commitment) reduced mod r (EIP-4844 compute_challenge)."""
    data = (FIAT_SHAMIR_PROTOCOL_DOMAIN
            + FIELD_ELEMENTS_PER_BLOB.to_bytes(16, "big")
            + blob + commitment)
    return int.from_bytes(hashlib.sha256(data).digest(), "big") % R
