"""BLS implementation selection at process start.

The reference refuses to boot before its accelerated BLS is proven
loadable (reference: teku/src/main/java/tech/pegasys/teku/Teku.java:74
preflight calling BLS.getBlsImpl, and the setBlsImplementation seam at
infrastructure/bls/src/main/java/tech/pegasys/teku/bls/BLS.java:51-62;
graceful degradation lives in BlstLoader.java:34-51).  This module is
that seam for the TPU build: `configure("auto"|"jax"|"pure")` installs
the chosen provider into the facade before any node service starts, so
every gossip / block-import / sync signature flows through the batched
device kernel rather than the pure-Python oracle.

"auto" probes the accelerator with a bounded deadline: a wedged TPU
tunnel must not hang node startup (the same failure mode bench.py
guards against), so the probe runs in a daemon thread and on timeout
the node falls back to the oracle with a loud log.  "jax" makes probe
failure fatal, mirroring the reference's hard preflight.
"""

import logging
import os
import threading
from typing import Optional

from . import get_implementation, reset_implementation, set_implementation

_LOG = logging.getLogger(__name__)

# generator pubkey (secret key 1): a cheap known-good probe input
_PROBE_PK = bytes.fromhex(
    "97f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58"
    "6c55e83ff97a1aeffb3af00adb22c6bb")

CHOICES = ("auto", "jax", "pure")


class BlsLoadError(RuntimeError):
    """The requested BLS implementation could not be brought up."""


def _probe_jax(max_batch: int, min_bucket: int):
    """Instantiate the device provider and prove the backend executes:
    one pubkey-validation dispatch (the small program; the five staged
    verify programs compile lazily on first real batch)."""
    from ...ops.provider import JaxBls12381

    impl = JaxBls12381(max_batch=max_batch, min_bucket=min_bucket)
    if not impl.public_key_is_valid(_PROBE_PK):
        raise BlsLoadError("device probe rejected the generator pubkey")
    import jax
    return impl, str(jax.devices()[0])


def configure(choice: str = "auto", *, max_batch: int = 256,
              min_bucket: int = 16,
              probe_timeout_s: Optional[float] = None) -> str:
    """Install the BLS provider for this process; returns its name.

    auto: try the JAX/TPU provider under a deadline, fall back to the
          pure oracle with a loud warning on any failure.
    jax:  require the JAX/TPU provider; raise BlsLoadError on failure.
    pure: install the oracle (also the explicit opt-out for tests).
    """
    if choice not in CHOICES:
        raise ValueError(f"unknown bls impl {choice!r} (use one of "
                         f"{'/'.join(CHOICES)})")
    if choice == "pure":
        reset_implementation()
        _reset_kzg_backend()
        return "pure"
    if probe_timeout_s is None:
        probe_timeout_s = float(
            os.environ.get("TEKU_TPU_BLS_PROBE_TIMEOUT_S", "120"))

    result: dict = {}

    def run():
        try:
            result["ok"] = _probe_jax(max_batch, min_bucket)
        except BaseException as exc:  # noqa: BLE001 - report any failure
            result["err"] = exc

    t = threading.Thread(target=run, daemon=True,
                         name="bls-loader-probe")
    t.start()
    t.join(probe_timeout_s)
    if t.is_alive():
        err: BaseException = BlsLoadError(
            f"backend probe exceeded {probe_timeout_s:.0f}s "
            "(wedged device tunnel?)")
    else:
        err = result.get("err")
    if err is None:
        impl, device = result["ok"]
        set_implementation(impl)
        # KZG rides the same kernel base: install the device backend
        # alongside (the reference's initKzg moment,
        # BeaconChainController.java:557-572)
        try:
            from .. import kzg as kzg_facade
            from ...ops.kzg import JaxKzg
            kzg_facade.set_backend(JaxKzg())
        except Exception as exc:  # pragma: no cover - defensive
            _LOG.warning("device KZG backend unavailable: %s", exc)
        _LOG.info("BLS implementation: %s on %s", impl.name, device)
        return impl.name
    if choice == "jax":
        raise BlsLoadError(f"--bls-impl jax: {err}") from (
            err if isinstance(err, Exception) else None)
    _LOG.warning(
        "BLS accelerator unavailable (%s: %s) — FALLING BACK to the "
        "pure-Python oracle; node-side signature verification will be "
        "slow", type(err).__name__, err)
    reset_implementation()
    _reset_kzg_backend()
    return "pure"


def _reset_kzg_backend() -> None:
    try:
        from .. import kzg as kzg_facade
        kzg_facade.set_backend(None)
    except Exception:  # pragma: no cover - import-order edge
        pass


def current_name() -> str:
    impl = get_implementation()
    return getattr(impl, "name", type(impl).__name__)
