"""BLS backend selection and supervised bring-up.

The reference refuses to boot before its accelerated BLS is proven
loadable (reference: teku/src/main/java/tech/pegasys/teku/Teku.java:74
preflight calling BLS.getBlsImpl, and the setBlsImplementation seam at
infrastructure/bls/src/main/java/tech/pegasys/teku/bls/BLS.java:51-62;
graceful degradation lives in BlstLoader.java:34-51).  That shape works
when the backend loads in milliseconds.  This repo's accelerator does
not: the TPU plugin can take ~25 minutes to initialize (VERDICT round
5), so a blocking preflight either hangs the node or silently strands
it on the pure oracle forever.

Two bring-up shapes live here:

- ``configure("jax"|"pure"|"auto")`` — the legacy blocking path: probe
  under a deadline, install or fall back.  Kept for tests, offline
  tools, and operators who explicitly want a hard preflight.
- ``make_supervisor()`` — the supervised path (`infra/supervisor.py`):
  the node boots immediately on the oracle, a background task drives
  bring-up with unbounded-but-observable patience, and on READY the
  facade hot-swaps to a breaker-guarded device provider.  ``auto`` on
  the CLI now means this.

``GuardedBls12381`` is the hot-swap target: every device dispatch runs
under the supervisor's CircuitBreaker (per-dispatch deadline,
consecutive-failure trip, half-open re-close), and any device failure
falls back to the pure oracle for THAT call — correctness never
degrades, only latency.
"""

import logging
import os
import threading
import time
from typing import Optional, Sequence, Tuple

from . import get_implementation, reset_implementation, set_implementation
from ...infra import aotstore, compilecache, faults, tracing
from ...infra.env import env_bool, env_float, env_int, env_str
from ...infra.metrics import GLOBAL_REGISTRY, MetricsRegistry
from ...infra.supervisor import (BackendSupervisor, CircuitBreaker,
                                 CircuitOpenError, DispatchTimeoutError,
                                 WarmupVetoError)
from .pure_impl import PureBls12381
from .spi import BLS12381, BatchSemiAggregate

_LOG = logging.getLogger(__name__)

# generator pubkey (secret key 1): a cheap known-good probe input
_PROBE_PK = bytes.fromhex(
    "97f1d3a73197d7942695638c4fa9ac0fc3688c4f9774b905a14e3a3f171bac58"
    "6c55e83ff97a1aeffb3af00adb22c6bb")

CHOICES = ("auto", "supervised", "jax", "pure")


class BlsLoadError(RuntimeError):
    """The requested BLS implementation could not be brought up."""


def _probe_jax(max_batch: int, min_bucket: int, mont_path=None,
               msm_path=None, mesh=None):
    """Instantiate the device provider and prove the backend executes:
    one pubkey-validation dispatch (the small program; the five staged
    verify programs compile lazily on first real batch).

    `mont_path` installs the process-global mont_mul engine choice
    (vpu | mxu | auto, ops/mxu.py) and `msm_path` the scalars-stage
    choice (ladder | pippenger | auto, ops/msm.py) BEFORE any kernel
    traces — the seams the CLI's `--mont-path`/`--msm-path` thread
    through.  `mesh` (off | auto | N, CLI `--mesh` / TEKU_TPU_MESH;
    None reads the env) resolves to the largest pow-2 device count
    available (teku_tpu/parallel.resolve_mesh_devices — an
    over-ambitious N demotes with one WARN, never fails bring-up) and
    constructs JaxBls12381(mesh=...) so production dispatches shard
    group-aligned across the chips.  The warmup batches downstream
    then compile the resolved (mesh x scalars-path) shape set off the
    gossip path."""
    from ...ops import msm, mxu
    from ...ops.provider import JaxBls12381

    if mont_path is not None:
        mxu.set_path(mont_path)
    if msm_path is not None:
        msm.set_path(msm_path)
    if mesh is None:
        mesh = env_str("TEKU_TPU_MESH", "off")
    from ... import parallel
    mesh_obj = None
    n_mesh = parallel.resolve_mesh_devices(mesh)
    if n_mesh >= 2:
        mesh_obj = parallel.make_mesh(n_mesh)
    impl = JaxBls12381(max_batch=max_batch, min_bucket=min_bucket,
                       mesh=mesh_obj)
    if not impl.public_key_is_valid(_PROBE_PK):
        raise BlsLoadError("device probe rejected the generator pubkey")
    import jax
    device = str(jax.devices()[0])
    if impl.mesh_info:
        device = f"mesh[{impl.mesh_info['n_devices']}] {device}"
    return impl, device


# --------------------------------------------------------------------------
# Guarded provider: the hot-swap target installed at READY
# --------------------------------------------------------------------------

# Atomically-swapped state registration for the static analyzer:
# `_serving` holds the (provider, device-entry lock) PAIR as one tuple
# so a reader can never observe a half-swap — which is only true if
# every reader performs exactly ONE attribute load and destructures
# the snapshot.  `cli lint`'s torn-read checker enforces the
# single-read rule tree-wide for every attribute declared here (the
# two-read bug shipped twice during PR 12 review).
__swap_attrs__ = ("_serving",)


class _DeferredSemi(BatchSemiAggregate):
    """Raw triple held until complete_batch_verify, so the guarded
    provider can route the WHOLE batch to whichever backend the circuit
    allows at dispatch time (device-specific semis must not outlive a
    mid-flight trip)."""

    __slots__ = ("triple",)

    def __init__(self, triple):
        self.triple = triple


class GuardedBls12381(BLS12381):
    """Device provider under a circuit breaker with oracle fallback.

    Verification dispatches go to the device while the circuit is
    closed; a trip (consecutive failures / deadline overruns) routes
    them to the pure oracle until half-open probing re-closes the
    circuit.  Non-batch host ops (keys, signing, aggregation) go to the
    oracle directly — the device provider delegates them there anyway.
    """

    def __init__(self, device: BLS12381, breaker: CircuitBreaker,
                 oracle: Optional[BLS12381] = None,
                 registry: MetricsRegistry = GLOBAL_REGISTRY):
        self.breaker = breaker
        self.oracle = oracle or PureBls12381()
        # optional mesh self-healer (parallel/selfheal.MeshHealer):
        # dispatch failures are reported so shard-level fault
        # isolation can eject the sick device and reshape, instead of
        # the whole-backend breaker cliff being the only containment
        self.healer = None
        # degraded-mode visibility: every guarded dispatch labeled by
        # the backend that actually served it and why — a node quietly
        # paying oracle latency must show up on one PromQL ratio
        self._m_requests = registry.labeled_counter(
            "bls_verify_requests_total",
            "guarded BLS dispatches by serving backend and reason",
            labelnames=("backend", "reason"))
        # (provider, device-entry lock) as ONE atomically-swapped pair.
        # The lock serializes device entry: a timed-out dispatch's
        # orphaned thread may still be running (e.g. finishing a cold
        # compile) and the provider's caches are not safe under
        # concurrent mutation.  A later dispatch blocks there until
        # the orphan drains; the breaker deadline bounds that wait and
        # accounts it as a timeout, so a busy device reads as a busy
        # device.  The mesh-reshape hot-swap replaces the PAIR in one
        # reference assignment: dispatches that grabbed the old pair
        # complete on the old plan (their orphans keep the old lock),
        # new dispatches take the new provider immediately and never
        # queue behind a wedged orphan.
        self._serving = (device, threading.Lock())

    @property
    def device(self) -> BLS12381:
        return self._serving[0]

    @property
    def _device_lock(self) -> threading.Lock:
        return self._serving[1]

    def swap_device(self, new_device: BLS12381) -> None:
        """Atomic mid-mesh hot-swap (the reshape install hook): one
        reference assignment, same invariant as the PR-1 install swap
        — in-flight verifies complete on the implementation pair they
        grabbed, new verifies take the reshaped provider."""
        self._serving = (new_device, threading.Lock())

    def _notify_healer(self, exc: BaseException, timeout: bool) -> None:
        healer = self.healer
        if healer is None:
            return
        try:
            healer.on_dispatch_failure(
                error=f"{type(exc).__name__}: {exc}", timeout=timeout)
        except Exception:  # pragma: no cover - healing must not kill
            _LOG.exception("mesh healer notification failed")

    @property
    def name(self) -> str:
        return self.device.name

    @property
    def serving(self) -> str:
        """Which backend the NEXT dispatch will use."""
        return ("oracle" if self.breaker.state == CircuitBreaker.OPEN
                else "device")

    # --- host ops: straight to the oracle ----------------------------
    def secret_key_to_public_key(self, secret: int) -> bytes:
        return self.oracle.secret_key_to_public_key(secret)

    def sign(self, secret: int, message: bytes) -> bytes:
        return self.oracle.sign(secret, message)

    def aggregate_public_keys(self, public_keys: Sequence[bytes]) -> bytes:
        return self.oracle.aggregate_public_keys(public_keys)

    def aggregate_signatures(self, signatures: Sequence[bytes]) -> bytes:
        return self.oracle.aggregate_signatures(signatures)

    def signature_is_valid(self, signature: bytes) -> bool:
        return self.oracle.signature_is_valid(signature)

    # --- guarded device dispatches ------------------------------------
    def _guarded(self, op: str, *args):
        # ONE read of the serving pair: the provider and its entry
        # lock stay consistent even when a reshape swaps mid-call
        device, lock = self._serving
        device_fn = getattr(device, op)

        def locked():
            with lock:
                return device_fn(*args)

        try:
            result = self.breaker.call(locked)
            self._m_requests.labels(backend="device", reason="ok").inc()
            return result
        except CircuitOpenError:
            # expected while tripped: silent oracle service
            self._m_requests.labels(backend="oracle",
                                    reason="breaker_open").inc()
        except DispatchTimeoutError as exc:
            self._m_requests.labels(backend="oracle",
                                    reason="fallback").inc()
            _LOG.warning("device %s overran deadline (%s); serving "
                         "this call from the oracle", op, exc)
            self._notify_healer(exc, timeout=True)
        except Exception as exc:  # noqa: BLE001 - any device fault
            self._m_requests.labels(backend="oracle",
                                    reason="fallback").inc()
            _LOG.warning("device %s failed (%s: %s); serving this "
                         "call from the oracle", op,
                         type(exc).__name__, exc)
            self._notify_healer(exc, timeout=False)
        # the oracle serving a device's call IS the degraded-mode cost:
        # a separate stage so traces show where the p50 went
        with tracing.span("oracle_execute"):
            return getattr(self.oracle, op)(*args)

    def public_key_is_valid(self, public_key: bytes) -> bool:
        return self._guarded("public_key_is_valid", public_key)

    def verify(self, public_key: bytes, message: bytes,
               signature: bytes) -> bool:
        return self._guarded("verify", public_key, message, signature)

    def fast_aggregate_verify(self, public_keys: Sequence[bytes],
                              message: bytes, signature: bytes) -> bool:
        return self._guarded("fast_aggregate_verify", public_keys,
                             message, signature)

    def aggregate_verify(self, public_keys: Sequence[bytes],
                         messages: Sequence[bytes],
                         signature: bytes) -> bool:
        return self._guarded("aggregate_verify", public_keys, messages,
                             signature)

    def batch_verify(
        self, triples: Sequence[Tuple[Sequence[bytes], bytes, bytes]],
    ) -> bool:
        return self._guarded("batch_verify", triples)

    # prepare/complete defer routing to complete-time: a device semi
    # prepared before a trip must not reach the oracle's completer
    def prepare_batch_verify(self, triple) -> Optional[BatchSemiAggregate]:
        return _DeferredSemi(triple)

    def complete_batch_verify(
        self, semi_aggregates: Sequence[Optional[BatchSemiAggregate]]
    ) -> bool:
        if any(sa is None for sa in semi_aggregates):
            return False
        # semis prepared BEFORE the hot-swap (by the oracle, the only
        # other installable facade impl) complete on the oracle — an
        # in-flight prepare/complete pair must finish on the
        # implementation family it started with, never crash
        deferred = [sa for sa in semi_aggregates
                    if isinstance(sa, _DeferredSemi)]
        foreign = [sa for sa in semi_aggregates
                   if not isinstance(sa, _DeferredSemi)]
        ok = True
        if deferred:
            ok = self.batch_verify([sa.triple for sa in deferred])
        if foreign:
            ok = self.oracle.complete_batch_verify(foreign) and ok
        return ok


def _warmup_batches(impl, max_batch: int) -> None:
    """Compile the verify pipeline OFF the gossip path (VERDICT r5
    weak #3: the first real batch used to pay a multi-minute staged
    compile in the hot path), at the two batch shapes the node
    dispatches most: the min_bucket pad and the primary bucket.
    Other (pow-2 × kmax) shapes still compile lazily — a cold compile
    that overruns the breaker deadline serves that call from the
    oracle while the orphaned dispatch thread finishes populating the
    jit cache, so the shape warms itself.  Shared by supervisor
    WARMING and the mesh self-healer's reshape warm (the shrunken
    sharded shape set must compile off-path too).  Raises
    WarmupVetoError on a wrong verdict — a device that gets a KNOWN
    answer wrong must never serve."""
    oracle = PureBls12381()
    msg = b"teku-tpu warmup"
    sig = oracle.sign(1, msg)
    triple = ([_PROBE_PK], msg, sig)
    if not impl.batch_verify([triple]):
        raise WarmupVetoError("warmup batch (x1) did not verify")
    # primary bucket with DISTINCT messages: the dedup-aware
    # pipeline specializes on the unique-message bucket, and
    # all-unique (fresh gossip, dup factor 1) is the worst-case
    # shape — warm that first
    batch = [([_PROBE_PK], m, oracle.sign(1, m))
             for m in (b"teku-tpu warmup %d" % i
                       for i in range(max_batch))]
    if not impl.batch_verify(batch):
        # a wrong verdict on a known-good signature is a device
        # we must never install
        raise WarmupVetoError(
            f"warmup batch (x{max_batch}) did not verify")
    if max_batch >= 8:
        # committee-duplicated shape (dup factor 8, the common
        # gossip mix): the grouped pipeline specializes on the
        # (unique, group) bucket pair, and the first REAL committee
        # batch must not pay that compile inside a breaker-guarded
        # live dispatch
        dup = [batch[i // 8] for i in range(max_batch)]
        if not impl.batch_verify(dup):
            raise WarmupVetoError(
                f"warmup batch (x{max_batch}, dup 8) did not verify")


# --------------------------------------------------------------------------
# Mesh self-healing wiring (parallel/selfheal.MeshHealer, jax world)
# --------------------------------------------------------------------------

def make_mesh_healer(guarded: GuardedBls12381,
                     breaker: Optional[CircuitBreaker] = None, *,
                     max_batch: int = 256, min_bucket: int = 16,
                     supervisor=None,
                     registry: MetricsRegistry = GLOBAL_REGISTRY,
                     warm: bool = True,
                     **healer_kw):
    """Wire shard-level fault isolation around a mesh-backed guarded
    provider: per-device health ledger, eject + reshape onto the
    largest surviving pow-2 subset, AOT warm of the shrunken shape
    set, atomic ``swap_device`` install, background readmit.

    Returns the ``MeshHealer`` (also assigned to ``guarded.healer``),
    or None when the serving provider is not mesh-backed or
    ``TEKU_TPU_MESH_SELF_HEAL=0`` opts out."""
    import numpy as _np

    from ...infra import capacity
    from ... import parallel
    from ...parallel import selfheal

    impl = guarded.device
    sharded = getattr(impl, "_sharded", None)
    if sharded is None or not env_bool("TEKU_TPU_MESH_SELF_HEAL", True):
        return None
    mesh_devices = list(_np.ravel(sharded.mesh.devices))
    names = [str(d) for d in mesh_devices]

    def probe(idx: int) -> None:
        # the keyed fault site first (keys are device NAMES, the same
        # vocabulary the collective dispatch passes): the chaos
        # harness wedges exactly one chip by key, and only that
        # chip's probe may fail here
        faults.check(selfheal.FAULT_SITE, keys=(names[idx],))
        import jax
        import jax.numpy as jnp
        # a tiny computation PLACED on the device proves its runtime
        # executes and answers; the reshape warm below proves the
        # full verify pipeline on the surviving collective
        x = jax.device_put(_np.arange(8, dtype=_np.int32),
                           mesh_devices[idx])
        if int(jnp.sum(x)) != 28:
            raise BlsLoadError(
                f"device {names[idx]} probe computed garbage")

    def make_backend(live):
        from ...ops.provider import JaxBls12381
        if len(live) >= 2:
            # advertise=False: this is a CANDIDATE — the gauge and
            # readiness keep describing the SERVING mesh until the
            # install hook swaps (a vetoed warm must leave them
            # untouched)
            mesh_obj = parallel.make_mesh(
                devices=[mesh_devices[i] for i in live],
                advertise=False)
            return JaxBls12381(max_batch=max_batch,
                               min_bucket=min_bucket, mesh=mesh_obj)
        # one healthy chip left: single-device dispatch
        return JaxBls12381(max_batch=max_batch, min_bucket=min_bucket)

    def heal_warm(new_impl, live):
        if not warm:
            return
        # bounded reshape warm: recovery time is the objective, so the
        # warm batch is a knob (default a fraction of the service
        # bucket; the persistent compile cache usually turns this into
        # disk loads).  A wrong verdict VETOES the install.
        wb = max(1, env_int("TEKU_TPU_MESH_WARM_BATCH",
                            min(max_batch, 64)))
        cc_before = compilecache.stats()
        aot_before = aotstore.stats()
        t0 = time.monotonic()
        try:
            _warmup_batches(new_impl, wb)
        except WarmupVetoError as exc:
            raise selfheal.InstallVetoError(str(exc)) from exc
        moved = compilecache.delta(cc_before)
        aot_moved = aotstore.delta(aot_before)
        # the reshape-under-fire observable: recovery warm must be
        # load-not-compile (AOT store / disk cache), never a fresh
        # multi-minute XLA compile while the backlog deepens
        _LOG.info(
            "reshape warm (x%d) in %.1fs: %d AOT load(s), %d "
            "compile-cache load(s), %d fresh compile(s) (%d "
            "kernel-grade)", wb, time.monotonic() - t0,
            aot_moved["loads"], moved["hits"], moved["misses"],
            moved["kernel_compiles"])

    healer_box: list = []

    def heal_install(backend, live, epoch):
        if backend is None:
            # mesh shrank to ZERO healthy devices: the oracle is the
            # last resort — keep the old guarded pair; its breaker
            # trips on the next failure and owns recovery from there.
            # The gauge must agree with the readiness snapshot below:
            # no serving mesh to advertise
            parallel.reset_active_mesh()
            _LOG.error(
                "mesh shrank to zero healthy devices; oracle is the "
                "last resort (backend breaker owns recovery)")
        else:
            backend.mesh_epoch = epoch
            guarded.swap_device(backend)
            # the INSTALLED topology is now the serving truth: publish
            # it (candidate meshes were built with advertise=False)
            mesh_info = getattr(backend, "mesh_info", None)
            if mesh_info:
                parallel.advertise_mesh(mesh_info["devices"],
                                        mesh_info.get("axis")
                                        or parallel.DEFAULT_AXIS)
            else:
                parallel.reset_active_mesh()
            try:
                # the admission planner's batch sizing must model the
                # LIVE topology: retire latency series recorded under
                # the old mesh size so plans shrink with the mesh
                capacity.TELEMETRY.latency.retire_mesh_shapes(
                    len(live) if len(live) >= 2 else 0)
            except Exception:  # pragma: no cover - advisory
                _LOG.exception("latency-series retirement failed")
            if breaker is not None:
                # the reshape warm just verified known-good signatures
                # on the new backend: close the circuit so serving
                # resumes immediately instead of waiting out a cooldown
                breaker.record_success()
        if supervisor is not None:
            mesh_desc = (getattr(backend, "mesh_info", None)
                         if backend is not None else None)
            if mesh_desc is None and backend is not None:
                mesh_desc = {"devices": [names[i] for i in live],
                             "n_devices": len(live), "axis": None}
            sup_mesh = dict(mesh_desc
                            or {"devices": [], "n_devices": 0,
                                "axis": None})
            if healer_box:
                # the FULL healer snapshot, same schema the initial
                # install publishes — with live/epoch overridden from
                # the hook args (the healer updates its installed-live
                # field only after this hook returns)
                snap = healer_box[0].snapshot()
                snap["live"] = len(live)
                snap["live_devices"] = [names[i] for i in live]
                snap["epoch"] = epoch
                sup_mesh["self_heal"] = snap
            supervisor.mesh = sup_mesh

    healer = selfheal.MeshHealer(
        names, probe=probe, make_backend=make_backend,
        install=heal_install, warm=heal_warm,
        registry=registry, **healer_kw)
    healer_box.append(healer)
    guarded.healer = healer
    return healer


# --------------------------------------------------------------------------
# Supervised bring-up (the CLI's `auto`)
# --------------------------------------------------------------------------

def make_supervisor(*, max_batch: int = 256, min_bucket: int = 16,
                    name: str = "bls_backend",
                    breaker_name: str = "bls_device",
                    registry: MetricsRegistry = GLOBAL_REGISTRY,
                    breaker: Optional[CircuitBreaker] = None,
                    warm: bool = True, mont_path: Optional[str] = None,
                    msm_path: Optional[str] = None,
                    mesh: Optional[str] = None,
                    **supervisor_kw) -> BackendSupervisor:
    """Build the production BackendSupervisor: boot-on-oracle now,
    background JAX bring-up, breaker-guarded hot-swap at READY for both
    BLS (`set_implementation`) and KZG (`crypto/kzg.py:set_backend`).

    The node owns the returned service's lifecycle
    (`node/node.py:do_start`); nothing here blocks.
    """
    def _make_breaker(bname: str) -> CircuitBreaker:
        return CircuitBreaker(
            name=bname, registry=registry,
            failure_threshold=env_int("TEKU_TPU_BREAKER_THRESHOLD", 3,
                                      lo=1),
            deadline_s=env_float("TEKU_TPU_DISPATCH_DEADLINE_S", 30.0,
                                 lo=0.1),
            cooldown_s=env_float("TEKU_TPU_BREAKER_COOLDOWN_S", 30.0,
                                 lo=0.1))

    if breaker is None:
        # `bls_device_*` metric series, per the README/PERF.md contract
        breaker = _make_breaker(breaker_name)
    # the KZG family gets its OWN breaker: with a shared one, healthy
    # KZG dispatches would keep resetting the BLS consecutive-failure
    # count (and vice versa), so a device wedged in only one program
    # family would never trip.  No supervisor reprobe on this one: it
    # half-opens on live KZG traffic, bounded by its own deadline
    kzg_breaker = _make_breaker("kzg_device")
    supervisor_box: list = []
    installed: dict = {}

    def probe():
        return _probe_jax(max_batch, min_bucket, mont_path=mont_path,
                          msm_path=msm_path, mesh=mesh)

    def warmup(backend):
        if not warm:
            return
        impl, _ = backend
        _warmup_batches(impl, max_batch)

    def install(backend):
        impl, device = backend
        guarded = GuardedBls12381(impl, breaker)
        installed["guarded"] = guarded
        set_implementation(guarded)
        try:
            from .. import kzg as kzg_facade
            from ...ops.kzg import JaxKzg
            kzg_facade.set_backend(
                GuardedKzgBackend(JaxKzg(), kzg_breaker))
        except Exception as exc:  # pragma: no cover - defensive
            _LOG.warning("device KZG backend unavailable: %s", exc)
        if supervisor_box:
            supervisor_box[0].backend_detail = device
            # the readiness snapshot must self-describe the mesh (which
            # devices, how many, which axis) — MULTICHIP runs and
            # multi-node operators read it from /teku/v1/admin/readiness
            supervisor_box[0].mesh = getattr(impl, "mesh_info", None)
        if getattr(impl, "mesh_info", None):
            # shard-level fault isolation: a wedged chip costs 1/N
            # capacity (eject + reshape + readmit), not the whole-mesh
            # breaker cliff.  Failure here degrades to the PR-10
            # semantics (one breaker per backend), never blocks install
            try:
                healer = make_mesh_healer(
                    guarded, breaker, max_batch=max_batch,
                    min_bucket=min_bucket, registry=registry,
                    supervisor=(supervisor_box[0] if supervisor_box
                                else None))
                if healer is not None:
                    installed["healer"] = healer
                    if supervisor_box:
                        sup_mesh = dict(impl.mesh_info)
                        sup_mesh["self_heal"] = healer.snapshot()
                        supervisor_box[0].mesh = sup_mesh
            except Exception:  # pragma: no cover - defensive
                _LOG.exception("mesh self-healing unavailable; the "
                               "whole-mesh breaker remains the only "
                               "containment")
        _LOG.info("BLS implementation hot-swapped: %s on %s "
                  "(breaker deadline %.1fs)", impl.name, device,
                  breaker.deadline_s)

    def uninstall():
        reset_implementation()
        _reset_kzg_backend()
        healer = installed.pop("healer", None)
        if healer is not None:
            healer.close()
        if supervisor_box:
            # no installed backend => no serving mesh: the name-
            # prefixed gauge and readiness snapshot must not keep
            # advertising a mesh the oracle is serving for
            supervisor_box[0].mesh = None

    def reprobe():
        # synthetic known-good dispatch for supervisor-driven half-open
        # probing: live traffic never pays the deadline_s probe cost.
        # Raises (keeping the circuit open) on failure OR wrong verdict
        guarded = installed.get("guarded")
        if guarded is None:
            raise BlsLoadError("no device backend installed")
        oracle = PureBls12381()
        msg = b"teku-tpu reprobe"
        sig = oracle.sign(1, msg)
        # ONE read of the (provider, lock) pair — two property reads
        # could straddle a reshape swap and dispatch on the new
        # provider while holding the OLD pair's lock
        device, lock = guarded._serving
        with lock:                     # same orphan-thread rule
            ok = device.batch_verify([([_PROBE_PK], msg, sig)])
        if not ok:
            raise BlsLoadError("reprobe batch did not verify")

    sup = BackendSupervisor(
        probe=probe, warmup=warmup, install=install, uninstall=uninstall,
        reprobe=reprobe, breaker=breaker, name=name, registry=registry,
        **supervisor_kw)
    supervisor_box.append(sup)
    # supervisor-name-prefixed mesh gauge (multi-node devnets keep the
    # series distinct, like the admission controller's families): the
    # device count of the mesh THIS supervisor's backend dispatches
    # over — 0 until a mesh backend installs
    registry.gauge(
        f"{name}_mesh_devices",
        "device count of this supervisor's installed verify mesh "
        "(0 = single-device or not yet installed)",
        supplier=lambda: float((sup.mesh or {}).get("n_devices", 0)))
    return sup


class GuardedKzgBackend:
    """Breaker-guarded device KZG backend: any device fault surfaces as
    `kzg.BackendUnavailable`, which the facade treats as 'fall through
    to the host path' — a tripped device must cost latency, never a
    wrong DA verdict."""

    def __init__(self, inner, breaker: CircuitBreaker):
        self.inner = inner
        self.breaker = breaker
        self.name = f"guarded({getattr(inner, 'name', 'device')})"
        self._device_lock = threading.Lock()   # same orphan-thread rule
                                               # as GuardedBls12381
        self._m_requests = GLOBAL_REGISTRY.labeled_counter(
            "kzg_verify_requests_total",
            "guarded KZG dispatches by serving backend and reason",
            labelnames=("backend", "reason"))

    def _call(self, op: str, *args):
        from .. import kzg as kzg_facade
        fn = getattr(self.inner, op)

        def run():
            # KzgError is a VERDICT on the input, not device sickness:
            # capture it so the breaker records the dispatch as healthy
            # instead of tripping on malformed blobs.  The fault site
            # fires INSIDE the guarded call so injected hangs meet the
            # deadline and injected raises feed the trip counters
            try:
                with self._device_lock:
                    faults.check("kzg.dispatch")
                    return ("ok", fn(*args))
            except kzg_facade.KzgError as exc:
                return ("kzg", exc)

        try:
            kind, value = self.breaker.call(run)
        except CircuitOpenError as exc:
            self._m_requests.labels(backend="oracle",
                                    reason="breaker_open").inc()
            raise kzg_facade.BackendUnavailable(str(exc)) from exc
        except DispatchTimeoutError as exc:
            self._m_requests.labels(backend="oracle",
                                    reason="fallback").inc()
            raise kzg_facade.BackendUnavailable(str(exc)) from exc
        except Exception as exc:  # noqa: BLE001 - any device fault
            self._m_requests.labels(backend="oracle",
                                    reason="fallback").inc()
            _LOG.warning("device KZG %s failed (%s: %s); host path "
                         "serves this call", op, type(exc).__name__, exc)
            raise kzg_facade.BackendUnavailable(str(exc)) from exc
        # KzgError verdicts executed on the device: still backend=device
        self._m_requests.labels(backend="device", reason="ok").inc()
        if kind == "kzg":
            raise value
        return value

    def g1_lincomb(self, setup, scalars):
        return self._call("g1_lincomb", setup, scalars)

    def verify_blob_kzg_proof(self, blob, commitment, proof, setup):
        return self._call("verify_blob_kzg_proof", blob, commitment,
                          proof, setup)

    def verify_blob_kzg_proof_batch(self, blobs, commitments, proofs,
                                    setup):
        return self._call("verify_blob_kzg_proof_batch", blobs,
                          commitments, proofs, setup)


# --------------------------------------------------------------------------
# Legacy blocking configure (tests, offline tools, explicit preflight)
# --------------------------------------------------------------------------

def configure(choice: str = "auto", *, max_batch: int = 256,
              min_bucket: int = 16,
              probe_timeout_s: Optional[float] = None,
              mont_path: Optional[str] = None,
              msm_path: Optional[str] = None,
              mesh: Optional[str] = None) -> str:
    """Install the BLS provider for this process; returns its name.

    auto: try the JAX/TPU provider under a deadline, fall back to the
          pure oracle with a loud warning on any failure.  (The CLI's
          `auto` uses make_supervisor() instead — this blocking form
          remains for tests and synchronous tools.)
    jax:  require the JAX/TPU provider; raise BlsLoadError on failure.
    pure: install the oracle (also the explicit opt-out for tests).
    supervised: install the oracle now; the caller is expected to run
          a make_supervisor() service for background bring-up.
    """
    if choice not in CHOICES:
        raise ValueError(f"unknown bls impl {choice!r} (use one of "
                         f"{'/'.join(CHOICES)})")
    if choice in ("pure", "supervised"):
        reset_implementation()
        _reset_kzg_backend()
        return "pure"
    if probe_timeout_s is None:
        probe_timeout_s = env_float("TEKU_TPU_BLS_PROBE_TIMEOUT_S",
                                    120.0, lo=1.0)

    result: dict = {}

    def run():
        try:
            result["ok"] = _probe_jax(max_batch, min_bucket,
                                      mont_path=mont_path,
                                      msm_path=msm_path, mesh=mesh)
        except BaseException as exc:  # noqa: BLE001 - report any failure
            result["err"] = exc

    t = threading.Thread(target=run, daemon=True,
                         name="bls-loader-probe")
    t.start()
    t.join(probe_timeout_s)
    if t.is_alive():
        err: BaseException = BlsLoadError(
            f"backend probe exceeded {probe_timeout_s:.0f}s "
            "(wedged device tunnel?)")
    else:
        err = result.get("err")
    if err is None:
        impl, device = result["ok"]
        set_implementation(impl)
        # KZG rides the same kernel base: install the device backend
        # alongside (the reference's initKzg moment,
        # BeaconChainController.java:557-572)
        try:
            from .. import kzg as kzg_facade
            from ...ops.kzg import JaxKzg
            kzg_facade.set_backend(JaxKzg())
        except Exception as exc:  # pragma: no cover - defensive
            _LOG.warning("device KZG backend unavailable: %s", exc)
        _LOG.info("BLS implementation: %s on %s", impl.name, device)
        return impl.name
    if choice == "jax":
        raise BlsLoadError(f"--bls-impl jax: {err}") from (
            err if isinstance(err, Exception) else None)
    _LOG.warning(
        "BLS accelerator unavailable (%s: %s) — FALLING BACK to the "
        "pure-Python oracle; node-side signature verification will be "
        "slow", type(err).__name__, err)
    reset_implementation()
    _reset_kzg_backend()
    return "pure"


def _reset_kzg_backend() -> None:
    try:
        from .. import kzg as kzg_facade
        kzg_facade.set_backend(None)
    except Exception:  # pragma: no cover - import-order edge
        pass


def current_name() -> str:
    impl = get_implementation()
    return getattr(impl, "name", type(impl).__name__)
