"""BLS12-381 elliptic curve group operations (pure Python oracle).

Generic Jacobian-coordinate point arithmetic parameterized over the base
field, instantiated for G1 (over Fq, y^2 = x^3 + 4) and G2 (over Fq2,
y^2 = x^3 + 4(1+u)).  Also implements the ZCash/ETH2 point compression
format used on the wire by the reference client (reference:
infrastructure/bls/src/main/java/tech/pegasys/teku/bls/impl/blst/
BlstPublicKey.java, BlstSignature.java — there delegated to native blst).

Points are tuples (X, Y, Z) in Jacobian coordinates (x = X/Z^2, y = Y/Z^3),
with Z == zero meaning the point at infinity.
"""

from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

from . import fields as F
from .constants import (B_G1, B_G2, G1_X, G1_Y, G2_X0, G2_X1, G2_Y0, G2_Y1,
                        P, R)


@dataclass(frozen=True)
class FieldOps:
    zero: Any
    one: Any
    add: Callable
    sub: Callable
    mul: Callable
    sqr: Callable
    neg: Callable
    inv: Callable
    is_zero: Callable
    eq: Callable
    sqrt: Callable
    b: Any  # curve coefficient


FQ_OPS = FieldOps(
    zero=0, one=1,
    add=F.fq_add, sub=F.fq_sub, mul=F.fq_mul,
    sqr=lambda a: (a * a) % P, neg=F.fq_neg, inv=F.fq_inv,
    is_zero=lambda a: a % P == 0, eq=lambda a, b: (a - b) % P == 0,
    sqrt=F.fq_sqrt, b=B_G1,
)

FQ2_OPS = FieldOps(
    zero=F.FQ2_ZERO, one=F.FQ2_ONE,
    add=F.fq2_add, sub=F.fq2_sub, mul=F.fq2_mul,
    sqr=F.fq2_sqr, neg=F.fq2_neg, inv=F.fq2_inv,
    is_zero=F.fq2_is_zero, eq=F.fq2_eq,
    sqrt=F.fq2_sqrt, b=B_G2,
)

Point = Tuple[Any, Any, Any]


def infinity(ops: FieldOps) -> Point:
    return (ops.one, ops.one, ops.zero)


def is_infinity(ops: FieldOps, p: Point) -> bool:
    return ops.is_zero(p[2])


def from_affine(ops: FieldOps, x, y) -> Point:
    return (x, y, ops.one)


def to_affine(ops: FieldOps, p: Point) -> Optional[Tuple[Any, Any]]:
    if is_infinity(ops, p):
        return None
    zinv = ops.inv(p[2])
    zinv2 = ops.sqr(zinv)
    return (ops.mul(p[0], zinv2), ops.mul(p[1], ops.mul(zinv2, zinv)))


def point_neg(ops: FieldOps, p: Point) -> Point:
    return (p[0], ops.neg(p[1]), p[2])


def point_double(ops: FieldOps, p: Point) -> Point:
    """Jacobian doubling (a = 0 curves)."""
    X1, Y1, Z1 = p
    if ops.is_zero(Z1):
        return p
    A = ops.sqr(X1)
    B = ops.sqr(Y1)
    C = ops.sqr(B)
    # D = 2*((X1+B)^2 - A - C)
    D = ops.sub(ops.sub(ops.sqr(ops.add(X1, B)), A), C)
    D = ops.add(D, D)
    E = ops.add(ops.add(A, A), A)
    Fv = ops.sqr(E)
    X3 = ops.sub(Fv, ops.add(D, D))
    C8 = ops.add(ops.add(ops.add(C, C), ops.add(C, C)),
                 ops.add(ops.add(C, C), ops.add(C, C)))
    Y3 = ops.sub(ops.mul(E, ops.sub(D, X3)), C8)
    Z3 = ops.mul(ops.add(Y1, Y1), Z1)
    return (X3, Y3, Z3)


def point_add(ops: FieldOps, p: Point, q: Point) -> Point:
    """General Jacobian addition."""
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    if ops.is_zero(Z1):
        return q
    if ops.is_zero(Z2):
        return p
    Z1Z1 = ops.sqr(Z1)
    Z2Z2 = ops.sqr(Z2)
    U1 = ops.mul(X1, Z2Z2)
    U2 = ops.mul(X2, Z1Z1)
    S1 = ops.mul(Y1, ops.mul(Z2, Z2Z2))
    S2 = ops.mul(Y2, ops.mul(Z1, Z1Z1))
    if ops.eq(U1, U2):
        if ops.eq(S1, S2):
            return point_double(ops, p)
        return infinity(ops)
    H = ops.sub(U2, U1)
    I = ops.sqr(ops.add(H, H))
    J = ops.mul(H, I)
    rr = ops.sub(S2, S1)
    rr = ops.add(rr, rr)
    V = ops.mul(U1, I)
    X3 = ops.sub(ops.sub(ops.sqr(rr), J), ops.add(V, V))
    S1J = ops.mul(S1, J)
    Y3 = ops.sub(ops.mul(rr, ops.sub(V, X3)), ops.add(S1J, S1J))
    Z1Z2 = ops.mul(Z1, Z2)
    Z3 = ops.mul(ops.add(Z1Z2, Z1Z2), H)
    return (X3, Y3, Z3)


def point_mul(ops: FieldOps, k: int, p: Point) -> Point:
    """Scalar multiplication (double-and-add; oracle only, not constant time)."""
    if k < 0:
        return point_mul(ops, -k, point_neg(ops, p))
    result = infinity(ops)
    addend = p
    while k:
        if k & 1:
            result = point_add(ops, result, addend)
        addend = point_double(ops, addend)
        k >>= 1
    return result


def point_eq(ops: FieldOps, p: Point, q: Point) -> bool:
    if is_infinity(ops, p) or is_infinity(ops, q):
        return is_infinity(ops, p) and is_infinity(ops, q)
    Z1Z1 = ops.sqr(p[2])
    Z2Z2 = ops.sqr(q[2])
    if not ops.eq(ops.mul(p[0], Z2Z2), ops.mul(q[0], Z1Z1)):
        return False
    return ops.eq(ops.mul(p[1], ops.mul(q[2], Z2Z2)),
                  ops.mul(q[1], ops.mul(p[2], Z1Z1)))


def is_on_curve(ops: FieldOps, p: Point) -> bool:
    if is_infinity(ops, p):
        return True
    X1, Y1, Z1 = p
    # Y^2 = X^3 + b Z^6
    lhs = ops.sqr(Y1)
    z2 = ops.sqr(Z1)
    z6 = ops.mul(ops.sqr(z2), z2)
    rhs = ops.add(ops.mul(ops.sqr(X1), X1), ops.mul(ops.b, z6))
    return ops.eq(lhs, rhs)


# ---------------------------------------------------------------------------
# Group generators and subgroup checks
# ---------------------------------------------------------------------------

G1_GENERATOR: Point = (G1_X, G1_Y, 1)
G2_GENERATOR: Point = ((G2_X0, G2_X1), (G2_Y0, G2_Y1), F.FQ2_ONE)


def g1_in_subgroup(p: Point) -> bool:
    return is_on_curve(FQ_OPS, p) and is_infinity(FQ_OPS, point_mul(FQ_OPS, R, p))


def g2_in_subgroup(p: Point) -> bool:
    return is_on_curve(FQ2_OPS, p) and is_infinity(FQ2_OPS, point_mul(FQ2_OPS, R, p))


# ---------------------------------------------------------------------------
# ZCash/ETH2 serialization
# ---------------------------------------------------------------------------
# Flag bits live in the MSBs of the first byte:
#   0x80 compressed, 0x40 infinity, 0x20 lexicographically-largest y.

_HALF_P = (P - 1) // 2


def _fq_is_large(y: int) -> bool:
    return y > _HALF_P


def _fq2_is_large(y) -> bool:
    y0, y1 = y[0] % P, y[1] % P
    return y1 > _HALF_P or (y1 == 0 and y0 > _HALF_P)


def g1_compress(p: Point) -> bytes:
    if is_infinity(FQ_OPS, p):
        return bytes([0xC0] + [0] * 47)
    x, y = to_affine(FQ_OPS, p)
    flags = 0x80 | (0x20 if _fq_is_large(y) else 0)
    b = x.to_bytes(48, "big")
    return bytes([b[0] | flags]) + b[1:]


def g1_decompress(data: bytes) -> Point:
    """Decompress + validate a 48-byte G1 point (curve + subgroup checks)."""
    if len(data) != 48:
        raise ValueError("G1 compressed point must be 48 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G1 encoding not supported")
    if flags & 0x40:
        if any(data[1:]) or (flags & 0x3F):
            raise ValueError("malformed infinity encoding")
        return infinity(FQ_OPS)
    x = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:], "big")
    if x >= P:
        raise ValueError("x coordinate out of range")
    y = F.fq_sqrt((x * x % P * x + B_G1) % P)
    if y is None:
        raise ValueError("point not on curve")
    if _fq_is_large(y) != bool(flags & 0x20):
        y = F.fq_neg(y)
    p = from_affine(FQ_OPS, x, y)
    if not g1_in_subgroup(p):
        raise ValueError("point not in G1 subgroup")
    return p


def g2_compress(p: Point) -> bytes:
    if is_infinity(FQ2_OPS, p):
        return bytes([0xC0] + [0] * 95)
    x, y = to_affine(FQ2_OPS, p)
    flags = 0x80 | (0x20 if _fq2_is_large(y) else 0)
    b = x[1].to_bytes(48, "big") + x[0].to_bytes(48, "big")  # c1 first
    return bytes([b[0] | flags]) + b[1:]


def g2_decompress(data: bytes) -> Point:
    """Decompress + validate a 96-byte G2 point (curve + subgroup checks)."""
    if len(data) != 96:
        raise ValueError("G2 compressed point must be 96 bytes")
    flags = data[0]
    if not flags & 0x80:
        raise ValueError("uncompressed G2 encoding not supported")
    if flags & 0x40:
        if any(data[1:]) or (flags & 0x3F):
            raise ValueError("malformed infinity encoding")
        return infinity(FQ2_OPS)
    x1 = int.from_bytes(bytes([data[0] & 0x1F]) + data[1:48], "big")
    x0 = int.from_bytes(data[48:96], "big")
    if x0 >= P or x1 >= P:
        raise ValueError("x coordinate out of range")
    x = (x0, x1)
    rhs = F.fq2_add(F.fq2_mul(F.fq2_sqr(x), x), B_G2)
    y = F.fq2_sqrt(rhs)
    if y is None:
        raise ValueError("point not on curve")
    if _fq2_is_large(y) != bool(flags & 0x20):
        y = F.fq2_neg(y)
    p = from_affine(FQ2_OPS, x, y)
    if not g2_in_subgroup(p):
        raise ValueError("point not in G2 subgroup")
    return p
