"""BLS provider SPI — the seam between the node and a BLS implementation.

Mirrors the reference's pluggable provider interface (reference:
infrastructure/bls/src/main/java/tech/pegasys/teku/bls/impl/BLS12381.java:34-157
and bls/BLS.java:51-62 setBlsImplementation) so the pure-Python oracle and
the JAX/TPU implementation are interchangeable: the pure impl is the
always-available fallback (the analogue of the reference's BlstLoader
graceful-degradation path, BlstLoader.java:34-51) and the TPU impl is the
performance path.

Keys/signatures cross this boundary as *bytes* (48-byte compressed G1
pubkeys, 96-byte compressed G2 signatures); implementations own parsing,
validation and caching.
"""

import abc
from typing import List, Optional, Sequence, Tuple


class BatchSemiAggregate:
    """Opaque per-triple preparation result for split batch verification.

    Equivalent of the reference's BatchSemiAggregate (bls/BatchSemiAggregate.java):
    produced by prepare_batch_verify, consumed by complete_batch_verify, so
    async pipelines can overlap preparation with queueing.
    """


class ResolvedHandle:
    """Trivially-resolved async-verify handle: the shared shape for
    batches whose verdict is known at begin time (empty, host-rejected)
    — same .result() contract as a live dispatch handle."""

    __slots__ = ("_verdict",)

    def __init__(self, verdict: bool):
        self._verdict = bool(verdict)

    def result(self) -> bool:
        return self._verdict


class BLS12381(abc.ABC):
    """Provider interface: everything the node needs from a BLS library."""

    name: str = "abstract"

    # --- key operations -------------------------------------------------
    @abc.abstractmethod
    def secret_key_to_public_key(self, secret: int) -> bytes:
        """48-byte compressed public key for a secret scalar."""

    @abc.abstractmethod
    def sign(self, secret: int, message: bytes) -> bytes:
        """96-byte compressed signature over message (PoP ciphersuite)."""

    # --- validation -----------------------------------------------------
    @abc.abstractmethod
    def public_key_is_valid(self, public_key: bytes) -> bool:
        """Curve + subgroup + non-infinity check (KeyValidate)."""

    @abc.abstractmethod
    def signature_is_valid(self, signature: bytes) -> bool:
        """Curve + subgroup check (infinity allowed at this layer)."""

    # --- aggregation ----------------------------------------------------
    @abc.abstractmethod
    def aggregate_public_keys(self, public_keys: Sequence[bytes]) -> bytes:
        ...

    @abc.abstractmethod
    def aggregate_signatures(self, signatures: Sequence[bytes]) -> bytes:
        ...

    # --- verification ---------------------------------------------------
    @abc.abstractmethod
    def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        ...

    @abc.abstractmethod
    def aggregate_verify(self, public_keys: Sequence[bytes],
                         messages: Sequence[bytes], signature: bytes) -> bool:
        ...

    @abc.abstractmethod
    def fast_aggregate_verify(self, public_keys: Sequence[bytes],
                              message: bytes, signature: bytes) -> bool:
        ...

    # --- batch verification (random multiplier scheme) ------------------
    @abc.abstractmethod
    def batch_verify(
        self,
        triples: Sequence[Tuple[Sequence[bytes], bytes, bytes]],
    ) -> bool:
        """One combined check over (public_keys, message, signature) triples.

        Each triple has fast_aggregate_verify semantics; the whole batch is
        combined with 64-bit random multipliers (ethresear.ch/5407 scheme,
        reference BLS.java:230-254) into a single multi-pairing.  Returns
        True iff every triple would verify individually (with overwhelming
        probability).
        """

    @abc.abstractmethod
    def prepare_batch_verify(
        self, triple: Tuple[Sequence[bytes], bytes, bytes]
    ) -> Optional[BatchSemiAggregate]:
        """Per-triple preparation; None signals an invalid triple."""

    @abc.abstractmethod
    def complete_batch_verify(
        self, semi_aggregates: Sequence[Optional[BatchSemiAggregate]]
    ) -> bool:
        ...
