"""Hash-to-curve for BLS12-381 G2 (RFC 9380, BLS12381G2_XMD:SHA-256_SSWU_RO_).

Pipeline: expand_message_xmd(SHA-256) -> hash_to_field(Fq2, m=2, count=2)
-> simplified SWU on the 3-isogenous curve E' -> isogeny map to E -> point
addition -> cofactor clearing.

Cofactor clearing has two implementations: multiplication by the effective
cofactor h_eff (slow, straight from the RFC — used as the validation oracle)
and the psi-endomorphism (Budroni-Pintore) method used in production and
mirrored by the JAX kernel.  The DST is the ETH2 proof-of-possession suite
(reference: infrastructure/bls/.../impl/blst/HashToCurve.java:23).
"""

import hashlib
from typing import Tuple

from . import fields as F
from .curve import (FQ2_OPS, Point, from_affine, infinity, point_add,
                    point_mul, point_neg, to_affine)
from .constants import (DST_G2_POP, H_EFF_G2, ISO3_X_DEN, ISO3_X_NUM,
                        ISO3_Y_DEN, ISO3_Y_NUM, P, SSWU_A2, SSWU_B2, SSWU_Z2,
                        X as BLS_X)

# ---------------------------------------------------------------------------
# expand_message_xmd (SHA-256)
# ---------------------------------------------------------------------------

_B_IN_BYTES = 32   # SHA-256 output size
_R_IN_BYTES = 64   # SHA-256 block size
_L = 64            # bytes per field element draw (ceil((381 + 128) / 8))


def expand_message_xmd(msg: bytes, dst: bytes, len_in_bytes: int) -> bytes:
    if len(dst) > 255:
        dst = hashlib.sha256(b"H2C-OVERSIZE-DST-" + dst).digest()
    ell = (len_in_bytes + _B_IN_BYTES - 1) // _B_IN_BYTES
    if ell > 255:
        raise ValueError("len_in_bytes too large")
    dst_prime = dst + bytes([len(dst)])
    z_pad = bytes(_R_IN_BYTES)
    l_i_b = len_in_bytes.to_bytes(2, "big")
    b0 = hashlib.sha256(z_pad + msg + l_i_b + b"\x00" + dst_prime).digest()
    b = hashlib.sha256(b0 + b"\x01" + dst_prime).digest()
    out = b
    prev = b
    for i in range(2, ell + 1):
        prev = hashlib.sha256(
            bytes(x ^ y for x, y in zip(b0, prev)) + bytes([i]) + dst_prime
        ).digest()
        out += prev
    return out[:len_in_bytes]


def hash_to_field_fq2(msg: bytes, count: int, dst: bytes = DST_G2_POP):
    """Draw `count` elements of Fq2 from msg (m=2, L=64)."""
    len_in_bytes = count * 2 * _L
    uniform = expand_message_xmd(msg, dst, len_in_bytes)
    out = []
    for i in range(count):
        coords = []
        for j in range(2):
            off = _L * (j + i * 2)
            coords.append(int.from_bytes(uniform[off:off + _L], "big") % P)
        out.append(tuple(coords))
    return out


# ---------------------------------------------------------------------------
# Simplified SWU map on E' (y^2 = x^3 + A'x + B' over Fq2)
# ---------------------------------------------------------------------------


def _gx_prime(x):
    """g(x) = x^3 + A'x + B' on the isogenous curve."""
    x3 = F.fq2_mul(F.fq2_sqr(x), x)
    return F.fq2_add(F.fq2_add(x3, F.fq2_mul(SSWU_A2, x)), SSWU_B2)


def map_to_curve_sswu_g2(u) -> Tuple:
    """RFC 9380 6.6.2 simplified SWU; returns an affine point on E'."""
    z_u2 = F.fq2_mul(SSWU_Z2, F.fq2_sqr(u))
    tv = F.fq2_add(F.fq2_sqr(z_u2), z_u2)  # Z^2 u^4 + Z u^2
    if F.fq2_is_zero(tv):
        # exceptional case: x1 = B' / (Z * A')
        x1 = F.fq2_mul(SSWU_B2, F.fq2_inv(F.fq2_mul(SSWU_Z2, SSWU_A2)))
    else:
        # x1 = (-B'/A') * (1 + 1/tv)
        x1 = F.fq2_mul(
            F.fq2_neg(F.fq2_mul(SSWU_B2, F.fq2_inv(SSWU_A2))),
            F.fq2_add(F.FQ2_ONE, F.fq2_inv(tv)))
    gx1 = _gx_prime(x1)
    y1 = F.fq2_sqrt(gx1)
    if y1 is not None:
        x, y = x1, y1
    else:
        x2 = F.fq2_mul(z_u2, x1)
        gx2 = _gx_prime(x2)
        y2 = F.fq2_sqrt(gx2)
        if y2 is None:
            raise AssertionError("SSWU: neither gx1 nor gx2 is square")
        x, y = x2, y2
    if F.fq2_sgn0(u) != F.fq2_sgn0(y):
        y = F.fq2_neg(y)
    return (x, y)


def iso_map_g2(p_prime) -> Tuple:
    """3-isogeny E' -> E (affine in, affine out)."""
    x, y = p_prime

    def horner(coeffs):
        acc = F.FQ2_ZERO
        for c in reversed(coeffs):
            acc = F.fq2_add(F.fq2_mul(acc, x), c)
        return acc

    x_num = horner(ISO3_X_NUM)
    x_den = horner(ISO3_X_DEN)
    y_num = horner(ISO3_Y_NUM)
    y_den = horner(ISO3_Y_DEN)
    return (F.fq2_mul(x_num, F.fq2_inv(x_den)),
            F.fq2_mul(y, F.fq2_mul(y_num, F.fq2_inv(y_den))))


# ---------------------------------------------------------------------------
# psi endomorphism and cofactor clearing
# ---------------------------------------------------------------------------
# psi = twist o Frobenius o untwist on E'(Fq2):
#   psi(x, y) = (c_x * conj(x), c_y * conj(y))
# with c_x = 1/xi^((p-1)/3), c_y = 1/xi^((p-1)/2).  Validated in tests
# against multiplication by h_eff.

PSI_CX = F.fq2_inv(F.fq2_pow(F.XI, (P - 1) // 3))
PSI_CY = F.fq2_inv(F.fq2_pow(F.XI, (P - 1) // 2))


def psi(p: Point) -> Point:
    aff = to_affine(FQ2_OPS, p)
    if aff is None:
        return infinity(FQ2_OPS)
    x, y = aff
    return from_affine(FQ2_OPS,
                       F.fq2_mul(PSI_CX, F.fq2_conj(x)),
                       F.fq2_mul(PSI_CY, F.fq2_conj(y)))


def clear_cofactor_g2_slow(p: Point) -> Point:
    """Multiplication by h_eff (RFC 9380 8.8.2) — oracle path."""
    return point_mul(FQ2_OPS, H_EFF_G2, p)


def clear_cofactor_g2(p: Point) -> Point:
    """Budroni-Pintore: h_eff*P = [x^2-x-1]P + [x-1]psi(P) + psi^2(2P)."""
    a = point_add(FQ2_OPS, point_mul(FQ2_OPS, BLS_X, p), point_neg(FQ2_OPS, p))
    res = point_add(FQ2_OPS, point_mul(FQ2_OPS, BLS_X, a), point_neg(FQ2_OPS, p))
    res = point_add(FQ2_OPS, res, psi(a))
    res = point_add(FQ2_OPS, res, psi(psi(point_add(FQ2_OPS, p, p))))
    return res


# ---------------------------------------------------------------------------
# hash_to_curve
# ---------------------------------------------------------------------------


def hash_to_g2(msg: bytes, dst: bytes = DST_G2_POP) -> Point:
    """Full hash_to_curve for G2; returns a Jacobian point in the subgroup."""
    u0, u1 = hash_to_field_fq2(msg, 2, dst)
    q0 = iso_map_g2(map_to_curve_sswu_g2(u0))
    q1 = iso_map_g2(map_to_curve_sswu_g2(u1))
    r = point_add(FQ2_OPS,
                  from_affine(FQ2_OPS, *q0),
                  from_affine(FQ2_OPS, *q1))
    return clear_cofactor_g2(r)
