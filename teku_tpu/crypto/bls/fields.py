"""BLS12-381 field tower arithmetic (pure Python, host-side oracle).

Fq  : integers mod P
Fq2 : Fq[u]/(u^2 + 1),      represented as tuple (c0, c1)
Fq6 : Fq2[v]/(v^3 - xi),    xi = 1 + u, represented as 3-tuple of Fq2
Fq12: Fq6[w]/(w^2 - v),     represented as 2-tuple of Fq6

This module is the correctness oracle for the JAX/TPU kernels in
teku_tpu/ops (which mirror these algorithms on fixed-width limb arrays) and
the CPU fallback implementation behind the BLS SPI — the same dual role the
reference gives its pluggable BLS12381 providers (reference:
infrastructure/bls/src/main/java/tech/pegasys/teku/bls/impl/BLS12381.java:34).

All functions are pure; elements are immutable tuples of ints.  Frobenius
coefficients are *computed* at import time from first principles rather than
hard-coded, so they cannot silently disagree with P.
"""

from .constants import P

# ---------------------------------------------------------------------------
# Fq
# ---------------------------------------------------------------------------

def fq_add(a, b):
    return (a + b) % P


def fq_sub(a, b):
    return (a - b) % P


def fq_mul(a, b):
    return (a * b) % P


def fq_neg(a):
    return (-a) % P


def fq_inv(a):
    if a % P == 0:
        raise ZeroDivisionError("inverse of 0 in Fq")
    return pow(a, -1, P)  # extended-gcd path, ~20x faster than Fermat


def fq_sqrt(a):
    """Square root in Fq (P = 3 mod 4). Returns None if a is not a square."""
    c = pow(a, (P + 1) // 4, P)
    return c if (c * c) % P == a % P else None


# ---------------------------------------------------------------------------
# Fq2 = Fq[u] / (u^2 + 1)
# ---------------------------------------------------------------------------

FQ2_ZERO = (0, 0)
FQ2_ONE = (1, 0)
XI = (1, 1)  # the Fq6 non-residue 1 + u


def fq2(c0, c1):
    return (c0 % P, c1 % P)


def fq2_add(a, b):
    return ((a[0] + b[0]) % P, (a[1] + b[1]) % P)


def fq2_sub(a, b):
    return ((a[0] - b[0]) % P, (a[1] - b[1]) % P)


def fq2_neg(a):
    return ((-a[0]) % P, (-a[1]) % P)


def fq2_mul(a, b):
    # (a0 + a1 u)(b0 + b1 u) = (a0 b0 - a1 b1) + (a0 b1 + a1 b0) u
    a0, a1 = a
    b0, b1 = b
    t0 = a0 * b0
    t1 = a1 * b1
    t2 = (a0 + a1) * (b0 + b1)
    return ((t0 - t1) % P, (t2 - t0 - t1) % P)


def fq2_sqr(a):
    # (a0 + a1 u)^2 = (a0+a1)(a0-a1) + 2 a0 a1 u
    a0, a1 = a
    return (((a0 + a1) * (a0 - a1)) % P, (2 * a0 * a1) % P)


def fq2_scalar_mul(a, k):
    return ((a[0] * k) % P, (a[1] * k) % P)


def fq2_conj(a):
    return (a[0], (-a[1]) % P)


def fq2_mul_by_xi(a):
    # a * (1 + u) = (a0 - a1) + (a0 + a1) u
    a0, a1 = a
    return ((a0 - a1) % P, (a0 + a1) % P)


def fq2_inv(a):
    a0, a1 = a
    norm = (a0 * a0 + a1 * a1) % P
    ninv = fq_inv(norm)
    return ((a0 * ninv) % P, ((-a1) * ninv) % P)


def fq2_pow(a, n):
    if n < 0:
        return fq2_pow(fq2_inv(a), -n)
    result = FQ2_ONE
    base = a
    while n:
        if n & 1:
            result = fq2_mul(result, base)
        base = fq2_sqr(base)
        n >>= 1
    return result


def fq2_is_zero(a):
    return a[0] % P == 0 and a[1] % P == 0


def fq2_eq(a, b):
    return a[0] % P == b[0] % P and a[1] % P == b[1] % P


def fq2_sgn0(a):
    """RFC 9380 sgn0 for Fq2 (extension degree 2, lexicographic)."""
    a0, a1 = a[0] % P, a[1] % P
    sign_0 = a0 & 1
    zero_0 = a0 == 0
    return sign_0 | (int(zero_0) & (a1 & 1))


# Tonelli-Shanks in Fq2.  q = P^2, q - 1 = 2^S * M with S = 3 for BLS12-381.
_Q = P * P
_S = 0
_M = _Q - 1
while _M % 2 == 0:
    _M //= 2
    _S += 1
# 1 + u has norm 2, a non-residue mod P (P = 3 mod 8), so it is a QNR in Fq2.
_TS_Z = fq2_pow(XI, _M)  # generator of the 2-Sylow subgroup


def fq2_sqrt(a):
    """Square root in Fq2 via Tonelli-Shanks. Returns None if not a square."""
    if fq2_is_zero(a):
        return FQ2_ZERO
    t = fq2_pow(a, (_M - 1) // 2)
    x = fq2_mul(a, t)          # a^((M+1)/2)
    b = fq2_mul(x, t)          # a^M
    z = _TS_Z
    m = _S
    while not fq2_eq(b, FQ2_ONE):
        # find least k with b^(2^k) == 1
        k = 0
        t2 = b
        while not fq2_eq(t2, FQ2_ONE):
            t2 = fq2_sqr(t2)
            k += 1
            if k >= m:
                return None  # not a square
        # z^(2^(m-k-1))
        gs = z
        for _ in range(m - k - 1):
            gs = fq2_sqr(gs)
        x = fq2_mul(x, gs)
        z = fq2_sqr(gs)
        b = fq2_mul(b, z)
        m = k
    return x if fq2_eq(fq2_sqr(x), a) else None


# ---------------------------------------------------------------------------
# Fq6 = Fq2[v] / (v^3 - xi)
# ---------------------------------------------------------------------------

FQ6_ZERO = (FQ2_ZERO, FQ2_ZERO, FQ2_ZERO)
FQ6_ONE = (FQ2_ONE, FQ2_ZERO, FQ2_ZERO)


def fq6_add(a, b):
    return (fq2_add(a[0], b[0]), fq2_add(a[1], b[1]), fq2_add(a[2], b[2]))


def fq6_sub(a, b):
    return (fq2_sub(a[0], b[0]), fq2_sub(a[1], b[1]), fq2_sub(a[2], b[2]))


def fq6_neg(a):
    return (fq2_neg(a[0]), fq2_neg(a[1]), fq2_neg(a[2]))


def fq6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fq2_mul(a0, b0)
    t1 = fq2_mul(a1, b1)
    t2 = fq2_mul(a2, b2)
    # c0 = t0 + xi * ((a1 + a2)(b1 + b2) - t1 - t2)
    c0 = fq2_add(t0, fq2_mul_by_xi(
        fq2_sub(fq2_sub(fq2_mul(fq2_add(a1, a2), fq2_add(b1, b2)), t1), t2)))
    # c1 = (a0 + a1)(b0 + b1) - t0 - t1 + xi * t2
    c1 = fq2_add(fq2_sub(fq2_sub(fq2_mul(fq2_add(a0, a1), fq2_add(b0, b1)), t0), t1),
                 fq2_mul_by_xi(t2))
    # c2 = (a0 + a2)(b0 + b2) - t0 - t2 + t1
    c2 = fq2_add(fq2_sub(fq2_sub(fq2_mul(fq2_add(a0, a2), fq2_add(b0, b2)), t0), t2), t1)
    return (c0, c1, c2)


def fq6_sqr(a):
    # Chung-Hasan SQR2: 3 squarings + 2 multiplications instead of 6 muls.
    a0, a1, a2 = a
    s0 = fq2_sqr(a0)
    s1 = fq2_mul(a0, a1)
    s1 = fq2_add(s1, s1)
    s2 = fq2_sqr(fq2_add(fq2_sub(a0, a1), a2))
    s3 = fq2_mul(a1, a2)
    s3 = fq2_add(s3, s3)
    s4 = fq2_sqr(a2)
    c0 = fq2_add(s0, fq2_mul_by_xi(s3))
    c1 = fq2_add(s1, fq2_mul_by_xi(s4))
    c2 = fq2_sub(fq2_add(fq2_add(s1, s2), s3), fq2_add(s0, s4))
    return (c0, c1, c2)


def fq6_mul_by_v(a):
    # (a0 + a1 v + a2 v^2) * v = xi*a2 + a0 v + a1 v^2
    return (fq2_mul_by_xi(a[2]), a[0], a[1])


def fq6_mul_by_fq2(a, s):
    return (fq2_mul(a[0], s), fq2_mul(a[1], s), fq2_mul(a[2], s))


def fq6_inv(a):
    a0, a1, a2 = a
    t0 = fq2_sub(fq2_sqr(a0), fq2_mul_by_xi(fq2_mul(a1, a2)))
    t1 = fq2_sub(fq2_mul_by_xi(fq2_sqr(a2)), fq2_mul(a0, a1))
    t2 = fq2_sub(fq2_sqr(a1), fq2_mul(a0, a2))
    # norm = a0 t0 + xi (a2 t1 + a1 t2)
    norm = fq2_add(fq2_mul(a0, t0),
                   fq2_mul_by_xi(fq2_add(fq2_mul(a2, t1), fq2_mul(a1, t2))))
    ninv = fq2_inv(norm)
    return (fq2_mul(t0, ninv), fq2_mul(t1, ninv), fq2_mul(t2, ninv))


def fq6_is_zero(a):
    return all(fq2_is_zero(c) for c in a)


def fq6_eq(a, b):
    return all(fq2_eq(x, y) for x, y in zip(a, b))


# ---------------------------------------------------------------------------
# Fq12 = Fq6[w] / (w^2 - v)
# ---------------------------------------------------------------------------

FQ12_ZERO = (FQ6_ZERO, FQ6_ZERO)
FQ12_ONE = (FQ6_ONE, FQ6_ZERO)


def fq12_add(a, b):
    return (fq6_add(a[0], b[0]), fq6_add(a[1], b[1]))


def fq12_sub(a, b):
    return (fq6_sub(a[0], b[0]), fq6_sub(a[1], b[1]))


def fq12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fq6_mul(a0, b0)
    t1 = fq6_mul(a1, b1)
    c0 = fq6_add(t0, fq6_mul_by_v(t1))
    c1 = fq6_sub(fq6_sub(fq6_mul(fq6_add(a0, a1), fq6_add(b0, b1)), t0), t1)
    return (c0, c1)


def fq12_sqr(a):
    # Complex squaring: (a0 + a1 w)^2 with w^2 = v costs 2 Fq6 muls.
    a0, a1 = a
    t = fq6_mul(a0, a1)
    c0 = fq6_sub(fq6_mul(fq6_add(a0, a1), fq6_add(a0, fq6_mul_by_v(a1))),
                 fq6_add(t, fq6_mul_by_v(t)))
    c1 = fq6_add(t, t)
    return (c0, c1)


def fq12_conj(a):
    """Conjugation = Frobenius^6 (negates the w component)."""
    return (a[0], fq6_neg(a[1]))


def _fp4_sqr(a, b):
    """(a + b s)^2 in Fq4 = Fq2[s]/(s^2 - xi); returns coefficient pair."""
    t = fq2_mul(a, b)
    return (fq2_add(fq2_sqr(a), fq2_mul_by_xi(fq2_sqr(b))), fq2_add(t, t))


def fq12_cyclo_sqr(a):
    """Granger-Scott squaring, valid ONLY for cyclotomic-subgroup elements.

    Decomposes Fq12 = Fq4[w]/(w^3 - s) with s = v*w, Fq4 = Fq2[s]/(s^2 - xi):
    coefficient pairs A0=(g0,h1), A1=(h0,g2), A2=(g1,h2).  For cyclotomic
    f = A0 + A1 w + A2 w^2,  f^2 = (3A0^2 - 2conj(A0))
    + (3 s A2^2 + 2conj(A1)) w + (3A1^2 - 2conj(A2)) w^2.
    Validated against generic fq12_sqr in tests.
    """
    (g0, g1, g2), (h0, h1, h2) = a
    a0, a1 = _fp4_sqr(g0, h1)
    b0, b1 = _fp4_sqr(h0, g2)
    c0, c1 = _fp4_sqr(g1, h2)
    sc0, sc1 = fq2_mul_by_xi(c1), c0  # s * A2^2

    def comb(s0, s1, o0, o1, sign):
        # 3*(s0,s1) + sign*2*conj(o0,o1) with conj(x,y) = (x,-y)
        t0 = fq2_add(fq2_add(s0, s0), s0)
        t1 = fq2_add(fq2_add(s1, s1), s1)
        d0 = fq2_add(o0, o0)
        d1 = fq2_add(o1, o1)
        if sign > 0:
            return (fq2_add(t0, d0), fq2_sub(t1, d1))
        return (fq2_sub(t0, d0), fq2_add(t1, d1))

    B0 = comb(a0, a1, g0, h1, -1)
    B1 = comb(sc0, sc1, h0, g2, +1)
    B2 = comb(b0, b1, g1, h2, -1)
    return ((B0[0], B2[0], B1[1]), (B1[0], B0[1], B2[1]))


def fq12_inv(a):
    a0, a1 = a
    norm = fq6_sub(fq6_sqr(a0), fq6_mul_by_v(fq6_sqr(a1)))
    ninv = fq6_inv(norm)
    return (fq6_mul(a0, ninv), fq6_neg(fq6_mul(a1, ninv)))


def fq12_pow(a, n):
    if n < 0:
        return fq12_pow(fq12_inv(a), -n)
    result = FQ12_ONE
    base = a
    while n:
        if n & 1:
            result = fq12_mul(result, base)
        base = fq12_sqr(base)
        n >>= 1
    return result


def fq12_eq(a, b):
    return fq6_eq(a[0], b[0]) and fq6_eq(a[1], b[1])


def fq12_is_one(a):
    return fq12_eq(a, FQ12_ONE)


# ---------------------------------------------------------------------------
# Frobenius endomorphism (computed, not hard-coded)
# ---------------------------------------------------------------------------
# pi(a) = a^P.  On Fq2 this is conjugation.  On the towers, v^P = g6 * v and
# w^P = g12 * w with g6 = xi^((P-1)/3) in Fq2, g12 = xi^((P-1)/6) in Fq2
# (exponents exact because P = 7 mod 12).

assert P % 12 == 7
FROB6_C1 = fq2_pow(XI, (P - 1) // 3)
FROB6_C2 = fq2_pow(XI, 2 * (P - 1) // 3)
FROB12_C1 = fq2_pow(XI, (P - 1) // 6)


def fq6_frobenius(a):
    return (fq2_conj(a[0]),
            fq2_mul(fq2_conj(a[1]), FROB6_C1),
            fq2_mul(fq2_conj(a[2]), FROB6_C2))


def fq12_frobenius(a, power=1):
    result = a
    for _ in range(power % 12):
        c0 = fq6_frobenius(result[0])
        c1 = fq6_frobenius(result[1])
        c1 = fq6_mul_by_fq2(c1, FROB12_C1)
        result = (c0, c1)
    return result
