"""Pure-Python BLS12-381 provider (oracle + CPU fallback).

Implements the eth2 BLS signature scheme (proof-of-possession ciphersuite)
entirely on host Python bigints.  It is the test oracle for the JAX/TPU
provider and the graceful-degradation fallback when no accelerator is
available — the same dual role split the reference has between blst and its
SPI (reference: infrastructure/bls/.../impl/blst/BlstBLS12381.java).
"""

import hashlib
import hmac
import secrets
from typing import List, Optional, Sequence, Tuple

from . import curve as C
from . import fields as F
from . import pairing as PR
from .constants import P, R
from .hash_to_curve import hash_to_g2
from .spi import BLS12381, BatchSemiAggregate

_G1_NEG_AFFINE = C.to_affine(C.FQ_OPS, C.point_neg(C.FQ_OPS, C.G1_GENERATOR))

# Compressed encodings of the points at infinity.
G1_INFINITY = bytes([0xC0] + [0] * 47)
G2_INFINITY = bytes([0xC0] + [0] * 95)


def keygen(ikm: bytes, key_info: bytes = b"") -> int:
    """draft-irtf-cfrg-bls-signature-05 KeyGen (HKDF-based, deterministic)."""
    salt = b"BLS-SIG-KEYGEN-SALT-"
    sk = 0
    while sk == 0:
        salt = hashlib.sha256(salt).digest()
        prk = hmac.new(salt, ikm + b"\x00", hashlib.sha256).digest()
        l = 48
        okm = b""
        t = b""
        i = 1
        info = key_info + l.to_bytes(2, "big")
        while len(okm) < l:
            t = hmac.new(prk, t + info + bytes([i]), hashlib.sha256).digest()
            okm += t
            i += 1
        sk = int.from_bytes(okm[:l], "big") % R
    return sk


def random_secret_key() -> int:
    return keygen(secrets.token_bytes(32))


class _SemiAggregate(BatchSemiAggregate):
    """Miller-loop product + multiplier-weighted signature for one triple."""

    __slots__ = ("ml", "weighted_sig")

    def __init__(self, ml, weighted_sig):
        self.ml = ml
        self.weighted_sig = weighted_sig


class PureBls12381(BLS12381):
    """Pure-Python provider. Slow but exactly the eth2 scheme."""

    name = "pure-python"

    # -- parsing with tiny memo caches (mirrors reference lazy parsing) --
    def __init__(self) -> None:
        self._pk_cache: dict = {}
        self._sig_cache: dict = {}

    _MISS = object()  # cache sentinel: None is a legitimate value (infinity)

    def _parse_pk(self, pk: bytes):
        """Returns affine G1 point, None for infinity; raises if invalid."""
        hit = self._pk_cache.get(pk, self._MISS)
        if hit is self._MISS:
            point = C.g1_decompress(pk)
            hit = C.to_affine(C.FQ_OPS, point)  # None when infinity
            if len(self._pk_cache) > 100_000:
                self._pk_cache.clear()
            self._pk_cache[pk] = hit
        return hit

    def _parse_sig(self, sig: bytes):
        hit = self._sig_cache.get(sig, self._MISS)
        if hit is self._MISS:
            point = C.g2_decompress(sig)
            hit = C.to_affine(C.FQ2_OPS, point)
            if len(self._sig_cache) > 100_000:
                self._sig_cache.clear()
            self._sig_cache[sig] = hit
        return hit

    # -- keys ------------------------------------------------------------
    def secret_key_to_public_key(self, secret: int) -> bytes:
        if not 0 < secret < R:
            raise ValueError("secret key out of range")
        return C.g1_compress(C.point_mul(C.FQ_OPS, secret, C.G1_GENERATOR))

    def sign(self, secret: int, message: bytes) -> bytes:
        # Zero-key signing is prohibited (reference BlstBLS12381.java:54-56).
        if not 0 < secret < R:
            raise ValueError("secret key out of range")
        q = hash_to_g2(message)
        return C.g2_compress(C.point_mul(C.FQ2_OPS, secret, q))

    # -- validation ------------------------------------------------------
    def public_key_is_valid(self, public_key: bytes) -> bool:
        try:
            return self._parse_pk(public_key) is not None  # infinity invalid
        except ValueError:
            return False

    def signature_is_valid(self, signature: bytes) -> bool:
        try:
            self._parse_sig(signature)
            return True
        except ValueError:
            return False

    # -- aggregation -----------------------------------------------------
    def aggregate_public_keys(self, public_keys: Sequence[bytes]) -> bytes:
        if not public_keys:
            raise ValueError("cannot aggregate empty public key list")
        acc = C.infinity(C.FQ_OPS)
        for pk in public_keys:
            aff = self._parse_pk(pk)
            if aff is None:
                raise ValueError("infinity public key in aggregation")
            acc = C.point_add(C.FQ_OPS, acc, C.from_affine(C.FQ_OPS, *aff))
        return C.g1_compress(acc)

    def aggregate_signatures(self, signatures: Sequence[bytes]) -> bytes:
        if not signatures:
            raise ValueError("cannot aggregate empty signature list")
        acc = C.infinity(C.FQ2_OPS)
        for sig in signatures:
            aff = self._parse_sig(sig)
            if aff is not None:
                acc = C.point_add(C.FQ2_OPS, acc, C.from_affine(C.FQ2_OPS, *aff))
        return C.g2_compress(acc)

    # -- verification ----------------------------------------------------
    def verify(self, public_key: bytes, message: bytes, signature: bytes) -> bool:
        return self.fast_aggregate_verify([public_key], message, signature)

    def aggregate_verify(self, public_keys: Sequence[bytes],
                         messages: Sequence[bytes], signature: bytes) -> bool:
        if not public_keys or len(public_keys) != len(messages):
            return False
        try:
            sig_aff = self._parse_sig(signature)
            pks = [self._parse_pk(pk) for pk in public_keys]
        except ValueError:
            return False
        if any(pk is None for pk in pks):
            return False  # KeyValidate rejects infinity
        pairs = [(pk, PR_hash(msg)) for pk, msg in zip(pks, messages)]
        pairs.append((_G1_NEG_AFFINE, sig_aff))
        return F.fq12_is_one(PR.multi_pairing(pairs))

    def fast_aggregate_verify(self, public_keys: Sequence[bytes],
                              message: bytes, signature: bytes) -> bool:
        if not public_keys:
            return False
        try:
            sig_aff = self._parse_sig(signature)
            pks = [self._parse_pk(pk) for pk in public_keys]
        except ValueError:
            return False
        if any(pk is None for pk in pks):
            return False
        acc = C.infinity(C.FQ_OPS)
        for pk in pks:
            acc = C.point_add(C.FQ_OPS, acc, C.from_affine(C.FQ_OPS, *pk))
        agg = C.to_affine(C.FQ_OPS, acc)
        if agg is None:
            return False  # keys summed to infinity
        pairs = [(agg, PR_hash(message)), (_G1_NEG_AFFINE, sig_aff)]
        return F.fq12_is_one(PR.multi_pairing(pairs))

    # -- batch verification ----------------------------------------------
    def prepare_batch_verify(
        self, triple: Tuple[Sequence[bytes], bytes, bytes]
    ) -> Optional[BatchSemiAggregate]:
        public_keys, message, signature = triple
        if not public_keys:
            return None
        try:
            sig_aff = self._parse_sig(signature)
            pks = [self._parse_pk(pk) for pk in public_keys]
        except ValueError:
            return None
        if any(pk is None for pk in pks):
            return None
        acc = C.infinity(C.FQ_OPS)
        for pk in pks:
            acc = C.point_add(C.FQ_OPS, acc, C.from_affine(C.FQ_OPS, *pk))
        # Random 64-bit nonzero multiplier (reference BlstBLS12381.java:191-195)
        r = 0
        while r == 0:
            r = secrets.randbits(64)
        pk_r = C.to_affine(C.FQ_OPS, C.point_mul(C.FQ_OPS, r, acc))
        if pk_r is None:
            return None
        ml = PR.miller_loop(pk_r, PR_hash(message))
        if sig_aff is None:
            weighted_sig = C.infinity(C.FQ2_OPS)
        else:
            weighted_sig = C.point_mul(
                C.FQ2_OPS, r, C.from_affine(C.FQ2_OPS, *sig_aff))
        return _SemiAggregate(ml, weighted_sig)

    def complete_batch_verify(
        self, semi_aggregates: Sequence[Optional[BatchSemiAggregate]]
    ) -> bool:
        if any(sa is None for sa in semi_aggregates):
            return False
        if not semi_aggregates:
            return True
        f = F.FQ12_ONE
        sig_acc = C.infinity(C.FQ2_OPS)
        for sa in semi_aggregates:
            f = F.fq12_mul(f, sa.ml)
            sig_acc = C.point_add(C.FQ2_OPS, sig_acc, sa.weighted_sig)
        sig_aff = C.to_affine(C.FQ2_OPS, sig_acc)
        f = F.fq12_mul(f, PR.miller_loop(_G1_NEG_AFFINE, sig_aff))
        return F.fq12_is_one(PR.final_exponentiation(f))

    def batch_verify(
        self,
        triples: Sequence[Tuple[Sequence[bytes], bytes, bytes]],
    ) -> bool:
        return self.complete_batch_verify(
            [self.prepare_batch_verify(t) for t in triples])


# Message -> H(m) affine-point cache: hashing dominates the oracle's runtime
# and tests/batches repeat messages heavily.
_H2G_CACHE: dict = {}


def PR_hash(message: bytes):
    hit = _H2G_CACHE.get(message)
    if hit is None:
        hit = C.to_affine(C.FQ2_OPS, hash_to_g2(message))
        if len(_H2G_CACHE) > 50_000:
            _H2G_CACHE.clear()
        _H2G_CACHE[message] = hit
    return hit
