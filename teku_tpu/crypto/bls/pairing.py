"""Optimal ate pairing on BLS12-381 (pure Python oracle).

The oracle favours clarity over speed: the Miller loop runs in affine
coordinates directly in Fq12 after untwisting the G2 point, so there is no
twist-type case analysis and no sparse-multiplication trickery.  Subfield
factors (line denominators, sign conventions) are killed by the final
exponentiation, which is why they are elided.

This is the correctness reference for the batched JAX Miller-loop kernel in
teku_tpu/ops/pairing.py.  Reference client equivalent: native blst pairing
behind infrastructure/bls/.../impl/blst/BlstBLS12381.java:124-189.
"""

from typing import List, Optional, Tuple

from . import fields as F
from .constants import P, R, X_ABS

# ---------------------------------------------------------------------------
# Embeddings into Fq12
# ---------------------------------------------------------------------------


def fq_to_fq12(a: int):
    return (((a % P, 0), F.FQ2_ZERO, F.FQ2_ZERO), F.FQ6_ZERO)


def fq2_to_fq12(a):
    return ((a, F.FQ2_ZERO, F.FQ2_ZERO), F.FQ6_ZERO)


# w = (0, (1, 0, 0)) in our tower; w^2 = v, w^6 = xi.
FQ12_W = (F.FQ6_ZERO, F.FQ6_ONE)
FQ12_W2 = F.fq12_mul(FQ12_W, FQ12_W)
FQ12_W3 = F.fq12_mul(FQ12_W2, FQ12_W)
FQ12_W2_INV = F.fq12_inv(FQ12_W2)
FQ12_W3_INV = F.fq12_inv(FQ12_W3)


def untwist(q_affine) -> Tuple:
    """Map an affine G2 point on E'(Fq2) to E(Fq12): (x/w^2, y/w^3)."""
    x, y = q_affine
    return (F.fq12_mul(fq2_to_fq12(x), FQ12_W2_INV),
            F.fq12_mul(fq2_to_fq12(y), FQ12_W3_INV))


# ---------------------------------------------------------------------------
# Miller loop (affine, Fq12)
# ---------------------------------------------------------------------------

_X_BITS = bin(X_ABS)[3:]  # bits below the MSB, as '0'/'1' chars


def _line_eval(lam, a, p):
    """(y_P - y_A) - lam * (x_P - x_A), all in Fq12."""
    ax, ay = a
    px, py = p
    return F.fq12_sub(F.fq12_sub(py, ay),
                      F.fq12_mul(lam, F.fq12_sub(px, ax)))


def _affine_double(t):
    x, y = t
    x2 = F.fq12_sqr(x)
    lam = F.fq12_mul(F.fq12_add(F.fq12_add(x2, x2), x2),
                     F.fq12_inv(F.fq12_add(y, y)))
    x3 = F.fq12_sub(F.fq12_sqr(lam), F.fq12_add(x, x))
    y3 = F.fq12_sub(F.fq12_mul(lam, F.fq12_sub(x, x3)), y)
    return lam, (x3, y3)


def _affine_add(t, q):
    tx, ty = t
    qx, qy = q
    lam = F.fq12_mul(F.fq12_sub(ty, qy), F.fq12_inv(F.fq12_sub(tx, qx)))
    x3 = F.fq12_sub(F.fq12_sub(F.fq12_sqr(lam), tx), qx)
    y3 = F.fq12_sub(F.fq12_mul(lam, F.fq12_sub(tx, x3)), ty)
    return lam, (x3, y3)


def miller_loop(p_affine: Optional[Tuple[int, int]],
                q_affine: Optional[Tuple]) -> Tuple:
    """Miller loop of the optimal ate pairing.

    p_affine: affine G1 point (x, y) as ints, or None for infinity.
    q_affine: affine G2 point ((x0,x1),(y0,y1)) in Fq2, or None for infinity.
    Returns an Fq12 element (un-exponentiated).
    """
    if p_affine is None or q_affine is None:
        return F.FQ12_ONE
    p12 = (fq_to_fq12(p_affine[0]), fq_to_fq12(p_affine[1]))
    q12 = untwist(q_affine)
    t = q12
    f = F.FQ12_ONE
    for c in _X_BITS:
        # tangent line at the *current* T, evaluated at P
        prev = t
        lam, t = _affine_double(t)
        f = F.fq12_mul(F.fq12_sqr(f), _line_eval(lam, prev, p12))
        if c == "1":
            prev = t
            lam, t = _affine_add(t, q12)
            f = F.fq12_mul(f, _line_eval(lam, prev, p12))
    # BLS parameter x is negative: conjugate.
    return F.fq12_conj(f)


# ---------------------------------------------------------------------------
# Final exponentiation
# ---------------------------------------------------------------------------

_HARD_EXP = (P ** 4 - P ** 2 + 1) // R


def final_exponentiation(f) -> Tuple:
    # easy part: f^((p^6 - 1)(p^2 + 1))
    g = F.fq12_mul(F.fq12_conj(f), F.fq12_inv(f))
    g = F.fq12_mul(F.fq12_frobenius(g, 2), g)
    # hard part: g^((p^4 - p^2 + 1) / r)
    return F.fq12_pow(g, _HARD_EXP)


def pairing(p_affine, q_affine) -> Tuple:
    """Full pairing e(P, Q): final_exponentiation(miller_loop(P, Q))."""
    return final_exponentiation(miller_loop(p_affine, q_affine))


def multi_pairing(pairs: List[Tuple]) -> Tuple:
    """prod_i e(P_i, Q_i) with a single shared final exponentiation."""
    f = F.FQ12_ONE
    for p_affine, q_affine in pairs:
        f = F.fq12_mul(f, miller_loop(p_affine, q_affine))
    return final_exponentiation(f)
