"""Optimal ate pairing on BLS12-381 (pure Python oracle).

The oracle favours clarity over speed: the Miller loop runs in affine
coordinates directly in Fq12 after untwisting the G2 point, so there is no
twist-type case analysis and no sparse-multiplication trickery.  Subfield
factors (line denominators, sign conventions) are killed by the final
exponentiation, which is why they are elided.

This is the correctness reference for the batched JAX Miller-loop kernel in
teku_tpu/ops/pairing.py.  Reference client equivalent: native blst pairing
behind infrastructure/bls/.../impl/blst/BlstBLS12381.java:124-189.
"""

from typing import List, Optional, Tuple

from . import fields as F
from .constants import P, R, X, X_ABS

# ---------------------------------------------------------------------------
# Embeddings into Fq12
# ---------------------------------------------------------------------------


def fq_to_fq12(a: int):
    return (((a % P, 0), F.FQ2_ZERO, F.FQ2_ZERO), F.FQ6_ZERO)


def fq2_to_fq12(a):
    return ((a, F.FQ2_ZERO, F.FQ2_ZERO), F.FQ6_ZERO)


# w = (0, (1, 0, 0)) in our tower; w^2 = v, w^6 = xi.
FQ12_W = (F.FQ6_ZERO, F.FQ6_ONE)
FQ12_W2 = F.fq12_mul(FQ12_W, FQ12_W)
FQ12_W3 = F.fq12_mul(FQ12_W2, FQ12_W)
FQ12_W2_INV = F.fq12_inv(FQ12_W2)
FQ12_W3_INV = F.fq12_inv(FQ12_W3)


def untwist(q_affine) -> Tuple:
    """Map an affine G2 point on E'(Fq2) to E(Fq12): (x/w^2, y/w^3)."""
    x, y = q_affine
    return (F.fq12_mul(fq2_to_fq12(x), FQ12_W2_INV),
            F.fq12_mul(fq2_to_fq12(y), FQ12_W3_INV))


# ---------------------------------------------------------------------------
# Miller loop (affine, Fq12)
# ---------------------------------------------------------------------------

_X_BITS = bin(X_ABS)[3:]  # bits below the MSB, as '0'/'1' chars


def _line_eval(lam, a, p):
    """(y_P - y_A) - lam * (x_P - x_A), all in Fq12."""
    ax, ay = a
    px, py = p
    return F.fq12_sub(F.fq12_sub(py, ay),
                      F.fq12_mul(lam, F.fq12_sub(px, ax)))


def _affine_double(t):
    x, y = t
    x2 = F.fq12_sqr(x)
    lam = F.fq12_mul(F.fq12_add(F.fq12_add(x2, x2), x2),
                     F.fq12_inv(F.fq12_add(y, y)))
    x3 = F.fq12_sub(F.fq12_sqr(lam), F.fq12_add(x, x))
    y3 = F.fq12_sub(F.fq12_mul(lam, F.fq12_sub(x, x3)), y)
    return lam, (x3, y3)


def _affine_add(t, q):
    tx, ty = t
    qx, qy = q
    lam = F.fq12_mul(F.fq12_sub(ty, qy), F.fq12_inv(F.fq12_sub(tx, qx)))
    x3 = F.fq12_sub(F.fq12_sub(F.fq12_sqr(lam), tx), qx)
    y3 = F.fq12_sub(F.fq12_mul(lam, F.fq12_sub(tx, x3)), ty)
    return lam, (x3, y3)


def miller_loop_untwist(p_affine: Optional[Tuple[int, int]],
                        q_affine: Optional[Tuple]) -> Tuple:
    """Miller loop via untwisted affine arithmetic directly in Fq12.

    The clarity-first construction (inversion per step, dense Fq12 muls);
    retained as the independent cross-check for the production twist-
    coordinate loop below, which the JAX kernel mirrors.
    """
    if p_affine is None or q_affine is None:
        return F.FQ12_ONE
    p12 = (fq_to_fq12(p_affine[0]), fq_to_fq12(p_affine[1]))
    q12 = untwist(q_affine)
    t = q12
    f = F.FQ12_ONE
    for c in _X_BITS:
        # tangent line at the *current* T, evaluated at P
        prev = t
        lam, t = _affine_double(t)
        f = F.fq12_mul(F.fq12_sqr(f), _line_eval(lam, prev, p12))
        if c == "1":
            prev = t
            lam, t = _affine_add(t, q12)
            f = F.fq12_mul(f, _line_eval(lam, prev, p12))
    # BLS parameter x is negative: conjugate.
    return F.fq12_conj(f)


# ---------------------------------------------------------------------------
# Production Miller loop: Jacobian coordinates on the twist, sparse lines
# ---------------------------------------------------------------------------
# The tangent/chord line through the untwisted point, evaluated at embedded
# P = (px, py) and multiplied through by an Fq2 factor (killed by the final
# exponentiation), is the sparse Fq12 element
#     l = c0 + (c1 v + c2 v^2) w
# with c0, c1, c2 in Fq2:
#   doubling T=(X,Y,Z):  c0 = Z3*Z^2*xi*py, c1 = E*X - 2B, c2 = -E*Z^2*px
#                        (E = 3X^2, B = Y^2, Z3 = 2YZ)
#   mixed add of Q=(xq,yq): c0 = Z3*xi*py, c1 = r*xq - yq*Z3, c2 = -r*px
#                        (r = yq*Z^3 - Y, H = xq*Z^2 - X, Z3 = Z*H)
# Branch-free except for the static Miller bit pattern, so the batched JAX
# kernel (teku_tpu/ops) can mirror it 1:1; the untwist loop above is the
# independent oracle for both.


def _dbl_step(t, px, py):
    """Double T (Jacobian on E'/Fq2); return (T2, line coeffs)."""
    X, Y, Z = t
    A = F.fq2_sqr(X)
    B = F.fq2_sqr(Y)
    Cc = F.fq2_sqr(B)
    Z2 = F.fq2_sqr(Z)
    D = F.fq2_sub(F.fq2_sub(F.fq2_sqr(F.fq2_add(X, B)), A), Cc)
    D = F.fq2_add(D, D)
    E = F.fq2_add(F.fq2_add(A, A), A)
    Fv = F.fq2_sqr(E)
    X3 = F.fq2_sub(Fv, F.fq2_add(D, D))
    C8 = F.fq2_add(Cc, Cc)
    C8 = F.fq2_add(C8, C8)
    C8 = F.fq2_add(C8, C8)
    Y3 = F.fq2_sub(F.fq2_mul(E, F.fq2_sub(D, X3)), C8)
    YZ = F.fq2_mul(Y, Z)
    Z3 = F.fq2_add(YZ, YZ)
    c0 = F.fq2_scalar_mul(F.fq2_mul_by_xi(F.fq2_mul(Z3, Z2)), py)
    c1 = F.fq2_sub(F.fq2_mul(E, X), F.fq2_add(B, B))
    c2 = F.fq2_scalar_mul(F.fq2_mul(E, Z2), (-px) % P)
    return (X3, Y3, Z3), (c0, c1, c2)


def _add_step(t, q, px, py):
    """Mixed-add affine Q into Jacobian T; return (T+Q, line coeffs)."""
    X, Y, Z = t
    xq, yq = q
    Z2 = F.fq2_sqr(Z)
    U2 = F.fq2_mul(xq, Z2)
    S2 = F.fq2_mul(yq, F.fq2_mul(Z2, Z))
    H = F.fq2_sub(U2, X)
    r = F.fq2_sub(S2, Y)
    H2 = F.fq2_sqr(H)
    H3 = F.fq2_mul(H, H2)
    V = F.fq2_mul(X, H2)
    X3 = F.fq2_sub(F.fq2_sub(F.fq2_sqr(r), H3), F.fq2_add(V, V))
    Y3 = F.fq2_sub(F.fq2_mul(r, F.fq2_sub(V, X3)), F.fq2_mul(Y, H3))
    Z3 = F.fq2_mul(Z, H)
    c0 = F.fq2_scalar_mul(F.fq2_mul_by_xi(Z3), py)
    c1 = F.fq2_sub(F.fq2_mul(r, xq), F.fq2_mul(yq, Z3))
    c2 = F.fq2_scalar_mul(r, (-px) % P)
    return (X3, Y3, Z3), (c0, c1, c2)


def _fq6_mul_sparse_v(a, c1, c2):
    """(a0 + a1 v + a2 v^2) * (c1 v + c2 v^2)."""
    a0, a1, a2 = a
    return (F.fq2_mul_by_xi(F.fq2_add(F.fq2_mul(a1, c2), F.fq2_mul(a2, c1))),
            F.fq2_add(F.fq2_mul(a0, c1), F.fq2_mul_by_xi(F.fq2_mul(a2, c2))),
            F.fq2_add(F.fq2_mul(a0, c2), F.fq2_mul(a1, c1)))


def _mul_by_line(f, line):
    """f * (c0 + (c1 v + c2 v^2) w), exploiting sparsity."""
    c0, c1, c2 = line
    f0, f1 = f
    t1 = _fq6_mul_sparse_v(f1, c1, c2)
    # res0 = f0 l0 + f1 l1 v ;  (x0 + x1 v + x2 v^2) v = (xi x2, x0, x1)
    res0 = F.fq6_add(F.fq6_mul_by_fq2(f0, c0),
                     (F.fq2_mul_by_xi(t1[2]), t1[0], t1[1]))
    # res1 = f0 l1 + f1 l0
    res1 = F.fq6_add(_fq6_mul_sparse_v(f0, c1, c2), F.fq6_mul_by_fq2(f1, c0))
    return (res0, res1)


def miller_loop(p_affine: Optional[Tuple[int, int]],
                q_affine: Optional[Tuple]) -> Tuple:
    """Miller loop of the optimal ate pairing (twist coordinates).

    p_affine: affine G1 point (x, y) as ints, or None for infinity.
    q_affine: affine G2 point ((x0,x1),(y0,y1)) on E'/Fq2, or None.
    Returns an Fq12 element (un-exponentiated).  Agrees with
    miller_loop_untwist up to final exponentiation (validated in tests).
    """
    if p_affine is None or q_affine is None:
        return F.FQ12_ONE
    px, py = p_affine
    t = (q_affine[0], q_affine[1], F.FQ2_ONE)
    f = F.FQ12_ONE
    for c in _X_BITS:
        f = F.fq12_sqr(f)
        t, line = _dbl_step(t, px, py)
        f = _mul_by_line(f, line)
        if c == "1":
            t, line = _add_step(t, q_affine, px, py)
            f = _mul_by_line(f, line)
    # BLS parameter x is negative: conjugate.
    return F.fq12_conj(f)


# ---------------------------------------------------------------------------
# Final exponentiation
# ---------------------------------------------------------------------------

_HARD_EXP = (P ** 4 - P ** 2 + 1) // R

# Hard-part decomposition (Hayashida-Hayasaka-Teruya, validated at import):
#   3 * (p^4 - p^2 + 1)/r = (z-1)^2 * (z+p) * (z^2 + p^2 - 1) + 3
# with z the (negative) BLS parameter.  We therefore compute f^(3d) rather
# than f^d; since the target group has prime order r (and 3 does not divide
# r), f^(3d) == 1  iff  f^d == 1, and bilinearity is unaffected, so every
# consumer (verification is_one checks, property tests) is preserved.

assert 3 * _HARD_EXP == (X - 1) ** 2 * (X + P) * (X ** 2 + P ** 2 - 1) + 3


def _cyclo_pow_abs_x(f):
    """f^|z| for cyclotomic f: Granger-Scott squarings, Hamming weight 6."""
    result = f
    for c in _X_BITS:
        result = F.fq12_cyclo_sqr(result)
        if c == "1":
            result = F.fq12_mul(result, f)
    return result


def _pow_z(f):
    """f^z for cyclotomic f (z < 0, so conjugate = inverse applies)."""
    return F.fq12_conj(_cyclo_pow_abs_x(f))


def final_exponentiation(f) -> Tuple:
    """f^(3 * (p^12-1)/r): easy part then the x-chain hard part above."""
    # easy part: f^((p^6 - 1)(p^2 + 1)) — lands in the cyclotomic subgroup,
    # where inverse == conjugate (used by _pow_z).
    g = F.fq12_mul(F.fq12_conj(f), F.fq12_inv(f))
    g = F.fq12_mul(F.fq12_frobenius(g, 2), g)
    # hard part: g^(3 * (p^4 - p^2 + 1)/r) via the decomposition.
    a = F.fq12_mul(_pow_z(g), F.fq12_conj(g))            # g^(z-1)
    a = F.fq12_mul(_pow_z(a), F.fq12_conj(a))            # g^((z-1)^2)
    b = F.fq12_mul(_pow_z(a), F.fq12_frobenius(a, 1))    # a^(z+p)
    c = F.fq12_mul(F.fq12_mul(_pow_z(_pow_z(b)), F.fq12_frobenius(b, 2)),
                   F.fq12_conj(b))                       # b^(z^2+p^2-1)
    return F.fq12_mul(c, F.fq12_mul(F.fq12_sqr(g), g))   # * g^3


def pairing(p_affine, q_affine) -> Tuple:
    """Pairing check value e(P, Q)^3 (see final_exponentiation).

    NOT the canonical GT element: the exponent carries a fixed cofactor 3,
    which preserves is_one checks, equality between values produced by this
    module, bilinearity, and non-degeneracy — the only consumers here — but
    would mismatch a GT known-answer vector computed with the exact
    (p^12-1)/r exponent.
    """
    return final_exponentiation(miller_loop(p_affine, q_affine))


def multi_pairing(pairs: List[Tuple]) -> Tuple:
    """prod_i e(P_i, Q_i)^3 with a single shared final exponentiation."""
    f = F.FQ12_ONE
    for p_affine, q_affine in pairs:
        f = F.fq12_mul(f, miller_loop(p_affine, q_affine))
    return final_exponentiation(f)
