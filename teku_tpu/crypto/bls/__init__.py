"""BLS facade — single entry point for all BLS operations in the framework.

Mirrors the reference's static BLS facade with a pluggable provider
(reference: infrastructure/bls/src/main/java/tech/pegasys/teku/bls/BLS.java:40-62):
all node code calls these functions, never a provider directly, so swapping
the pure-Python fallback for the JAX/TPU provider is one call to
set_implementation().  Also carries the eth2-spec wrapper semantics
(eth_aggregate_pubkeys / eth_fast_aggregate_verify empty-list rules) and the
verification kill-switch (reference BLS.java:93 BLSConstants.verificationDisabled).
"""

from typing import List, Optional, Sequence, Tuple

from ...infra import faults
from .pure_impl import (G1_INFINITY, G2_INFINITY, PureBls12381, keygen,
                        random_secret_key)
from .spi import BLS12381, BatchSemiAggregate, ResolvedHandle

_IMPL: BLS12381 = PureBls12381()

# Kill-switch for test scenarios where signature checking must be skipped.
verification_disabled = False


def set_implementation(impl: BLS12381) -> None:
    global _IMPL
    _IMPL = impl


def get_implementation() -> BLS12381:
    return _IMPL


def reset_implementation() -> None:
    set_implementation(PureBls12381())


# --- keys ----------------------------------------------------------------

def secret_to_public_key(secret: int) -> bytes:
    return _IMPL.secret_key_to_public_key(secret)


def sign(secret: int, message: bytes) -> bytes:
    return _IMPL.sign(secret, message)


def public_key_is_valid(public_key: bytes) -> bool:
    return _IMPL.public_key_is_valid(public_key)


def signature_is_valid(signature: bytes) -> bool:
    return _IMPL.signature_is_valid(signature)


# --- aggregation ---------------------------------------------------------

def aggregate_signatures(signatures: Sequence[bytes]) -> bytes:
    return _IMPL.aggregate_signatures(signatures)


def aggregate_public_keys(public_keys: Sequence[bytes]) -> bytes:
    return _IMPL.aggregate_public_keys(public_keys)


def eth_aggregate_pubkeys(public_keys: Sequence[bytes]) -> bytes:
    """eth2 spec eth_aggregate_pubkeys: all keys must be valid, list nonempty."""
    if not public_keys:
        raise ValueError("eth_aggregate_pubkeys of empty list")
    for pk in public_keys:
        if not _IMPL.public_key_is_valid(pk):
            raise ValueError("invalid public key in eth_aggregate_pubkeys")
    return _IMPL.aggregate_public_keys(public_keys)


# --- verification --------------------------------------------------------

def verify(public_key: bytes, message: bytes, signature: bytes) -> bool:
    if verification_disabled:
        return True
    return _IMPL.verify(public_key, message, signature)


def aggregate_verify(public_keys: Sequence[bytes], messages: Sequence[bytes],
                     signature: bytes) -> bool:
    if verification_disabled:
        return True
    return _IMPL.aggregate_verify(public_keys, messages, signature)


def fast_aggregate_verify(public_keys: Sequence[bytes], message: bytes,
                          signature: bytes) -> bool:
    if verification_disabled:
        return True
    return _IMPL.fast_aggregate_verify(public_keys, message, signature)


def eth_fast_aggregate_verify(public_keys: Sequence[bytes], message: bytes,
                              signature: bytes) -> bool:
    """eth2 wrapper: empty key list + infinity signature verifies (deneb rule)."""
    if verification_disabled:
        return True
    if not public_keys and signature == G2_INFINITY:
        return True
    return _IMPL.fast_aggregate_verify(public_keys, message, signature)


def batch_verify(
    triples: Sequence[Tuple[Sequence[bytes], bytes, bytes]],
) -> bool:
    if verification_disabled:
        return True
    if not triples:
        return True
    # `bls.batch_verify` fault site: every backend's batch dispatch
    # crosses this facade, so wrong-result/hang/raise injection here
    # exercises the service-layer bisect and breaker paths uniformly
    faults.check("bls.batch_verify")
    if len(triples) == 1:
        pks, msg, sig = triples[0]
        ok = _IMPL.fast_aggregate_verify(pks, msg, sig)
    else:
        ok = _IMPL.batch_verify(triples)
    return faults.transform("bls.batch_verify", ok)


class _FaultCheckedHandle:
    """Applies the `bls.batch_verify` result-transform faults at the
    sync point, mirroring what the sync facade does inline."""

    __slots__ = ("_inner",)

    def __init__(self, inner):
        self._inner = inner

    def result(self) -> bool:
        return faults.transform("bls.batch_verify", self._inner.result())


def supports_async_verify() -> bool:
    """True when the active implementation exposes the async begin
    seam (callers avoid a thread hop per batch otherwise)."""
    return getattr(_IMPL, "begin_batch_verify", None) is not None


def begin_batch_verify(
    triples: Sequence[Tuple[Sequence[bytes], bytes, bytes]],
):
    """Async-dispatch twin of batch_verify: host_prep + device enqueue
    now, verdict at handle.result() (the only sync point) — the
    batching service overlaps the next batch's host_prep with the
    in-flight device execute through this seam.

    Returns None when the active implementation has no async path
    (pure-Python oracle, breaker-guarded backends — the breaker must
    own its dispatch deadline, so guarded deployments stay on the sync
    path); callers fall back to batch_verify."""
    if verification_disabled or not triples:
        return ResolvedHandle(True)
    begin = getattr(_IMPL, "begin_batch_verify", None)
    if begin is None:
        return None
    faults.check("bls.batch_verify")
    inner = begin(triples)
    if inner is None:
        return None
    return _FaultCheckedHandle(inner)


def prepare_batch_verify(
    triple: Tuple[Sequence[bytes], bytes, bytes]
) -> Optional[BatchSemiAggregate]:
    return _IMPL.prepare_batch_verify(triple)


def complete_batch_verify(
    semi_aggregates: Sequence[Optional[BatchSemiAggregate]]
) -> bool:
    if verification_disabled:
        return True
    return _IMPL.complete_batch_verify(semi_aggregates)
