"""Suppression file: every entry needs a checker, a key match, and a
real justification.

Policy (README "Static analysis"): a suppression is a debt record,
not an off switch.  An entry's `match` must EQUAL the finding's
stable `path:token` (the key minus its checker prefix — no line
numbers, so edits can't silently orphan them; no substring matching,
so an entry can never silently WIDEN to cover a new finding that
merely shares a prefix).  An entry whose justification is missing or
hand-wavy short is a HARD error: the file fails to load and lint
exits 2, because an unjustified suppression is indistinguishable from
a silenced bug.  Unused entries are reported so the file shrinks as
fixes land.
"""

import json
from typing import Dict, List, Tuple

from .findings import Finding

MIN_JUSTIFICATION = 16      # characters; "wontfix" is not a reason


class SuppressionError(ValueError):
    """The suppression file itself is invalid — a hard error, never a
    silent skip."""


def load(path: str) -> List[Dict[str, str]]:
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return []
    except (OSError, json.JSONDecodeError) as exc:
        raise SuppressionError(f"cannot read suppression file {path}: "
                               f"{exc}")
    entries = doc.get("suppressions") if isinstance(doc, dict) else None
    if entries is None or not isinstance(entries, list):
        raise SuppressionError(
            f"{path}: expected {{\"suppressions\": [...]}}")
    for i, entry in enumerate(entries):
        if not isinstance(entry, dict):
            raise SuppressionError(f"{path}: entry {i} is not an object")
        for field in ("checker", "match", "justification"):
            value = entry.get(field)
            if not isinstance(value, str) or not value.strip():
                raise SuppressionError(
                    f"{path}: entry {i} is missing `{field}` — every "
                    "suppression needs a checker, a key match, and a "
                    "justification")
        if len(entry["justification"].strip()) < MIN_JUSTIFICATION:
            raise SuppressionError(
                f"{path}: entry {i} justification "
                f"{entry['justification']!r} is too short (< "
                f"{MIN_JUSTIFICATION} chars) — say WHY the finding is "
                "deliberate")
    return entries


def apply(findings: List[Finding], entries: List[Dict[str, str]]
          ) -> Tuple[List[Finding], List[Dict[str, str]]]:
    """Mark suppressed findings in place; return (findings, unused
    entries)."""
    used = [False] * len(entries)
    for finding in findings:
        for i, entry in enumerate(entries):
            # EXACT key equality: `checker:match` == the finding key.
            # Substring matching would let one justified entry
            # silently swallow every future finding sharing a prefix
            # (e.g. a TEKU_TPU_MSM entry absorbing TEKU_TPU_MSM_SEG).
            if finding.key == f"{entry['checker']}:{entry['match']}":
                finding.suppressed = True
                finding.justification = entry["justification"]
                used[i] = True
                break
    unused = [entry for i, entry in enumerate(entries) if not used[i]]
    return findings, unused
