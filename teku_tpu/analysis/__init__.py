"""tekulint: AST-based invariant analyzer for the teku-tpu tree.

Twelve PRs of review hardening fixed the same bug classes by hand —
typo'd ``TEKU_TPU_*`` knobs read raw from ``os.environ`` that degrade
or kill boot, torn two-read access to atomically-swapped state,
private copies of shared helpers, unbounded metric label vocabularies,
and trace-time side effects inside jit'd kernels.  This package makes
those invariants a BUILD property: a self-contained stdlib-``ast``
analyzer with a checker registry, a finding model (file:line, checker
id, evidence, fix hint), a suppression file requiring per-entry
justification, and a ``cli lint`` front end that exits 1 on any
unsuppressed finding.

Checkers (see each module's docstring for the past bug it mechanizes):

- ``env-knob``         every TEKU_TPU_* env read goes through
                       ``infra/env.py`` helpers (env_knob.py)
- ``knob-doc``         the auto-extracted knob registry matches the
                       README knob docs both ways (knob_docs.py)
- ``jit-purity``       functions reachable from jax.jit / shard_map /
                       lax.scan closures perform no host side effects
                       (jit_purity.py)
- ``torn-read``        registered swap attributes are read at most
                       once per function (torn_read.py)
- ``metric-contract``  counter/histogram naming by type + bounded
                       label-value expressions (metric_contract.py)
- ``closed-registry``  fault sites and flight-recorder event kinds are
                       declared in their registry modules
                       (registries.py)
- ``dup-helper``       no near-identical private helper is defined in
                       two modules (dup_helpers.py)

The analyzer never imports the code it checks — a tree that cannot
even import (the exact failure mode the env checker guards against)
still lints.
"""

from .findings import Finding, Report                     # noqa: F401
from .runner import run_lint, DEFAULT_SUPPRESSIONS        # noqa: F401
