"""env-knob: every TEKU_TPU_* environment read goes through
``infra/env.py``.

The mechanized bug class (PR 11's ledger-capacity fix, PR 7's three
private ``_env_float`` copies, and the seed run of this checker): a
knob read raw as ``float(os.environ.get("TEKU_TPU_X", "5"))`` turns an
operator's typo into a boot-killing ValueError, and a raw
``os.environ.get`` with local parsing re-invents the degrade contract
differently at every site.  The ``infra/env.py`` helpers are the ONE
definition: malformed values degrade to the default with one WARN,
bounds clamp, and every read lands in the knob registry this module
also extracts (the input to the ``knob-doc`` drift checker and
``cli lint --knobs``).

The checker resolves key expressions through module-level string
constants (``ENV_VAR = "TEKU_TPU_MSM"``), f-strings, and ``+``
concatenation, so neither the knob-module idiom nor a dynamically
assembled prefix read can hide a raw access.
"""

import ast
from typing import Dict, List, Optional

from .astutil import ModuleIndex, Project, dotted
from .findings import Finding

CHECKER = "env-knob"
PREFIX = "TEKU_TPU_"
ENV_MODULE = "teku_tpu.infra.env"
# the sanctioned read helpers (env_knob findings say "use one of these")
HELPERS = ("env_float", "env_int", "env_str", "env_bool", "env_choice",
           "env_raw")
FIX_HINT = ("read the knob through teku_tpu/infra/env.py "
            f"({'/'.join(HELPERS)}; env_override for save/set/restore) "
            "so a typo degrades with one WARN instead of raising")


def _knob_in_key(idx: ModuleIndex, expr: ast.AST) -> Optional[str]:
    """The TEKU_TPU_* name (or name prefix) a key expression reads, or
    None when the expression cannot touch the knob namespace."""
    parts = idx.str_parts(expr)
    if parts is not None:
        prefix, _suffix, exact = parts
        if prefix.startswith(PREFIX):
            return prefix
        if exact:
            return None
    # opaque expression: does any Name inside resolve to a TEKU_TPU_
    # constant (the `ENV_PREFIX + name.upper()` layering idiom)?
    for node in ast.walk(expr):
        if isinstance(node, ast.Name):
            value = idx.consts.get(node.id)
            if value is not None and value.startswith(PREFIX):
                return value + "*"
    return None


def _raw_read_key(node: ast.Call) -> Optional[ast.AST]:
    """The key expression of a raw environ READ call, else None.
    Mutations (pop / setdefault-as-write / __setitem__) are the CLI's
    legitimate seam for handing choices to subprocess-visible state."""
    chain = dotted(node.func)
    if chain is None:
        return None
    if chain.endswith("os.environ.get") or chain.endswith("os.getenv") \
            or chain == "environ.get" or chain == "getenv":
        return node.args[0] if node.args else None
    return None


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for idx in project.modules.values():
        if idx.modname == ENV_MODULE:
            continue        # the helpers themselves own raw access
        for node in ast.walk(idx.tree):
            key_expr = None
            if isinstance(node, ast.Call):
                key_expr = _raw_read_key(node)
            elif isinstance(node, ast.Subscript) \
                    and isinstance(node.ctx, ast.Load) \
                    and dotted(node.value) in ("os.environ", "environ"):
                key_expr = node.slice
            if key_expr is None:
                continue
            knob = _knob_in_key(idx, key_expr)
            if knob is None:
                continue
            findings.append(Finding(
                checker=CHECKER, path=idx.relpath, line=node.lineno,
                message=f"raw os.environ read of {knob} outside "
                        "infra/env.py",
                evidence=ast.get_source_segment(idx.source, node)
                or knob, fix_hint=FIX_HINT, token=knob))
    return findings


# --------------------------------------------------------------------------
# knob-registry extraction (cli lint --knobs + the knob-doc checker)
# --------------------------------------------------------------------------

def _pattern_from_parts(prefix: str, suffix: str) -> str:
    return f"{prefix}*{suffix}"


def _default_repr(expr: Optional[ast.AST]) -> str:
    if expr is None:
        return ""
    if isinstance(expr, ast.Constant):
        return repr(expr.value)
    chain = dotted(expr)
    if chain is not None:
        return chain
    return "<expr>"


def collect_knobs(project: Project) -> List[Dict[str, object]]:
    """Every TEKU_TPU_* knob the tree reads, auto-extracted: env-helper
    calls (name resolved through constants / f-string patterns) plus
    the CLI's ``layered_value`` seam, whose env name derives from the
    literal flag name.  Sorted, de-duplicated on (name, path)."""
    knobs: Dict[tuple, Dict[str, object]] = {}

    def add(name: str, helper: str, default: str, idx: ModuleIndex,
            line: int) -> None:
        key = (name, idx.relpath)
        entry = knobs.get(key)
        if entry is None:
            knobs[key] = {"name": name, "helper": helper,
                          "default": default, "path": idx.relpath,
                          "line": line}

    for idx in project.modules.values():
        for node in ast.walk(idx.tree):
            if not isinstance(node, ast.Call):
                continue
            target = None
            if isinstance(node.func, ast.Name):
                target = idx.imports.get(node.func.id)
                if target is None and idx.modname == ENV_MODULE:
                    target = f"{ENV_MODULE}.{node.func.id}"
            elif isinstance(node.func, ast.Attribute):
                chain = dotted(node.func)
                if chain is not None:
                    root_name = chain.split(".")[0]
                    base = idx.imports.get(root_name)
                    if base is not None:
                        target = base + chain[len(root_name):]
            if target is not None and target.startswith(ENV_MODULE + ".") \
                    and target.rsplit(".", 1)[1] in HELPERS + (
                        "env_override",):
                helper = target.rsplit(".", 1)[1]
                if not node.args:
                    continue
                parts = idx.str_parts(node.args[0])
                if parts is None:
                    continue
                prefix, suffix, exact = parts
                name = prefix if exact else _pattern_from_parts(
                    prefix, suffix)
                if not name.startswith(PREFIX):
                    continue
                if name == PREFIX + "*":
                    # the CLI layering seam reads the whole namespace
                    # dynamically; its per-flag layered_value rows
                    # below carry the real registry entries
                    continue
                default = _default_repr(
                    node.args[1] if len(node.args) > 1 else next(
                        (kw.value for kw in node.keywords
                         if kw.arg == "default"), None))
                add(name, helper, default, idx, node.lineno)
            # the CLI layering seam: layered_value("flag-name", ...)
            # reads TEKU_TPU_FLAG_NAME (cli.py derives it exactly so)
            elif isinstance(node.func, ast.Name) \
                    and node.func.id == "layered_value" and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                flag = node.args[0].value
                name = PREFIX + flag.upper().replace("-", "_")
                default = _default_repr(
                    node.args[3] if len(node.args) > 3 else next(
                        (kw.value for kw in node.keywords
                         if kw.arg == "default"), None))
                add(name, "layered_value", default, idx, node.lineno)
    return sorted(knobs.values(),
                  key=lambda k: (k["name"], k["path"]))  # type: ignore


def render_knob_table(knob_list: List[Dict[str, object]]) -> str:
    """The knob registry as a markdown table (``cli lint --knobs``) —
    the same rows the README knob section is checked against."""
    lines = ["| Knob | Reader | Default | Where |",
             "| --- | --- | --- | --- |"]
    for k in knob_list:
        lines.append(f"| `{k['name']}` | {k['helper']} | "
                     f"`{k['default'] or '-'}` | "
                     f"`{k['path']}:{k['line']}` |")
    return "\n".join(lines)
