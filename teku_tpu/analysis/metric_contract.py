"""metric-contract: full-tree static enforcement of the metric naming
and label-boundedness conventions.

The mechanized bug class: ``tests/test_metrics_exposition.py`` lints
the families its test imports happen to register at RUNTIME — a new
module whose metrics no imported test touches ships an unlinted
vocabulary (this happened repeatedly; each PR extended the runtime
lint by hand).  This checker statically enumerates every
Counter/Gauge/Histogram/StateGauge construction in the tree:

- counters (``counter`` / ``labeled_counter`` / the class ctors) end
  in ``_total``; gauges never do;
- histograms built on ``LATENCY_BUCKETS_S`` (or the labeled default,
  which is latency) are durations and end ``_seconds``; count/size
  histograms on ``DEFAULT_BUCKETS`` must not claim ``_seconds``;
- metric names resolve statically (literal or prefix-f-string — the
  node-name-prefixed families) so the enumeration is complete;
- ``.labels(...)`` values must be bounded expressions: f-strings,
  string concatenation/``%`` and ``.format`` produce open vocabularies
  (label-cardinality explosions) and are rejected — label values come
  from closed enums, module constants, or plain closed-fold helpers
  (``plan_mode_label``-style).
"""

import ast
from typing import List, Optional

from .astutil import ModuleIndex, Project, dotted
from .findings import Finding

CHECKER = "metric-contract"
METRICS_MODULE = "teku_tpu.infra.metrics"

# factory attr / ctor name -> metric kind
_KINDS = {
    "counter": "counter", "labeled_counter": "counter",
    "Counter": "counter", "LabeledCounter": "counter",
    "gauge": "gauge", "labeled_gauge": "gauge",
    "Gauge": "gauge", "LabeledGauge": "gauge",
    "histogram": "histogram", "labeled_histogram": "histogram",
    "Histogram": "histogram", "LabeledHistogram": "histogram",
    "state_gauge": "state", "StateGauge": "state",
}
# constructions whose omitted `buckets` default to the latency buckets
_LATENCY_DEFAULT = {"labeled_histogram", "LabeledHistogram"}


def _metric_call_kind(idx: ModuleIndex, call: ast.Call) -> Optional[str]:
    if isinstance(call.func, ast.Attribute):
        name = call.func.attr
        if name in _KINDS and name[0].islower():
            return name
    elif isinstance(call.func, ast.Name):
        name = call.func.id
        if name in _KINDS and name[0].isupper() and idx.imports.get(
                name, "").startswith(METRICS_MODULE + "."):
            return name
    return None


def _kwarg(call: ast.Call, name: str) -> Optional[ast.AST]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _buckets_expr(call: ast.Call, ctor: str) -> Optional[ast.AST]:
    expr = _kwarg(call, "buckets")
    if expr is not None:
        return expr
    pos = {"histogram": 2, "Histogram": 2,
           "labeled_histogram": 3, "LabeledHistogram": 3}.get(ctor)
    if pos is not None and len(call.args) > pos:
        return call.args[pos]
    return None


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for idx in project.modules.values():
        if idx.modname == METRICS_MODULE:
            continue    # the registry factories pass names through
        for node in ast.walk(idx.tree):
            if not isinstance(node, ast.Call):
                continue
            _check_labels_call(idx, node, findings)
            ctor = _metric_call_kind(idx, node)
            if ctor is None:
                continue
            kind = _KINDS[ctor]
            name_expr = node.args[0] if node.args else _kwarg(node,
                                                             "name")
            parts = idx.str_parts(name_expr) if name_expr is not None \
                else None
            if parts is None:
                continue    # not a string-ish first arg: not a metric
            prefix, suffix, exact = parts
            name = prefix if exact else f"{prefix}…{suffix}"
            if not exact and not suffix:
                findings.append(Finding(
                    checker=CHECKER, path=idx.relpath, line=node.lineno,
                    message=f"{kind} name is not statically "
                            "enumerable (dynamic tail)",
                    evidence=ast.get_source_segment(idx.source,
                                                    name_expr) or name,
                    fix_hint="give the family a constant suffix so the "
                             "static lint can enforce naming",
                    token=name))
                continue
            if kind == "counter" and not suffix.endswith("_total"):
                findings.append(Finding(
                    checker=CHECKER, path=idx.relpath, line=node.lineno,
                    message=f"counter `{name}` must end in `_total`",
                    evidence=f"{ctor}(...) construction",
                    fix_hint="rename the family; Prometheus counter "
                             "convention (test_metrics_exposition "
                             "enforces it at runtime for imported "
                             "modules)",
                    token=name))
            elif kind == "gauge" and suffix.endswith("_total"):
                findings.append(Finding(
                    checker=CHECKER, path=idx.relpath, line=node.lineno,
                    message=f"gauge `{name}` must not end in `_total` "
                            "(that suffix promises a counter)",
                    evidence=f"{ctor}(...) construction",
                    fix_hint="rename the gauge or use a counter",
                    token=name))
            elif kind == "histogram":
                buckets = _buckets_expr(node, ctor)
                bucket_chain = dotted(buckets) if buckets is not None \
                    else None
                if buckets is None:
                    is_latency = ctor in _LATENCY_DEFAULT
                elif bucket_chain is not None:
                    if "LATENCY" in bucket_chain:
                        is_latency = True
                    elif "DEFAULT" in bucket_chain:
                        is_latency = False
                    else:
                        continue    # custom named buckets: no claim
                else:
                    continue        # inline bucket literal: no claim
                ends_seconds = suffix.endswith("_seconds")
                if is_latency and not ends_seconds:
                    findings.append(Finding(
                        checker=CHECKER, path=idx.relpath,
                        line=node.lineno,
                        message=f"histogram `{name}` uses the latency "
                                "buckets but is not named `*_seconds`",
                        evidence=f"{ctor}(..., buckets="
                                 f"{bucket_chain or 'default'})",
                        fix_hint="durations are measured in seconds "
                                 "and named *_seconds "
                                 "(LATENCY_BUCKETS_S contract)",
                        token=name))
                elif not is_latency and ends_seconds:
                    findings.append(Finding(
                        checker=CHECKER, path=idx.relpath,
                        line=node.lineno,
                        message=f"histogram `{name}` claims seconds "
                                "but uses count/size buckets",
                        evidence=f"{ctor}(..., buckets="
                                 f"{bucket_chain or 'default'})",
                        fix_hint="pass LATENCY_BUCKETS_S or drop the "
                                 "_seconds suffix",
                        token=name))
    return findings


def _is_open_vocabulary(expr: ast.AST) -> Optional[str]:
    """Why a label-value expression is an unbounded vocabulary, else
    None.  Closed sources (names, enum attrs, constants, str() folds
    of closed helpers) pass."""
    if isinstance(expr, ast.JoinedStr) and any(
            isinstance(v, ast.FormattedValue) for v in expr.values):
        return "f-string label value"
    if isinstance(expr, ast.BinOp) and isinstance(
            expr.op, (ast.Add, ast.Mod)):
        for side in (expr.left, expr.right):
            if isinstance(side, (ast.Constant, ast.JoinedStr)) and (
                    not isinstance(side, ast.Constant)
                    or isinstance(side.value, str)):
                return "string-built label value"
        return None
    if isinstance(expr, ast.Call) and isinstance(expr.func,
                                                 ast.Attribute) \
            and expr.func.attr == "format":
        return ".format() label value"
    return None


def _check_labels_call(idx: ModuleIndex, node: ast.Call,
                       findings: List[Finding]) -> None:
    if not (isinstance(node.func, ast.Attribute)
            and node.func.attr == "labels" and node.keywords):
        return
    pairs = []      # (label name, value expr)
    for kw in node.keywords:
        if kw.arg is not None:
            pairs.append((kw.arg, kw.value))
        elif isinstance(kw.value, ast.Dict):
            # labels(**{"class": ...}) — the tree's standard idiom for
            # reserved-word label names; the dict values are label
            # values all the same
            for key, value in zip(kw.value.keys, kw.value.values):
                name = key.value if isinstance(key, ast.Constant) \
                    and isinstance(key.value, str) else "<dynamic>"
                pairs.append((name, value))
    for label_name, value_expr in pairs:
        why = _is_open_vocabulary(value_expr)
        if why is not None:
            findings.append(Finding(
                checker=CHECKER, path=idx.relpath, line=node.lineno,
                message=f"label `{label_name}` built from an open "
                        f"vocabulary ({why})",
                evidence=ast.get_source_segment(idx.source, value_expr)
                or why,
                fix_hint="source label values from a closed enum / "
                         "module constant / bounded fold helper — "
                         "open vocabularies explode scrape "
                         "cardinality",
                token=f"labels:{label_name}"))
