"""Finding model + report envelope for the static analyzer.

A Finding carries everything a reviewer (or the suppression matcher)
needs: WHERE (repo-relative path, 1-based line), WHAT (checker id +
one-line message), WHY IT'S REAL (evidence string quoting the code
fact that fired the rule), HOW TO FIX (fix_hint naming the shared
helper / registry to use), and a STABLE KEY.  The key deliberately
excludes the line number: suppressions anchor on (checker, path,
semantic token) so unrelated edits shifting lines cannot silently
orphan — or worse, silently widen — a suppression.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional

# bump when the --json field set changes shape (tests pin this)
SCHEMA_VERSION = 1


@dataclass
class Finding:
    checker: str          # registry id, e.g. "env-knob"
    path: str             # repo-relative, forward slashes
    line: int             # 1-based
    message: str          # one sentence: the violated invariant
    evidence: str = ""    # the code fact (knob name, call chain, ...)
    fix_hint: str = ""    # the shared helper / registry to use instead
    token: str = ""       # stable semantic token (knob/metric/attr name)
    suppressed: bool = False
    justification: str = ""   # from the matching suppression entry

    @property
    def key(self) -> str:
        """Stable suppression anchor: checker + path + semantic token
        (NOT the line number)."""
        return f"{self.checker}:{self.path}:{self.token or self.evidence}"

    def to_dict(self) -> dict:
        d = {"checker": self.checker, "path": self.path,
             "line": self.line, "message": self.message,
             "evidence": self.evidence, "fix_hint": self.fix_hint,
             "key": self.key, "suppressed": self.suppressed}
        if self.suppressed:
            d["justification"] = self.justification
        return d


@dataclass
class Report:
    root: str
    files_scanned: int = 0
    findings: List[Finding] = field(default_factory=list)
    unused_suppressions: List[dict] = field(default_factory=list)
    knobs: List[dict] = field(default_factory=list)

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    def counts(self) -> Dict[str, int]:
        by: Dict[str, int] = {}
        for f in self.unsuppressed:
            by[f.checker] = by.get(f.checker, 0) + 1
        return by

    def to_dict(self) -> dict:
        return {
            "version": SCHEMA_VERSION,
            "root": self.root,
            "files_scanned": self.files_scanned,
            "findings": [f.to_dict() for f in sorted(
                self.findings, key=lambda f: (f.path, f.line, f.checker))],
            "counts": {
                "total": len(self.findings),
                "unsuppressed": len(self.unsuppressed),
                "suppressed": len(self.findings) - len(self.unsuppressed),
                "by_checker": self.counts(),
            },
            "unused_suppressions": self.unused_suppressions,
        }

    def render_text(self) -> str:
        lines: List[str] = []
        for f in sorted(self.unsuppressed,
                        key=lambda f: (f.path, f.line, f.checker)):
            lines.append(f"{f.path}:{f.line}: [{f.checker}] {f.message}")
            if f.evidence:
                lines.append(f"    evidence: {f.evidence}")
            if f.fix_hint:
                lines.append(f"    fix: {f.fix_hint}")
        n_sup = len(self.findings) - len(self.unsuppressed)
        for entry in self.unused_suppressions:
            lines.append(
                f"lint_suppressions.json: UNUSED suppression "
                f"{entry.get('checker')}:{entry.get('match')!r} — remove "
                f"it (the finding it justified is gone)")
        lines.append(
            f"tekulint: {self.files_scanned} files, "
            f"{len(self.unsuppressed)} finding(s)"
            + (f", {n_sup} suppressed" if n_sup else "")
            + (f", {len(self.unused_suppressions)} unused suppression(s)"
               if self.unused_suppressions else ""))
        return "\n".join(lines)

    @property
    def clean(self) -> bool:
        return not self.unsuppressed and not self.unused_suppressions
