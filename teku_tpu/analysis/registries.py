"""closed-registry: fault sites and flight-recorder event kinds are
declared, in one registry module each.

The mechanized bug class: ``faults.check("bls.mesh_shard")`` strings
and flight-recorder event kinds grew by grep — the faults docstring
lists sites "in use (grep for faults.check)", and the doctor keys on
literal kind strings it hopes emitters spell the same way.  A typo'd
site silently never fires its fault; a typo'd event kind silently
never matches its doctor analyzer.  This checker closes both
vocabularies:

- ``infra/faults.py`` declares ``SITES``; every ``faults.check(site)``
  / ``faults.transform(site, ...)`` literal must be a member, and
  every member must be used somewhere (a dead site is a stale
  contract);
- ``infra/flightrecorder.py`` declares ``EVENT_KINDS``; every
  ``record("kind", ...)`` on a recorder must be a member, and members
  must be emitted somewhere in the tree.
- ``infra/timeline.py`` declares ``TRACKS`` and ``PHASES``; every
  ``timeline.interval(track, phase, ...)`` /
  ``timeline.instant(track, phase, ...)`` emit must name declared
  members, and every member must have an emit site — the Perfetto
  export and the doctor's stall analyzers key on these exact strings,
  so a typo'd phase silently lands on the wrong track.

Dynamic (non-literal) sites/kinds outside the registry modules are
findings too — an unverifiable vocabulary is an open one.  The
registry modules themselves may forward dynamics (``record(kind)``).
"""

import ast
from typing import Dict, List, Optional, Set, Tuple

from .astutil import ModuleIndex, Project, dotted
from .findings import Finding

CHECKER = "closed-registry"
FAULTS_MODULE = "teku_tpu.infra.faults"
FLIGHT_MODULE = "teku_tpu.infra.flightrecorder"
SITES_NAME = "SITES"
KINDS_NAME = "EVENT_KINDS"
TIMELINE_MODULE = "teku_tpu.infra.timeline"
TRACKS_NAME = "TRACKS"
PHASES_NAME = "PHASES"


def _declared_set(idx: Optional[ModuleIndex], name: str
                  ) -> Optional[Dict[str, int]]:
    """{member: line} of a module-level ``NAME = frozenset({...})``
    (or set/tuple/list literal), else None when absent."""
    if idx is None:
        return None
    for node in idx.tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name):
            continue
        value = node.value
        if isinstance(value, ast.Call) and dotted(value.func) in (
                "frozenset", "set") and value.args:
            value = value.args[0]
        if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
            return {elt.value: elt.lineno for elt in value.elts
                    if isinstance(elt, ast.Constant)
                    and isinstance(elt.value, str)}
    return None


def _fault_site_arg(idx: ModuleIndex, call: ast.Call
                    ) -> Optional[ast.AST]:
    chain = dotted(call.func)
    if chain is not None and chain.split(".")[-1] in ("check",
                                                      "transform"):
        parts = chain.split(".")
        if "faults" in parts[:-1]:
            return call.args[0] if call.args else None
    if isinstance(call.func, ast.Name) and idx.imports.get(
            call.func.id, "").startswith(FAULTS_MODULE + "."):
        if call.func.id in ("check", "transform") or idx.imports[
                call.func.id].rsplit(".", 1)[1] in ("check",
                                                    "transform"):
            return call.args[0] if call.args else None
    return None


def _event_kind_arg(idx: ModuleIndex, call: ast.Call
                    ) -> Optional[Tuple[ast.AST, bool]]:
    """(kind expr, is_config_demotion) of a flight-recorder emit."""
    chain = dotted(call.func)
    if chain is not None:
        parts = chain.split(".")
        last = parts[-1]
        recorder_ish = any("recorder" in p.lower()
                           or p == "flightrecorder"
                           for p in parts[:-1])
        if last == "record" and recorder_ish:
            return (call.args[0], False) if call.args else None
        if last == "config_demotion" and ("flightrecorder" in parts[:-1]
                                          or len(parts) == 1):
            return None     # fixed-kind helper; kind is closed by def
    if isinstance(call.func, ast.Name):
        target = idx.imports.get(call.func.id, "")
        if target == f"{FLIGHT_MODULE}.record":
            return (call.args[0], False) if call.args else None
    return None


def _timeline_emit_call(idx: ModuleIndex, call: ast.Call) -> bool:
    """True when the call is a ``timeline.interval``/``.instant``
    emit (dotted through any alias containing "timeline", or a
    bare name imported from infra/timeline)."""
    chain = dotted(call.func)
    if chain is not None:
        parts = chain.split(".")
        if parts[-1] in ("interval", "instant") and any(
                "timeline" in p for p in parts[:-1]):
            return True
    if isinstance(call.func, ast.Name):
        target = idx.imports.get(call.func.id, "")
        if target in (f"{TIMELINE_MODULE}.interval",
                      f"{TIMELINE_MODULE}.instant"):
            return True
    return False


def _timeline_track_arg(idx: ModuleIndex, call: ast.Call
                        ) -> Optional[ast.AST]:
    if _timeline_emit_call(idx, call):
        return call.args[0] if call.args else None
    return None


def _timeline_phase_arg(idx: ModuleIndex, call: ast.Call
                        ) -> Optional[ast.AST]:
    if _timeline_emit_call(idx, call):
        return call.args[1] if len(call.args) > 1 else None
    return None


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    faults_idx = project.modules.get(FAULTS_MODULE)
    flight_idx = project.modules.get(FLIGHT_MODULE)
    timeline_idx = project.modules.get(TIMELINE_MODULE)
    specs = [
        ("fault site", faults_idx, FAULTS_MODULE, SITES_NAME,
         _declared_set(faults_idx, SITES_NAME), _fault_site_arg,
         "declare the site in infra/faults.py SITES"),
        ("event kind", flight_idx, FLIGHT_MODULE, KINDS_NAME,
         _declared_set(flight_idx, KINDS_NAME),
         lambda idx, call: _event_kind_arg(idx, call) and
         _event_kind_arg(idx, call)[0],
         "declare the kind in infra/flightrecorder.py EVENT_KINDS"),
        ("timeline track", timeline_idx, TIMELINE_MODULE, TRACKS_NAME,
         _declared_set(timeline_idx, TRACKS_NAME),
         _timeline_track_arg,
         "declare the track in infra/timeline.py TRACKS"),
        ("timeline phase", timeline_idx, TIMELINE_MODULE, PHASES_NAME,
         _declared_set(timeline_idx, PHASES_NAME),
         _timeline_phase_arg,
         "declare the phase in infra/timeline.py PHASES"),
    ]
    for (label, reg_idx, reg_mod, reg_name, declared, extract,
         hint) in specs:
        if reg_idx is None:
            continue        # registry module not in the scanned tree
        if declared is None:
            findings.append(Finding(
                checker=CHECKER, path=reg_idx.relpath, line=1,
                message=f"registry module declares no `{reg_name}` — "
                        f"the {label} vocabulary is open",
                evidence=f"{reg_mod} has no module-level {reg_name}",
                fix_hint=hint, token=reg_name))
            continue
        # members the registry module itself emits do so through its
        # own internals (rec.record("fatal_crash"), the dump header
        # dict) — count any string literal inside its FUNCTION BODIES
        # as a local reference (the declaration itself is module-level
        # and must not mark its own members used)
        used: Set[str] = set()
        for fnode in ast.walk(reg_idx.tree):
            if isinstance(fnode, (ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                for sub in ast.walk(fnode):
                    if isinstance(sub, ast.Constant) \
                            and isinstance(sub.value, str) \
                            and sub.value in declared:
                        used.add(sub.value)
        for idx in project.modules.values():
            for node in ast.walk(idx.tree):
                if not isinstance(node, ast.Call):
                    continue
                arg = extract(idx, node)
                if arg is None:
                    continue
                value = project.resolve_str(idx, arg)
                if value is None:
                    if idx.modname != reg_mod:
                        findings.append(Finding(
                            checker=CHECKER, path=idx.relpath,
                            line=node.lineno,
                            message=f"dynamic {label} — the closed "
                                    "vocabulary cannot be verified",
                            evidence=ast.get_source_segment(
                                idx.source, node) or "<dynamic>",
                            fix_hint="pass a literal (or registry-"
                                     "declared constant) " + label,
                            token=f"dynamic:{idx.modname}"))
                    continue
                used.add(value)
                if value not in declared:
                    findings.append(Finding(
                        checker=CHECKER, path=idx.relpath,
                        line=node.lineno,
                        message=f"undeclared {label} `{value}`",
                        evidence=ast.get_source_segment(
                            idx.source, node) or value,
                        fix_hint=hint, token=value))
        for member, line in declared.items():
            if member not in used:
                findings.append(Finding(
                    checker=CHECKER, path=reg_idx.relpath, line=line,
                    message=f"declared {label} `{member}` is never "
                            "used in the tree",
                    evidence=f"{reg_name} member with no emit site",
                    fix_hint="remove the stale member (or wire the "
                             "missing emitter)",
                    token=member))
    return findings
