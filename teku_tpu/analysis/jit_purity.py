"""jit-purity: no host side effects reachable from traced closures.

The mechanized bug class: code inside a function handed to ``jax.jit``
/ ``shard_map`` / ``lax.scan`` (or any other tracing combinator) runs
at TRACE time — once per compiled shape, in whatever thread triggered
the compile — not once per dispatch.  A ``time.monotonic()`` there
reads the compile's clock forever after; a metric ``.inc()`` charges
one compile as one dispatch and silently corrupts the PR 2/11 per-stage
attribution the doctor ranks findings by; a log line fires from inside
a breaker dispatch thread mid-trace.  Reviewers caught these by eye
for twelve PRs; this checker walks the actual call graph.

Mechanics: entry points are callables passed to the tracing
combinators (``jax.jit``, ``shard_map``, ``lax.scan`` /
``while_loop`` / ``fori_loop`` / ``cond`` / ``switch``, ``vmap`` /
``pmap``) or decorated with ``@jax.jit``, anywhere in the scanned
tree.  From each entry the checker BFS-walks resolvable calls —
nested defs, same-class methods (``self._kernel``), module functions,
and imports into other scanned modules — and flags any call matching
the impurity denylist (time/random/os.environ/logging/print/metrics
mutation/flight-recorder/fault-site/tracing-span).  Unresolvable
targets (jnp primitives, stdlib math) are opaque leaves, not errors.
"""

import ast
from typing import Dict, List, Optional, Set, Tuple

from .astutil import ModuleIndex, Project, dotted
from .findings import Finding

CHECKER = "jit-purity"
FIX_HINT = ("hoist the side effect to the host-side caller (provider "
            "dispatch seam) — trace-time effects fire once per compile, "
            "not per dispatch")

# combinators whose callable arguments trace: {dotted suffix: arg spec}
# "first" = first positional arg only; "all" = every callable-ish arg
TRACING_ENTRY = {
    "jax.jit": "first", "jit": "first",
    "shard_map": "first",
    "lax.scan": "first", "jax.lax.scan": "first",
    "jax.vmap": "first", "vmap": "first",
    "jax.pmap": "first", "pmap": "first",
    "lax.cond": "all", "jax.lax.cond": "all",
    "lax.switch": "all", "jax.lax.switch": "all",
    "lax.while_loop": "all", "jax.lax.while_loop": "all",
    "lax.fori_loop": "all", "jax.lax.fori_loop": "all",
    "lax.map": "first", "jax.lax.map": "first",
}

_LOGGER_NAMES = {"log", "logger", "logging", "_log", "LOG", "_LOG",
                 "LOGGER"}
_LOG_METHODS = {"debug", "info", "warning", "warn", "error",
                "exception", "critical", "log"}
_TIME_FNS = {"time", "monotonic", "perf_counter", "perf_counter_ns",
             "monotonic_ns", "time_ns", "sleep", "process_time"}
_METRIC_MUTATORS = {"inc", "observe", "set_state", "labels"}
_IMPURE_MODULES = ("teku_tpu.infra.flightrecorder",
                   "teku_tpu.infra.faults",
                   "teku_tpu.infra.tracing",
                   "teku_tpu.infra.metrics",
                   "teku_tpu.infra.env",
                   "teku_tpu.infra.timeline",
                   "teku_tpu.infra.clock")


def _impure_reason(idx: ModuleIndex, call: ast.Call) -> Optional[str]:
    chain = dotted(call.func)
    if chain is None:
        return None
    parts = chain.split(".")
    head, last = parts[0], parts[-1]
    if chain in ("print", "input", "open", "breakpoint"):
        return f"host I/O `{chain}()`"
    if head == "time" and idx.imports.get("time", "time") == "time" \
            and len(parts) > 1 and last in _TIME_FNS:
        return f"wall/monotonic clock `{chain}()`"
    if head == "random" and idx.imports.get(
            "random", "random") == "random" and len(parts) > 1:
        return f"host RNG `{chain}()`"
    if len(parts) >= 2 and parts[1] == "random" \
            and idx.imports.get(head, "") in ("numpy", "numpy.random"):
        return f"host RNG `{chain}()`"
    if chain.endswith("os.environ.get") or chain.endswith("os.getenv") \
            or chain in ("environ.get", "getenv"):
        return f"environment read `{chain}()`"
    if last in _LOG_METHODS and any(p in _LOGGER_NAMES for p in
                                    parts[:-1]):
        return f"logging call `{chain}()`"
    if last in _METRIC_MUTATORS and len(parts) > 1:
        return f"metric mutation `{chain}()`"
    if last in ("record", "config_demotion") and any(
            "recorder" in p.lower() or p == "flightrecorder"
            for p in parts[:-1]):
        return f"flight-recorder event `{chain}()`"
    if last in ("check", "transform") and "faults" in parts[:-1]:
        return f"fault-site hook `{chain}()`"
    if last in ("span", "trace") and "tracing" in parts[:-1]:
        return f"tracing span `{chain}()`"
    # bare names imported from the impure infra modules; env helpers
    # flag at THEIR call site so the finding (and any suppression)
    # names the kernel-side read, not the shared helper body
    if isinstance(call.func, ast.Name):
        target = idx.imports.get(call.func.id, "")
        if target.startswith("teku_tpu.infra.env."):
            return f"environment read `{call.func.id}()`"
        if target.startswith(_IMPURE_MODULES):
            return f"infra side effect `{call.func.id}()` ({target})"
    return None


def _entry_args(call: ast.Call, spec: str) -> List[ast.AST]:
    args = list(call.args)
    if spec == "first":
        return args[:1]
    out = []
    for a in args:
        if isinstance(a, (ast.Name, ast.Attribute, ast.Lambda)):
            out.append(a)
    return out


def _iter_calls_with_scope(idx: ModuleIndex):
    """(scope function or None, Call node) for every call in the
    module, scope tracked through nested defs."""
    def visit(node: ast.AST, scope: Optional[ast.AST]):
        for child in ast.iter_child_nodes(node):
            child_scope = scope
            if isinstance(child, (ast.FunctionDef,
                                  ast.AsyncFunctionDef, ast.Lambda)):
                child_scope = child
            if isinstance(child, ast.Call):
                yield scope, child
            yield from visit(child, child_scope)
    yield from visit(idx.tree, None)


def _find_entries(idx: ModuleIndex
                  ) -> List[Tuple[Optional[ast.AST], ast.AST, str]]:
    """(call-site scope, callable expr, label) for every traced
    closure handed to a combinator or decorated with one."""
    entries: List[Tuple[Optional[ast.AST], ast.AST, str]] = []
    for scope, call in _iter_calls_with_scope(idx):
        chain = dotted(call.func)
        if chain is None:
            continue
        for suffix, spec in TRACING_ENTRY.items():
            if chain == suffix or chain.endswith("." + suffix):
                for arg in _entry_args(call, spec):
                    entries.append((scope, arg,
                                    f"{chain}(...) at "
                                    f"{idx.relpath}:{call.lineno}"))
                break
    for node in ast.walk(idx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                chain = dotted(target)
                if chain is None:
                    continue
                is_jit = chain in ("jax.jit", "jit") \
                    or chain.endswith(".jit")
                if chain in ("partial", "functools.partial") \
                        and isinstance(dec, ast.Call) and dec.args:
                    inner = dotted(dec.args[0])
                    is_jit = inner in ("jax.jit", "jit")
                if is_jit:
                    # the decorated def itself — NOT a synthetic Name,
                    # which would only resolve for module-level
                    # functions and silently drop decorated methods
                    # and nested defs as entry points
                    entries.append(
                        (None, node,
                         f"@{chain} on {node.name} at "
                         f"{idx.relpath}:{node.lineno}"))
    return entries


def check(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    seen_findings: Set[str] = set()
    visited: Set[int] = set()
    # (module, function node, label of the entry that reached it)
    queue: List[Tuple[ModuleIndex, ast.AST, str]] = []

    def enqueue(idx: ModuleIndex, scope: Optional[ast.AST],
                expr: ast.AST, label: str) -> None:
        if isinstance(expr, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            queue.append((idx, expr, label))
            return
        resolved = project.resolve_call(idx, scope, expr)
        if resolved is not None:
            queue.append((resolved[0], resolved[1], label))

    for idx in project.modules.values():
        for scope, expr, label in _find_entries(idx):
            enqueue(idx, scope, expr, label)

    while queue:
        idx, func, label = queue.pop()
        if id(func) in visited:
            continue
        visited.add(id(func))
        name = getattr(func, "name", "<lambda>")
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            reason = _impure_reason(idx, node)
            if reason is not None:
                token = f"{name}:{dotted(node.func)}"
                dedup = f"{idx.relpath}:{token}"
                if dedup in seen_findings:
                    continue
                seen_findings.add(dedup)
                findings.append(Finding(
                    checker=CHECKER, path=idx.relpath,
                    line=node.lineno,
                    message=f"{reason} inside `{name}`, which traces "
                            "under a jit/scan/shard_map closure",
                    evidence=f"reached from {label}",
                    fix_hint=FIX_HINT, token=token))
                continue
            # the scope for resolution is the function whose body the
            # call appears in (nearest enclosing def inside `func`)
            resolved = project.resolve_call(idx, func, node.func)
            if resolved is not None:
                queue.append((resolved[0], resolved[1], label))
    return findings
