"""dup-helper: no near-identical private helper defined in two modules.

The mechanized bug class: three private copies each of ``_next_pow2``
(hoisted to ``infra/pow2.py`` in PR 10 — after the mesh self-heal PR
found a FOURTH inline copy) and ``_env_float`` (hoisted to
``infra/env.py`` in PR 7).  Copies drift: one gains a clamp, the
others keep the bug, and the reviewer has to notice that three
modules changed when one did.

Detection: module-level ``_``-prefixed function defs are normalized
(docstring stripped, then structural ``ast.dump`` — argument NAMES
count, so only genuinely copy-pasted bodies match) and grouped by
(name, normalized body) across modules.  Groups spanning ≥2 modules
fire one finding per extra copy, pointing at the first definition as
the hoist target.  Tiny passthroughs (< MIN_NODES AST nodes) are
ignored — a two-line property is idiom, not duplication.
"""

import ast
import copy
from typing import Dict, List, Tuple

from .astutil import Project
from .findings import Finding

CHECKER = "dup-helper"
MIN_NODES = 10


def _normalized(func: ast.AST) -> Tuple[str, int]:
    """(structural dump of the body minus docstring, node count)."""
    node = copy.deepcopy(func)
    body = node.body
    if body and isinstance(body[0], ast.Expr) \
            and isinstance(body[0].value, ast.Constant) \
            and isinstance(body[0].value.value, str):
        body = body[1:]
    wrapper = ast.Module(body=body, type_ignores=[])
    count = sum(1 for _ in ast.walk(wrapper))
    return ast.dump(wrapper, annotate_fields=False), count


def check(project: Project) -> List[Finding]:
    groups: Dict[Tuple[str, str], List[Tuple[str, int]]] = {}
    for idx in project.modules.values():
        for name, func in idx.functions.items():
            if not name.startswith("_") or name.startswith("__"):
                continue
            dump, count = _normalized(func)
            if count < MIN_NODES:
                continue
            groups.setdefault((name, dump), []).append(
                (idx.relpath, func.lineno))
    findings: List[Finding] = []
    for (name, _dump), sites in groups.items():
        if len({path for path, _ in sites}) < 2:
            continue
        sites = sorted(sites)
        canonical = sites[0]
        for path, line in sites[1:]:
            findings.append(Finding(
                checker=CHECKER, path=path, line=line,
                message=f"private helper `{name}` duplicates the "
                        f"definition at {canonical[0]}:{canonical[1]}",
                evidence=f"{len(sites)} identical copies: " + ", ".join(
                    f"{p}:{ln}" for p, ln in sites),
                fix_hint="hoist ONE definition into a shared infra "
                         "module (the _next_pow2 -> infra/pow2.py "
                         "precedent) and import it",
                token=name))
    return findings
