"""Shared AST plumbing for the checkers.

One parse per file, one ModuleIndex per module, and the handful of
resolution helpers every checker needs: module-level string constants
(``ENV_VAR = "TEKU_TPU_MSM"`` — the idiom the knob modules use, which a
literal-only scanner would miss), import maps including relative
imports (``from ..infra.env import env_float``), dotted call chains,
and a scope model precise enough to resolve a bare-name call inside a
jitted kernel to the helper it actually invokes — same function, nested
function, same class, same module, or another module in the scanned
tree.
"""

import ast
from typing import Dict, Iterator, List, Optional, Tuple

FuncNode = ast.AST          # FunctionDef | AsyncFunctionDef | Lambda


def module_name(relpath: str) -> str:
    """'teku_tpu/ops/verify.py' -> 'teku_tpu.ops.verify';
    '__init__.py' files name the package itself."""
    parts = relpath.replace("\\", "/").split("/")
    parts[-1] = parts[-1][:-3]          # strip .py
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class ModuleIndex:
    """Everything the checkers ask of one parsed module."""

    def __init__(self, path: str, relpath: str, tree: ast.Module,
                 source: str):
        self.path = path
        self.relpath = relpath
        self.modname = module_name(relpath)
        self.tree = tree
        self.source = source
        self.consts: Dict[str, str] = {}
        # local name -> fully dotted target.  Module imports map to the
        # module ('np' -> 'numpy'); from-imports map to the symbol
        # ('env_float' -> 'teku_tpu.infra.env.env_float').
        self.imports: Dict[str, str] = {}
        self.functions: Dict[str, ast.AST] = {}           # module level
        self.classes: Dict[str, Dict[str, ast.AST]] = {}  # cls -> methods
        self.enclosing_class: Dict[ast.AST, str] = {}
        self.parent_func: Dict[ast.AST, Optional[ast.AST]] = {}
        self.local_funcs: Dict[ast.AST, Dict[str, ast.AST]] = {}
        self._index()

    # ------------------------------------------------------------------
    def _index(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Constant) \
                    and isinstance(node.value.value, str):
                self.consts[node.targets[0].id] = node.value.value
        self._index_imports()
        self._index_scopes(self.tree, parent=None, cls=None)

    def _index_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname
                                 or alias.name.split(".")[0]] = alias.name
            elif isinstance(node, ast.ImportFrom):
                base = self._resolve_from_base(node)
                if base is None:
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    self.imports[alias.asname or alias.name] = \
                        f"{base}.{alias.name}" if base else alias.name

    def _resolve_from_base(self, node: ast.ImportFrom) -> Optional[str]:
        if node.level == 0:
            return node.module or ""
        parts = self.modname.split(".")
        if node.level > len(parts):
            return None
        # level 1 = the containing package: for a plain module that is
        # modname minus the leaf, for an __init__.py modname IS it
        drop = node.level if not self.relpath.endswith("__init__.py") \
            else node.level - 1
        base_parts = parts[:len(parts) - drop]
        if node.module:
            base_parts = base_parts + node.module.split(".")
        return ".".join(base_parts)

    def _index_scopes(self, node: ast.AST, parent: Optional[ast.AST],
                      cls: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.parent_func[child] = parent
                self.local_funcs.setdefault(child, {})
                if cls is not None and parent is None:
                    self.enclosing_class[child] = cls
                    self.classes.setdefault(cls, {})[child.name] = child
                elif parent is None:
                    self.functions[child.name] = child
                else:
                    self.local_funcs.setdefault(parent, {})[
                        child.name] = child
                    if cls is not None:
                        self.enclosing_class[child] = cls
                self._index_scopes(child, parent=child, cls=cls)
            elif isinstance(child, ast.ClassDef):
                self.classes.setdefault(child.name, {})
                self._index_scopes(child, parent=parent,
                                   cls=child.name if parent is None
                                   else cls)
            else:
                self._index_scopes(child, parent=parent, cls=cls)

    # ------------------------------------------------------------------
    def resolve_str(self, expr: ast.AST) -> Optional[str]:
        """Exact string value of an expression, following module-level
        Name constants one hop."""
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return expr.value
        if isinstance(expr, ast.Name):
            return self.consts.get(expr.id)
        return None

    def str_parts(self, expr: ast.AST) -> Optional[Tuple[str, str, bool]]:
        """(prefix, suffix, exact) of a string-ish expression.  Handles
        literals, Name constants, f-strings (constant head/tail), and
        `+` concatenation whose ends resolve.  None = not string-ish."""
        exact = self.resolve_str(expr)
        if exact is not None:
            return exact, exact, True
        if isinstance(expr, ast.JoinedStr) and expr.values:
            head = expr.values[0]
            tail = expr.values[-1]
            prefix = head.value if isinstance(head, ast.Constant) \
                and isinstance(head.value, str) else ""
            suffix = tail.value if isinstance(tail, ast.Constant) \
                and isinstance(tail.value, str) else ""
            return prefix, suffix, False
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            left = self.str_parts(expr.left)
            right = self.str_parts(expr.right)
            if left is not None or right is not None:
                prefix = left[0] if left is not None and (
                    left[2] or left[0]) else ""
                suffix = right[1] if right is not None and (
                    right[2] or right[1]) else ""
                return prefix, suffix, False
        return None


def dotted(expr: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain; None for anything else."""
    parts: List[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def iter_scope(func: ast.AST) -> Iterator[ast.AST]:
    """Nodes in `func`'s own body, NOT descending into nested
    function/class scopes (each scope is its own unit of analysis)."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def all_functions(idx: ModuleIndex) -> Iterator[Tuple[str, ast.AST]]:
    """Every (qualified name, function node) in the module, any depth."""
    for node in ast.walk(idx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            cls = idx.enclosing_class.get(node)
            name = f"{cls}.{node.name}" if cls else node.name
            yield name, node


class Project:
    """The scanned tree: {module name: ModuleIndex} + the repo root.

    Cross-module resolution: `resolve_function('teku_tpu.ops.limbs',
    'mont_mul')` finds the def wherever the dotted target lands inside
    the scanned set (functions only — the purity walker treats
    unresolvable targets as opaque leaves, not errors)."""

    def __init__(self, root: str, modules: Dict[str, ModuleIndex]):
        self.root = root
        self.modules = modules

    def resolve_str(self, idx: ModuleIndex, expr: ast.AST
                    ) -> Optional[str]:
        """Like ModuleIndex.resolve_str, but also follows one
        cross-module hop: `selfheal.FAULT_SITE` through an imported
        module, or a Name imported with `from mod import CONST`."""
        value = idx.resolve_str(expr)
        if value is not None:
            return value
        target = None
        if isinstance(expr, ast.Name) and expr.id in idx.imports:
            target = idx.imports[expr.id]
        else:
            chain = dotted(expr)
            if chain is not None and "." in chain:
                root_name = chain.split(".")[0]
                base = idx.imports.get(root_name)
                if base is not None:
                    target = base + chain[len(root_name):]
        if target is not None and "." in target:
            modpart, _, leaf = target.rpartition(".")
            mod = self.modules.get(modpart)
            if mod is not None:
                return mod.consts.get(leaf)
        return None

    def resolve_target(self, target: str
                       ) -> Optional[Tuple[ModuleIndex, ast.AST]]:
        """A dotted import target -> (module, function node), when the
        target is a function defined in the scanned tree."""
        if "." in target:
            modpart, _, leaf = target.rpartition(".")
            mod = self.modules.get(modpart)
            if mod is not None and leaf in mod.functions:
                return mod, mod.functions[leaf]
        mod = self.modules.get(target)
        return None

    def resolve_call(self, idx: ModuleIndex, scope: Optional[ast.AST],
                     func_expr: ast.AST
                     ) -> Optional[Tuple[ModuleIndex, ast.AST]]:
        """Resolve a call's func expression to a function def in the
        scanned tree: nested defs outward, same class (self.X), module
        functions, imported symbols, imported-module attributes."""
        if isinstance(func_expr, ast.Name):
            name = func_expr.id
            f = scope
            while f is not None:
                local = idx.local_funcs.get(f, {})
                if name in local:
                    return idx, local[name]
                f = idx.parent_func.get(f)
            if name in idx.functions:
                return idx, idx.functions[name]
            if name in idx.imports:
                return self.resolve_target(idx.imports[name])
            return None
        if isinstance(func_expr, ast.Attribute):
            base = func_expr.value
            if isinstance(base, ast.Name) and base.id in ("self", "cls") \
                    and scope is not None:
                f = scope
                while f is not None and f not in idx.enclosing_class:
                    f = idx.parent_func.get(f)
                cls = idx.enclosing_class.get(f) if f is not None else None
                if cls is not None:
                    method = idx.classes.get(cls, {}).get(func_expr.attr)
                    if method is not None:
                        return idx, method
                return None
            chain = dotted(func_expr)
            if chain is None:
                return None
            root_name = chain.split(".")[0]
            if root_name in idx.imports:
                resolved = idx.imports[root_name] + chain[len(root_name):]
                return self.resolve_target(resolved)
        return None
