"""torn-read: registered swap attributes are read at most once per
function.

The mechanized bug class (fixed twice in PR 12 alone): state that hot-
swaps atomically — ``GuardedBls12381._serving`` holds its (provider,
device-entry lock) as ONE tuple precisely so readers can't observe a
half-swap — is only atomic if each reader performs ONE attribute load
and destructures the snapshot.  Two reads in the same function
(``self._serving[0]`` … ``self._serving[1]``, or a re-read after a
blocking call) can straddle a swap and pair the new provider with the
old lock: the exact bug the supervisor reprobe and the bench chaos
phase each shipped once.

Registration lives with the owning module: a module-level

    __swap_attrs__ = ("_serving",)

declares its atomically-swapped attributes; the checker collects every
declaration in the tree and then enforces the single-read rule on all
scanned functions (any module — cross-module readers like
``loader._warmup`` read ``guarded._serving`` too).
"""

import ast
from typing import Dict, List, Set

from .astutil import Project, all_functions, iter_scope
from .findings import Finding

CHECKER = "torn-read"
DECL = "__swap_attrs__"


def declared_swap_attrs(project: Project) -> Set[str]:
    attrs: Set[str] = set()
    for idx in project.modules.values():
        for node in idx.tree.body:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and node.targets[0].id == DECL \
                    and isinstance(node.value, (ast.Tuple, ast.List)):
                for elt in node.value.elts:
                    if isinstance(elt, ast.Constant) \
                            and isinstance(elt.value, str):
                        attrs.add(elt.value)
    return attrs


def check(project: Project) -> List[Finding]:
    swap_attrs = declared_swap_attrs(project)
    if not swap_attrs:
        return []
    findings: List[Finding] = []
    for idx in project.modules.values():
        for qualname, func in all_functions(idx):
            reads: Dict[str, List[int]] = {}
            for node in iter_scope(func):
                if isinstance(node, ast.Attribute) \
                        and isinstance(node.ctx, ast.Load) \
                        and node.attr in swap_attrs:
                    reads.setdefault(node.attr, []).append(node.lineno)
            for attr, lines in reads.items():
                if len(lines) > 1:
                    findings.append(Finding(
                        checker=CHECKER, path=idx.relpath,
                        line=lines[1],
                        message=f"swap attribute `{attr}` read "
                                f"{len(lines)} times in `{qualname}` — "
                                "a second read can straddle an atomic "
                                "swap",
                        evidence=f"reads at lines "
                                 f"{', '.join(map(str, lines))}",
                        fix_hint="read once into a local "
                                 f"(`snap = x.{attr}`) and destructure "
                                 "the snapshot",
                        token=f"{qualname}:{attr}"))
    return findings
