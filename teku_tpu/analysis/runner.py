"""Orchestration: walk the tree, parse once, run every checker, apply
suppressions, render.

Scope: the production tree — the ``teku_tpu`` package, ``tools/``,
and ``bench.py``.  Tests are deliberately OUT of scope (they
monkeypatch env vars and fabricate metric families as fixtures; the
invariants guard production code).  When pointed at a root with no
``teku_tpu`` package (the fixture trees in tests/test_analysis.py)
every ``*.py`` under the root is scanned instead, so checkers prove
out on small synthetic trees.

A file that fails to PARSE is itself a finding (checker ``parse``) —
the analyzer must never report "clean" on a tree it could not read.
"""

import ast
import os
from typing import Callable, Dict, List, Optional, Tuple

from . import (dup_helpers, env_knob, jit_purity, knob_docs,
               metric_contract, registries, suppress, torn_read)
from .astutil import ModuleIndex, Project
from .findings import Finding, Report

DEFAULT_SUPPRESSIONS = "lint_suppressions.json"

# id -> run(project) — the checker registry (knob-doc runs separately:
# it needs the extracted knob list and the README text)
CHECKERS: List[Tuple[str, Callable[[Project], List[Finding]]]] = [
    (env_knob.CHECKER, env_knob.check),
    (jit_purity.CHECKER, jit_purity.check),
    (torn_read.CHECKER, torn_read.check),
    (metric_contract.CHECKER, metric_contract.check),
    (registries.CHECKER, registries.check),
    (dup_helpers.CHECKER, dup_helpers.check),
]


def default_root() -> str:
    """The repo root: parent of the teku_tpu package directory."""
    package_dir = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    return os.path.dirname(package_dir)


def discover_files(root: str) -> List[str]:
    """Repo-relative paths of the production tree (or every *.py for
    a fixture root without the package)."""
    out: List[str] = []
    package = os.path.join(root, "teku_tpu")
    if os.path.isdir(package):
        scan_dirs = [package, os.path.join(root, "tools")]
        for path in (os.path.join(root, "bench.py"),):
            if os.path.isfile(path):
                out.append(os.path.relpath(path, root))
    else:
        scan_dirs = [root]
    for base in scan_dirs:
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__"
                           and not d.startswith(".")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.relpath(
                        os.path.join(dirpath, fn), root))
    return sorted(set(p.replace(os.sep, "/") for p in out))


def build_project(root: str, relpaths: List[str]
                  ) -> Tuple[Project, List[Finding]]:
    modules: Dict[str, ModuleIndex] = {}
    parse_findings: List[Finding] = []
    for relpath in relpaths:
        path = os.path.join(root, relpath)
        try:
            with open(path, encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=relpath)
        except (OSError, SyntaxError, ValueError) as exc:
            parse_findings.append(Finding(
                checker="parse", path=relpath,
                line=getattr(exc, "lineno", 1) or 1,
                message=f"file cannot be parsed: {exc}",
                fix_hint="a tree the analyzer cannot read cannot be "
                         "declared clean",
                token="parse-error"))
            continue
        idx = ModuleIndex(path, relpath, tree, source)
        modules[idx.modname] = idx
    return Project(root, modules), parse_findings


def run_lint(root: Optional[str] = None,
             suppressions_path: Optional[str] = None,
             checker_ids: Optional[List[str]] = None) -> Report:
    """Run the analyzer over `root` (default: this repo).  Raises
    suppress.SuppressionError on an invalid suppression file."""
    root = os.path.abspath(root or default_root())
    relpaths = discover_files(root)
    project, findings = build_project(root, relpaths)

    for checker_id, run in CHECKERS:
        if checker_ids is not None and checker_id not in checker_ids:
            continue
        findings.extend(run(project))

    knobs = env_knob.collect_knobs(project)
    if checker_ids is None or knob_docs.CHECKER in checker_ids:
        readme = os.path.join(root, "README.md")
        readme_text = ""
        if os.path.isfile(readme):
            with open(readme, encoding="utf-8") as fh:
                readme_text = fh.read()
        findings.extend(knob_docs.check(project, knobs, readme_text))

    entries = suppress.load(
        suppressions_path if suppressions_path is not None
        else os.path.join(root, DEFAULT_SUPPRESSIONS))
    findings, unused = suppress.apply(findings, entries)

    report = Report(root=root, files_scanned=len(relpaths),
                    findings=findings, unused_suppressions=unused,
                    knobs=knobs)
    return report
