"""knob-doc: the auto-extracted knob registry and the README knob
docs agree, both ways.

The mechanized bug class: README knob tables grew by hand PR over PR;
a renamed knob leaves a stale doc row that operators copy into unit
files (where, pre-PR-11, a typo'd name silently no-op'd — or worse,
the OLD spelling silently no-op'd while the table still showed it).
The registry side is extracted by ``env_knob.collect_knobs`` from the
actual read sites, so the comparison is code-vs-doc, not doc-vs-doc.

Matching: doc tokens may be patterns (``TEKU_TPU_VERIFY_CLASS_
<CLASS>_DEADLINE_MS``, ``TEKU_TPU_BROWNOUT_*``) and code knobs may be
patterns too (f-string reads); ``<...>`` normalizes to ``*`` and
fnmatch runs in both directions.  Findings:

- a code knob no README token covers -> undocumented knob;
- a README token no code knob matches -> stale doc.
"""

import fnmatch
import re
from typing import Dict, List

from .astutil import Project
from .findings import Finding

CHECKER = "knob-doc"
_TOKEN_RE = re.compile(r"TEKU_TPU_[A-Z0-9_]*(?:<[A-Za-z_]+>[A-Z0-9_]*)*"
                       r"(?:\*[A-Z0-9_]*)*")


def _normalize(token: str) -> str:
    token = re.sub(r"<[A-Za-z_]+>", "*", token)
    return token.rstrip("_") if token.endswith("_") and \
        not token.endswith("_*") else token


def doc_tokens(readme_text: str) -> Dict[str, int]:
    """{normalized token: first line} of every TEKU_TPU_* mention."""
    tokens: Dict[str, int] = {}
    for lineno, line in enumerate(readme_text.splitlines(), 1):
        for m in _TOKEN_RE.finditer(line):
            token = _normalize(m.group(0))
            # the bare namespace wildcard ("every TEKU_TPU_* knob...")
            # is prose, not documentation — counting it would make the
            # undocumented-knob direction vacuously green
            if len(token) > len("TEKU_TPU_") and token != "TEKU_TPU_*":
                tokens.setdefault(token, lineno)
    return tokens


def _covers(doc_token: str, knob: str) -> bool:
    if doc_token == knob:
        return True
    if "*" in doc_token and fnmatch.fnmatchcase(knob, doc_token):
        return True
    if "*" in knob and fnmatch.fnmatchcase(doc_token, knob):
        return True
    return False


def check(project: Project, knobs: List[dict],
          readme_text: str, readme_path: str = "README.md"
          ) -> List[Finding]:
    if not readme_text:
        return []
    tokens = doc_tokens(readme_text)
    findings: List[Finding] = []
    knob_names = sorted({str(k["name"]) for k in knobs})
    for name in knob_names:
        if not any(_covers(tok, name) for tok in tokens):
            where = next(f"{k['path']}:{k['line']}" for k in knobs
                         if k["name"] == name)
            findings.append(Finding(
                checker=CHECKER, path=where.split(":")[0],
                line=int(where.split(":")[1]),
                message=f"knob `{name}` is read here but never "
                        f"documented in {readme_path}",
                evidence=f"registry entry from {where}",
                fix_hint="add the knob to the README knob table "
                         "(`cli lint --knobs` emits the row)",
                token=name))
    for token, lineno in sorted(tokens.items()):
        if not any(_covers(token, name) for name in knob_names):
            findings.append(Finding(
                checker=CHECKER, path=readme_path, line=lineno,
                message=f"documented knob `{token}` matches no env "
                        "read in the tree (stale doc)",
                evidence=f"first mention at {readme_path}:{lineno}",
                fix_hint="remove the stale row, or wire the knob "
                         "through infra/env.py so the registry "
                         "sees it",
                token=token))
    return findings
