"""Aggregating signature verification service — the TPU batch scheduler.

Async front-end that converts bursty per-message verification requests
into device-sized batches, preserving the semantics of the reference's
gossip-side batcher (reference: ethereum/statetransition/src/main/java/
tech/pegasys/teku/statetransition/validation/signatures/
AggregatingSignatureVerificationService.java:41-262):

- bounded queue; overflow raises ServiceCapacityExceeded (:146-160);
- worker drain of queued tasks into ONE batch verify (:171-205) — here
  a single TPU dispatch via the provider, whose power-of-two padding
  keeps jit shapes static;
- on batch failure: single task fails; >= split_threshold bisects
  recursively; otherwise tasks verify individually (:213-226);
- multi-signature tasks stay atomic — a task's triples verify together
  or not at all (AsyncBatchBLSSignatureVerifier.java:24-60 grouping);
- queue-size gauge, batch/task counters, batch-size histogram (:76-98).

Overload resilience on top of the reference semantics (ROADMAP 3):

- PRIORITY CLASSES (``services/admission.py:VerifyClass``): the queue
  is per-class with STRICT-PRIORITY drain — VIP > BLOCK_IMPORT >
  SYNC_CRITICAL > GOSSIP > OPTIMISTIC.  A VIP task (single signature,
  e.g. a block's proposer sig) bypasses aggregation entirely and is
  dispatched alone.  Per-class depth/age metrics expose where a burst
  is queuing.
- ADAPTIVE BATCHING: when an ``AdmissionController`` is wired, each
  drain consults its ``BatchPlan`` — pow-2 bucket-aligned batch size
  picked from live depth + the per-shape device-latency model + the
  p50 burn rate, plus a flush deadline that lets workers hold a
  partial batch open ONLY when utilization says throughput is the
  constraint — replacing the fixed ``max_batch_size`` drain.
- SHED-BY-CLASS: queue overflow evicts a strictly-lower-priority
  sheddable task to admit a higher-class arrival (never the reverse);
  brownout (controller-declared, hysteretic) sheds OPTIMISTIC first,
  then GOSSIP by oldest deadline — BLOCK_IMPORT and VIP are never
  shed.  Every shed lands in the flight recorder with its class and
  the originating trace id, and in ``*_rejected_total{class=...}``.

Two dedup/overlap layers (PR 5):

- identical in-flight triples coalesce — gossip re-delivers the same
  (pks, msg, sig); duplicates ride the already-pending task and the
  verdict fans out to every waiter (``*_coalesced_total``).  A waiter
  of a HIGHER class promotes the shared task's effective class (and
  its queue position), so a VIP duplicate of a queued GOSSIP verify
  gets VIP treatment;
- async overlap — when the BLS implementation exposes the async begin
  seam (bls.begin_batch_verify), a worker host_preps + enqueues batch
  N+1 while batch N executes on device, synchronizing only at verdict
  read (``TEKU_TPU_ASYNC_OVERLAP=0`` disables).

Deliberate departure from the reference: its workers block up to 30 s
waiting to fill a batch, which is throughput-friendly but latency-naive;
here the flush deadline is CONTROLLED — zero (take whatever is queued)
while the node has headroom, nonzero only under measured pressure.
"""

import asyncio
import logging
import os
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..crypto import bls
from ..infra import (capacity, dispatchledger, faults, flightrecorder,
                     timeline, tracing)
from ..infra.metrics import (GLOBAL_REGISTRY, LATENCY_BUCKETS_S,
                             MetricsRegistry)
from ..infra.env import env_bool, env_float
from .admission import (AdmissionController, BatchPlan, SHEDDABLE,
                        VerifyClass, class_deadline_s)

Triple = Tuple[Sequence[bytes], bytes, bytes]

_LOG = logging.getLogger(__name__)

# Overlap host_prep of batch N+1 with device execution of batch N: the
# worker begins (host_prep + async device enqueue) the next batch
# BEFORE synchronizing the previous one — JAX async dispatch keeps the
# device busy while the host packs arrays.  Engages only when the
# active BLS implementation exposes an async begin (the raw JAX
# provider; breaker-guarded backends stay sync — the breaker owns its
# dispatch deadline).  TEKU_TPU_ASYNC_OVERLAP=0 disables.
ENV_OVERLAP = "TEKU_TPU_ASYNC_OVERLAP"


def _overlap_default() -> bool:
    return env_bool(ENV_OVERLAP, True)


class ServiceCapacityExceededError(Exception):
    """Task shed — the caller treats it as load shedding (gossip
    IGNORE).  Raised at submission for rejected arrivals; set on the
    future for tasks evicted from the queue after admission."""


@dataclass(eq=False)   # identity eq: queue remove() wants THIS task,
class _Task:           # not a payload-equal twin, and field-wise eq
    triples: List[Triple]  # would byte-compare signatures per scan
    future: asyncio.Future = field(repr=False)
    # stamped at enqueue: queue-wait attribution + the caller's root
    # trace (the gossip validator's), so the worker can attribute its
    # stages to the trace that is awaiting this task's future
    t_enqueue: float = 0.0
    trace: Optional[tracing.Trace] = field(default=None, repr=False)
    # priority class + the enqueue-to-verdict deadline it implies
    # (monotonic): brownout sheds GOSSIP oldest-deadline-first
    cls: VerifyClass = VerifyClass.GOSSIP
    deadline: float = 0.0
    # in-flight dedup: gossip re-delivers the same (pks, msg, sig) —
    # identical pending triples coalesce onto ONE queued task, and the
    # verdict fans out to every waiter future
    key: Optional[tuple] = None
    waiters: List[asyncio.Future] = field(default_factory=list,
                                          repr=False)
    # class of each coalesced waiter, parallel to `waiters`: a
    # cancelled primary recomputes the effective class from survivors
    waiter_classes: List[VerifyClass] = field(default_factory=list)

    def settle(self, result: Optional[bool] = None,
               exc: Optional[BaseException] = None) -> None:
        """Resolve the primary future AND every coalesced waiter."""
        for fut in (self.future, *self.waiters):
            if fut.done():
                continue
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(result)


class _PriorityQueue:
    """Per-class bounded FIFO deques with strict-priority pop.

    Everything runs on the event loop (like the asyncio.Queue it
    replaces), so no locks.  Capacity bounds the TOTAL across classes
    — the reference's ArrayBlockingQueue.offer semantics per class
    would let a gossip storm starve the shared budget invisibly."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._qs: Dict[VerifyClass, deque] = {
            c: deque() for c in VerifyClass}
        self._size = 0
        self._triples = 0
        self._nonempty = asyncio.Event()
        # pulse on every put: flush-deadline waiters wake per arrival
        self._arrival = asyncio.Event()
        # timeline: start of the current queue-nonempty interval (the
        # wall-time denominator of overlap_efficiency); None while the
        # queue is empty or the timeline is disabled
        self._t_nonempty: Optional[float] = None

    def _note_size_change(self) -> None:
        """Close the queue-nonempty timeline interval when the queue
        drains (every decrement path funnels here)."""
        if self._size == 0 and self._t_nonempty is not None:
            t0 = self._t_nonempty
            self._t_nonempty = None
            timeline.interval("worker", "queue_nonempty",
                              time.perf_counter() - t0, t_mono=t0)

    def qsize(self) -> int:
        return self._size

    @property
    def triples(self) -> int:
        return self._triples

    def depth(self, cls: VerifyClass) -> int:
        return len(self._qs[cls])

    def oldest_deadline(self, cls: VerifyClass) -> Optional[float]:
        q = self._qs[cls]
        return min(t.deadline for t in q) if q else None

    def put_nowait(self, task: _Task) -> None:
        if self._size >= self.capacity:
            raise asyncio.QueueFull
        self._qs[task.cls].append(task)
        self._size += 1
        self._triples += len(task.triples)
        if self._t_nonempty is None and timeline.enabled():
            self._t_nonempty = time.perf_counter()
        self._nonempty.set()
        self._arrival.set()

    def best_class(self) -> Optional[VerifyClass]:
        """Highest-priority class with queued work (None = empty)."""
        for c in VerifyClass:
            if self._qs[c]:
                return c
        return None

    def get_nowait(self, prefer_non_vip: bool = False) -> _Task:
        """Strict-priority pop (VIP first).  ``prefer_non_vip`` is the
        anti-starvation guard: after a VIP-only dispatch the worker
        takes the best NON-VIP task when one is queued, so a steady
        VIP trickle cannot monopolize the device with tiny padded
        dispatches — a VIP then waits at most one bounded batch."""
        order = list(VerifyClass)
        if prefer_non_vip:
            order = order[1:] + order[:1]
        for c in order:
            q = self._qs[c]
            if q:
                return self._pop(q, 0)
        raise asyncio.QueueEmpty

    def pop_class(self, cls: VerifyClass) -> Optional[_Task]:
        q = self._qs[cls]
        return self._pop(q, 0) if q else None

    async def get(self, prefer_non_vip: bool = False) -> _Task:
        while True:
            try:
                return self.get_nowait(prefer_non_vip)
            except asyncio.QueueEmpty:
                self._nonempty.clear()
                await self._nonempty.wait()

    async def wait_arrival(self, timeout: float) -> None:
        self._arrival.clear()
        try:
            await asyncio.wait_for(self._arrival.wait(), timeout)
        except asyncio.TimeoutError:
            pass

    def _pop(self, q: deque, idx: int) -> _Task:
        if idx == 0:
            task = q.popleft()
        else:
            task = q[idx]
            del q[idx]
        self._size -= 1
        self._triples -= len(task.triples)
        self._note_size_change()
        return task

    def remove(self, task: _Task) -> bool:
        """Withdraw a specific queued task (promotion / shed)."""
        q = self._qs[task.cls]
        try:
            idx = q.index(task)
        except ValueError:
            return False
        self._pop(q, idx)
        return True

    def promote(self, task: _Task, cls: VerifyClass) -> None:
        """Raise a queued task's class (re-files it under the higher-
        priority deque; a task already in flight just re-labels)."""
        if self.remove(task):
            task.cls = cls
            self.put_nowait(task)
        else:
            task.cls = cls

    def evict_for(self, cls: VerifyClass) -> Optional[_Task]:
        """Pick a victim to admit a `cls` arrival on a full queue:
        the lowest-priority SHEDDABLE class strictly below the
        arrival, oldest deadline first.  None = the arrival itself is
        the least valuable thing here."""
        for victim_cls in SHEDDABLE:   # OPTIMISTIC, then GOSSIP
            if victim_cls <= cls:
                continue               # never evict peers or betters
            q = self._qs[victim_cls]
            if q:
                idx = min(range(len(q)), key=lambda i: q[i].deadline)
                return self._pop(q, idx)
        return None

    def drain_class(self, cls: VerifyClass) -> List[_Task]:
        q = self._qs[cls]
        victims = list(q)
        for t in victims:
            self._size -= 1
            self._triples -= len(t.triples)
        q.clear()
        self._note_size_change()
        return victims

    def _drop_many(self, cls: VerifyClass,
                   victims: List[_Task]) -> None:
        """Remove a victim set in ONE rebuild pass — per-victim
        remove() would rescan the deque per victim, O(victims x
        depth) on the event loop at peak overload."""
        if not victims:
            return
        victim_ids = {id(t) for t in victims}
        q = self._qs[cls]
        keep = [t for t in q if id(t) not in victim_ids]
        q.clear()
        q.extend(keep)
        for t in victims:
            self._size -= 1
            self._triples -= len(t.triples)
        self._note_size_change()

    def drain_expired(self, cls: VerifyClass, now: float
                      ) -> List[_Task]:
        """Shed every `cls` task whose deadline already passed:
        past-deadline work can no longer make its SLO, and verifying
        it spends device time the still-viable queue needs."""
        victims = [t for t in self._qs[cls] if t.deadline <= now]
        self._drop_many(cls, victims)
        return victims

    def drain_oldest(self, cls: VerifyClass, keep: int) -> List[_Task]:
        """Shed `cls` down to `keep` tasks, oldest deadline first."""
        q = self._qs[cls]
        excess = len(q) - keep
        if excess <= 0:
            return []
        victims = sorted(q, key=lambda t: t.deadline)[:excess]
        self._drop_many(cls, victims)
        return victims

    def drain_all(self) -> List[_Task]:
        out = []
        for c in VerifyClass:
            out.extend(self.drain_class(c))
        return out


class AggregatingSignatureVerificationService:
    """Queue/drain/dispatch batch verifier over the pluggable BLS SPI."""

    def __init__(self, num_workers: int = 2, queue_capacity: int = 15_000,
                 max_batch_size: int = 250, split_threshold: int = 25,
                 registry: MetricsRegistry = GLOBAL_REGISTRY,
                 name: str = "signature_verifications",
                 overlap: Optional[bool] = None,
                 controller: Optional[AdmissionController] = None,
                 default_class: VerifyClass = VerifyClass.GOSSIP,
                 telemetry: Optional[capacity.CapacityTelemetry]
                 = None,
                 recorder: Optional[flightrecorder.FlightRecorder]
                 = None,
                 clock: Callable[[], float] = time.monotonic):
        if num_workers < 1:
            raise ValueError("need at least one worker")
        # the capacity sink (arrivals/sheds/queue depth) and the shed
        # event sink: injectable so closed-loop simulations run on a
        # virtual clock without touching process-global state
        self._telemetry = telemetry or capacity.TELEMETRY
        self._recorder = recorder or flightrecorder.RECORDER
        # deadline clock: task deadlines (enqueue + class budget) and
        # the expiry checks against them run on this clock, so the
        # virtual-clock overload sim ages queues deterministically.
        # Worker-liveness stamps stay on real monotonic time — a
        # stalled worker is a wall-clock fact.
        self._clock = clock
        # flight-recorder flood guard: during a brownout every rejected
        # arrival is a shed; recording each one would wash the valuable
        # brownout-edge events out of the bounded ring.  Per
        # (class, reason) at most one event per cooldown window; the
        # next recorded event carries the suppressed count.
        self._shed_event_cooldown_s = env_float(
            "TEKU_TPU_SHED_EVENT_COOLDOWN_S", 1.0)
        self._shed_event_last: Dict[tuple, float] = {}
        self._shed_event_suppressed: Dict[tuple, int] = {}
        # REAL-TIME flush failsafe: the batch-fill hold runs on the
        # service clock (virtual in sims), with a wall-clock
        # termination bound so a stalled virtual clock can never hold
        # a worker forever.  Env-tunable (TEKU_TPU_FLUSH_FAILSAFE_MS;
        # 0 = the plan's own flush deadline, the legacy bound).  The
        # r10 investigation SUSPECTED this silent failsafe for a 3.6 s
        # loadgen block-import p50 on 1-core boxes — each firing is
        # now counted, flight-recorded, and stamped into the fired
        # batch's own ledger record (and that evidence shows the
        # loadgen inflation fires ZERO failsafes, ruling this path
        # out).
        # clamped: a negative typo'd value would read truthy and put
        # the wall deadline in the past, firing the failsafe on EVERY
        # fill hold (degrade-never-fail, like every env knob here)
        self._flush_failsafe_s = max(0.0, env_float(
            "TEKU_TPU_FLUSH_FAILSAFE_MS", 0.0) / 1e3)
        self._failsafe_event_last = 0.0
        self._m_flush_failsafe = registry.counter(
            f"{name}_flush_failsafe_total",
            "batch-fill holds terminated by the wall-clock failsafe "
            "instead of the service-clock flush deadline")
        self.num_workers = num_workers
        self._name = name
        self.overlap = _overlap_default() if overlap is None else overlap
        self.queue_capacity = queue_capacity
        self.max_batch_size = max_batch_size
        self.split_threshold = split_threshold
        # the feedback controller (None = fixed-policy legacy mode:
        # max_batch_size drain, overflow-only shedding, no brownout)
        self.controller = controller
        self.default_class = default_class
        # Genuinely bounded, like the reference's ArrayBlockingQueue.offer
        # (AggregatingSignatureVerificationService.java:146-160): put_nowait
        # on a full queue raises QueueFull -> shed-by-class or
        # capacity-exceeded, so concurrent producers cannot overshoot.
        self._queue = _PriorityQueue(queue_capacity)
        self._workers: List[asyncio.Task] = []
        self._started = False
        self._stopped = False
        self._m_queue = registry.gauge(
            f"{name}_queue_size", "pending verification tasks",
            supplier=lambda: self._queue.qsize())
        self._m_batches = registry.counter(
            f"{name}_batch_count_total", "batches dispatched")
        self._m_tasks = registry.counter(
            f"{name}_task_count_total", "tasks completed")
        self._m_batch_size = registry.histogram(
            f"{name}_batch_size", "signatures per dispatched batch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
        # batch LATENCY next to batch size: a regressed p50 with a flat
        # size distribution points at the dispatch, not the batching
        self._m_batch_duration = registry.histogram(
            f"{name}_batch_duration_seconds",
            "wall seconds per batch dispatch (device call inclusive)",
            buckets=LATENCY_BUCKETS_S)
        # first-try vs bisect-recursion dispatches: the failure path
        # amplifies one bad batch into O(log n) extra device calls, and
        # that amplification used to be invisible
        self._m_dispatches = registry.labeled_counter(
            f"{name}_dispatch_total",
            "batch dispatches by kind (first_try vs bisect recursion)",
            labelnames=("kind",))
        # shedding by CLASS: a node rejecting gossip under load while
        # protecting block import must be distinguishable from one
        # rejecting blindly (bounded cardinality: VerifyClass is a
        # closed enum)
        self._m_rejected = registry.labeled_counter(
            f"{name}_rejected_total",
            "tasks shed (queue overflow, preemption by a higher class, "
            "or brownout), by priority class",
            labelnames=("class",))
        # per-class queue observability: depth + age of the oldest
        # queued task — WHERE a burst is queuing, not just how much
        self._m_class_depth = registry.labeled_gauge(
            f"{name}_class_queue_depth",
            "pending tasks per priority class",
            labelnames=("class",))
        self._m_class_age = registry.labeled_gauge(
            f"{name}_class_oldest_wait_seconds",
            "how long the oldest queued task of each class has waited",
            labelnames=("class",))
        for c in VerifyClass:          # complete family from scrape 1
            self._m_class_depth.labels(**{"class": c.label}).set(0.0)
            self._m_class_age.labels(**{"class": c.label}).set(0.0)
        # gossip re-delivery dedup: each coalesced submission rode an
        # already-pending identical task instead of a fresh lane
        self._m_coalesced = registry.counter(
            f"{name}_coalesced_total",
            "duplicate in-flight submissions coalesced onto a pending "
            "identical task")
        # identical-triples key -> the pending task carrying it (entries
        # removed when the task settles; all on the event loop, no lock)
        self._pending: Dict[tuple, _Task] = {}
        # (queue saturation is served by health_snapshot() / the
        # readiness endpoint, not a supplier gauge: get_or_create would
        # pin the family to the FIRST service instance's closure)
        # worker liveness: monotonic stamp of the last time ANY worker
        # made progress (took or finished a batch) — queued work plus a
        # stale stamp is the signature of every worker wedged in a
        # dispatch, which no throughput counter can distinguish from
        # simple idleness
        self._last_worker_progress = time.monotonic()
        # dispatches currently crossing the thread boundary (inside an
        # asyncio.to_thread BLS call).  Event-loop-only mutation, no
        # lock.  Virtual-clock harnesses gate their clock advancement
        # on this: while a dispatch is in flight, spinning the event
        # loop (and the virtual clock) starves the executor thread of
        # the GIL on small hosts, charging wall scheduling time to the
        # task's VIRTUAL latency — the r10 3.6 s loadgen block-import
        # p50 on a 1-core box (see loadgen/driver.py)
        self._inflight_dispatches = 0

    # ------------------------------------------------------------------
    async def start(self) -> None:
        if self._started:
            return
        self._started = True
        for i in range(self.num_workers):
            self._workers.append(
                asyncio.create_task(self._worker(), name=f"sig-verify-{i}"))

    async def stop(self) -> None:
        self._stopped = True
        for w in self._workers:
            w.cancel()
        for w in self._workers:
            try:
                await w
            except asyncio.CancelledError:
                pass
        self._workers.clear()
        # Fail tasks still in the queue so callers never hang on shutdown.
        for task in self._queue.drain_all():
            for fut in (task.future, *task.waiters):
                if not fut.done():
                    fut.cancel()
        self._pending.clear()

    # ------------------------------------------------------------------
    def verify(self, public_keys: Sequence[bytes], message: bytes,
               signature: bytes,
               cls: Optional[VerifyClass] = None,
               source: Optional[str] = None
               ) -> "asyncio.Future[bool]":
        """Queue one fast-aggregate triple; resolves with the verdict."""
        return self.verify_multi([(public_keys, message, signature)],
                                 cls=cls, source=source)

    @staticmethod
    def _task_key(triples: Sequence[Triple]) -> tuple:
        return tuple((tuple(pks), msg, sig) for pks, msg, sig in triples)

    @property
    def inflight_dispatches(self) -> int:
        """Dispatches currently inside an ``asyncio.to_thread`` BLS
        call (enqueue or sync).  0 = the service is quiescent at the
        thread boundary — the virtual-clock harness gate."""
        return self._inflight_dispatches

    async def _dispatch_in_thread(self, fn, *args):
        """One BLS call on a worker thread, counted as in-flight for
        the whole thread round-trip."""
        self._inflight_dispatches += 1
        try:
            return await asyncio.to_thread(fn, *args)
        finally:
            self._inflight_dispatches -= 1

    def _current_plan(self) -> Optional[BatchPlan]:
        if self.controller is None:
            return None
        try:
            return self.controller.plan()
        except Exception:  # noqa: BLE001 - control must not kill verify
            _LOG.exception("admission controller plan() failed")
            return None

    def verify_multi(self, triples: Sequence[Triple],
                     cls: Optional[VerifyClass] = None,
                     source: Optional[str] = None
                     ) -> "asyncio.Future[bool]":
        """Queue several triples as ONE atomic task (e.g. the three
        signatures of a SignedAggregateAndProof verify together).

        ``source`` names the arrival's demand stream in the capacity
        model (default: this service's name) — the sync-committee verbs
        pass ``capacity.SOURCE_SYNC_COMMITTEE`` so their load is
        attributable separately from attestation gossip.

        Identical in-flight submissions coalesce: gossip re-delivers
        the same (pks, msg, sig), and re-verifying a triple that is
        already pending wastes a lane — the duplicate rides the pending
        task and its future resolves with the same verdict.  A waiter
        of a HIGHER class promotes the shared task."""
        if not self._started or self._stopped:
            raise RuntimeError("service not running")
        cls = self.default_class if cls is None else VerifyClass(cls)
        if cls is VerifyClass.VIP and len(triples) != 1:
            raise ValueError("the VIP lane is single-signature only")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        key = self._task_key(triples)
        pending = self._pending.get(key)
        if pending is not None and not pending.future.cancelled():
            pending.waiters.append(fut)
            pending.waiter_classes.append(cls)
            if cls < pending.cls:
                # the shared lane inherits the most urgent waiter's
                # class — a VIP duplicate must not queue at GOSSIP
                self._queue.promote(pending, cls)
                pending.deadline = min(
                    pending.deadline,
                    self._clock() + class_deadline_s(cls))
            self._m_coalesced.inc()
            # timeline: the waiter's trace joins the pending task's
            # in-flight lane — the Perfetto export draws the async
            # arrow from this mark to the carrying dispatch
            timeline.instant(
                "worker", "coalesce",
                trace_id=(pending.trace.trace_id
                          if pending.trace is not None else ""),
                waiter_class=cls.label,
                waiters=len(pending.waiters))
            return fut
        # capacity input: demand is OFFERED load — a shed arrival is
        # still demand (counting only accepted work would read
        # utilization low during exactly the overload the brownout
        # controller exists to manage)
        self._telemetry.record_arrival(source or self._name,
                                       len(triples))
        plan = self._current_plan()
        if plan is not None and plan.sheds(cls):
            # brownout admission control: the controller already
            # declared this class shed — reject before it costs a slot
            self._count_shed(cls, len(triples), reason="brownout",
                             trace=tracing.current_trace())
            raise ServiceCapacityExceededError(
                f"brownout level {plan.brownout_level}: "
                f"{cls.label} shed")
        task = _Task(
            list(triples), fut, t_enqueue=time.perf_counter(),
            trace=tracing.current_trace(), key=key, cls=cls,
            deadline=self._clock() + class_deadline_s(cls))
        try:
            # `sigservice.enqueue` fault site: Overflow injection proves
            # the shed path (metrics + WARN) without a 15k-deep queue
            faults.check("sigservice.enqueue")
            self._queue.put_nowait(task)
        except asyncio.QueueFull:
            # shed-by-class: a full queue admits a higher-priority
            # arrival by evicting the least valuable queued task
            # (OPTIMISTIC first, then GOSSIP oldest-deadline; never
            # BLOCK_IMPORT/VIP) — only when the arrival outranks it
            victim = self._queue.evict_for(cls)
            if victim is not None:
                self._shed_task(victim, reason="preempted")
                self._queue.put_nowait(task)
            else:
                self._count_shed(cls, len(triples), reason="overflow",
                                 trace=task.trace)
                _LOG.warning(
                    "signature verification queue at capacity "
                    "(%d/%d pending) — shedding %s task (%d triples)",
                    self._queue.qsize(), self.queue_capacity,
                    cls.label, len(triples))
                raise ServiceCapacityExceededError(
                    f"queue at capacity ({self.queue_capacity})"
                ) from None
        self._pending[key] = task
        self._m_class_depth.labels(**{"class": cls.label}).set(
            self._queue.depth(cls))
        # the queue-depth time series the admin endpoint serves and
        # the admission controller sizes batches from — in TRIPLES
        # (lanes), the unit the batch plan and demand rate use, not
        # tasks (an aggregate task is 3 triples)
        self._telemetry.record_queue_depth(self._queue.triples)
        return fut

    # ------------------------------------------------------------------
    def _count_shed(self, cls: VerifyClass, triples: int, reason: str,
                    trace: Optional[tracing.Trace] = None) -> None:
        """Shared shed bookkeeping: class-labeled counter, capacity
        demand, and a flight-recorder event naming the class AND the
        originating trace id."""
        self._m_rejected.labels(**{"class": cls.label}).inc()
        self._telemetry.record_shed(triples)
        key = (cls.label, reason)
        now = time.monotonic()
        last = self._shed_event_last.get(key)
        if (last is not None
                and now - last < self._shed_event_cooldown_s):
            # ring flood guard: the counter above is the authoritative
            # shed count; the event stream keeps only the edges
            self._shed_event_suppressed[key] = (
                self._shed_event_suppressed.get(key, 0) + 1)
            return
        self._shed_event_last[key] = now
        suppressed = self._shed_event_suppressed.pop(key, 0)
        trace_id = trace.trace_id if trace is not None else None
        self._recorder.record(
            "queue_shed", trace_id=trace_id, service=self._name,
            reason=reason, queue_size=self._queue.qsize(),
            capacity=self.queue_capacity, triples=triples,
            suppressed_since_last=suppressed,
            **{"class": cls.label})

    def _shed_task(self, task: _Task, reason: str) -> None:
        """Shed an ALREADY-QUEUED task: fail its future (and every
        coalesced waiter) with the capacity error the callers already
        treat as load shedding."""
        self._drop_pending(task)
        self._count_shed(task.cls, len(task.triples), reason=reason,
                         trace=task.trace)
        self._m_class_depth.labels(**{"class": task.cls.label}).set(
            self._queue.depth(task.cls))
        task.settle(exc=ServiceCapacityExceededError(
            f"{task.cls.label} task shed ({reason})"))

    def _apply_brownout(self, plan: BatchPlan) -> int:
        """Trim the queue per the controller's brownout level: all
        queued OPTIMISTIC at level >= 1; GOSSIP down to two batches'
        worth, oldest deadline first, at level 2.  Returns sheds."""
        if plan.brownout_level < 1:
            return 0
        victims = self._queue.drain_class(VerifyClass.OPTIMISTIC)
        # deadline-aware: while browned out, a GOSSIP task that cannot
        # produce its verdict inside its deadline budget (its deadline
        # falls before now + one modeled device dispatch) is dead
        # weight at ANY level — verifying it spends the device time
        # the still-viable queue needs, and serving a seconds-stale
        # backlog is what turns a 2x overload transient into a blown
        # p50
        horizon = self._clock() + (plan.modeled_batch_s or 0.0)
        victims += self._queue.drain_expired(VerifyClass.GOSSIP,
                                             horizon)
        if plan.brownout_level >= 2:
            keep = max(1, plan.batch_size * 2)
            victims += self._queue.drain_oldest(VerifyClass.GOSSIP,
                                                keep)
        for t in victims:
            self._shed_task(t, reason="brownout")
        return len(victims)

    # ------------------------------------------------------------------
    def queue_snapshot(self) -> dict:
        """Per-class queue state (the admin endpoint body); also
        refreshes the per-class depth/age gauges."""
        now = self._clock()
        classes = {}
        for c in VerifyClass:
            depth = self._queue.depth(c)
            oldest = self._queue.oldest_deadline(c)
            # oldest wait = how far the oldest task is INTO its
            # deadline budget (>= 0; clamped — a promoted task keeps
            # its original, possibly tighter, deadline)
            age = 0.0
            if oldest is not None:
                age = max(0.0, class_deadline_s(c) - (oldest - now))
            classes[c.label] = {"depth": depth,
                                "oldest_wait_s": round(age, 4)}
            self._m_class_depth.labels(**{"class": c.label}).set(depth)
            self._m_class_age.labels(**{"class": c.label}).set(
                round(age, 4))
        return {"total": self._queue.qsize(),
                "triples": self._queue.triples,
                "capacity": self.queue_capacity,
                "classes": classes}

    def health_snapshot(self) -> dict:
        """Queue + worker liveness for `infra/health.py`'s check:
        `stalled_s` is nonzero only while tasks are QUEUED with no
        worker progress — an idle service never reads as stalled."""
        qsize = self._queue.qsize()
        stalled_s = 0.0
        if qsize > 0 and self._started and not self._stopped:
            stalled_s = max(
                0.0, time.monotonic() - self._last_worker_progress)
        return {"queue_size": qsize,
                "capacity": self.queue_capacity,
                "saturation": qsize / self.queue_capacity,
                "workers": len(self._workers),
                "stalled_s": stalled_s,
                "classes": self.queue_snapshot()["classes"],
                "brownout_level": (self.controller.brownout_level
                                   if self.controller else 0),
                # the derived capacity signals (arrival rate,
                # utilization, headroom, occupancy) the SLO engine, the
                # health check and the admission controller consume —
                # full per-shape detail lives on /teku/v1/admin/capacity
                "capacity_model": self._telemetry.summary()}

    # ------------------------------------------------------------------
    async def _worker(self) -> None:
        # At most ONE in-flight async dispatch per worker: batch N
        # executes on device while this loop assembles and host_preps
        # batch N+1 (bls.begin_batch_verify), then retires N.  The
        # overlap only defers the SYNC, so when the queue is empty the
        # in-flight batch retires immediately — no added latency.
        inflight: Optional[tuple] = None
        vip_streak = False      # last dispatch was VIP-only
        try:
            while not self._stopped:
                if inflight is not None:
                    try:
                        first = self._queue.get_nowait(vip_streak)
                    except asyncio.QueueEmpty:
                        prev, inflight = inflight, None
                        await self._retire(*prev)
                        continue
                else:
                    first = await self._queue.get(vip_streak)
                self._last_worker_progress = time.monotonic()
                plan = self._current_plan()
                if plan is not None:
                    self._apply_brownout(plan)
                    if plan.sheds(first.cls):
                        # admitted before the brownout edge: device
                        # time is the scarce resource now
                        self._shed_task(first, reason="brownout")
                        continue
                tasks, failsafe_fired = await self._take_batch(
                    first, plan)
                if not tasks:
                    continue
                vip_streak = all(t.cls is VerifyClass.VIP
                                 for t in tasks)
                try:
                    handle = t0 = None
                    if self.overlap and bls.supports_async_verify():
                        handle, t0 = await self._begin(
                            tasks, plan, failsafe_fired)
                    if handle is None:
                        # sync path: implementation has no async seam
                        if inflight is not None:
                            prev, inflight = inflight, None
                            await self._retire(*prev)
                        await self._verify_batch(
                            tasks, plan=plan,
                            flush_failsafe=failsafe_fired)
                    else:
                        prev, inflight = inflight, (tasks, handle, t0)
                        if prev is not None:
                            await self._retire(*prev)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:  # provider/JAX runtime error
                    # The worker must survive (the reference at least
                    # logs worker death, doStart .finish(err ->
                    # LOG.error)); fail the affected futures so callers
                    # never await forever.
                    _LOG.exception("signature batch verification failed")
                    for t in tasks:
                        self._drop_pending(t)
                        t.settle(exc=exc)
                finally:
                    self._last_worker_progress = time.monotonic()
        finally:
            # shutdown/cancellation with a batch still in flight: never
            # leave its callers awaiting forever
            if inflight is not None:
                for t in inflight[0]:
                    self._drop_pending(t)
                    for fut in (t.future, *t.waiters):
                        if not fut.done():
                            fut.cancel()

    async def _take_batch(
            self, first: _Task,
            plan: Optional[BatchPlan]) -> Tuple[List[_Task], bool]:
        """Assemble one dispatch batch under the current plan: VIP
        bypasses aggregation (dispatched alone, immediately); other
        classes drain up to the plan's pow-2 batch size, optionally
        holding the batch open up to the flush deadline when the
        controller says throughput is the constraint.  Returns
        ``(tasks, failsafe_fired)`` — the flag rides with THIS batch
        into its ledger annotation (a shared instance flag would let
        one worker's firing stamp another worker's record)."""
        # recompute the effective class first: a cancelled VIP primary
        # with GOSSIP waiters must not hold the express lane
        live = self._drop_cancelled([first])
        if not live:
            return [], False
        first = live[0]
        budget = plan.batch_size if plan is not None \
            else self.max_batch_size
        if first.cls is VerifyClass.VIP:
            # bypass aggregation: no flush wait, no lower-class lanes
            # — but other QUEUED VIPs ride the same dispatch (one
            # padded shape serves them all; leaving them behind would
            # cost a full extra dispatch each)
            return self._drop_cancelled(
                self._assemble(first, budget, vip_only=True)), False
        failsafe_fired = False
        if plan is not None and plan.flush_deadline_s > 0:
            needed = budget - len(first.triples)
            # elapsed runs on the service clock (virtual in the sim, so
            # the hold window is deterministic while load flows and
            # arrivals pulse re-checks); the REAL-time deadline is the
            # termination failsafe — a virtual clock that stops
            # advancing (sim load window over) must not hold a worker
            # forever.  TEKU_TPU_FLUSH_FAILSAFE_MS tightens the wall
            # bound independently of the plan's (virtual) deadline.
            start = self._clock()
            failsafe_s = self._flush_failsafe_s \
                or plan.flush_deadline_s
            real_deadline = time.monotonic() + failsafe_s
            while self._queue.triples < needed:
                best = self._queue.best_class()
                if best is not None and best < first.cls:
                    # a more urgent class arrived mid-hold: stop
                    # gathering and dispatch NOW — a proposer
                    # signature must not wait out a gossip batch's
                    # fill window (it rides this immediate dispatch)
                    break
                remaining = (plan.flush_deadline_s
                             - (self._clock() - start))
                real_remaining = real_deadline - time.monotonic()
                if remaining <= 0:
                    break
                if real_remaining <= 0:
                    # the wall clock beat the service clock: the
                    # failsafe (not the flush policy) ended this hold
                    # — the silent 1-core latency source r10 chased
                    self._note_flush_failsafe(plan, failsafe_s,
                                              remaining)
                    failsafe_fired = True
                    break
                await self._queue.wait_arrival(
                    min(remaining, real_remaining))
        return (self._drop_cancelled(self._assemble(first, budget)),
                failsafe_fired)

    def _note_flush_failsafe(self, plan: BatchPlan, failsafe_s: float,
                             virtual_remaining_s: float) -> None:
        """Stamp a real-time flush-failsafe firing: counter always,
        flight-recorder event edge-throttled (a stalled virtual clock
        fires once per drain); the ledger flag rides _take_batch's
        return with the batch whose hold fired it."""
        self._m_flush_failsafe.inc()
        now = time.monotonic()
        if now - self._failsafe_event_last \
                >= self._shed_event_cooldown_s:
            self._failsafe_event_last = now
            self._recorder.record(
                "flush_failsafe", service=self._name,
                failsafe_ms=round(failsafe_s * 1e3, 3),
                flush_deadline_ms=round(
                    plan.flush_deadline_s * 1e3, 3),
                virtual_remaining_ms=round(
                    virtual_remaining_s * 1e3, 3),
                detail="wall clock beat the service clock during the "
                       "batch-fill hold (TEKU_TPU_FLUSH_FAILSAFE_MS)")

    def _assemble(self, first: _Task, budget_triples: int,
                  vip_only: bool = False) -> List[_Task]:
        """Drain up to the batch budget into one batch + stamp
        queue-wait/assembly attribution (strict priority: the pow-2
        plan size keeps the padded dispatch bucket-aligned).
        ``vip_only`` restricts the drain to the VIP deque (the express
        dispatch carries no lower-class lanes)."""
        t_first = time.perf_counter()
        tasks = [first]
        budget = budget_triples - len(first.triples)
        while budget > 0:
            if vip_only:
                nxt = self._queue.pop_class(VerifyClass.VIP)
                if nxt is None:
                    break
            else:
                try:
                    nxt = self._queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
            tasks.append(nxt)
            budget -= len(nxt.triples)
        # drain-side depth sample (triples): the series shows both the
        # burst build-up (enqueue stamps) and the worker's drawdown
        self._telemetry.record_queue_depth(self._queue.triples)
        for c in VerifyClass:
            self._m_class_depth.labels(**{"class": c.label}).set(
                self._queue.depth(c))
        if tracing.enabled():
            # per-task attribution: each task experienced its own
            # queue-wait and the whole batch's assembly time
            assembly = time.perf_counter() - t_first
            for t in tasks:
                trs = (t.trace,) if t.trace is not None else ()
                # exact start offsets: queue_wait began at enqueue,
                # assembly at the drain — the timeline's span tree
                # tiles on these
                tracing.record_stage(
                    "queue_wait", t_first - t.t_enqueue, trs,
                    t0=t.t_enqueue)
                tracing.record_stage("assembly", assembly, trs,
                                     t0=t_first)
        return tasks

    def _dispatch_annotations(self, tasks: List[_Task],
                              plan: Optional[BatchPlan] = None,
                              flush_failsafe: bool = False) -> dict:
        """The admission context the dispatch-ledger record carries:
        the plan that GOVERNED this batch (the worker passes the plan
        it assembled under — re-fetching controller.plan() here could
        tick a brownout edge mid-flight and stamp a mode the batch was
        never admitted under), the batch's verify-class mix, and
        whether the real-time flush failsafe ended the fill hold.
        Bound via dispatchledger.annotate() so asyncio.to_thread
        carries it into the provider's _begin_dispatch.  Bisect
        re-dispatches carry no governing plan and fall back to a
        passive last_plan() read (no tick side effects)."""
        mix: Dict[str, int] = {}
        for t in tasks:
            mix[t.cls.label] = mix.get(t.cls.label, 0) + 1
        ann: dict = {"classes": mix, "service": self._name}
        if plan is None and self.controller is not None:
            try:
                plan = self.controller.last_plan()
            except Exception:  # noqa: BLE001 - annotation must not kill
                plan = None
        if plan is not None:
            ann.update(plan_mode=plan.mode,
                       brownout_level=plan.brownout_level,
                       plan_batch_size=plan.batch_size,
                       flush_deadline_s=plan.flush_deadline_s)
        else:
            ann.update(plan_mode=None, brownout_level=0)
        if flush_failsafe:
            ann["flush_failsafe"] = True
        return ann

    async def _begin(self, tasks: List[_Task],
                     plan: Optional[BatchPlan] = None,
                     flush_failsafe: bool = False):
        """Async-dispatch a batch: host_prep + device enqueue on a
        worker thread.  Returns (handle, t0); handle is None when the
        active implementation has no async path."""
        triples = [tr for t in tasks for tr in t.triples]
        t0 = time.perf_counter()
        with tracing.attach([t.trace for t in tasks]), \
                dispatchledger.annotate(
                    **self._dispatch_annotations(
                        tasks, plan, flush_failsafe)):
            with tracing.span("dispatch"):
                handle = await self._dispatch_in_thread(
                    bls.begin_batch_verify, triples)
        if handle is None:
            return None, t0
        self._m_batches.inc()
        self._m_batch_size.observe(len(triples))
        self._m_dispatches.labels(kind="first_try").inc()
        return handle, t0

    async def _retire(self, tasks: List[_Task], handle, t0) -> None:
        """Synchronize an in-flight dispatch and settle its tasks
        (bisecting failures through the sync path)."""
        try:
            # the handle records the device_enqueue/device_sync spans
            # itself (it
            # captured the batch's traces at dispatch time)
            ok = await self._dispatch_in_thread(handle.result)
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            _LOG.exception("signature batch verification failed")
            for t in tasks:
                self._drop_pending(t)
                t.settle(exc=exc)
            return
        self._m_batch_duration.observe(time.perf_counter() - t0)
        await self._resolve_batch(tasks, ok)

    def _drop_cancelled(self, tasks: List[_Task]) -> List[_Task]:
        """Filter cancelled tasks, releasing their pending-map entries.

        A cancelled PRIMARY with live coalesced waiters does not kill
        the task: the waiters' callers still want the verdict (only the
        original submitter bailed), so the first live waiter is
        promoted to primary — and the task's effective class becomes
        the most urgent SURVIVING waiter's class (a cancelled VIP
        primary must neither strand its GOSSIP waiters nor keep the
        express lane for them)."""
        live = []
        for t in tasks:
            if t.future.cancelled():
                survivors = [(f, c) for f, c in
                             zip(t.waiters, t.waiter_classes)
                             if not f.done()]
                if survivors:
                    t.future = survivors[0][0]
                    t.waiters = [f for f, _ in survivors[1:]]
                    t.waiter_classes = [c for _, c in survivors[1:]]
                    t.cls = min(c for _, c in survivors)
                    live.append(t)
                    continue
                self._drop_pending(t)
            else:
                live.append(t)
        return live

    async def _verify_batch(self, tasks: List[_Task],
                            first_try: bool = True,
                            plan: Optional[BatchPlan] = None,
                            flush_failsafe: bool = False) -> None:
        tasks = self._drop_cancelled(tasks)
        if not tasks:
            return
        triples = [tr for t in tasks for tr in t.triples]
        self._m_batches.inc()
        self._m_batch_size.observe(len(triples))
        self._m_dispatches.labels(
            kind="first_try" if first_try else "bisect").inc()
        # the dispatch runs with the whole batch's traces bound to the
        # context: asyncio.to_thread copies it, so the provider's
        # host_prep/device_enqueue/device_sync spans attribute to
        # every trace
        t0 = time.perf_counter()
        with tracing.attach([t.trace for t in tasks]), \
                dispatchledger.annotate(
                    **self._dispatch_annotations(
                        tasks, plan, flush_failsafe)):
            with tracing.span("dispatch"):
                ok = await self._dispatch_in_thread(
                    bls.batch_verify, triples)
        self._m_batch_duration.observe(time.perf_counter() - t0)
        await self._resolve_batch(tasks, ok)

    async def _resolve_batch(self, tasks: List[_Task], ok: bool) -> None:
        """Post-dispatch settlement: complete on success, bisect on
        failure (shared by the sync and the async-overlap paths)."""
        if ok:
            for t in tasks:
                self._complete(t, True)
            return
        if len(tasks) == 1:
            self._complete(tasks[0], False)
            return
        if len(tasks) >= self.split_threshold:
            half = len(tasks) // 2
            await self._verify_batch(tasks[:half], first_try=False)
            await self._verify_batch(tasks[half:], first_try=False)
        else:
            for t in tasks:
                await self._verify_batch([t], first_try=False)

    def _drop_pending(self, task: _Task) -> None:
        if task.key is not None and self._pending.get(task.key) is task:
            del self._pending[task.key]

    def _complete(self, task: _Task, result: bool) -> None:
        self._m_tasks.inc()
        self._drop_pending(task)
        task.settle(result)
