"""Aggregating signature verification service — the TPU batch scheduler.

Async front-end that converts bursty per-message verification requests
into device-sized batches, preserving the semantics of the reference's
gossip-side batcher (reference: ethereum/statetransition/src/main/java/
tech/pegasys/teku/statetransition/validation/signatures/
AggregatingSignatureVerificationService.java:41-262):

- bounded queue; overflow raises ServiceCapacityExceeded (:146-160);
- worker drain of up to max_batch_size tasks into ONE batch verify
  (:171-205) — here a single TPU dispatch via the provider, whose
  power-of-two padding keeps jit shapes static;
- on batch failure: single task fails; >= split_threshold bisects
  recursively; otherwise tasks verify individually (:213-226);
- multi-signature tasks stay atomic — a task's triples verify together
  or not at all (AsyncBatchBLSSignatureVerifier.java:24-60 grouping);
- queue-size gauge, batch/task counters, batch-size histogram (:76-98).

Deliberate departure from the reference: its workers block up to 30 s
waiting to fill a batch, which is throughput-friendly but latency-naive;
here a worker takes whatever is queued the moment it goes idle (the
dispatch itself provides natural batching back-pressure), optimizing the
attestation-gossip p50 the north star measures.

Two dedup/overlap layers on top of the reference semantics:

- identical in-flight triples coalesce — gossip re-delivers the same
  (pks, msg, sig); duplicates ride the already-pending task and the
  verdict fans out to every waiter (``*_coalesced_total``);
- async overlap — when the BLS implementation exposes the async begin
  seam (bls.begin_batch_verify), a worker host_preps + enqueues batch
  N+1 while batch N executes on device, synchronizing only at verdict
  read (``TEKU_TPU_ASYNC_OVERLAP=0`` disables).
"""

import asyncio
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..crypto import bls
from ..infra import capacity, faults, flightrecorder, tracing
from ..infra.metrics import (GLOBAL_REGISTRY, LATENCY_BUCKETS_S,
                             MetricsRegistry)

Triple = Tuple[Sequence[bytes], bytes, bytes]

_LOG = logging.getLogger(__name__)

# Overlap host_prep of batch N+1 with device execution of batch N: the
# worker begins (host_prep + async device enqueue) the next batch
# BEFORE synchronizing the previous one — JAX async dispatch keeps the
# device busy while the host packs arrays.  Engages only when the
# active BLS implementation exposes an async begin (the raw JAX
# provider; breaker-guarded backends stay sync — the breaker owns its
# dispatch deadline).  TEKU_TPU_ASYNC_OVERLAP=0 disables.
ENV_OVERLAP = "TEKU_TPU_ASYNC_OVERLAP"


def _overlap_default() -> bool:
    return os.environ.get(ENV_OVERLAP, "1") not in ("0", "off", "false")


class ServiceCapacityExceededError(Exception):
    """Queue full — the caller sheds load (gossip IGNORE)."""


@dataclass
class _Task:
    triples: List[Triple]
    future: asyncio.Future = field(repr=False)
    # stamped at enqueue: queue-wait attribution + the caller's root
    # trace (the gossip validator's), so the worker can attribute its
    # stages to the trace that is awaiting this task's future
    t_enqueue: float = 0.0
    trace: Optional[tracing.Trace] = field(default=None, repr=False)
    # in-flight dedup: gossip re-delivers the same (pks, msg, sig) —
    # identical pending triples coalesce onto ONE queued task, and the
    # verdict fans out to every waiter future
    key: Optional[tuple] = None
    waiters: List[asyncio.Future] = field(default_factory=list,
                                          repr=False)

    def settle(self, result: Optional[bool] = None,
               exc: Optional[BaseException] = None) -> None:
        """Resolve the primary future AND every coalesced waiter."""
        for fut in (self.future, *self.waiters):
            if fut.done():
                continue
            if exc is not None:
                fut.set_exception(exc)
            else:
                fut.set_result(result)


class AggregatingSignatureVerificationService:
    """Queue/drain/dispatch batch verifier over the pluggable BLS SPI."""

    def __init__(self, num_workers: int = 2, queue_capacity: int = 15_000,
                 max_batch_size: int = 250, split_threshold: int = 25,
                 registry: MetricsRegistry = GLOBAL_REGISTRY,
                 name: str = "signature_verifications",
                 overlap: Optional[bool] = None):
        if num_workers < 1:
            raise ValueError("need at least one worker")
        self.num_workers = num_workers
        self._name = name
        self.overlap = _overlap_default() if overlap is None else overlap
        self.queue_capacity = queue_capacity
        self.max_batch_size = max_batch_size
        self.split_threshold = split_threshold
        # Genuinely bounded, like the reference's ArrayBlockingQueue.offer
        # (AggregatingSignatureVerificationService.java:146-160): put_nowait
        # on a full queue raises QueueFull -> capacity-exceeded, so
        # concurrent producers cannot overshoot the capacity.
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=queue_capacity)
        self._workers: List[asyncio.Task] = []
        self._started = False
        self._stopped = False
        self._m_queue = registry.gauge(
            f"{name}_queue_size", "pending verification tasks",
            supplier=lambda: self._queue.qsize())
        self._m_batches = registry.counter(
            f"{name}_batch_count_total", "batches dispatched")
        self._m_tasks = registry.counter(
            f"{name}_task_count_total", "tasks completed")
        self._m_batch_size = registry.histogram(
            f"{name}_batch_size", "signatures per dispatched batch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
        # batch LATENCY next to batch size: a regressed p50 with a flat
        # size distribution points at the dispatch, not the batching
        self._m_batch_duration = registry.histogram(
            f"{name}_batch_duration_seconds",
            "wall seconds per batch dispatch (device call inclusive)",
            buckets=LATENCY_BUCKETS_S)
        # first-try vs bisect-recursion dispatches: the failure path
        # amplifies one bad batch into O(log n) extra device calls, and
        # that amplification used to be invisible
        self._m_dispatches = registry.labeled_counter(
            f"{name}_dispatch_total",
            "batch dispatches by kind (first_try vs bisect recursion)",
            labelnames=("kind",))
        # overflow shedding used to be invisible in metrics: a node
        # rejecting gossip under load looked identical to a healthy one
        self._m_rejected = registry.counter(
            f"{name}_rejected_total",
            "tasks shed because the queue was at capacity")
        # gossip re-delivery dedup: each coalesced submission rode an
        # already-pending identical task instead of a fresh lane
        self._m_coalesced = registry.counter(
            f"{name}_coalesced_total",
            "duplicate in-flight submissions coalesced onto a pending "
            "identical task")
        # identical-triples key -> the pending task carrying it (entries
        # removed when the task settles; all on the event loop, no lock)
        self._pending: Dict[tuple, _Task] = {}
        # (queue saturation is served by health_snapshot() / the
        # readiness endpoint, not a supplier gauge: get_or_create would
        # pin the family to the FIRST service instance's closure)
        # worker liveness: monotonic stamp of the last time ANY worker
        # made progress (took or finished a batch) — queued work plus a
        # stale stamp is the signature of every worker wedged in a
        # dispatch, which no throughput counter can distinguish from
        # simple idleness
        self._last_worker_progress = time.monotonic()

    # ------------------------------------------------------------------
    async def start(self) -> None:
        if self._started:
            return
        self._started = True
        for i in range(self.num_workers):
            self._workers.append(
                asyncio.create_task(self._worker(), name=f"sig-verify-{i}"))

    async def stop(self) -> None:
        self._stopped = True
        for w in self._workers:
            w.cancel()
        for w in self._workers:
            try:
                await w
            except asyncio.CancelledError:
                pass
        self._workers.clear()
        # Fail tasks still in the queue so callers never hang on shutdown.
        while True:
            try:
                task = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            for fut in (task.future, *task.waiters):
                if not fut.done():
                    fut.cancel()
        self._pending.clear()

    # ------------------------------------------------------------------
    def verify(self, public_keys: Sequence[bytes], message: bytes,
               signature: bytes) -> "asyncio.Future[bool]":
        """Queue one fast-aggregate triple; resolves with the verdict."""
        return self.verify_multi([(public_keys, message, signature)])

    @staticmethod
    def _task_key(triples: Sequence[Triple]) -> tuple:
        return tuple((tuple(pks), msg, sig) for pks, msg, sig in triples)

    def verify_multi(self, triples: Sequence[Triple]
                     ) -> "asyncio.Future[bool]":
        """Queue several triples as ONE atomic task (e.g. the three
        signatures of a SignedAggregateAndProof verify together).

        Identical in-flight submissions coalesce: gossip re-delivers
        the same (pks, msg, sig), and re-verifying a triple that is
        already pending wastes a lane — the duplicate rides the pending
        task and its future resolves with the same verdict."""
        if not self._started or self._stopped:
            raise RuntimeError("service not running")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        key = self._task_key(triples)
        pending = self._pending.get(key)
        if pending is not None and not pending.future.cancelled():
            pending.waiters.append(fut)
            self._m_coalesced.inc()
            return fut
        # capacity input: demand is OFFERED load — a shed arrival is
        # still demand (counting only accepted work would read
        # utilization low during exactly the overload the headroom-
        # exhausted event exists to flag)
        capacity.record_arrival(self._name, len(triples))
        try:
            # `sigservice.enqueue` fault site: Overflow injection proves
            # the shed path (metrics + WARN) without a 15k-deep queue
            faults.check("sigservice.enqueue")
            task = _Task(
                list(triples), fut, t_enqueue=time.perf_counter(),
                trace=tracing.current_trace(), key=key)
            self._queue.put_nowait(task)
            self._pending[key] = task
            # the queue-depth time series the admin endpoint serves
            capacity.record_queue_depth(self._queue.qsize())
        except asyncio.QueueFull:
            self._m_rejected.inc()
            capacity.record_shed(len(triples))
            flightrecorder.record(
                "queue_shed", service=self._name,
                queue_size=self._queue.qsize(),
                capacity=self.queue_capacity, triples=len(triples))
            _LOG.warning(
                "signature verification queue at capacity "
                "(%d/%d pending) — shedding task (%d triples)",
                self._queue.qsize(), self.queue_capacity, len(triples))
            raise ServiceCapacityExceededError(
                f"queue at capacity ({self.queue_capacity})") from None
        return fut

    def health_snapshot(self) -> dict:
        """Queue + worker liveness for `infra/health.py`'s check:
        `stalled_s` is nonzero only while tasks are QUEUED with no
        worker progress — an idle service never reads as stalled."""
        qsize = self._queue.qsize()
        stalled_s = 0.0
        if qsize > 0 and self._started and not self._stopped:
            stalled_s = max(
                0.0, time.monotonic() - self._last_worker_progress)
        return {"queue_size": qsize,
                "capacity": self.queue_capacity,
                "saturation": qsize / self.queue_capacity,
                "workers": len(self._workers),
                "stalled_s": stalled_s,
                # the derived capacity signals (arrival rate,
                # utilization, headroom, occupancy) the SLO engine and
                # the future adaptive batcher consume — full per-shape
                # detail lives on /teku/v1/admin/capacity
                "capacity_model": capacity.summary()}

    # ------------------------------------------------------------------
    async def _worker(self) -> None:
        # At most ONE in-flight async dispatch per worker: batch N
        # executes on device while this loop assembles and host_preps
        # batch N+1 (bls.begin_batch_verify), then retires N.  The
        # overlap only defers the SYNC, so when the queue is empty the
        # in-flight batch retires immediately — no added latency.
        inflight: Optional[tuple] = None
        try:
            while not self._stopped:
                if inflight is not None:
                    try:
                        first = self._queue.get_nowait()
                    except asyncio.QueueEmpty:
                        prev, inflight = inflight, None
                        await self._retire(*prev)
                        continue
                else:
                    first = await self._queue.get()
                self._last_worker_progress = time.monotonic()
                tasks = self._drop_cancelled(self._assemble(first))
                if not tasks:
                    continue
                try:
                    handle = t0 = None
                    if self.overlap and bls.supports_async_verify():
                        handle, t0 = await self._begin(tasks)
                    if handle is None:
                        # sync path: implementation has no async seam
                        if inflight is not None:
                            prev, inflight = inflight, None
                            await self._retire(*prev)
                        await self._verify_batch(tasks)
                    else:
                        prev, inflight = inflight, (tasks, handle, t0)
                        if prev is not None:
                            await self._retire(*prev)
                except asyncio.CancelledError:
                    raise
                except Exception as exc:  # provider/JAX runtime error
                    # The worker must survive (the reference at least
                    # logs worker death, doStart .finish(err ->
                    # LOG.error)); fail the affected futures so callers
                    # never await forever.
                    _LOG.exception("signature batch verification failed")
                    for t in tasks:
                        self._drop_pending(t)
                        t.settle(exc=exc)
                finally:
                    self._last_worker_progress = time.monotonic()
        finally:
            # shutdown/cancellation with a batch still in flight: never
            # leave its callers awaiting forever
            if inflight is not None:
                for t in inflight[0]:
                    self._drop_pending(t)
                    for fut in (t.future, *t.waiters):
                        if not fut.done():
                            fut.cancel()

    def _assemble(self, first: _Task) -> List[_Task]:
        """Drain up to max_batch_size triples into one batch + stamp
        queue-wait/assembly attribution."""
        t_first = time.perf_counter()
        tasks = [first]
        budget = self.max_batch_size - len(first.triples)
        while budget > 0:
            try:
                nxt = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            tasks.append(nxt)
            budget -= len(nxt.triples)
        # drain-side depth sample: the series shows both the burst
        # build-up (enqueue stamps) and the worker's drawdown
        capacity.record_queue_depth(self._queue.qsize())
        if tracing.enabled():
            # per-task attribution: each task experienced its own
            # queue-wait and the whole batch's assembly time
            assembly = time.perf_counter() - t_first
            for t in tasks:
                trs = (t.trace,) if t.trace is not None else ()
                tracing.record_stage(
                    "queue_wait", t_first - t.t_enqueue, trs)
                tracing.record_stage("assembly", assembly, trs)
        return tasks

    async def _begin(self, tasks: List[_Task]):
        """Async-dispatch a batch: host_prep + device enqueue on a
        worker thread.  Returns (handle, t0); handle is None when the
        active implementation has no async path."""
        triples = [tr for t in tasks for tr in t.triples]
        t0 = time.perf_counter()
        with tracing.attach([t.trace for t in tasks]):
            with tracing.span("dispatch"):
                handle = await asyncio.to_thread(
                    bls.begin_batch_verify, triples)
        if handle is None:
            return None, t0
        self._m_batches.inc()
        self._m_batch_size.observe(len(triples))
        self._m_dispatches.labels(kind="first_try").inc()
        return handle, t0

    async def _retire(self, tasks: List[_Task], handle, t0) -> None:
        """Synchronize an in-flight dispatch and settle its tasks
        (bisecting failures through the sync path)."""
        try:
            # the handle records the device_enqueue/device_sync spans
            # itself (it
            # captured the batch's traces at dispatch time)
            ok = await asyncio.to_thread(handle.result)
        except asyncio.CancelledError:
            raise
        except Exception as exc:
            _LOG.exception("signature batch verification failed")
            for t in tasks:
                self._drop_pending(t)
                t.settle(exc=exc)
            return
        self._m_batch_duration.observe(time.perf_counter() - t0)
        await self._resolve_batch(tasks, ok)

    def _drop_cancelled(self, tasks: List[_Task]) -> List[_Task]:
        """Filter cancelled tasks, releasing their pending-map entries.

        A cancelled PRIMARY with live coalesced waiters does not kill
        the task: the waiters' callers still want the verdict (only the
        original submitter bailed), so the first live waiter is
        promoted to primary and the task verifies normally."""
        live = []
        for t in tasks:
            if t.future.cancelled():
                survivors = [f for f in t.waiters if not f.done()]
                if survivors:
                    t.future, t.waiters = survivors[0], survivors[1:]
                    live.append(t)
                    continue
                self._drop_pending(t)
            else:
                live.append(t)
        return live

    async def _verify_batch(self, tasks: List[_Task],
                            first_try: bool = True) -> None:
        tasks = self._drop_cancelled(tasks)
        if not tasks:
            return
        triples = [tr for t in tasks for tr in t.triples]
        self._m_batches.inc()
        self._m_batch_size.observe(len(triples))
        self._m_dispatches.labels(
            kind="first_try" if first_try else "bisect").inc()
        # the dispatch runs with the whole batch's traces bound to the
        # context: asyncio.to_thread copies it, so the provider's
        # host_prep/device_enqueue/device_sync spans attribute to
        # every trace
        t0 = time.perf_counter()
        with tracing.attach([t.trace for t in tasks]):
            with tracing.span("dispatch"):
                ok = await asyncio.to_thread(bls.batch_verify, triples)
        self._m_batch_duration.observe(time.perf_counter() - t0)
        await self._resolve_batch(tasks, ok)

    async def _resolve_batch(self, tasks: List[_Task], ok: bool) -> None:
        """Post-dispatch settlement: complete on success, bisect on
        failure (shared by the sync and the async-overlap paths)."""
        if ok:
            for t in tasks:
                self._complete(t, True)
            return
        if len(tasks) == 1:
            self._complete(tasks[0], False)
            return
        if len(tasks) >= self.split_threshold:
            half = len(tasks) // 2
            await self._verify_batch(tasks[:half], first_try=False)
            await self._verify_batch(tasks[half:], first_try=False)
        else:
            for t in tasks:
                await self._verify_batch([t], first_try=False)

    def _drop_pending(self, task: _Task) -> None:
        if task.key is not None and self._pending.get(task.key) is task:
            del self._pending[task.key]

    def _complete(self, task: _Task, result: bool) -> None:
        self._m_tasks.inc()
        self._drop_pending(task)
        task.settle(result)
