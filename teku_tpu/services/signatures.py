"""Aggregating signature verification service — the TPU batch scheduler.

Async front-end that converts bursty per-message verification requests
into device-sized batches, preserving the semantics of the reference's
gossip-side batcher (reference: ethereum/statetransition/src/main/java/
tech/pegasys/teku/statetransition/validation/signatures/
AggregatingSignatureVerificationService.java:41-262):

- bounded queue; overflow raises ServiceCapacityExceeded (:146-160);
- worker drain of up to max_batch_size tasks into ONE batch verify
  (:171-205) — here a single TPU dispatch via the provider, whose
  power-of-two padding keeps jit shapes static;
- on batch failure: single task fails; >= split_threshold bisects
  recursively; otherwise tasks verify individually (:213-226);
- multi-signature tasks stay atomic — a task's triples verify together
  or not at all (AsyncBatchBLSSignatureVerifier.java:24-60 grouping);
- queue-size gauge, batch/task counters, batch-size histogram (:76-98).

Deliberate departure from the reference: its workers block up to 30 s
waiting to fill a batch, which is throughput-friendly but latency-naive;
here a worker takes whatever is queued the moment it goes idle (the
dispatch itself provides natural batching back-pressure), optimizing the
attestation-gossip p50 the north star measures.
"""

import asyncio
import logging
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

from ..crypto import bls
from ..infra import faults, flightrecorder, tracing
from ..infra.metrics import (GLOBAL_REGISTRY, LATENCY_BUCKETS_S,
                             MetricsRegistry)

Triple = Tuple[Sequence[bytes], bytes, bytes]

_LOG = logging.getLogger(__name__)


class ServiceCapacityExceededError(Exception):
    """Queue full — the caller sheds load (gossip IGNORE)."""


@dataclass
class _Task:
    triples: List[Triple]
    future: asyncio.Future = field(repr=False)
    # stamped at enqueue: queue-wait attribution + the caller's root
    # trace (the gossip validator's), so the worker can attribute its
    # stages to the trace that is awaiting this task's future
    t_enqueue: float = 0.0
    trace: Optional[tracing.Trace] = field(default=None, repr=False)


class AggregatingSignatureVerificationService:
    """Queue/drain/dispatch batch verifier over the pluggable BLS SPI."""

    def __init__(self, num_workers: int = 2, queue_capacity: int = 15_000,
                 max_batch_size: int = 250, split_threshold: int = 25,
                 registry: MetricsRegistry = GLOBAL_REGISTRY,
                 name: str = "signature_verifications"):
        if num_workers < 1:
            raise ValueError("need at least one worker")
        self.num_workers = num_workers
        self._name = name
        self.queue_capacity = queue_capacity
        self.max_batch_size = max_batch_size
        self.split_threshold = split_threshold
        # Genuinely bounded, like the reference's ArrayBlockingQueue.offer
        # (AggregatingSignatureVerificationService.java:146-160): put_nowait
        # on a full queue raises QueueFull -> capacity-exceeded, so
        # concurrent producers cannot overshoot the capacity.
        self._queue: asyncio.Queue = asyncio.Queue(maxsize=queue_capacity)
        self._workers: List[asyncio.Task] = []
        self._started = False
        self._stopped = False
        self._m_queue = registry.gauge(
            f"{name}_queue_size", "pending verification tasks",
            supplier=lambda: self._queue.qsize())
        self._m_batches = registry.counter(
            f"{name}_batch_count_total", "batches dispatched")
        self._m_tasks = registry.counter(
            f"{name}_task_count_total", "tasks completed")
        self._m_batch_size = registry.histogram(
            f"{name}_batch_size", "signatures per dispatched batch",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512))
        # batch LATENCY next to batch size: a regressed p50 with a flat
        # size distribution points at the dispatch, not the batching
        self._m_batch_duration = registry.histogram(
            f"{name}_batch_duration_seconds",
            "wall seconds per batch dispatch (device call inclusive)",
            buckets=LATENCY_BUCKETS_S)
        # first-try vs bisect-recursion dispatches: the failure path
        # amplifies one bad batch into O(log n) extra device calls, and
        # that amplification used to be invisible
        self._m_dispatches = registry.labeled_counter(
            f"{name}_dispatch_total",
            "batch dispatches by kind (first_try vs bisect recursion)",
            labelnames=("kind",))
        # overflow shedding used to be invisible in metrics: a node
        # rejecting gossip under load looked identical to a healthy one
        self._m_rejected = registry.counter(
            f"{name}_rejected_total",
            "tasks shed because the queue was at capacity")
        # (queue saturation is served by health_snapshot() / the
        # readiness endpoint, not a supplier gauge: get_or_create would
        # pin the family to the FIRST service instance's closure)
        # worker liveness: monotonic stamp of the last time ANY worker
        # made progress (took or finished a batch) — queued work plus a
        # stale stamp is the signature of every worker wedged in a
        # dispatch, which no throughput counter can distinguish from
        # simple idleness
        self._last_worker_progress = time.monotonic()

    # ------------------------------------------------------------------
    async def start(self) -> None:
        if self._started:
            return
        self._started = True
        for i in range(self.num_workers):
            self._workers.append(
                asyncio.create_task(self._worker(), name=f"sig-verify-{i}"))

    async def stop(self) -> None:
        self._stopped = True
        for w in self._workers:
            w.cancel()
        for w in self._workers:
            try:
                await w
            except asyncio.CancelledError:
                pass
        self._workers.clear()
        # Fail tasks still in the queue so callers never hang on shutdown.
        while True:
            try:
                task = self._queue.get_nowait()
            except asyncio.QueueEmpty:
                break
            if not task.future.done():
                task.future.cancel()

    # ------------------------------------------------------------------
    def verify(self, public_keys: Sequence[bytes], message: bytes,
               signature: bytes) -> "asyncio.Future[bool]":
        """Queue one fast-aggregate triple; resolves with the verdict."""
        return self.verify_multi([(public_keys, message, signature)])

    def verify_multi(self, triples: Sequence[Triple]
                     ) -> "asyncio.Future[bool]":
        """Queue several triples as ONE atomic task (e.g. the three
        signatures of a SignedAggregateAndProof verify together)."""
        if not self._started or self._stopped:
            raise RuntimeError("service not running")
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        try:
            # `sigservice.enqueue` fault site: Overflow injection proves
            # the shed path (metrics + WARN) without a 15k-deep queue
            faults.check("sigservice.enqueue")
            self._queue.put_nowait(_Task(
                list(triples), fut, t_enqueue=time.perf_counter(),
                trace=tracing.current_trace()))
        except asyncio.QueueFull:
            self._m_rejected.inc()
            flightrecorder.record(
                "queue_shed", service=self._name,
                queue_size=self._queue.qsize(),
                capacity=self.queue_capacity, triples=len(triples))
            _LOG.warning(
                "signature verification queue at capacity "
                "(%d/%d pending) — shedding task (%d triples)",
                self._queue.qsize(), self.queue_capacity, len(triples))
            raise ServiceCapacityExceededError(
                f"queue at capacity ({self.queue_capacity})") from None
        return fut

    def health_snapshot(self) -> dict:
        """Queue + worker liveness for `infra/health.py`'s check:
        `stalled_s` is nonzero only while tasks are QUEUED with no
        worker progress — an idle service never reads as stalled."""
        qsize = self._queue.qsize()
        stalled_s = 0.0
        if qsize > 0 and self._started and not self._stopped:
            stalled_s = max(
                0.0, time.monotonic() - self._last_worker_progress)
        return {"queue_size": qsize,
                "capacity": self.queue_capacity,
                "saturation": qsize / self.queue_capacity,
                "workers": len(self._workers),
                "stalled_s": stalled_s}

    # ------------------------------------------------------------------
    async def _worker(self) -> None:
        while not self._stopped:
            first = await self._queue.get()
            self._last_worker_progress = time.monotonic()
            t_first = time.perf_counter()
            tasks = [first]
            budget = self.max_batch_size - len(first.triples)
            while budget > 0:
                try:
                    nxt = self._queue.get_nowait()
                except asyncio.QueueEmpty:
                    break
                tasks.append(nxt)
                budget -= len(nxt.triples)
            t_assembled = time.perf_counter()
            if tracing.enabled():
                # per-task attribution: each task experienced its own
                # queue-wait and the whole batch's assembly time
                assembly = t_assembled - t_first
                for t in tasks:
                    trs = (t.trace,) if t.trace is not None else ()
                    tracing.record_stage(
                        "queue_wait", t_first - t.t_enqueue, trs)
                    tracing.record_stage("assembly", assembly, trs)
            try:
                await self._verify_batch(tasks)
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # provider/JAX runtime error
                # The worker must survive (the reference at least logs
                # worker death, doStart .finish(err -> LOG.error)); fail
                # the affected futures so callers never await forever.
                _LOG.exception("signature batch verification failed")
                for t in tasks:
                    if not t.future.done():
                        t.future.set_exception(exc)
            finally:
                self._last_worker_progress = time.monotonic()

    async def _verify_batch(self, tasks: List[_Task],
                            first_try: bool = True) -> None:
        tasks = [t for t in tasks if not t.future.cancelled()]
        if not tasks:
            return
        triples = [tr for t in tasks for tr in t.triples]
        self._m_batches.inc()
        self._m_batch_size.observe(len(triples))
        self._m_dispatches.labels(
            kind="first_try" if first_try else "bisect").inc()
        # the dispatch runs with the whole batch's traces bound to the
        # context: asyncio.to_thread copies it, so the provider's
        # host_prep/device_execute spans attribute to every trace
        t0 = time.perf_counter()
        with tracing.attach([t.trace for t in tasks]):
            with tracing.span("dispatch"):
                ok = await asyncio.to_thread(bls.batch_verify, triples)
        self._m_batch_duration.observe(time.perf_counter() - t0)
        if ok:
            for t in tasks:
                self._complete(t, True)
            return
        if len(tasks) == 1:
            self._complete(tasks[0], False)
            return
        if len(tasks) >= self.split_threshold:
            half = len(tasks) // 2
            await self._verify_batch(tasks[:half], first_try=False)
            await self._verify_batch(tasks[half:], first_try=False)
        else:
            for t in tasks:
                await self._verify_batch([t], first_try=False)

    def _complete(self, task: _Task, result: bool) -> None:
        self._m_tasks.inc()
        if not task.future.done():
            task.future.set_result(result)
