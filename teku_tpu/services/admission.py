"""Overload control: priority classes, adaptive batching, brownout.

ROADMAP item 3 closes the loop the previous PRs instrumented: PR 2 gave
per-stage latency, PR 3 the SLO burn-rate engine, PR 6 the capacity
model (per-shape device latency, utilization/headroom).  This module is
the controller that acts on those sensors so the node HOLDS its 100 ms
attestation-verify p50 under 10x sustained load instead of collapsing
(ACE Runtime, PAPERS.md: sub-second cryptographic finality as a runtime
property enforced by feedback control).

Three pieces:

- ``VerifyClass`` — the priority vocabulary every verification carries:
  ``VIP > BLOCK_IMPORT > SYNC_CRITICAL > GOSSIP > OPTIMISTIC``.  VIP is
  the single-signature express lane (a block's proposer signature gates
  the whole slot): it bypasses aggregation entirely — a VIP task is
  dispatched ALONE the moment a worker sees it.  Classes are a closed
  set on purpose: they are also metric label values, and the
  exposition's cardinality must stay bounded.
- ``AdmissionController`` — per-tick feedback controller producing a
  ``BatchPlan``: the drain target (pow-2 bucket-aligned, so padded
  dispatch shapes match the shapes the latency model already measured
  and padding waste stays low), the flush deadline (how long a worker
  may wait to fill a batch — zero when latency-optimal, nonzero only
  when utilization says throughput is the constraint), and the brownout
  level.  Inputs: live queue depth, the per-``{shape,path}``
  ``ShapeLatencyModel`` (the modeled device time of each candidate
  pow-2 batch), capacity-model utilization, and the
  ``attestation_verify_p50`` burn rate.
- Brownout state machine — EDGE-TRIGGERED and HYSTERETIC: entry at
  ``utilization >= UTIL_ENTER`` or ``burn >= BURN_ENTER`` (level 1
  sheds OPTIMISTIC; escalation thresholds raise it to level 2 which
  also sheds GOSSIP by oldest deadline), exit only after the signals
  have stayed below the LOWER exit thresholds for ``HOLD_TICKS``
  consecutive ticks — a controller oscillating around one threshold
  cannot flap.  Load that settles BETWEEN the exit and enter bands
  de-escalates one level per hold window instead of pinning the
  spike's level forever.  Every transition records one
  flight-recorder event with the originating trace id.  BLOCK_IMPORT
  and VIP are never shed.

The batching service (``services/signatures.py``) consumes the plan at
enqueue (admission control) and drain (batch assembly) time; the node
health tick keeps the controller evaluating while the queue is idle.

Knobs (env, documented in README "Overload & priority classes"):
``TEKU_TPU_ADMISSION_TICK_S``, ``TEKU_TPU_BROWNOUT_UTIL_ENTER`` /
``_EXIT``, ``TEKU_TPU_BROWNOUT_BURN_ENTER`` / ``_EXIT``,
``TEKU_TPU_BROWNOUT_HOLD_TICKS``, ``TEKU_TPU_ADMISSION_DEVICE_BUDGET``,
``TEKU_TPU_VERIFY_CLASS_<CLASS>_DEADLINE_MS``.
"""

import enum
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

from ..infra import capacity, flightrecorder, timeline, tracing
from ..infra.env import env_float, env_int
from ..infra.metrics import GLOBAL_REGISTRY, MetricsRegistry

_LOG = logging.getLogger(__name__)


class VerifyClass(enum.IntEnum):
    """Priority of one verification task; LOWER value = drained first.

    The enum is the complete label vocabulary for every per-class
    metric family (``{class}`` label) — adding a member here is the
    only way the cardinality can grow."""

    VIP = 0             # single-sig express lane, bypasses aggregation
    BLOCK_IMPORT = 1    # gates block import — never shed
    SYNC_CRITICAL = 2   # aggregates/sync-weight — never shed
    GOSSIP = 3          # ordinary gossip — shed under level-2 brownout
    OPTIMISTIC = 4      # speculative re-validation — shed first

    @property
    def label(self) -> str:
        return self.name.lower()


# shed order under pressure; everything else is NEVER shed
SHEDDABLE = (VerifyClass.OPTIMISTIC, VerifyClass.GOSSIP)

CLASS_LABELS = tuple(c.label for c in VerifyClass)


# per-class latency deadlines: the budget a task of that class has from
# enqueue to verdict before a brownout shed considers it already lost
# (oldest-deadline-first shedding drops the tasks least likely to make
# their SLO, not the freshest arrivals)
_DEADLINE_DEFAULT_MS = {
    VerifyClass.VIP: 50.0,
    VerifyClass.BLOCK_IMPORT: 1000.0,
    VerifyClass.SYNC_CRITICAL: 250.0,
    VerifyClass.GOSSIP: 100.0,
    VerifyClass.OPTIMISTIC: 400.0,
}


def class_deadline_s(cls: VerifyClass) -> float:
    """The class's enqueue-to-verdict deadline budget in seconds
    (``TEKU_TPU_VERIFY_CLASS_<CLASS>_DEADLINE_MS`` overrides)."""
    return env_float(
        f"TEKU_TPU_VERIFY_CLASS_{cls.name}_DEADLINE_MS",
        _DEADLINE_DEFAULT_MS[cls]) / 1e3


from ..infra.pow2 import next_pow2 as _next_pow2  # noqa: E402 - the
# shared padding rule (provider bucketing and the mesh shard planner
# use the same definition)


@dataclass(frozen=True)
class BatchPlan:
    """One tick's output: what the drain loop should do right now."""

    batch_size: int            # pow-2 drain target (triples)
    flush_deadline_s: float    # max wait to fill a batch (0 = none)
    brownout_level: int        # 0 none | 1 shed OPTIMISTIC | 2 +GOSSIP
    utilization: float = 0.0
    burn_rate: float = 0.0
    modeled_batch_s: Optional[float] = None  # device time at batch_size
    # which rule sized the batch: "latency" (smallest pow-2 covering
    # the queue) or "throughput" (largest fit under the device budget)
    # — the dispatch ledger's plan_mode decision label
    mode: str = "latency"

    def sheds(self, cls: VerifyClass) -> bool:
        """Does the current brownout level shed this class?"""
        if self.brownout_level >= 1 and cls is VerifyClass.OPTIMISTIC:
            return True
        if self.brownout_level >= 2 and cls is VerifyClass.GOSSIP:
            return True
        return False


class AdmissionController:
    """Deadline-aware adaptive batching + shed-by-class brownout.

    ``plan()`` is the hot-path read: it lazily re-ticks when the last
    evaluation is older than ``tick_s`` (the worker drain loop and the
    enqueue path both call it, so the controller stays fresh exactly as
    fast as traffic moves; the node health tick covers the idle case).
    The clock is injectable so every control decision is deterministic
    under test."""

    def __init__(self,
                 telemetry: Optional[capacity.CapacityTelemetry] = None,
                 burn_getter: Optional[Callable[[], float]] = None,
                 min_bucket: int = 8, max_batch: int = 256,
                 slo_p50_s: Optional[float] = None,
                 tick_s: Optional[float] = None,
                 hold_ticks: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 registry: MetricsRegistry = GLOBAL_REGISTRY,
                 recorder: Optional[flightrecorder.FlightRecorder]
                 = None,
                 name: str = "node"):
        self.telemetry = telemetry or capacity.TELEMETRY
        self.burn_getter = burn_getter or (lambda: 0.0)
        self.min_bucket = max(1, _next_pow2(min_bucket))
        self.max_batch = max(self.min_bucket, _next_pow2(max_batch))
        self.slo_p50_s = (slo_p50_s if slo_p50_s is not None else
                          env_float("TEKU_TPU_SLO_VERIFY_P50_MS",
                                     100.0) / 1e3)
        self.tick_s = (tick_s if tick_s is not None else
                       env_float("TEKU_TPU_ADMISSION_TICK_S", 0.5))
        # the fraction of the p50 SLO one device dispatch may consume:
        # queue wait + host prep need the rest of the budget
        self.device_budget_s = self.slo_p50_s * env_float(
            "TEKU_TPU_ADMISSION_DEVICE_BUDGET", 0.5)
        self.util_enter = env_float("TEKU_TPU_BROWNOUT_UTIL_ENTER", 1.0)
        self.util_exit = env_float("TEKU_TPU_BROWNOUT_UTIL_EXIT", 0.7)
        self.burn_enter = env_float("TEKU_TPU_BROWNOUT_BURN_ENTER", 1.5)
        self.burn_exit = env_float("TEKU_TPU_BROWNOUT_BURN_EXIT", 0.8)
        self.hold_ticks = max(1, hold_ticks if hold_ticks is not None
                              else env_int(
                                  "TEKU_TPU_BROWNOUT_HOLD_TICKS", 3))
        # utilization at which a worker starts WAITING to fill batches
        # (below it, latency wins: dispatch whatever is queued)
        self.gather_util = env_float("TEKU_TPU_ADMISSION_GATHER_UTIL",
                                      0.6)
        self._clock = clock
        self._recorder = recorder or flightrecorder.RECORDER
        self.name = name
        self._lock = threading.Lock()
        self._level = 0
        self._calm_ticks = 0
        self._deesc_ticks = 0
        self._ticks = 0
        self._enters = 0
        self._exits = 0
        self._deescalations = 0
        self._last_tick_t: Optional[float] = None
        self._plan = BatchPlan(batch_size=self.max_batch,
                               flush_deadline_s=0.0, brownout_level=0)
        # families are prefixed with the controller's name, like the
        # signature service's: a multi-node process (devnet) must not
        # silently collapse every node onto node0's gauges
        self._m_batch = registry.gauge(
            f"{name}_admission_batch_size",
            "current adaptive drain target (pow-2 triples per batch)",
            supplier=lambda: float(self._plan.batch_size))
        self._m_flush = registry.gauge(
            f"{name}_admission_flush_deadline_seconds",
            "current max wait to fill a batch before flushing",
            supplier=lambda: self._plan.flush_deadline_s)
        self._m_level = registry.gauge(
            f"{name}_admission_brownout_level",
            "0 = normal, 1 = shedding OPTIMISTIC, 2 = also shedding "
            "GOSSIP by oldest deadline",
            supplier=lambda: float(self._level))
        self._m_transitions = registry.labeled_counter(
            f"{name}_admission_brownout_transitions_total",
            "edge-triggered brownout state changes",
            labelnames=("direction",))

    # ------------------------------------------------------------------
    def plan(self) -> BatchPlan:
        """The current plan, re-ticking lazily when stale."""
        now = self._clock()
        with self._lock:
            fresh = (self._last_tick_t is not None
                     and now - self._last_tick_t < self.tick_s)
        if fresh:
            return self._plan
        return self.tick()

    def last_plan(self) -> BatchPlan:
        """The most recently computed plan, with NO lazy re-tick — a
        passive read for observability annotation (plan() may run the
        brownout edge logic as a side effect)."""
        with self._lock:
            return self._plan

    def tick(self) -> BatchPlan:
        """Recompute the plan from the live sensors and run the
        brownout edge logic.  Cheap enough for every drain."""
        util = self.telemetry.utilization()
        try:
            burn = float(self.burn_getter() or 0.0)
        except Exception:  # noqa: BLE001 - a sick sensor reads calm
            burn = 0.0
        depth = self.telemetry.queue_depth.current
        size, modeled, mode = self._pick_batch(depth, util, burn)
        flush = self._pick_flush(depth, size, util)
        with self._lock:
            self._ticks += 1
            level = self._brownout_edge_locked(util, burn)
            self._plan = BatchPlan(
                batch_size=size, flush_deadline_s=flush,
                brownout_level=level, utilization=round(util, 4),
                burn_rate=round(burn, 4), modeled_batch_s=modeled,
                mode=mode)
            self._last_tick_t = self._clock()
            return self._plan

    # ------------------------------------------------------------------
    def _fit_batch(self) -> int:
        """Largest pow-2 batch whose MODELED device time fits the
        per-dispatch latency budget (no shape evidence = max_batch:
        until the model has data there is nothing to act on)."""
        b = self.max_batch
        while b > self.min_bucket:
            lat = self.telemetry.latency.latency_for_lanes(b)
            if lat is None or lat <= self.device_budget_s:
                break
            b //= 2
        return b

    def _pick_batch(self, depth: int, util: float,
                    burn: float) -> tuple:
        fit = self._fit_batch()
        if util >= self.gather_util or burn > 1.0:
            # throughput mode: queueing dominates latency, so drain the
            # largest batch that still fits the device budget — fewer
            # dispatch overheads raise sustainable capacity
            size, mode = fit, "throughput"
        else:
            # latency mode: smallest pow-2 covering what is queued cuts
            # padding waste without adding wait
            size = min(fit, max(self.min_bucket,
                                _next_pow2(max(depth, 1))))
            mode = "latency"
        return (size, self.telemetry.latency.latency_for_lanes(size),
                mode)

    def _pick_flush(self, depth: int, size: int, util: float) -> float:
        """How long a worker may hold a partial batch open.  Only under
        pressure (filling batches raises capacity), bounded by the
        time demand needs to supply the missing triples and by half the
        remaining latency budget."""
        if util < self.gather_util or depth >= size:
            return 0.0
        demand = self.telemetry.demand_sigs_per_second()
        if demand <= 0:
            return 0.0
        return round(min((size - depth) / demand,
                         self.device_budget_s * 0.5), 6)

    # ------------------------------------------------------------------
    def _brownout_edge_locked(self, util: float, burn: float) -> int:
        """Edge-triggered, hysteretic brownout transitions (caller
        holds the lock)."""
        target = 0
        if util >= self.util_enter or burn >= self.burn_enter:
            target = 1
        if util >= self.util_enter * 1.5 or burn >= self.burn_enter * 2:
            target = 2
        if target > self._level:
            old, self._level = self._level, target
            self._calm_ticks = 0
            self._deesc_ticks = 0
            self._enters += 1
            self._m_transitions.labels(direction="enter").inc()
            trace_id = (tracing.current_trace_id()
                        or self._recorder.last_trace_id())
            self._recorder.record(
                "brownout_enter", trace_id=trace_id, level=target,
                from_level=old, utilization=round(util, 3),
                burn_rate=round(burn, 3),
                detail="shedding " + "+".join(
                    c.label for c in SHEDDABLE[:target]))
            # admission overlay track: the timeline pairs this with
            # the matching exit/deescalate mark into a state interval
            timeline.instant("admission", "brownout_enter",
                             trace_id=trace_id, level=target)
            _LOG.warning(
                "brownout ENTER level %d (util %.2f, burn %.2f): "
                "shedding %s", target, util, burn,
                "+".join(c.label for c in SHEDDABLE[:target]))
        elif self._level > 0:
            calm = util <= self.util_exit and burn <= self.burn_exit
            self._calm_ticks = self._calm_ticks + 1 if calm else 0
            self._deesc_ticks = (self._deesc_ticks + 1
                                 if target < self._level else 0)
            if self._calm_ticks >= self.hold_ticks:
                old, self._level = self._level, 0
                self._calm_ticks = 0
                self._deesc_ticks = 0
                self._exits += 1
                self._m_transitions.labels(direction="exit").inc()
                self._recorder.record(
                    "brownout_exit", from_level=old,
                    utilization=round(util, 3),
                    burn_rate=round(burn, 3),
                    detail=f"calm for {self.hold_ticks} ticks")
                timeline.instant("admission", "brownout_exit",
                                 level=0, from_level=old)
                _LOG.info("brownout EXIT (util %.2f, burn %.2f)",
                          util, burn)
            elif (self._level > 1
                  and self._deesc_ticks >= self.hold_ticks):
                # DE-ESCALATE one level: the signals no longer justify
                # this level (below its entry threshold for a full
                # hold window) but are not calm enough for a full
                # exit — without this step a node whose load settles
                # in the exit..enter band after a spike would shed
                # GOSSIP forever on a stale level-2 verdict
                old, self._level = self._level, self._level - 1
                self._deesc_ticks = 0
                self._deescalations += 1
                self._m_transitions.labels(
                    direction="deescalate").inc()
                self._recorder.record(
                    "brownout_deescalate", from_level=old,
                    level=self._level, utilization=round(util, 3),
                    burn_rate=round(burn, 3),
                    detail=f"below level-{old} entry for "
                           f"{self.hold_ticks} ticks")
                timeline.instant("admission", "brownout_deescalate",
                                 level=self._level, from_level=old)
                _LOG.info(
                    "brownout DE-ESCALATE to level %d "
                    "(util %.2f, burn %.2f)", self._level, util, burn)
        return self._level

    # ------------------------------------------------------------------
    @property
    def brownout_level(self) -> int:
        return self._level

    def snapshot(self) -> dict:
        """The /teku/v1/admin/admission controller view."""
        with self._lock:
            plan = self._plan
            return {
                "plan": {
                    "batch_size": plan.batch_size,
                    "flush_deadline_s": plan.flush_deadline_s,
                    "modeled_batch_s": plan.modeled_batch_s,
                    "mode": plan.mode,
                },
                "inputs": {
                    "utilization": plan.utilization,
                    "burn_rate": plan.burn_rate,
                    "queue_depth": self.telemetry.queue_depth.current,
                },
                "brownout": {
                    "level": self._level,
                    "shedding": [c.label
                                 for c in SHEDDABLE[:self._level]],
                    "calm_ticks": self._calm_ticks,
                    "deesc_ticks": self._deesc_ticks,
                    "enters": self._enters,
                    "exits": self._exits,
                    "deescalations": self._deescalations,
                },
                "config": {
                    "tick_s": self.tick_s,
                    "min_bucket": self.min_bucket,
                    "max_batch": self.max_batch,
                    "slo_p50_ms": round(self.slo_p50_s * 1e3, 1),
                    "device_budget_ms": round(
                        self.device_budget_s * 1e3, 1),
                    "util_enter": self.util_enter,
                    "util_exit": self.util_exit,
                    "burn_enter": self.burn_enter,
                    "burn_exit": self.burn_exit,
                    "hold_ticks": self.hold_ticks,
                    "class_deadlines_ms": {
                        c.label: round(class_deadline_s(c) * 1e3, 1)
                        for c in VerifyClass},
                },
                "ticks": self._ticks,
            }
