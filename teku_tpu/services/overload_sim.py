"""Closed-loop overload simulation: the REAL control plane on a
virtual clock.

The acceptance property of ROADMAP 3 — "hold the 100 ms p50 SLO at 10x
sustained offered load" — is a property of the CONTROL PLANE (per-class
queues, admission controller, brownout shedding), not of any one
device's absolute speed.  This harness drives the real
``AggregatingSignatureVerificationService`` + ``AdmissionController``
(production code paths, unmodified) with:

- a VIRTUAL clock shared by the capacity telemetry, the controller and
  the device model, so the run is deterministic and takes milliseconds
  of wall time regardless of host speed;
- a calibrated DEVICE MODEL standing in for the BLS backend: each
  dispatch costs ``overhead_s + padded_lanes / capacity_sigs_per_sec``
  virtual seconds and feeds the same ``record_dispatch`` accounting the
  real provider's dispatch handle feeds — so the controller sees
  exactly the per-shape latency evidence it sees in production;
- a CLOSED arrival loop: while the virtual clock is inside the load
  window, every virtual second of device time generates
  ``offered_x * capacity`` new arrivals across the class mix — offered
  load is proportional to elapsed time, which is what "10x sustained"
  means.

Task latency is measured in virtual time (enqueue clock → the clock
stamp the device model records at the dispatch that settled it), so
the reported p50 is the queueing+batching+device latency the policy
actually produced.  bench.py's overload phase runs this at several
offered-load factors and ``tests/test_admission.py`` asserts the
acceptance properties on the 10x run with a FakeClock-style clock.
"""

import asyncio
import random
from collections import deque
from typing import Dict, Optional

from ..infra import capacity as capacity_mod
from ..infra import flightrecorder
from ..infra.metrics import MetricsRegistry
from .admission import AdmissionController, VerifyClass, _next_pow2
from .signatures import (AggregatingSignatureVerificationService,
                         ServiceCapacityExceededError)

# offered-load class mix, mainnet-shaped: the storm is speculative
# retries + subnet gossip; the protected core (aggregates, block
# import, proposer sigs) is a few percent of messages.  The protected
# share times offered_x must stay under the device's effective
# capacity — no shedding policy can protect more work than the device
# can do; what overload control guarantees is that the protected core
# KEEPS its latency while everything sheddable is dropped.
DEFAULT_MIX = {
    VerifyClass.OPTIMISTIC: 0.50,
    VerifyClass.GOSSIP: 0.465,
    VerifyClass.SYNC_CRITICAL: 0.02,
    VerifyClass.BLOCK_IMPORT: 0.01,
    VerifyClass.VIP: 0.005,
}


class VirtualClock:
    """Monotonic clock the simulation advances explicitly."""

    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class DeviceModel:
    """Stand-in BLS implementation: constant per-padded-lane cost plus
    a fixed dispatch overhead, advancing the virtual clock and feeding
    the capacity telemetry exactly like the real dispatch handle.  It
    stamps each message's completion clock so the driver can compute
    race-free virtual latencies after the run."""

    def __init__(self, clock: VirtualClock,
                 telemetry: capacity_mod.CapacityTelemetry,
                 capacity_sigs_per_sec: float,
                 overhead_s: float = 0.002, min_pad: int = 8):
        self.clock = clock
        self.telemetry = telemetry
        self.per_sig_s = 1.0 / capacity_sigs_per_sec
        self.overhead_s = overhead_s
        self.min_pad = min_pad
        self.completed_at: Dict[bytes, float] = {}
        self.dispatches = 0
        self.batch_sizes: list = []
        # accrued arrival credit: the driver converts device seconds
        # into offered arrivals (closed loop); only while load is on
        self.arrival_credit = 0.0
        self.load_until: Optional[float] = None
        self.offered_rate = 0.0

    def batch_verify(self, triples) -> bool:
        n = len(triples)
        padded = max(_next_pow2(n), self.min_pad)
        dt = self.overhead_s + padded * self.per_sig_s
        t0 = self.clock()
        self.clock.advance(dt)
        if self.load_until is not None and t0 < self.load_until:
            self.arrival_credit += self.offered_rate * dt
        self.telemetry.record_dispatch(f"{padded}x1", "sim", n, t0,
                                       self.clock())
        self.dispatches += 1
        self.batch_sizes.append(n)
        for _pks, msg, _sig in triples:
            self.completed_at[msg] = self.clock()
        return True

    def fast_aggregate_verify(self, pks, msg, sig) -> bool:
        return self.batch_verify([(pks, msg, sig)])


async def run_overload_sim(offered_x: float = 10.0,
                           duration_s: float = 8.0,
                           capacity_sigs_per_sec: float = 2000.0,
                           overhead_s: float = 0.002,
                           max_batch: int = 256,
                           queue_capacity: int = 4000,
                           slo_p50_s: float = 0.1,
                           mix: Optional[dict] = None,
                           seed: int = 3,
                           clock: Optional[VirtualClock] = None) -> dict:
    """One closed-loop run; returns the evidence dict bench.py embeds
    and the acceptance test asserts on."""
    from ..crypto import bls

    mix = dict(mix or DEFAULT_MIX)
    clock = clock or VirtualClock()
    registry = MetricsRegistry()
    recorder = flightrecorder.FlightRecorder(capacity=2048,
                                             registry=registry)
    # a short window makes the demand estimator (windowed total over
    # the FULL window) reach the true offered rate within ~2 virtual
    # seconds — the brownout entry lag IS part of what this measures
    telemetry = capacity_mod.CapacityTelemetry(
        registry=registry, window_s=2.5, clock=clock,
        recorder=recorder)
    impl = DeviceModel(clock, telemetry, capacity_sigs_per_sec,
                       overhead_s=overhead_s)
    offered_rate = offered_x * capacity_sigs_per_sec
    t_end = clock() + duration_s
    impl.load_until = t_end
    impl.offered_rate = offered_rate

    # SLO feedback: burn computed over the last completions' virtual
    # latencies — the closed loop's own measurement, same arithmetic
    # as the SloEngine's p50 objective (target_ratio 0.5)
    recent: deque = deque(maxlen=256)

    def burn() -> float:
        if len(recent) < 8:
            return 0.0
        bad = sum(1 for lat in recent if lat > slo_p50_s) / len(recent)
        return bad / 0.5

    # tick_s is scaled down 25x from the production default (0.02 vs
    # 0.5) so the controller reacts at sim speed; hold_ticks is scaled
    # UP by the same factor so the exit hysteresis covers the same
    # 0.5-1.5 s of calm it covers in production — otherwise the sim's
    # 60 ms hold would "measure" flapping no production config has
    controller = AdmissionController(
        telemetry=telemetry, burn_getter=burn, min_bucket=8,
        max_batch=max_batch, slo_p50_s=slo_p50_s, tick_s=0.02,
        hold_ticks=25, clock=clock, registry=registry,
        recorder=recorder, name="overload_sim")
    svc = AggregatingSignatureVerificationService(
        num_workers=1, queue_capacity=queue_capacity,
        max_batch_size=max_batch, registry=registry,
        name="overload_sim", overlap=False, controller=controller,
        telemetry=telemetry, recorder=recorder, clock=clock)

    rng = random.Random(seed)
    classes = list(mix)
    weights = [mix[c] for c in classes]
    pending: list = []           # (cls, submit_clock, msg, future)
    shed_at_admission: Dict[str, int] = {c.label: 0 for c in VerifyClass}
    submitted = 0
    seq = 0

    bls.set_implementation(impl)
    try:
        await svc.start()
        # seed burst: ~100 ms of offered load gets the loop turning
        impl.arrival_credit = offered_rate * 0.1
        idle_tick = 0.005
        while True:
            n = int(impl.arrival_credit)
            if n > 0:
                impl.arrival_credit -= n
                for _ in range(n):
                    cls = rng.choices(classes, weights)[0]
                    seq += 1
                    msg = b"ovl-%d" % seq
                    submitted += 1
                    t_sub = clock()
                    try:
                        fut = svc.verify([b"\xa0" + bytes(47)], msg,
                                         b"sig", cls=cls)
                    except ServiceCapacityExceededError:
                        shed_at_admission[cls.label] += 1
                        continue
                    except ValueError:
                        continue  # defensive; mix has no invalid class
                    pending.append((cls, t_sub, msg, fut))

                    # live SLO feedback: the completion callback feeds
                    # the burn estimator WHILE the loop runs (the
                    # device-model stamp makes the latency virtual),
                    # so burn-triggered brownout entry is exercised,
                    # not just the utilization path
                    def _feed_burn(f, t_sub=t_sub, msg=msg):
                        if f.cancelled() or f.exception() is not None:
                            return
                        done_at = impl.completed_at.get(msg)
                        if f.result() and done_at is not None:
                            recent.append(done_at - t_sub)
                    fut.add_done_callback(_feed_burn)
                # let the worker drain/dispatch (advances the clock,
                # which accrues the next arrivals — the closed loop)
                await asyncio.sleep(0)
                continue
            if svc.inflight_dispatches:
                # a dispatch is crossing the thread boundary: hold the
                # virtual clock and park in a REAL sleep so the
                # executor thread gets the GIL now.  Spinning sleep(0)
                # while advancing charged wall scheduler time (the
                # ~5 ms GIL switch interval per handoff on a 1-core
                # box) to VIRTUAL latency — the flaky
                # light-load-burns-out failure and the r10 loadgen
                # block-import p50 inflation (loadgen/driver.py has
                # the same gate)
                await asyncio.sleep(0.0005)
                continue
            if clock() < t_end:
                # queue drained faster than credit accrues (light
                # offered load): idle time still accrues offered work
                clock.advance(idle_tick)
                impl.arrival_credit += offered_rate * idle_tick
                await asyncio.sleep(0)
                continue
            # load window over: drain everything still in flight
            if svc._queue.qsize() == 0 and all(
                    f.done() for _, _, _, f in pending):
                break
            await asyncio.sleep(0)
        # collect verdicts + virtual latencies (device-model stamps:
        # immune to the wall-clock of this gather loop)
        completed = []
        shed_from_queue: Dict[str, int] = {
            c.label: 0 for c in VerifyClass}
        for cls, t_sub, msg, fut in pending:
            try:
                ok = await fut
            except ServiceCapacityExceededError:
                shed_from_queue[cls.label] += 1
                continue
            if ok and msg in impl.completed_at:
                lat = impl.completed_at[msg] - t_sub
                completed.append((cls, lat))
        # cool-down: load is off; the deque does not decay on its own
        # the way the SloEngine's rolling window does, so clearing it
        # models the window rolling past the overload — then tick the
        # controller through its hysteresis so the EXIT edge is
        # observable
        recent.clear()
        for _ in range(controller.hold_ticks + 20):
            if controller.brownout_level == 0:
                break
            clock.advance(max(telemetry.window_s / 4,
                              controller.tick_s))
            controller.tick()
        await svc.stop()
    finally:
        bls.reset_implementation()

    lats = sorted(lat for _, lat in completed)

    def pct(q: float) -> float:
        if not lats:
            return 0.0
        return lats[min(len(lats) - 1, int(q * len(lats)))] * 1e3

    sheds = {c.label: shed_at_admission[c.label]
             + shed_from_queue[c.label] for c in VerifyClass}
    events = [e for e in recorder.snapshot()
              if e["kind"] in ("brownout_enter", "brownout_exit")]
    # an ENTER is the 0 -> brownout edge; a level escalation while
    # already browned out is recorded but is not a new episode
    enters = sum(1 for e in events if e["kind"] == "brownout_enter"
                 and e.get("from_level", 0) == 0)
    escalations = sum(1 for e in events
                      if e["kind"] == "brownout_enter"
                      and e.get("from_level", 0) > 0)
    exits = sum(1 for e in events if e["kind"] == "brownout_exit")
    by_class: Dict[str, list] = {}
    for cls, lat in completed:
        by_class.setdefault(cls.label, []).append(lat)
    snap = controller.snapshot()
    return {
        "offered_x": offered_x,
        "offered_sigs_per_sec": round(offered_rate, 1),
        "capacity_sigs_per_sec": capacity_sigs_per_sec,
        "duration_s": duration_s,
        "submitted": submitted,
        "completed": len(completed),
        "completed_share": round(len(completed) / max(1, submitted), 4),
        "p50_ms": round(pct(0.50), 3),
        "p95_ms": round(pct(0.95), 3),
        "p99_ms": round(pct(0.99), 3),
        "p50_ms_by_class": {
            label: round(sorted(ls)[len(ls) // 2] * 1e3, 3)
            for label, ls in sorted(by_class.items())},
        "sheds": sheds,
        "shed_total": sum(sheds.values()),
        "brownout": {
            "enters": enters,
            "escalations": escalations,
            "exits": exits,
            # one sustained overload must produce ONE enter edge (a
            # level escalation is not a flap) and at most one exit
            "flapped": enters > 1 or exits > 1,
            "final_level": controller.brownout_level,
            "events": events[:16],
        },
        "dispatches": impl.dispatches,
        "batch_size_max": max(impl.batch_sizes or [0]),
        "final_plan": snap["plan"],
        "final_inputs": snap["inputs"],
    }


def run(offered_x: float = 10.0, **kw) -> dict:
    """Sync wrapper (bench.py phases are sync)."""
    return asyncio.run(run_overload_sim(offered_x=offered_x, **kw))
