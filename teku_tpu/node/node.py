"""BeaconNode: full in-process node wiring.

Equivalent of the reference's BeaconChainController + SlotProcessor
(reference: services/beaconchain/src/main/java/tech/pegasys/teku/
services/beaconchain/BeaconChainController.java:504-546 initAll order,
SlotProcessor.java:102-160): one object builds the store, chain data,
signature batching service, gossip validators, managers, attestation
pool and topic subscriptions, and exposes the slot-phase entry points
(slot start / attestation due / aggregation due) that either a real
timer or a devnet driver invokes.
"""

import asyncio
import logging
from typing import List, Optional

from ..infra import flightrecorder
from ..infra.env import env_bool, env_float
from ..infra.events import EventChannels, SlotEventsChannel
from ..infra.health import (CheckResult, EventLoopLagWatchdog,
                            HealthRegistry, HealthStatus, SloEngine,
                            admission_controller_check,
                            signature_service_check, supervisor_check)
from ..infra.logs import log_slot_event
from ..infra.service import Service
from ..services.admission import AdmissionController, VerifyClass
from ..services.signatures import (
    AggregatingSignatureVerificationService, ServiceCapacityExceededError)
from ..spec import Spec
from ..spec import helpers as H
from ..spec.verifiers import ServiceAsyncSignatureVerifier, verify_class
from ..storage.store import Store
from .chaindata import RecentChainData
from .gossip import (AGGREGATE_TOPIC, ATTESTER_SLASHING_TOPIC,
                     attestation_subnet_topic, BEACON_BLOCK_TOPIC,
                     GossipNetwork, PROPOSER_SLASHING_TOPIC,
                     SszTopicHandler, ValidationResult,
                     VOLUNTARY_EXIT_TOPIC)
from .managers import AttestationManager, BlockManager
from .pool import AggregatingAttestationPool
from .validators import (AggregateValidator, AttestationValidator,
                         BlockGossipValidator)

_LOG = logging.getLogger(__name__)


def compute_subnet_for_attestation(cfg, committees_per_slot: int,
                                   slot: int, committee_index: int) -> int:
    slots_since_epoch_start = slot % cfg.SLOTS_PER_EPOCH
    committees_since_epoch_start = committees_per_slot * slots_since_epoch_start
    return ((committees_since_epoch_start + committee_index)
            % cfg.ATTESTATION_SUBNET_COUNT)


class BeaconNode(Service):
    def __init__(self, spec: Spec, genesis_state, gossip: GossipNetwork,
                 name: str = "node", num_sig_workers: int = 2,
                 max_batch_size: int = 250,
                 store: Optional[Store] = None,
                 overload_control: Optional[bool] = None):
        super().__init__(name)
        self.spec = spec
        # backend supervisor (infra/supervisor.py), injected by the
        # process entry point after construction: the node boots on the
        # oracle and this service hot-swaps the device backend in the
        # background; the node owns its lifecycle (reference: the
        # preflight moment Teku.java:74, reshaped for 25-minute init)
        self.supervisor = None
        S = spec.schemas
        self.channels = EventChannels()
        if store is None:
            # the anchor block must use the schemas of the milestone
            # governing the anchor slot — otherwise its root disagrees
            # with the state's own latest_block_header and nothing can
            # ever chain onto genesis on a later-fork-at-genesis net
            A = spec.at_slot(genesis_state.slot).schemas
            anchor = A.BeaconBlock(
                slot=genesis_state.slot, parent_root=bytes(32),
                state_root=genesis_state.htr(), body=A.BeaconBlockBody())
            store = Store(spec.config, genesis_state, anchor)
        self.store = store
        self.chain = RecentChainData(spec, self.store, self.channels)
        # SLO engine first: the admission controller closes its loop
        # on the attestation_verify_p50 burn rate it computes
        self.slo = SloEngine(name=name)
        if overload_control is None:
            overload_control = env_bool("TEKU_TPU_OVERLOAD_CONTROL",
                                        True)
        self.admission = AdmissionController(
            burn_getter=lambda: self.slo.burn_rate(
                "attestation_verify_p50"),
            max_batch=max_batch_size,
            name=name) if overload_control else None
        self.sig_service = AggregatingSignatureVerificationService(
            num_workers=num_sig_workers, max_batch_size=max_batch_size,
            name=f"{name}_signature_verifications",
            controller=self.admission)
        self.verifier = ServiceAsyncSignatureVerifier(self.sig_service)
        self.pool = AggregatingAttestationPool(spec)
        from .oppool import make_operation_pools
        from .syncpool import SyncCommitteeMessagePool
        self.operation_pools = make_operation_pools(spec.config)
        self.sync_pool = SyncCommitteeMessagePool(spec.config)
        self.attestation_manager = AttestationManager(
            spec, self.chain, pool=self.pool)
        from .blobs import BlobSidecarPool
        self.blob_pool = BlobSidecarPool(
            max_blobs=spec.config.MAX_BLOBS_PER_BLOCK_ELECTRA)
        # optional eth1-bridge deposit source (node/deposits.py); when
        # set, block production includes proof-carrying deposits
        self.deposit_provider = None
        from ..infra.collections import LimitedSet
        self._seen_blob_sidecars = LimitedSet(16384)
        self.block_manager = BlockManager(spec, self.chain, self.channels,
                                          blob_pool=self.blob_pool)
        self.block_manager.on_imported.append(
            self.attestation_manager.on_block_imported)
        self.block_manager.on_imported.append(self._prune_included_ops)
        self.attestation_validator = AttestationValidator(
            spec, self.chain, self.verifier)
        self.aggregate_validator = AggregateValidator(
            spec, self.chain, self.verifier)
        self.block_validator = BlockGossipValidator(
            spec, self.chain, self.verifier)
        from .validators import ContributionValidator
        self.contribution_validator = ContributionValidator(
            spec, self.chain, self.verifier)
        self.gossip = gossip
        # one slot-advanced head state shared by all duty phases
        self._advanced_cache: Optional[tuple] = None
        # gossip awaiting re-validation (kind, message, retries)
        self._deferred_gossip: List[tuple] = []
        # health & SLO subsystem (infra/health.py): per-subsystem
        # checks aggregated behind /eth/v1/node/health, SLOs evaluated
        # continuously from the live metrics, everything edge-logged
        # into the process flight recorder
        self.flight_recorder = flightrecorder.RECORDER
        self.health = HealthRegistry(name=name)
        self.loop_watchdog = EventLoopLagWatchdog(name=name)
        self.health.register("backend",
                             supervisor_check(lambda: self.supervisor))
        self.health.register("signature_queue",
                             signature_service_check(self.sig_service))
        self.health.register(
            "admission",
            admission_controller_check(lambda: self.admission))
        self.health.register("event_loop", self.loop_watchdog.check)
        # late binding: bench/tests may swap the engine after wiring
        self.health.register("slo", lambda: self.slo.check())
        self.health.register("chain_head", self._chain_head_check)
        self._health_task: Optional[asyncio.Task] = None
        self._subscribe_topics()

    def _chain_head_check(self) -> CheckResult:
        """Head freshness: a head stuck N slots behind the wall clock
        is the node-side symptom of sync loss or import stall."""
        lag = max(0, self.chain.current_slot() - self.chain.head_slot())
        if lag > 4:
            return CheckResult(HealthStatus.DEGRADED,
                               f"head {lag} slots behind the clock")
        return CheckResult(HealthStatus.UP, f"head lag {lag} slot(s)")

    async def _health_tick_loop(self) -> None:
        """Periodic SLO window + health sweep.  The tick must survive
        any single broken check/objective — losing the watchdog because
        one gauge raised would be the observability layer's own
        silent-failure bug."""
        interval = env_float("TEKU_TPU_HEALTH_TICK_S", 5.0, lo=0.01)
        from ..infra import capacity, profiling
        while True:
            await asyncio.sleep(interval)
            try:
                slo_snap = self.slo.tick()
                # the admission controller re-plans lazily on traffic;
                # this tick covers the idle edge (a brownout must EXIT
                # when load stops arriving, not wait for the next
                # arrival to trigger a plan)
                if self.admission is not None:
                    self.admission.tick()
                self.health.evaluate()
                # capacity refresh fires the edge-triggered headroom-
                # exhausted event; the profiler poll stops an overdue
                # auto capture and evaluates the burn-rate trigger
                capacity.refresh()
                profiling.CONTROLLER.poll(slo_snap)
            except Exception:  # pragma: no cover - belt and braces
                _LOG.exception("health tick failed")

    def advanced_head_state(self, slot: int):
        """Head state advanced to `slot`, computed once per (head, slot)
        — proposal, attestation and aggregation duties all need it, and
        at epoch boundaries the advance includes full epoch processing."""
        head_root = self.chain.head_root
        cached = self._advanced_cache
        if cached is not None and cached[0] == (head_root, slot):
            return cached[1]
        state = self.chain.head_state()
        if state.slot < slot:
            state = self.spec.process_slots(state, slot)
        self._advanced_cache = ((head_root, slot), state)
        return state

    def _prune_included_ops(self, root: bytes) -> None:
        body = self.store.blocks[root].body
        self.operation_pools["proposer_slashings"].on_included(
            body.proposer_slashings)
        self.operation_pools["attester_slashings"].on_included(
            body.attester_slashings)
        self.operation_pools["voluntary_exits"].on_included(
            body.voluntary_exits)
        if hasattr(body, "bls_to_execution_changes"):
            self.operation_pools["bls_to_execution_changes"].on_included(
                body.bls_to_execution_changes)

    # ------------------------------------------------------------------
    def _subscribe_topics(self) -> None:
        # schema family of the milestone governing the chain's head:
        # a devnet starting at altair/deneb/electra must decode that
        # fork's gossip shapes (mid-run fork transitions would need the
        # reference's GossipForkManager resubscription — the in-memory
        # topics carry no fork digest yet)
        S = self.spec.at_slot(self.chain.head_slot()).schemas
        from ..spec.codec import deserialize_signed_block
        from ..spec.milestones import build_fork_schedule
        cfg = self.spec.config

        class _BlockWire:       # milestone-aware decode (spec/codec.py)
            @staticmethod
            def deserialize(data):
                return deserialize_signed_block(cfg, data)
        self.gossip.subscribe(BEACON_BLOCK_TOPIC, SszTopicHandler(
            _BlockWire, self._process_gossip_block, BEACON_BLOCK_TOPIC))
        self.gossip.subscribe(AGGREGATE_TOPIC, SszTopicHandler(
            S.SignedAggregateAndProof, self._process_gossip_aggregate,
            AGGREGATE_TOPIC))
        node = self

        class _AttestationWire:
            """Subnet wire decode, slot-validated per milestone (the
            shared spec/codec.py policy)."""
            @staticmethod
            def deserialize(data):
                from ..spec.codec import deserialize_attestation_wire
                return deserialize_attestation_wire(
                    cfg, data, node.chain.current_slot())

        for subnet in range(self.spec.config.ATTESTATION_SUBNET_COUNT):
            self.gossip.subscribe(
                attestation_subnet_topic(subnet), SszTopicHandler(
                    _AttestationWire, self._process_gossip_attestation,
                    f"attestation_{subnet}"))
        # operation gossip feeds the pools (reference: the per-type
        # validators in statetransition/validation/*Validator.java —
        # here the pool's apply-rule IS the validation)
        for topic, schema, pool_name in (
                (VOLUNTARY_EXIT_TOPIC, S.SignedVoluntaryExit,
                 "voluntary_exits"),
                (PROPOSER_SLASHING_TOPIC, S.ProposerSlashing,
                 "proposer_slashings"),
                (ATTESTER_SLASHING_TOPIC, S.AttesterSlashing,
                 "attester_slashings")):
            self.gossip.subscribe(topic, SszTopicHandler(
                schema, self._make_op_processor(pool_name), topic))
        self._subscribe_bls_change_topic()
        self._subscribe_sync_topic()
        self._subscribe_blob_topics()

    def _subscribe_bls_change_topic(self) -> None:
        from .gossip import BLS_TO_EXECUTION_CHANGE_TOPIC
        from ..spec.milestones import build_fork_schedule, SpecMilestone
        try:
            version = build_fork_schedule(self.spec.config).version_for(
                SpecMilestone.CAPELLA)
        except KeyError:
            return          # capella not scheduled on this network
        self.gossip.subscribe(
            BLS_TO_EXECUTION_CHANGE_TOPIC, SszTopicHandler(
                version.schemas.SignedBLSToExecutionChange,
                self._make_op_processor("bls_to_execution_changes"),
                BLS_TO_EXECUTION_CHANGE_TOPIC))

    def _subscribe_blob_topics(self) -> None:
        from ..spec.config import FAR_FUTURE_EPOCH
        from ..spec.deneb.block import max_blobs_for_slot
        from ..spec.deneb.datastructures import get_deneb_schemas
        from .gossip import blob_sidecar_topic
        cfg = self.spec.config
        if cfg.DENEB_FORK_EPOCH == FAR_FUTURE_EPOCH:
            return          # no blobs on this network
        schema = get_deneb_schemas(cfg).BlobSidecar
        n_subnets = max(cfg.MAX_BLOBS_PER_BLOCK,
                        cfg.MAX_BLOBS_PER_BLOCK_ELECTRA)
        for subnet in range(n_subnets):
            self.gossip.subscribe(
                blob_sidecar_topic(subnet), SszTopicHandler(
                    schema, self._process_gossip_blob_sidecar,
                    f"blob_sidecar_{subnet}"))

    async def _process_gossip_blob_sidecar(self, sidecar
                                           ) -> ValidationResult:
        """reference BlobSidecarGossipValidator → tracking pool: the
        proposer-signature check runs against a same-epoch state when
        the chain has one."""
        from .blobs import validate_spec_sidecar
        cfg = self.spec.config
        slot = sidecar.signed_block_header.message.slot
        # slot window FIRST — the slot is wire-controlled, and state
        # advancement below must stay bounded by the wall clock
        current = self.chain.current_slot()
        if slot > current:
            return ValidationResult.SAVE_FOR_FUTURE
        if slot + cfg.ATTESTATION_PROPAGATION_SLOT_RANGE < current:
            return ValidationResult.IGNORE
        try:
            state = self.advanced_head_state(slot)
        except Exception:
            state = None
        verdict = validate_spec_sidecar(cfg, sidecar, state=state,
                                        setup=self.blob_pool._setup,
                                        seen=self._seen_blob_sidecars)
        if verdict == "accept":
            # proof already verified just above — don't pay the
            # multi-pairing twice on the gossip hot path
            self.blob_pool.add_spec_sidecar(cfg, sidecar,
                                            proof_checked=True)
            self.block_manager.retry_pending_blobs()
        return ValidationResult(verdict)

    def _subscribe_sync_topic(self) -> None:
        from .gossip import SYNC_COMMITTEE_TOPIC
        from ..spec.milestones import build_fork_schedule, SpecMilestone
        try:
            version = build_fork_schedule(self.spec.config).version_for(
                SpecMilestone.ALTAIR)
        except KeyError:
            return          # altair not scheduled on this network
        self.gossip.subscribe(SYNC_COMMITTEE_TOPIC, SszTopicHandler(
            version.schemas.SyncCommitteeMessage,
            self._process_sync_message, SYNC_COMMITTEE_TOPIC))
        from .gossip import SYNC_CONTRIBUTION_TOPIC
        self.gossip.subscribe(SYNC_CONTRIBUTION_TOPIC, SszTopicHandler(
            version.schemas.SignedContributionAndProof,
            self._process_sync_contribution, SYNC_CONTRIBUTION_TOPIC))

    async def _process_sync_contribution(self, signed
                                         ) -> ValidationResult:
        result = await self.contribution_validator.validate(signed)
        if result is ValidationResult.ACCEPT:
            self.sync_pool.add_contribution(signed.message.contribution)
        return result

    async def _process_sync_message(self, msg) -> ValidationResult:
        """Gossiped sync-committee message: membership + signature
        checked (via the batcher), then pooled for the next proposer
        (reference SyncCommitteeMessageValidator)."""
        from ..spec.altair.helpers import sync_message_signing_root
        state = self.chain.head_state()
        if not hasattr(state, "current_sync_committee"):
            return ValidationResult.IGNORE     # pre-fork
        # only the live slot counts (reference
        # SyncCommitteeMessageValidator: message slot == current slot);
        # anything else would let one member spam junk (slot, root)
        # buckets that evict the live one from the bounded pool
        cur = self.chain.current_slot()
        if not (cur - 1 <= msg.slot <= cur):
            return ValidationResult.IGNORE
        if msg.validator_index >= len(state.validators):
            return ValidationResult.REJECT
        pubkey = state.validators[msg.validator_index].pubkey
        positions = [i for i, pk in enumerate(
            state.current_sync_committee.pubkeys) if pk == pubkey]
        if not positions:
            return ValidationResult.REJECT     # not in the committee
        root = sync_message_signing_root(self.spec.config, state,
                                         msg.slot, msg.beacon_block_root)
        from ..infra.capacity import SOURCE_SYNC_COMMITTEE
        if not await self.verifier.verify(
                [pubkey], root, msg.signature,
                cls=VerifyClass.GOSSIP, source=SOURCE_SYNC_COMMITTEE):
            return ValidationResult.REJECT
        for pos in positions:
            self.sync_pool.add(msg.slot, msg.beacon_block_root, pos,
                               msg.signature)
        return ValidationResult.ACCEPT

    def _make_op_processor(self, pool_name: str):
        async def process(op) -> ValidationResult:
            pool = self.operation_pools[pool_name]
            if pool.add(self.chain.head_state(), op):
                return ValidationResult.ACCEPT
            return ValidationResult.IGNORE   # duplicate or invalid here
        return process

    async def _process_gossip_block(self, signed_block) -> ValidationResult:
        result = await self.block_validator.validate(signed_block)
        if result in (ValidationResult.ACCEPT,
                      ValidationResult.SAVE_FOR_FUTURE):
            # future/unknown-parent blocks queue inside the manager and
            # re-enter the FULL import validation when retried
            self.block_manager.import_block(signed_block)
        return result

    async def _process_gossip_attestation(self, att) -> ValidationResult:
        # electra single attestations (the wire shape) normalize to the
        # one-hot committee-bits form everything downstream handles
        if hasattr(att, "attester_index"):
            from .validators import normalize_attestation
            try:
                state = self.advanced_head_state(
                    min(att.data.slot, self.chain.current_slot()))
            except Exception:
                return ValidationResult.IGNORE
            att = normalize_attestation(self.spec, state, att)
            if att is None:
                return ValidationResult.REJECT
        result = await self.attestation_validator.validate(att)
        if result is ValidationResult.ACCEPT:
            self.attestation_manager.add_attestation(att)
        elif result is ValidationResult.SAVE_FOR_FUTURE:
            # signature NOT yet checked (unknown block / future slot):
            # defer the raw message and RE-VALIDATE later — it must not
            # touch the pool or fork choice until it fully passes
            self._defer("att", att)
        return result

    async def _process_gossip_aggregate(self, agg) -> ValidationResult:
        result = await self.aggregate_validator.validate(agg)
        if result is ValidationResult.ACCEPT:
            self.attestation_manager.add_attestation(agg.message.aggregate)
        elif result is ValidationResult.SAVE_FOR_FUTURE:
            self._defer("agg", agg)
        return result

    def _defer(self, kind: str, msg) -> None:
        if len(self._deferred_gossip) < 1024:
            self._deferred_gossip.append((kind, msg, 0))

    async def _retry_deferred(self) -> None:
        """Re-validate deferred gossip (new slot or new blocks may have
        unblocked it); three strikes and a message is dropped.

        Retries run at OPTIMISTIC class: they are speculative (the
        message already failed once), so under brownout they are the
        first thing shed — live gossip must not queue behind them."""
        items, self._deferred_gossip = self._deferred_gossip, []
        with verify_class(VerifyClass.OPTIMISTIC):
            for kind, msg, tries in items:
                try:
                    if kind == "att":
                        result = await \
                            self.attestation_validator.validate(msg)
                        if result is ValidationResult.ACCEPT:
                            self.attestation_manager.add_attestation(msg)
                            continue
                    else:
                        result = await \
                            self.aggregate_validator.validate(msg)
                        if result is ValidationResult.ACCEPT:
                            self.attestation_manager.add_attestation(
                                msg.message.aggregate)
                            continue
                except ServiceCapacityExceededError:
                    # an OPTIMISTIC retry shed by brownout is load
                    # shedding working as designed, not a lost
                    # message class
                    continue
                except Exception:
                    # anything else is a real validator defect: keep
                    # the retry loop alive but make the drop loud
                    _LOG.exception(
                        "deferred %s gossip revalidation failed", kind)
                    continue
                if (result is ValidationResult.SAVE_FOR_FUTURE
                        and tries < 3
                        and len(self._deferred_gossip) < 1024):
                    self._deferred_gossip.append((kind, msg, tries + 1))

    # ------------------------------------------------------------------
    async def do_start(self) -> None:
        await self.sig_service.start()
        if self.supervisor is not None:
            await self.supervisor.start()
        self.loop_watchdog.start()
        self._health_task = asyncio.create_task(
            self._health_tick_loop(), name=f"{self.name}-health-tick")

    async def do_stop(self) -> None:
        if self._health_task is not None:
            self._health_task.cancel()
            try:
                await self._health_task
            except asyncio.CancelledError:
                pass
            self._health_task = None
        await self.loop_watchdog.stop()
        if self.supervisor is not None:
            await self.supervisor.stop()
        await self.sig_service.stop()

    # ------------------------------------------------------------------
    # slot phases (reference SlotProcessor.onSlot / attestation-due)
    # ------------------------------------------------------------------

    async def on_slot(self, slot: int) -> None:
        cfg = self.spec.config
        self.store.on_tick(self.store.genesis_time
                           + slot * cfg.SECONDS_PER_SLOT)
        self.block_manager.on_slot(slot)
        self.attestation_manager.on_slot(slot)
        await self._retry_deferred()
        head = self.chain.update_head()
        self.channels.publisher(SlotEventsChannel).on_slot(slot)
        if slot % cfg.SLOTS_PER_EPOCH == 0:
            log_slot_event(slot, slot // cfg.SLOTS_PER_EPOCH, head,
                           self.store.justified_checkpoint.epoch,
                           self.store.finalized_checkpoint.epoch)
            self.pool.prune(self.store.finalized_checkpoint.epoch)
