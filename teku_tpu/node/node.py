"""BeaconNode: full in-process node wiring.

Equivalent of the reference's BeaconChainController + SlotProcessor
(reference: services/beaconchain/src/main/java/tech/pegasys/teku/
services/beaconchain/BeaconChainController.java:504-546 initAll order,
SlotProcessor.java:102-160): one object builds the store, chain data,
signature batching service, gossip validators, managers, attestation
pool and topic subscriptions, and exposes the slot-phase entry points
(slot start / attestation due / aggregation due) that either a real
timer or a devnet driver invokes.
"""

import logging
from typing import Dict, List, Optional, Sequence

from ..crypto import bls
from ..infra.events import EventChannels, SlotEventsChannel
from ..infra.logs import log_slot_event
from ..infra.service import Service
from ..services.signatures import AggregatingSignatureVerificationService
from ..spec import Spec
from ..spec import helpers as H
from ..spec.builder import (is_aggregator, get_selection_proof,
                            make_local_signer, produce_aggregate_and_proof,
                            produce_block)
from ..spec.config import DOMAIN_BEACON_ATTESTER
from ..spec.verifiers import ServiceAsyncSignatureVerifier
from ..storage.store import Store
from .chaindata import RecentChainData
from .gossip import (AGGREGATE_TOPIC, attestation_subnet_topic,
                     BEACON_BLOCK_TOPIC, GossipNetwork, SszTopicHandler,
                     ValidationResult)
from .managers import AttestationManager, BlockManager
from .pool import AggregatingAttestationPool
from .validators import (AggregateValidator, AttestationValidator,
                         BlockGossipValidator)

_LOG = logging.getLogger(__name__)


def compute_subnet_for_attestation(cfg, committees_per_slot: int,
                                   slot: int, committee_index: int) -> int:
    slots_since_epoch_start = slot % cfg.SLOTS_PER_EPOCH
    committees_since_epoch_start = committees_per_slot * slots_since_epoch_start
    return ((committees_since_epoch_start + committee_index)
            % cfg.ATTESTATION_SUBNET_COUNT)


class BeaconNode(Service):
    def __init__(self, spec: Spec, genesis_state, gossip: GossipNetwork,
                 name: str = "node", num_sig_workers: int = 2,
                 max_batch_size: int = 250):
        super().__init__(name)
        self.spec = spec
        S = spec.schemas
        anchor = S.BeaconBlock(
            slot=genesis_state.slot, parent_root=bytes(32),
            state_root=genesis_state.htr(), body=S.BeaconBlockBody())
        self.channels = EventChannels()
        self.store = Store(spec.config, genesis_state, anchor)
        self.chain = RecentChainData(spec, self.store, self.channels)
        self.sig_service = AggregatingSignatureVerificationService(
            num_workers=num_sig_workers, max_batch_size=max_batch_size,
            name=f"{name}_signature_verifications")
        self.verifier = ServiceAsyncSignatureVerifier(self.sig_service)
        self.pool = AggregatingAttestationPool(spec)
        self.attestation_manager = AttestationManager(
            spec, self.chain, pool=self.pool)
        self.block_manager = BlockManager(spec, self.chain, self.channels)
        self.block_manager.on_imported.append(
            self.attestation_manager.on_block_imported)
        self.attestation_validator = AttestationValidator(
            spec, self.chain, self.verifier)
        self.aggregate_validator = AggregateValidator(
            spec, self.chain, self.verifier)
        self.block_validator = BlockGossipValidator(
            spec, self.chain, self.verifier)
        self.gossip = gossip
        # one slot-advanced head state shared by all duty phases
        self._advanced_cache: Optional[tuple] = None
        self._subscribe_topics()

    def advanced_head_state(self, slot: int):
        """Head state advanced to `slot`, computed once per (head, slot)
        — proposal, attestation and aggregation duties all need it, and
        at epoch boundaries the advance includes full epoch processing."""
        head_root = self.chain.head_root
        cached = self._advanced_cache
        if cached is not None and cached[0] == (head_root, slot):
            return cached[1]
        state = self.chain.head_state()
        if state.slot < slot:
            state = self.spec.process_slots(state, slot)
        self._advanced_cache = ((head_root, slot), state)
        return state

    # ------------------------------------------------------------------
    def _subscribe_topics(self) -> None:
        S = self.spec.schemas
        self.gossip.subscribe(BEACON_BLOCK_TOPIC, SszTopicHandler(
            S.SignedBeaconBlock, self._process_gossip_block,
            BEACON_BLOCK_TOPIC))
        self.gossip.subscribe(AGGREGATE_TOPIC, SszTopicHandler(
            S.SignedAggregateAndProof, self._process_gossip_aggregate,
            AGGREGATE_TOPIC))
        for subnet in range(self.spec.config.ATTESTATION_SUBNET_COUNT):
            self.gossip.subscribe(
                attestation_subnet_topic(subnet), SszTopicHandler(
                    S.Attestation, self._process_gossip_attestation,
                    f"attestation_{subnet}"))

    async def _process_gossip_block(self, signed_block) -> ValidationResult:
        result = await self.block_validator.validate(signed_block)
        if result is ValidationResult.ACCEPT:
            self.block_manager.import_block(signed_block)
        elif result is ValidationResult.SAVE_FOR_FUTURE:
            self.block_manager.import_block(signed_block)  # queues inside
        return result

    async def _process_gossip_attestation(self, att) -> ValidationResult:
        result = await self.attestation_validator.validate(att)
        if result in (ValidationResult.ACCEPT,
                      ValidationResult.SAVE_FOR_FUTURE):
            self.attestation_manager.add_attestation(att)
        return result

    async def _process_gossip_aggregate(self, agg) -> ValidationResult:
        result = await self.aggregate_validator.validate(agg)
        if result in (ValidationResult.ACCEPT,
                      ValidationResult.SAVE_FOR_FUTURE):
            self.attestation_manager.add_attestation(agg.message.aggregate)
        return result

    # ------------------------------------------------------------------
    async def do_start(self) -> None:
        await self.sig_service.start()

    async def do_stop(self) -> None:
        await self.sig_service.stop()

    # ------------------------------------------------------------------
    # slot phases (reference SlotProcessor.onSlot / attestation-due)
    # ------------------------------------------------------------------

    def on_slot(self, slot: int) -> None:
        cfg = self.spec.config
        self.store.on_tick(self.store.genesis_time
                           + slot * cfg.SECONDS_PER_SLOT)
        self.block_manager.on_slot(slot)
        self.attestation_manager.on_slot(slot)
        head = self.chain.update_head()
        self.channels.publisher(SlotEventsChannel).on_slot(slot)
        if slot % cfg.SLOTS_PER_EPOCH == 0:
            log_slot_event(slot, slot // cfg.SLOTS_PER_EPOCH, head,
                           self.store.justified_checkpoint.epoch,
                           self.store.finalized_checkpoint.epoch)
            self.pool.prune(self.store.finalized_checkpoint.epoch)


class InProcessValidatorClient:
    """Validator duties bound to one node — the devnet stand-in for the
    reference's ValidatorClientService (reference: validator/client/
    ValidatorClientService.java + duties/attestations/*): propose at
    slot start, attest at 1/3, aggregate at 2/3, all signatures local.
    """

    def __init__(self, node: BeaconNode, secret_keys: Dict[int, int]):
        self.node = node
        self.spec = node.spec
        self.keys = dict(secret_keys)
        self.signer = make_local_signer(self.keys)
        self.blocks_proposed = 0
        self.attestations_sent = 0

    # -- slot start: propose ------------------------------------------
    async def on_slot_start(self, slot: int) -> None:
        cfg = self.spec.config
        pre = self.node.advanced_head_state(slot)
        proposer = H.get_beacon_proposer_index(cfg, pre)
        if proposer not in self.keys:
            return
        atts = self.node.pool.get_attestations_for_block(
            pre, cfg.MAX_ATTESTATIONS)
        signed, post = produce_block(cfg, pre, slot, self.signer,
                                     attestations=atts)
        self.blocks_proposed += 1
        # local import + gossip publish
        self.node.block_manager.import_block(signed)
        await self.node.gossip.publish(
            BEACON_BLOCK_TOPIC,
            self.spec.schemas.SignedBeaconBlock.serialize(signed))

    # -- 1/3 slot: attest ---------------------------------------------
    async def on_attestation_due(self, slot: int) -> None:
        cfg = self.spec.config
        S = self.spec.schemas
        head_root = self.node.chain.head_root
        state = self.node.advanced_head_state(slot)
        epoch = H.compute_epoch_at_slot(cfg, slot)
        committees_per_slot = H.get_committee_count_per_slot(
            cfg, state, epoch)
        from ..spec.builder import attestation_data_for
        for ci in range(committees_per_slot):
            committee = H.get_beacon_committee(cfg, state, slot, ci)
            mine = [v for v in committee if v in self.keys]
            if not mine:
                continue
            data = attestation_data_for(cfg, state, slot, ci, head_root)
            domain = H.get_domain(cfg, state, DOMAIN_BEACON_ATTESTER, epoch)
            root = H.compute_signing_root(data, domain)
            subnet = compute_subnet_for_attestation(
                cfg, committees_per_slot, slot, ci)
            for v in mine:
                bits = tuple(m == v for m in committee)
                att = S.Attestation(aggregation_bits=bits, data=data,
                                    signature=self.signer(v, root))
                self.attestations_sent += 1
                self.node.attestation_manager.add_attestation(att)
                await self.node.gossip.publish(
                    attestation_subnet_topic(subnet),
                    S.Attestation.serialize(att))

    # -- 2/3 slot: aggregate ------------------------------------------
    async def on_aggregation_due(self, slot: int) -> None:
        cfg = self.spec.config
        S = self.spec.schemas
        state = self.node.advanced_head_state(slot)
        epoch = H.compute_epoch_at_slot(cfg, slot)
        committees_per_slot = H.get_committee_count_per_slot(
            cfg, state, epoch)
        for ci in range(committees_per_slot):
            committee = H.get_beacon_committee(cfg, state, slot, ci)
            for v in committee:
                if v not in self.keys:
                    continue
                proof = get_selection_proof(cfg, state, slot, v,
                                            self.signer)
                if not is_aggregator(cfg, state, slot, ci, proof):
                    continue
                from ..spec.builder import attestation_data_for
                data = attestation_data_for(
                    cfg, state, slot, ci, self.node.chain.head_root)
                agg = self.node.pool.get_aggregate(data)
                if agg is None:
                    continue
                signed_agg = produce_aggregate_and_proof(
                    cfg, state, agg, v, self.signer)
                await self.node.gossip.publish(
                    AGGREGATE_TOPIC,
                    S.SignedAggregateAndProof.serialize(signed_agg))
                break   # one aggregator per committee is enough locally
