"""Aggregating attestation pool for block production.

Equivalent of the reference's AggregatingAttestationPool +
MatchingDataAttestationGroup + AggregateAttestationBuilder (reference:
ethereum/statetransition/src/main/java/tech/pegasys/teku/
statetransition/attestation/): attestations with identical
AttestationData group together; non-overlapping bitlists OR into larger
aggregates; block production takes the best aggregates not yet included.
"""

from collections import defaultdict
from typing import Dict, List, Optional, Set, Tuple

from ..crypto import bls
from ..spec import Spec
from ..spec import helpers as H


class _Group:
    """All seen attestations for one AttestationData."""

    def __init__(self, data):
        self.data = data
        self.attestations: List = []
        self._seen_bits: Set[Tuple[bool, ...]] = set()

    def add(self, attestation) -> None:
        bits = tuple(attestation.aggregation_bits)
        if bits in self._seen_bits:
            return
        self._seen_bits.add(bits)
        self.attestations.append(attestation)

    def best_aggregate(self):
        """Greedy OR of non-overlapping bitlists, largest first
        (reference AggregateAttestationBuilder.aggregateAttestations).
        The aggregate keeps the stored attestations' own container
        family (electra shapes carry their committee_bits through)."""
        if not self.attestations:
            return None
        by_size = sorted(self.attestations,
                         key=lambda a: -sum(a.aggregation_bits))
        acc_bits = list(by_size[0].aggregation_bits)
        sigs = [by_size[0].signature]
        for att in by_size[1:]:
            bits = att.aggregation_bits
            if any(a and b for a, b in zip(acc_bits, bits)):
                continue
            acc_bits = [a or b for a, b in zip(acc_bits, bits)]
            sigs.append(att.signature)
        cls = type(by_size[0])
        kw = dict(
            aggregation_bits=tuple(acc_bits), data=self.data,
            signature=sigs[0] if len(sigs) == 1
            else bls.aggregate_signatures(sigs))
        if "committee_bits" in cls._ssz_fields:
            kw["committee_bits"] = by_size[0].committee_bits
        return cls(**kw)


class AggregatingAttestationPool:
    def __init__(self, spec: Spec, max_groups: int = 1024):
        self.spec = spec
        self._groups: Dict[bytes, _Group] = {}
        self._max_groups = max_groups

    @staticmethod
    def _group_key(attestation) -> bytes:
        """Pre-electra: one group per AttestationData.  Electra: the
        data no longer names the committee, so groups are scoped by
        (data, committee_bits) — bitlists from different committees
        must never OR together."""
        key = attestation.data.htr()
        cb = getattr(attestation, "committee_bits", None)
        if cb is not None:
            key += bytes(int(b) for b in cb)
        return key

    def add(self, attestation) -> None:
        key = self._group_key(attestation)
        group = self._groups.get(key)
        if group is None:
            if len(self._groups) >= self._max_groups:
                return
            group = self._groups[key] = _Group(attestation.data)
        group.add(attestation)

    def get_aggregate(self, data,
                      committee_index: Optional[int] = None
                      ) -> Optional[object]:
        """Best current aggregate for the given AttestationData (the
        aggregator duty's getAggregate).  Electra duties pass their
        committee_index, since the data alone no longer scopes one."""
        return self.get_aggregate_by_root(data.htr(), committee_index)

    def get_aggregate_by_root(self, data_root: bytes,
                              committee_index: Optional[int] = None
                              ) -> Optional[object]:
        """Aggregate keyed by AttestationData root — the REST
        aggregate_attestation endpoint's lookup shape.  For electra
        groups (root + committee_bits keys) an explicit committee
        narrows the lookup; otherwise the first matching group wins."""
        group = self._groups.get(data_root)
        if group is None and committee_index is not None:
            # an explicit committee narrows the lookup — and a miss is
            # a miss (falling back to another committee's group would
            # hand the aggregator a wrong-committee aggregate)
            cb = tuple(i == committee_index for i in range(
                self.spec.config.MAX_COMMITTEES_PER_SLOT))
            group = self._groups.get(data_root
                                     + bytes(int(b) for b in cb))
        elif group is None:
            for key, g in self._groups.items():
                if key.startswith(data_root):
                    group = g
                    break
        if group is None:
            return None
        return group.best_aggregate()

    def _includable(self, data, state, current, previous,
                    no_upper_window) -> bool:
        cfg = self.spec.config
        if data.target.epoch not in (current, previous):
            return False
        if data.slot + cfg.MIN_ATTESTATION_INCLUSION_DELAY > state.slot:
            return False
        if not no_upper_window \
                and state.slot > data.slot + cfg.SLOTS_PER_EPOCH:
            return False
        # source must match the state the block will execute on
        expected_source = (state.current_justified_checkpoint
                           if data.target.epoch == current
                           else state.previous_justified_checkpoint)
        return data.source == expected_source

    def get_attestations_for_block(self, state, limit: int) -> List:
        """Includable aggregates for a block on `state` (reference
        AggregatingAttestationPool.getAttestationsForBlock).  Electra
        merges every committee with the same AttestationData into ONE
        on-chain attestation (multi-bit committee_bits, concatenated
        aggregation_bits) — EIP-7549 lowered the per-block cap to 8 on
        the premise that a slot's committees share one entry."""
        cfg = self.spec.config
        out = []
        current = H.get_current_epoch(cfg, state)
        previous = H.get_previous_epoch(cfg, state)
        from ..spec.milestones import SpecMilestone
        milestone = self.spec.milestone_at_slot(state.slot)
        no_upper_window = milestone >= SpecMilestone.DENEB   # EIP-7045
        if milestone >= SpecMilestone.ELECTRA:
            return self._electra_attestations_for_block(
                state, limit, current, previous, no_upper_window)
        for group in sorted(self._groups.values(),
                            key=lambda g: -g.data.slot):
            data = group.data
            # pre-electra packing never includes electra shapes
            if group.attestations and hasattr(group.attestations[0],
                                              "committee_bits"):
                continue
            if not self._includable(data, state, current, previous,
                                    no_upper_window):
                continue
            agg = group.best_aggregate()
            if agg is not None:
                out.append(agg)
            if len(out) >= limit:
                break
        return out

    def _electra_attestations_for_block(self, state, limit: int,
                                        current, previous,
                                        no_upper_window) -> List:
        by_data: Dict[bytes, List[_Group]] = defaultdict(list)
        for group in self._groups.values():
            if not group.attestations or not hasattr(
                    group.attestations[0], "committee_bits"):
                continue
            if not self._includable(group.data, state, current,
                                    previous, no_upper_window):
                continue
            by_data[group.data.htr()].append(group)
        out = []
        for groups in sorted(by_data.values(),
                             key=lambda gs: -gs[0].data.slot):
            per_committee = []
            for g in groups:
                agg = g.best_aggregate()
                if agg is None:
                    continue
                set_bits = [i for i, b in enumerate(agg.committee_bits)
                            if b]
                if len(set_bits) != 1:
                    continue    # pool stores one-hot groups only
                per_committee.append((set_bits[0], agg))
            if not per_committee:
                continue
            per_committee.sort(key=lambda t: t[0])
            cls = type(per_committee[0][1])
            committees = {ci for ci, _ in per_committee}
            merged_bits: List[bool] = []
            sigs = []
            for ci, agg in per_committee:
                merged_bits.extend(agg.aggregation_bits)
                sigs.append(agg.signature)
            out.append(cls(
                aggregation_bits=tuple(merged_bits),
                data=per_committee[0][1].data,
                signature=sigs[0] if len(sigs) == 1
                else bls.aggregate_signatures(sigs),
                committee_bits=tuple(
                    i in committees for i in range(
                        self.spec.config.MAX_COMMITTEES_PER_SLOT))))
            if len(out) >= limit:
                break
        return out

    def prune(self, finalized_epoch: int) -> None:
        cfg = self.spec.config
        drop = [k for k, g in self._groups.items()
                if g.data.target.epoch < finalized_epoch]
        for k in drop:
            del self._groups[k]
