"""Aggregating attestation pool for block production.

Equivalent of the reference's AggregatingAttestationPool +
MatchingDataAttestationGroup + AggregateAttestationBuilder (reference:
ethereum/statetransition/src/main/java/tech/pegasys/teku/
statetransition/attestation/): attestations with identical
AttestationData group together; non-overlapping bitlists OR into larger
aggregates; block production takes the best aggregates not yet included.
"""

from collections import defaultdict
from typing import Dict, List, Optional, Set, Tuple

from ..crypto import bls
from ..spec import Spec
from ..spec import helpers as H


class _Group:
    """All seen attestations for one AttestationData."""

    def __init__(self, data):
        self.data = data
        self.attestations: List = []
        self._seen_bits: Set[Tuple[bool, ...]] = set()

    def add(self, attestation) -> None:
        bits = tuple(attestation.aggregation_bits)
        if bits in self._seen_bits:
            return
        self._seen_bits.add(bits)
        self.attestations.append(attestation)

    def best_aggregate(self, schema):
        """Greedy OR of non-overlapping bitlists, largest first
        (reference AggregateAttestationBuilder.aggregateAttestations)."""
        if not self.attestations:
            return None
        by_size = sorted(self.attestations,
                         key=lambda a: -sum(a.aggregation_bits))
        acc_bits = list(by_size[0].aggregation_bits)
        sigs = [by_size[0].signature]
        for att in by_size[1:]:
            bits = att.aggregation_bits
            if any(a and b for a, b in zip(acc_bits, bits)):
                continue
            acc_bits = [a or b for a, b in zip(acc_bits, bits)]
            sigs.append(att.signature)
        return schema(
            aggregation_bits=tuple(acc_bits), data=self.data,
            signature=sigs[0] if len(sigs) == 1
            else bls.aggregate_signatures(sigs))


class AggregatingAttestationPool:
    def __init__(self, spec: Spec, max_groups: int = 1024):
        self.spec = spec
        self._groups: Dict[bytes, _Group] = {}
        self._max_groups = max_groups

    def add(self, attestation) -> None:
        key = attestation.data.htr()
        group = self._groups.get(key)
        if group is None:
            if len(self._groups) >= self._max_groups:
                return
            group = self._groups[key] = _Group(attestation.data)
        group.add(attestation)

    def get_aggregate(self, data) -> Optional[object]:
        """Best current aggregate for the given AttestationData (the
        aggregator duty's getAggregate)."""
        return self.get_aggregate_by_root(data.htr())

    def get_aggregate_by_root(self, data_root: bytes) -> Optional[object]:
        """Aggregate keyed by AttestationData root — the REST
        aggregate_attestation endpoint's lookup shape."""
        group = self._groups.get(data_root)
        if group is None:
            return None
        return group.best_aggregate(self.spec.schemas.Attestation)

    def get_attestations_for_block(self, state, limit: int) -> List:
        """Includable aggregates for a block on `state` (reference
        AggregatingAttestationPool.getAttestationsForBlock)."""
        cfg = self.spec.config
        out = []
        current = H.get_current_epoch(cfg, state)
        previous = H.get_previous_epoch(cfg, state)
        for group in sorted(self._groups.values(),
                            key=lambda g: -g.data.slot):
            data = group.data
            if data.target.epoch not in (current, previous):
                continue
            if not (data.slot + cfg.MIN_ATTESTATION_INCLUSION_DELAY
                    <= state.slot <= data.slot + cfg.SLOTS_PER_EPOCH):
                continue
            # source must match the state the block will execute on
            expected_source = (state.current_justified_checkpoint
                               if data.target.epoch == current
                               else state.previous_justified_checkpoint)
            if data.source != expected_source:
                continue
            agg = group.best_aggregate(self.spec.schemas.Attestation)
            if agg is not None:
                out.append(agg)
            if len(out) >= limit:
                break
        return out

    def prune(self, finalized_epoch: int) -> None:
        cfg = self.spec.config
        drop = [k for k, g in self._groups.items()
                if g.data.target.epoch < finalized_epoch]
        for k in drop:
            del self._groups[k]
