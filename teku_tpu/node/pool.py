"""Aggregating attestation pool for block production.

Equivalent of the reference's AggregatingAttestationPool +
MatchingDataAttestationGroup + AggregateAttestationBuilder (reference:
ethereum/statetransition/src/main/java/tech/pegasys/teku/
statetransition/attestation/): attestations with identical
AttestationData group together; non-overlapping bitlists OR into larger
aggregates; block production takes the best aggregates not yet included.
"""

from collections import defaultdict
from typing import Dict, List, Optional, Set, Tuple

from ..crypto import bls
from ..spec import Spec
from ..spec import helpers as H


class _Group:
    """All seen attestations for one AttestationData."""

    def __init__(self, data):
        self.data = data
        self.attestations: List = []
        self._seen_bits: Set[Tuple[bool, ...]] = set()

    def add(self, attestation) -> None:
        bits = tuple(attestation.aggregation_bits)
        if bits in self._seen_bits:
            return
        self._seen_bits.add(bits)
        self.attestations.append(attestation)

    def best_aggregate(self):
        """Greedy OR of non-overlapping bitlists, largest first
        (reference AggregateAttestationBuilder.aggregateAttestations).
        The aggregate keeps the stored attestations' own container
        family (electra shapes carry their committee_bits through)."""
        if not self.attestations:
            return None
        by_size = sorted(self.attestations,
                         key=lambda a: -sum(a.aggregation_bits))
        acc_bits = list(by_size[0].aggregation_bits)
        sigs = [by_size[0].signature]
        for att in by_size[1:]:
            bits = att.aggregation_bits
            if any(a and b for a, b in zip(acc_bits, bits)):
                continue
            acc_bits = [a or b for a, b in zip(acc_bits, bits)]
            sigs.append(att.signature)
        cls = type(by_size[0])
        kw = dict(
            aggregation_bits=tuple(acc_bits), data=self.data,
            signature=sigs[0] if len(sigs) == 1
            else bls.aggregate_signatures(sigs))
        if "committee_bits" in cls._ssz_fields:
            kw["committee_bits"] = by_size[0].committee_bits
        return cls(**kw)


class AggregatingAttestationPool:
    def __init__(self, spec: Spec, max_groups: int = 1024):
        self.spec = spec
        self._groups: Dict[bytes, _Group] = {}
        self._max_groups = max_groups

    @staticmethod
    def _group_key(attestation) -> bytes:
        """Pre-electra: one group per AttestationData.  Electra: the
        data no longer names the committee, so groups are scoped by
        (data, committee_bits) — bitlists from different committees
        must never OR together."""
        key = attestation.data.htr()
        cb = getattr(attestation, "committee_bits", None)
        if cb is not None:
            key += bytes(int(b) for b in cb)
        return key

    def add(self, attestation) -> None:
        key = self._group_key(attestation)
        group = self._groups.get(key)
        if group is None:
            if len(self._groups) >= self._max_groups:
                return
            group = self._groups[key] = _Group(attestation.data)
        group.add(attestation)

    def get_aggregate(self, data,
                      committee_index: Optional[int] = None
                      ) -> Optional[object]:
        """Best current aggregate for the given AttestationData (the
        aggregator duty's getAggregate).  Electra duties pass their
        committee_index, since the data alone no longer scopes one."""
        return self.get_aggregate_by_root(data.htr(), committee_index)

    def get_aggregate_by_root(self, data_root: bytes,
                              committee_index: Optional[int] = None
                              ) -> Optional[object]:
        """Aggregate keyed by AttestationData root — the REST
        aggregate_attestation endpoint's lookup shape.  For electra
        groups (root + committee_bits keys) an explicit committee
        narrows the lookup; otherwise the first matching group wins."""
        group = self._groups.get(data_root)
        if group is None and committee_index is not None:
            # an explicit committee narrows the lookup — and a miss is
            # a miss (falling back to another committee's group would
            # hand the aggregator a wrong-committee aggregate)
            cb = tuple(i == committee_index for i in range(
                self.spec.config.MAX_COMMITTEES_PER_SLOT))
            group = self._groups.get(data_root
                                     + bytes(int(b) for b in cb))
        elif group is None:
            for key, g in self._groups.items():
                if key.startswith(data_root):
                    group = g
                    break
        if group is None:
            return None
        return group.best_aggregate()

    def get_attestations_for_block(self, state, limit: int) -> List:
        """Includable aggregates for a block on `state` (reference
        AggregatingAttestationPool.getAttestationsForBlock)."""
        cfg = self.spec.config
        out = []
        current = H.get_current_epoch(cfg, state)
        previous = H.get_previous_epoch(cfg, state)
        from ..spec.milestones import SpecMilestone
        milestone = self.spec.milestone_at_slot(state.slot)
        no_upper_window = milestone >= SpecMilestone.DENEB   # EIP-7045
        want_committee_bits = milestone >= SpecMilestone.ELECTRA
        for group in sorted(self._groups.values(),
                            key=lambda g: -g.data.slot):
            data = group.data
            # across the electra fork boundary the container family
            # changes: a block body only carries its own fork's shape
            has_cb = hasattr(group.attestations[0], "committee_bits") \
                if group.attestations else False
            if has_cb != want_committee_bits:
                continue
            if data.target.epoch not in (current, previous):
                continue
            if data.slot + cfg.MIN_ATTESTATION_INCLUSION_DELAY \
                    > state.slot:
                continue
            if not no_upper_window \
                    and state.slot > data.slot + cfg.SLOTS_PER_EPOCH:
                continue
            # source must match the state the block will execute on
            expected_source = (state.current_justified_checkpoint
                               if data.target.epoch == current
                               else state.previous_justified_checkpoint)
            if data.source != expected_source:
                continue
            agg = group.best_aggregate()
            if agg is not None:
                out.append(agg)
            if len(out) >= limit:
                break
        return out

    def prune(self, finalized_epoch: int) -> None:
        cfg = self.spec.config
        drop = [k for k, g in self._groups.items()
                if g.data.target.epoch < finalized_epoch]
        for k in drop:
            del self._groups[k]
