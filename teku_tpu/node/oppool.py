"""Operation pools: proposer/attester slashings + voluntary exits.

Equivalent of the reference's OperationPool family (reference:
ethereum/statetransition/src/main/java/tech/pegasys/teku/
statetransition/OperationPool.java, SimpleOperationPool,
MappedOperationPool): gossip/API-submitted operations are validated on
entry, deduplicated, selected for blocks by APPLYING them sequentially
(so mutually conflicting ops can't poison a proposal), and pruned when
included or invalidated on-chain.
"""

import logging
from typing import Callable, Dict, List, Optional

from ..spec.verifiers import SIMPLE

_LOG = logging.getLogger(__name__)


class OperationPool:
    """`apply_fn(state, op) -> new_state` both validates (by raising)
    and advances the selection state."""

    def __init__(self, name: str, key_fn: Callable, apply_fn: Callable,
                 max_size: int = 256):
        self.name = name
        self._key = key_fn
        self._apply = apply_fn
        self._ops: Dict = {}
        self._max = max_size

    def _valid(self, state, op) -> bool:
        try:
            self._apply(state, op)
            return True
        except Exception:
            return False

    def add(self, state, op) -> bool:
        key = self._key(op)
        if key in self._ops:
            return False
        # validate BEFORE the capacity check so junk can never occupy
        # a slot a valid op then gets refused for
        if not self._valid(state, op):
            return False
        if len(self._ops) >= self._max:
            return False
        self._ops[key] = op
        return True

    def get_for_block(self, limit: int, state=None) -> List:
        """Select ops by applying each to a RUNNING state: op #2 is
        checked against the world where op #1 already executed, so the
        selection can never make the proposal itself invalid.  Entries
        that fail against the canonical state are evicted (self-healing
        against on-chain invalidation under a different key)."""
        out = []
        if state is None:
            return list(self._ops.values())[:limit]
        dead = []
        for key, op in self._ops.items():
            if len(out) >= limit:
                break
            try:
                state = self._apply(state, op)
                out.append(op)
            except Exception:
                dead.append(key)
        for key in dead:
            del self._ops[key]
        return out

    def on_included(self, ops) -> None:
        for op in ops:
            self._ops.pop(self._key(op), None)

    def __len__(self) -> int:
        return len(self._ops)


def make_operation_pools(cfg):
    """The phase0 pools + the capella bls-change pool, with the spec
    process_* functions as their apply/validate rules (reference:
    SignedBlsToExecutionChangeValidator delegates to the same spec
    check + signature)."""
    from ..spec import block as B
    from ..spec.capella.block import process_bls_to_execution_change

    def _apply(fn):
        return lambda state, op: fn(cfg, state, op, SIMPLE)

    return {
        "proposer_slashings": OperationPool(
            "proposer_slashings",
            key_fn=lambda op: op.signed_header_1.message.proposer_index,
            apply_fn=_apply(B.process_proposer_slashing)),
        "attester_slashings": OperationPool(
            "attester_slashings",
            key_fn=lambda op: op.htr(),
            apply_fn=_apply(B.process_attester_slashing)),
        "voluntary_exits": OperationPool(
            "voluntary_exits",
            key_fn=lambda op: op.message.validator_index,
            apply_fn=_apply(B.process_voluntary_exit)),
        # pre-capella states simply fail the apply rule, so the pool
        # stays empty until the fork activates
        "bls_to_execution_changes": OperationPool(
            "bls_to_execution_changes",
            key_fn=lambda op: op.message.validator_index,
            apply_fn=_apply(process_bls_to_execution_change)),
    }
