"""Checkpoint sync: bootstrap a node from a remote finalized state.

Equivalent of the reference's --checkpoint-sync-url boot path
(reference: services/beaconchain/.../BeaconChainController.java:
1399-1461 fetching the initial state over REST, validated against weak
subjectivity per WeakSubjectivityValidator before use): fetch the
finalized state and its block, cross-check state_root, run the
weak-subjectivity window check, and build the fork-choice store
anchored there.  The node then follows gossip/sync forward; historical
backfill can fill in the past via blocks-by-range.
"""

import logging
import time
import urllib.request

from ..spec import Spec
from ..spec.codec import deserialize_signed_block, deserialize_state
from ..spec.weak_subjectivity import WeakSubjectivityValidator
from ..storage.store import Store

_LOG = logging.getLogger(__name__)


def fetch_checkpoint_anchor(spec: Spec, base_url: str,
                            timeout: float = 30.0):
    """(anchor_state, signed_anchor_block) from a trusted provider's
    REST API — the state/block pair of the provider's finalized
    checkpoint, cross-validated."""
    base = base_url.rstrip("/")

    def get(path: str) -> bytes:
        req = urllib.request.Request(
            base + path,
            headers={"Accept": "application/octet-stream"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read()

    state = deserialize_state(
        spec.config, get("/eth/v2/debug/beacon/states/finalized"))
    signed = deserialize_signed_block(
        spec.config, get("/eth/v2/beacon/blocks/finalized"))
    block = signed.message
    if block.state_root != state.htr():
        # finalization advanced between the two GETs: fetch the block
        # the state we already hold points at (its own header root)
        root = state.latest_block_header.copy_with(
            state_root=state.htr()).htr()
        signed = deserialize_signed_block(
            spec.config, get(f"/eth/v2/beacon/blocks/0x{root.hex()}"))
        block = signed.message
    if block.state_root != state.htr():
        raise ValueError("checkpoint provider's block/state disagree")
    if block.slot != state.slot:
        raise ValueError("checkpoint block and state are from "
                         "different slots")
    return state, signed


def checkpoint_sync_store(spec: Spec, base_url: str,
                          now: float = None) -> Store:
    """A fork-choice store anchored at a remote finalized checkpoint,
    weak-subjectivity validated against wall-clock time."""
    state, signed = fetch_checkpoint_anchor(spec, base_url)
    now = time.time() if now is None else now
    current_epoch = max(
        0, int(now - state.genesis_time)
        // spec.config.SECONDS_PER_SLOT) // spec.config.SLOTS_PER_EPOCH
    WeakSubjectivityValidator(spec.config).validate_anchor(
        state, current_epoch)
    store = Store(spec.config, state, signed.message)
    # keep the REAL signed envelope so RPC serves the true anchor
    store.signed_blocks[signed.message.htr()] = signed
    _LOG.info("checkpoint sync: anchored at slot %d (epoch %d)",
              state.slot, current_epoch)
    return store
