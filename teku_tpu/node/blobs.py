"""Blob sidecars: containers, tracking pool, availability checking.

Equivalent of the reference's blob plumbing (reference: ethereum/
statetransition/src/main/java/tech/pegasys/teku/statetransition/blobs/
BlockBlobSidecarsTrackersPool.java + BlobSidecarManager, and the
fork-choice availability gate ForkChoiceBlobSidecarsAvailability
Checker invoked from ForkChoice.onBlock): sidecars gossip per index,
collect per block root, and a block is importable only when every
commitment it carries has an availability-checked sidecar (KZG proof
verified on this repo's pairing base).

The deneb state/body containers land with the deneb milestone; this
module is the milestone-independent substrate (the reference splits it
the same way — statetransition/blobs has no fork dependency).
"""

import logging
from typing import Dict, List, Optional, Sequence

from ..crypto import kzg
from ..infra.collections import LimitedMap
from ..ssz import ByteList, Bytes32, Bytes48, Container, uint64
from ..ssz.types import _ContainerMeta

_LOG = logging.getLogger(__name__)

MAX_BLOBS_PER_BLOCK = 6

BlobSidecar = _ContainerMeta("BlobSidecar", (Container,), {
    "__annotations__": {
        "index": uint64,
        "blob": ByteList(kzg.BYTES_PER_BLOB),
        "kzg_commitment": Bytes48,
        "kzg_proof": Bytes48,
        "block_root": Bytes32,
        "slot": uint64,
    }})


class AvailabilityResult:
    AVAILABLE = "available"
    PENDING = "pending"          # sidecars still missing
    INVALID = "invalid"          # a proof failed — block unimportable


class BlobSidecarPool:
    """Per-block sidecar trackers (reference
    BlockBlobSidecarsTrackersPool): sidecars arrive out of order from
    gossip/RPC; the availability check runs once all indices are in."""

    def __init__(self, setup: Optional[kzg.TrustedSetup] = None,
                 max_blocks: int = 64):
        self._by_block: LimitedMap = LimitedMap(max_blocks)
        self._setup = setup
        self._verified: LimitedMap = LimitedMap(256)

    def add_sidecar(self, sidecar: BlobSidecar) -> bool:
        """Track one gossiped sidecar.  The sidecar's OWN proof is
        verified at the door and the bucket is keyed by
        (index, commitment): a junk sidecar can neither occupy an index
        (proof fails → dropped) nor shadow the honest one for the same
        index (different commitment → separate slot) — first-wins dedup
        on bare indices would let one bad message brick the block."""
        if sidecar.index >= MAX_BLOBS_PER_BLOCK:
            return False
        if len(sidecar.blob) != kzg.BYTES_PER_BLOB:
            return False
        bucket = self._by_block.get(sidecar.block_root)
        if bucket is None:
            bucket = {}
            self._by_block.put(sidecar.block_root, bucket)
        key = (sidecar.index, sidecar.kzg_commitment)
        if key in bucket:
            return False
        if not kzg.verify_blob_kzg_proof(
                bytes(sidecar.blob), sidecar.kzg_commitment,
                sidecar.kzg_proof, self._setup):
            return False
        bucket[key] = sidecar
        return True

    def sidecars_for(self, block_root: bytes) -> List[BlobSidecar]:
        bucket = self._by_block.get(block_root) or {}
        return [bucket[k] for k in sorted(bucket)]

    # -- the fork-choice gate -----------------------------------------
    def check_availability(self, block_root: bytes,
                           expected_commitments: Sequence[bytes]) -> str:
        """reference ForkChoiceBlobSidecarsAvailabilityChecker: every
        block commitment needs a proof-verified sidecar (verification
        happened at add time; here we only match commitments)."""
        if not expected_commitments:
            return AvailabilityResult.AVAILABLE
        cache_key = (block_root, bytes().join(expected_commitments))
        cached = self._verified.get(cache_key)
        if cached is not None:
            return cached
        bucket = self._by_block.get(block_root) or {}
        for i, commitment in enumerate(expected_commitments):
            if (i, commitment) not in bucket:
                return AvailabilityResult.PENDING
        self._verified.put(cache_key, AvailabilityResult.AVAILABLE)
        return AvailabilityResult.AVAILABLE

    def prune_block(self, block_root: bytes) -> None:
        self._by_block.pop(block_root)
        for key in [k for k in self._verified if k[0] == block_root]:
            self._verified.pop(key)
