"""Blob sidecars: containers, tracking pool, availability checking.

Equivalent of the reference's blob plumbing (reference: ethereum/
statetransition/src/main/java/tech/pegasys/teku/statetransition/blobs/
BlockBlobSidecarsTrackersPool.java + BlobSidecarManager, and the
fork-choice availability gate ForkChoiceBlobSidecarsAvailability
Checker invoked from ForkChoice.onBlock): sidecars gossip per index,
collect per block root, and a block is importable only when every
commitment it carries has an availability-checked sidecar (KZG proof
verified on this repo's pairing base).

The deneb state/body containers land with the deneb milestone; this
module is the milestone-independent substrate (the reference splits it
the same way — statetransition/blobs has no fork dependency).
"""

import logging
from typing import Dict, List, Optional, Sequence

from ..crypto import kzg
from ..infra.collections import LimitedMap
from ..ssz import ByteList, Bytes32, Bytes48, Container, uint64
from ..ssz.types import _ContainerMeta

_LOG = logging.getLogger(__name__)

MAX_BLOBS_PER_BLOCK = 6

BlobSidecar = _ContainerMeta("BlobSidecar", (Container,), {
    "__annotations__": {
        "index": uint64,
        "blob": ByteList(kzg.BYTES_PER_BLOB),
        "kzg_commitment": Bytes48,
        "kzg_proof": Bytes48,
        "block_root": Bytes32,
        "slot": uint64,
    }})


class AvailabilityResult:
    AVAILABLE = "available"
    PENDING = "pending"          # sidecars still missing
    INVALID = "invalid"          # a proof failed — block unimportable


class BlobSidecarPool:
    """Per-block sidecar trackers (reference
    BlockBlobSidecarsTrackersPool): sidecars arrive out of order from
    gossip/RPC; the availability check runs once all indices are in."""

    def __init__(self, setup: Optional[kzg.TrustedSetup] = None,
                 max_blocks: int = 64,
                 max_blobs: int = MAX_BLOBS_PER_BLOCK):
        self._by_block: LimitedMap = LimitedMap(max_blocks)
        self._setup = setup
        self._verified: LimitedMap = LimitedMap(256)
        # wire-format (deneb) sidecars retained for req/resp serving
        self._wire: LimitedMap = LimitedMap(max_blocks)
        self.max_blobs = max_blobs

    def add_sidecar(self, sidecar: BlobSidecar,
                    proof_checked: bool = False) -> bool:
        """Track one gossiped sidecar.  The sidecar's OWN proof is
        verified at the door and the bucket is keyed by
        (index, commitment): a junk sidecar can neither occupy an index
        (proof fails → dropped) nor shadow the honest one for the same
        index (different commitment → separate slot) — first-wins dedup
        on bare indices would let one bad message brick the block."""
        if sidecar.index >= self.max_blobs:
            return False
        if len(sidecar.blob) != kzg.BYTES_PER_BLOB:
            return False
        bucket = self._by_block.get(sidecar.block_root)
        if bucket is None:
            bucket = {}
            self._by_block.put(sidecar.block_root, bucket)
        key = (sidecar.index, sidecar.kzg_commitment)
        if key in bucket:
            return False
        if not proof_checked and not kzg.verify_blob_kzg_proof(
                bytes(sidecar.blob), sidecar.kzg_commitment,
                sidecar.kzg_proof, self._setup):
            return False
        bucket[key] = sidecar
        return True

    def sidecars_for(self, block_root: bytes) -> List[BlobSidecar]:
        bucket = self._by_block.get(block_root) or {}
        return [bucket[k] for k in sorted(bucket)]

    # -- the fork-choice gate -----------------------------------------
    def check_availability(self, block_root: bytes,
                           expected_commitments: Sequence[bytes]) -> str:
        """reference ForkChoiceBlobSidecarsAvailabilityChecker: every
        block commitment needs a proof-verified sidecar (verification
        happened at add time; here we only match commitments)."""
        if not expected_commitments:
            return AvailabilityResult.AVAILABLE
        cache_key = (block_root, bytes().join(expected_commitments))
        cached = self._verified.get(cache_key)
        if cached is not None:
            return cached
        bucket = self._by_block.get(block_root) or {}
        for i, commitment in enumerate(expected_commitments):
            if (i, commitment) not in bucket:
                return AvailabilityResult.PENDING
        self._verified.put(cache_key, AvailabilityResult.AVAILABLE)
        return AvailabilityResult.AVAILABLE

    def prune_block(self, block_root: bytes) -> None:
        self._by_block.pop(block_root)
        self._wire.pop(block_root)
        for key in [k for k in self._verified if k[0] == block_root]:
            self._verified.pop(key)

    def add_spec_sidecar(self, cfg, sidecar,
                         proof_checked: bool = False) -> bool:
        """Track a deneb wire-format sidecar (signed header + inclusion
        proof): the block root is derived from its own header, the
        inclusion proof binds the commitment to that block's body, and
        the blob proof is checked by the regular add path."""
        from ..spec.deneb.block import max_blobs_for_slot
        from ..spec.deneb.datastructures import (
            verify_commitment_inclusion_proof)
        header = sidecar.signed_block_header.message
        # per-sidecar bound from the slot's OWN milestone — never
        # ratchet pool-wide state off a wire-controlled header slot
        if sidecar.index >= max_blobs_for_slot(cfg, header.slot):
            return False
        if not verify_commitment_inclusion_proof(cfg, sidecar):
            return False
        root = header.htr()
        ok = self.add_sidecar(BlobSidecar(
            index=sidecar.index, blob=bytes(sidecar.blob),
            kzg_commitment=sidecar.kzg_commitment,
            kzg_proof=sidecar.kzg_proof,
            block_root=root, slot=header.slot),
            proof_checked=proof_checked)
        if ok:
            bucket = self._wire.get(root)
            if bucket is None:
                bucket = {}
                self._wire.put(root, bucket)
            bucket[sidecar.index] = sidecar
        return ok

    def wire_sidecars_for(self, block_root: bytes) -> List:
        """Deneb wire-format sidecars for one block, index order (the
        req/resp serving shape, reference BlobSidecarsByRoot/Range)."""
        bucket = self._wire.get(block_root) or {}
        return [bucket[i] for i in sorted(bucket)]


def validate_spec_sidecar(cfg, sidecar, state=None,
                          setup: Optional[kzg.TrustedSetup] = None,
                          seen: Optional[set] = None) -> str:
    """Gossip-grade validation of a deneb BlobSidecar (reference:
    statetransition/validation/BlobSidecarGossipValidator — index
    bound, dedup, inclusion proof, proposer header signature, KZG
    proof).  `state` enables the proposer-signature check (any state
    whose shuffling covers the sidecar's slot); returns an
    "accept"/"ignore"/"reject" string matching ValidationResult values.
    """
    from ..spec import helpers as H
    from ..spec.config import DOMAIN_BEACON_PROPOSER
    from ..spec.deneb.datastructures import (
        verify_commitment_inclusion_proof)
    from ..crypto import bls
    from ..spec.deneb.block import max_blobs_for_slot
    header = sidecar.signed_block_header.message
    if sidecar.index >= max_blobs_for_slot(cfg, header.slot):
        return "reject"
    key = (header.htr(), sidecar.index)
    if seen is not None and key in seen:
        return "ignore"
    if not verify_commitment_inclusion_proof(cfg, sidecar):
        return "reject"
    if state is not None:
        try:
            proposer = state.validators[header.proposer_index]
        except IndexError:
            return "reject"
        # the claimed proposer must BE the slot's expected proposer —
        # otherwise any validator could sign headers for junk sidecars
        try:
            expected = H.get_beacon_proposer_index(cfg, state,
                                                   slot=header.slot)
        except ValueError:
            return "ignore"   # state can't answer for this epoch
        if header.proposer_index != expected:
            return "reject"
        domain = H.get_domain(cfg, state, DOMAIN_BEACON_PROPOSER,
                              header.slot // cfg.SLOTS_PER_EPOCH)
        root = H.compute_signing_root(header, domain)
        if not bls.verify(proposer.pubkey, root,
                          sidecar.signed_block_header.signature):
            return "reject"
    if not kzg.verify_blob_kzg_proof(bytes(sidecar.blob),
                                     sidecar.kzg_commitment,
                                     sidecar.kzg_proof, setup):
        return "reject"
    if seen is not None:
        seen.add(key)
    return "accept"
