"""Node runtime: chain data, gossip, validators, managers, wiring.

Reference: /root/reference/services/beaconchain/ +
/root/reference/ethereum/statetransition/.
"""

from .chaindata import RecentChainData
from .devnet import Devnet
from .gossip import InMemoryGossipNetwork, TopicHandler, ValidationResult
from .managers import AttestationManager, BlockManager
from .node import BeaconNode
from .pool import AggregatingAttestationPool
