"""In-process devnet: N nodes, loopback gossip, interop validators.

The minimum end-to-end slice (SURVEY §7 stage 5): several BeaconNodes
share an InMemoryGossipNetwork, interop validators split across them,
every signature flows through each node's batching verification
service, and the chain justifies + finalizes.  The reference's
acceptance tests build the same topology with containers
(acceptance-tests/.../dsl/TekuNode.java); here it is one process and a
manually-advanced clock, which is what unit tests and the bench
latency phase drive.
"""

import asyncio
import logging
from typing import Dict, List, Optional

from ..infra.service import ServiceController
from ..spec import create_spec, Spec
from ..spec.genesis import interop_genesis
from .gossip import InMemoryGossipNetwork
from .node import BeaconNode

_LOG = logging.getLogger(__name__)


class Devnet:
    def __init__(self, n_nodes: int = 2, n_validators: int = 32,
                 network: str = "minimal", genesis_time: int = 1578009600,
                 spec: Optional[Spec] = None):
        self.spec = spec or create_spec(network)
        state, sks = interop_genesis(self.spec.config, n_validators,
                                     genesis_time)
        self.genesis_state = state
        self.net = InMemoryGossipNetwork()
        self.nodes: List[BeaconNode] = []
        self.clients: List = []
        from ..validator import (BeaconNodeValidatorApi, LocalSigner,
                                 SlashingProtectedSigner, ValidatorClient)
        from ..validator.slashing_protection import SlashingProtector
        for i in range(n_nodes):
            node = BeaconNode(self.spec, state, self.net.endpoint(),
                              name=f"node{i}")
            keys = {v: sks[v] for v in range(n_validators)
                    if v % n_nodes == i}
            self.nodes.append(node)
            # the REAL validator client: duties via the API channel,
            # slashing-protected local signer
            signer = SlashingProtectedSigner(
                LocalSigner(keys), SlashingProtector())
            self.clients.append(ValidatorClient(
                self.spec, BeaconNodeValidatorApi(node), signer,
                sorted(keys)))
        self.controller = ServiceController(self.nodes, "devnet")

    async def start(self) -> None:
        await self.controller.start()

    async def stop(self) -> None:
        await self.controller.stop()

    async def run_slot(self, slot: int) -> None:
        """One full slot: tick everywhere, propose, attest, aggregate —
        the three phases of the reference's SlotProcessor."""
        for node in self.nodes:
            await node.on_slot(slot)
        for client in self.clients:
            await client.on_slot_start(slot)
        for client in self.clients:
            await client.on_attestation_due(slot)
        for client in self.clients:
            await client.on_sync_committee_due(slot)
        for client in self.clients:
            await client.on_aggregation_due(slot)

    async def run_until_slot(self, last_slot: int,
                             first_slot: int = 1) -> None:
        for slot in range(first_slot, last_slot + 1):
            await self.run_slot(slot)

    # -- assertions/queries -------------------------------------------
    def heads(self) -> List[bytes]:
        return [n.chain.head_root for n in self.nodes]

    def heads_converged(self) -> bool:
        return len(set(self.heads())) == 1

    def min_finalized_epoch(self) -> int:
        return min(n.store.finalized_checkpoint.epoch for n in self.nodes)

    def min_justified_epoch(self) -> int:
        return min(n.store.justified_checkpoint.epoch for n in self.nodes)
