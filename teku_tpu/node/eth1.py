"""Eth1 deposit follower: JSON-RPC log polling with reorg-safe follow
distance, feeding the DepositTree and the eth1 voting data.

Equivalent of the reference's pow module (reference: beacon/pow/src/
main/java/tech/pegasys/teku/beacon/pow/Eth1DepositManager.java:38 —
DepositFetcher pulling DepositEvent logs over eth_getLogs,
Eth1HeadTracker following the chain ETH1_FOLLOW_DISTANCE behind head,
ValidatingEth1EventsPublisher asserting deposit-index contiguity, and
reorg handling by replay): every poll advances the follow target,
appends the new deposit events to the provider's tree in log order,
and publishes the candidate eth1_data (root/count at the followed
block) that proposers vote on.

DepositEvent log data is the deposit contract's ABI encoding — five
dynamic `bytes` fields (pubkey 48, withdrawal_credentials 32, amount 8
little-endian, signature 96, index 8 little-endian); the parser here
decodes that exact shape.
"""

import asyncio
import logging
from dataclasses import dataclass
from typing import List, Optional, Tuple

from ..spec.datastructures import DepositData, Eth1Data
from .deposits import DepositProvider

_LOG = logging.getLogger(__name__)

# keccak256("DepositEvent(bytes,bytes,bytes,bytes,bytes)") — the
# deposit contract's only event topic (public constant)
DEPOSIT_EVENT_TOPIC = ("0x649bbc62d0e31342afea4e5cd82d4049e7e1ee912fc0"
                       "889aa790803be39038c5")


@dataclass
class DepositEvent:
    data: DepositData
    index: int
    block_number: int
    block_hash: bytes


@dataclass
class Eth1Block:
    number: int
    hash: bytes
    parent_hash: bytes
    timestamp: int


class Eth1Provider:
    """What the follower needs from an execution client (reference
    Eth1Provider.java)."""

    async def get_latest_block_number(self) -> int:
        raise NotImplementedError

    async def get_block(self, number: int) -> Optional[Eth1Block]:
        raise NotImplementedError

    async def get_deposit_events(self, from_block: int,
                                 to_block: int) -> List[DepositEvent]:
        raise NotImplementedError


# -- ABI codec for DepositEvent --------------------------------------------

def abi_encode_deposit_event(data: DepositData, index: int) -> bytes:
    """The deposit contract's log data layout: head of five 32-byte
    offsets, then per-field [length word || right-padded bytes]."""
    fields = [bytes(data.pubkey), bytes(data.withdrawal_credentials),
              int(data.amount).to_bytes(8, "little"),
              bytes(data.signature), index.to_bytes(8, "little")]
    head = b""
    tail = b""
    offset = 32 * len(fields)
    for f in fields:
        head += offset.to_bytes(32, "big")
        padded = f.ljust((len(f) + 31) // 32 * 32, b"\x00")
        tail += len(f).to_bytes(32, "big") + padded
        offset += 32 + len(padded)
    return head + tail


def abi_decode_deposit_event(raw: bytes) -> Tuple[DepositData, int]:
    def field(i: int) -> bytes:
        off = int.from_bytes(raw[32 * i:32 * i + 32], "big")
        n = int.from_bytes(raw[off:off + 32], "big")
        out = raw[off + 32:off + 32 + n]
        if len(out) != n:
            raise ValueError("truncated ABI field")
        return out

    pubkey, creds, amount, signature, index = (field(i)
                                               for i in range(5))
    if (len(pubkey), len(creds), len(amount), len(signature),
            len(index)) != (48, 32, 8, 96, 8):
        raise ValueError("bad DepositEvent field sizes")
    return DepositData(
        pubkey=pubkey, withdrawal_credentials=creds,
        amount=int.from_bytes(amount, "little"),
        signature=signature), int.from_bytes(index, "little")


# -- JSON-RPC provider ------------------------------------------------------

class JsonRpcEth1Provider(Eth1Provider):
    """eth_blockNumber / eth_getBlockByNumber / eth_getLogs over plain
    HTTP JSON-RPC (reference Web3JEth1Provider)."""

    def __init__(self, host: str, port: int,
                 deposit_contract: str = "0x" + "00" * 20,
                 timeout: float = 10.0):
        self.host = host
        self.port = port
        self.deposit_contract = deposit_contract
        self.timeout = timeout
        self._id = 0

    async def _call(self, method: str, params):
        from ..infra.jsonrpc import http_json_rpc
        self._id += 1
        return await http_json_rpc(self.host, self.port, method, params,
                                   request_id=self._id,
                                   timeout=self.timeout)

    async def get_latest_block_number(self) -> int:
        return int(await self._call("eth_blockNumber", []), 16)

    async def get_block(self, number: int) -> Optional[Eth1Block]:
        out = await self._call("eth_getBlockByNumber",
                               [hex(number), False])
        if out is None:
            return None
        return Eth1Block(
            number=int(out["number"], 16),
            hash=bytes.fromhex(out["hash"][2:]),
            parent_hash=bytes.fromhex(out["parentHash"][2:]),
            timestamp=int(out["timestamp"], 16))

    async def get_deposit_events(self, from_block: int,
                                 to_block: int) -> List[DepositEvent]:
        logs = await self._call("eth_getLogs", [{
            "fromBlock": hex(from_block), "toBlock": hex(to_block),
            "address": self.deposit_contract,
            "topics": [DEPOSIT_EVENT_TOPIC]}])
        events = []
        for log in logs:
            data, index = abi_decode_deposit_event(
                bytes.fromhex(log["data"][2:]))
            events.append(DepositEvent(
                data=data, index=index,
                block_number=int(log["blockNumber"], 16),
                block_hash=bytes.fromhex(log["blockHash"][2:])))
        # eth_getLogs orders within a block but the spec needs global
        # deposit-index order
        events.sort(key=lambda e: e.index)
        return events


# -- the follower -----------------------------------------------------------

class Eth1DepositFollower:
    """Polls the eth1 provider, keeps the DepositProvider's tree in
    sync ETH1_FOLLOW_DISTANCE behind head, and publishes the voting
    eth1_data.  Reorg-safe: the previously-followed block's hash is
    re-checked each poll; a mismatch (reorg deeper than the follow
    distance) rebuilds the tree from scratch, exactly as the reference
    resubscribes from the last valid block."""

    def __init__(self, provider: DepositProvider, eth1: Eth1Provider,
                 follow_distance: int = 8, chunk: int = 1000):
        self.provider = provider
        self.eth1 = eth1
        self.follow_distance = follow_distance
        self.chunk = chunk
        self._followed: Optional[Eth1Block] = None
        self.rebuilds = 0
        self.polls = 0

    async def poll_once(self) -> bool:
        """One follow step; returns True if new deposits were added or
        the voting data advanced."""
        self.polls += 1
        head = await self.eth1.get_latest_block_number()
        target = head - self.follow_distance
        if target < 0:
            return False
        if self._followed is not None:
            prior = await self.eth1.get_block(self._followed.number)
            if prior is None or prior.hash != self._followed.hash:
                # reorg crossed the follow distance: the appended log
                # history is no longer canonical — rebuild
                _LOG.warning("eth1 reorg beyond follow distance; "
                             "rebuilding deposit tree")
                self.rebuilds += 1
                self.provider.reset()
                self._followed = None
        start = 0 if self._followed is None else self._followed.number + 1
        if self._followed is not None and target <= self._followed.number:
            return False
        # ATOMIC poll: gather everything first, mutate only at the end.
        # (a) a transient RPC failure mid-fetch leaves the tree
        #     untouched instead of half-appended (which the contiguity
        #     check would escalate into a full rebuild);
        # (b) the target hash is sampled before AND after the log fetch
        #     — a reorg racing the fetch could otherwise anchor
        #     old-branch deposits under the new branch's block hash,
        #     invisible to the next poll's reorg check
        block_before = await self.eth1.get_block(target)
        if block_before is None:
            return False
        pending: List[DepositEvent] = []
        for frm in range(start, target + 1, self.chunk):
            to = min(frm + self.chunk - 1, target)
            pending.extend(await self.eth1.get_deposit_events(frm, to))
        block_after = await self.eth1.get_block(target)
        if block_after is None or block_after.hash != block_before.hash:
            _LOG.info("eth1 reorg raced the log fetch; retrying")
            return False
        expected = self.provider.tree.count
        for ev in pending:
            if ev.index != expected:
                # gap or duplicate: corrupt view — rebuild next poll
                # (reference ValidatingEth1EventsPublisher throws on
                # non-contiguous indices); nothing was applied yet
                _LOG.warning(
                    "non-contiguous deposit index %d (expected %d)",
                    ev.index, expected)
                self.provider.reset()
                self._followed = None
                return False
            expected += 1
        for ev in pending:
            self.provider.on_deposit(ev.data)
        self._followed = block_after
        self.provider.set_canonical_eth1_data(Eth1Data(
            deposit_root=self.provider.tree.root(),
            deposit_count=self.provider.tree.count,
            block_hash=block_after.hash))
        return True

    async def run(self, poll_interval: float = 2.0) -> None:
        while True:
            try:
                await self.poll_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                _LOG.exception("eth1 poll failed; retrying")
            await asyncio.sleep(poll_interval)
