"""RecentChainData: the chain façade every component queries.

Equivalent of the reference's RecentChainData/CombinedChainDataClient
(reference: storage/src/main/java/tech/pegasys/teku/storage/client/
RecentChainData.java): head/justified/finalized views over the
fork-choice store, block and state lookup, and head-update events.
"""

from typing import Optional

from ..infra.events import (ChainHeadChannel, EventChannels,
                            FinalizedCheckpointChannel)
from ..spec import Spec
from ..storage.store import Store


class RecentChainData:
    def __init__(self, spec: Spec, store: Store,
                 channels: Optional[EventChannels] = None):
        self.spec = spec
        self.store = store
        self._channels = channels or EventChannels()
        self._head_root: bytes = store.justified_checkpoint.root
        self._finalized_epoch = store.finalized_checkpoint.epoch

    # -- queries -------------------------------------------------------
    @property
    def head_root(self) -> bytes:
        return self._head_root

    def head_state(self):
        return self.store.block_states[self._head_root]

    def head_slot(self) -> int:
        return self.store.blocks[self._head_root].slot

    def current_slot(self) -> int:
        return self.store.current_slot

    def get_block(self, root: bytes):
        return self.store.blocks.get(root)

    def get_state(self, root: bytes):
        return self.store.block_states.get(root)

    def contains_block(self, root: bytes) -> bool:
        return root in self.store.blocks

    @property
    def justified_checkpoint(self):
        return self.store.justified_checkpoint

    @property
    def finalized_checkpoint(self):
        return self.store.finalized_checkpoint

    def genesis_time(self) -> int:
        return self.store.genesis_time

    # -- updates -------------------------------------------------------
    def update_head(self) -> bytes:
        """Recompute head via fork choice; emit events on change
        (reference RecentChainData.updateHead)."""
        new_head = self.store.get_head()
        if new_head != self._head_root:
            old = self._head_root
            self._head_root = new_head
            reorg = not self.store.proto.is_descendant(old, new_head)
            self._channels.publisher(ChainHeadChannel).on_chain_head_updated(
                self.store.blocks[new_head].slot, new_head, reorg)
        if self.store.finalized_checkpoint.epoch > self._finalized_epoch:
            self._finalized_epoch = self.store.finalized_checkpoint.epoch
            self._channels.publisher(
                FinalizedCheckpointChannel).on_new_finalized_checkpoint(
                self.store.finalized_checkpoint)
        return self._head_root
