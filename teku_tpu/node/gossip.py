"""Gossip plumbing: topic handlers + an in-memory network.

The transport-agnostic seam mirrors the reference's TopicHandler /
GossipNetwork split (reference: networking/p2p/src/main/java/tech/
pegasys/teku/networking/p2p/gossip/TopicHandler.java and networking/
eth2/.../gossip/topics/topichandlers/Eth2TopicHandler.java:110-130):
handlers receive raw SSZ payloads, decode, hand to an operation
processor, and map the internal validation result to
ACCEPT/IGNORE/REJECT, which the router uses for propagation — so the
same handlers run unchanged over the in-memory bus (devnet/tests) and
the TCP gossip transport (teku_tpu/networking).

Topic names follow the consensus spec: beacon_block,
beacon_attestation_{subnet}, beacon_aggregate_and_proof
(GossipTopicName.java:18).
"""

import asyncio
import enum
import logging
from typing import Awaitable, Callable, Dict, List, Optional

_LOG = logging.getLogger(__name__)


class ValidationResult(enum.Enum):
    """reference: InternalValidationResult"""
    ACCEPT = "accept"
    IGNORE = "ignore"
    SAVE_FOR_FUTURE = "save_for_future"
    REJECT = "reject"


class TopicHandler:
    """Decodes + processes one topic's messages."""

    async def handle_message(self, data: bytes) -> ValidationResult:
        raise NotImplementedError


class SszTopicHandler(TopicHandler):
    """Decode SSZ then delegate (reference Eth2TopicHandler.handleMessage:
    deserialize → async process → map result)."""

    def __init__(self, schema, processor: Callable[[object],
                                                   Awaitable[ValidationResult]],
                 name: str = "topic"):
        self.schema = schema
        self.processor = processor
        self.name = name

    async def handle_message(self, data: bytes) -> ValidationResult:
        try:
            msg = self.schema.deserialize(data)
        except Exception:
            return ValidationResult.REJECT
        try:
            return await self.processor(msg)
        except Exception as exc:
            from ..services.signatures import (
                ServiceCapacityExceededError)
            if isinstance(exc, ServiceCapacityExceededError):
                # brownout/overflow shed: load shedding working as
                # designed — IGNORE the message quietly (the shed is
                # already counted and flight-recorded by the service);
                # a stack trace per shed at 10x overload would be its
                # own denial of service on the log pipeline
                return ValidationResult.IGNORE
            _LOG.exception("processor for %s failed", self.name)
            return ValidationResult.IGNORE


class GossipNetwork:
    """Transport interface: subscribe handlers, publish bytes."""

    async def publish(self, topic: str, data: bytes) -> None:
        raise NotImplementedError

    def subscribe(self, topic: str, handler: TopicHandler) -> None:
        raise NotImplementedError


class InMemoryGossipNetwork(GossipNetwork):
    """Loopback mesh for in-process devnets: publishing delivers to
    every OTHER endpoint's handler; a message a peer REJECTs is not
    re-propagated (gossipsub semantics, simplified to full-mesh).
    The reference achieves the same test topology with real libp2p over
    loopback (Eth2P2PNetworkFactory)."""

    def __init__(self):
        self._endpoints: List["InMemoryGossipEndpoint"] = []
        self.messages_published = 0

    def endpoint(self) -> "InMemoryGossipEndpoint":
        ep = InMemoryGossipEndpoint(self)
        self._endpoints.append(ep)
        return ep

    async def _deliver(self, origin, topic: str, data: bytes) -> None:
        self.messages_published += 1
        for ep in self._endpoints:
            if ep is origin:
                continue
            handler = ep._handlers.get(topic)
            if handler is not None:
                await handler.handle_message(data)


class InMemoryGossipEndpoint(GossipNetwork):
    def __init__(self, net: InMemoryGossipNetwork):
        self._net = net
        self._handlers: Dict[str, TopicHandler] = {}

    def subscribe(self, topic: str, handler: TopicHandler) -> None:
        self._handlers[topic] = handler

    async def publish(self, topic: str, data: bytes) -> None:
        await self._net._deliver(self, topic, data)


def attestation_subnet_topic(subnet_id: int) -> str:
    return f"beacon_attestation_{subnet_id}"


BEACON_BLOCK_TOPIC = "beacon_block"
AGGREGATE_TOPIC = "beacon_aggregate_and_proof"
VOLUNTARY_EXIT_TOPIC = "voluntary_exit"
SYNC_COMMITTEE_TOPIC = "sync_committee"
PROPOSER_SLASHING_TOPIC = "proposer_slashing"
ATTESTER_SLASHING_TOPIC = "attester_slashing"
BLS_TO_EXECUTION_CHANGE_TOPIC = "bls_to_execution_change"
SYNC_CONTRIBUTION_TOPIC = "sync_committee_contribution_and_proof"


def blob_sidecar_topic(subnet_id: int) -> str:
    """Deneb blob sidecars gossip per index subnet (spec
    blob_sidecar_{subnet_id})."""
    return f"blob_sidecar_{subnet_id}"
