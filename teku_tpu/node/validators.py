"""Gossip validators: the admission rules ahead of fork choice.

Equivalent of the reference's statetransition/validation package
(reference: ethereum/statetransition/src/main/java/tech/pegasys/teku/
statetransition/validation/AttestationValidator.java:34-120,
AggregateAttestationValidator.java, BlockGossipValidator.java, shared
GossipValidationHelper): protocol rules first (slot windows, single
bit, known block, committee bounds), THEN the signature enters the
async batch verifier — on the TPU provider that means gossip signatures
ride the device batcher (AsyncBatchSignatureVerifier keeps an
aggregate-and-proof's three signatures atomic in one task).
"""

import functools
import logging
from typing import Optional, Set, Tuple

from ..infra import tracing
from ..spec import Spec
from ..spec import helpers as H
from ..spec.block import is_valid_indexed_attestation
from ..spec.config import (DOMAIN_AGGREGATE_AND_PROOF,
                           DOMAIN_BEACON_ATTESTER, DOMAIN_BEACON_PROPOSER)
from ..infra.collections import LimitedSet
from ..spec.builder import is_aggregator
from ..services.admission import VerifyClass
from ..spec.verifiers import (AsyncBatchSignatureVerifier,
                              AsyncSignatureVerifier)
from .chaindata import RecentChainData
from .gossip import ValidationResult

_LOG = logging.getLogger(__name__)

ACCEPT = ValidationResult.ACCEPT
IGNORE = ValidationResult.IGNORE
REJECT = ValidationResult.REJECT
SAVE_FOR_FUTURE = ValidationResult.SAVE_FOR_FUTURE


def _traced_validate(topic: str):
    """Decorator opening the ROOT span of the hot path: one trace per
    gossip message, arrival → verdict, so a slow verify's time is
    attributable across queue-wait / assembly / dispatch / device.  The
    verdict is stamped as a trace label for the slow-trace dump."""
    def wrap(fn):
        @functools.wraps(fn)
        async def validate(self, message) -> ValidationResult:
            with tracing.trace("gossip_verify", topic=topic) as tr:
                result = await fn(self, message)
                if tr is not None:
                    tr.labels["result"] = result.value
                return result
        return validate
    return wrap


def _committee_index_of(attestation):
    """The committee an attestation addresses: data.index pre-electra;
    the single set committee bit (with data.index pinned to 0) for the
    electra aggregate shape; the explicit field on SingleAttestation.
    None = malformed electra shape (REJECT)."""
    if hasattr(attestation, "attester_index"):   # SingleAttestation
        return attestation.committee_index
    cb = getattr(attestation, "committee_bits", None)
    if cb is None:
        return attestation.data.index
    if attestation.data.index != 0:
        return None
    set_bits = [i for i, b in enumerate(cb) if b]
    if len(set_bits) != 1:
        return None
    return set_bits[0]


def normalize_attestation(spec: Spec, state, attestation):
    """Electra SingleAttestation (the subnet WIRE shape) → the one-hot
    committee-bits Attestation everything downstream pools and applies
    (reference: SingleAttestation conversion in AttestationValidator /
    ValidatableAttestation.convertFromSingleAttestation).  Pass-through
    for every other shape; None = the claimed attester is not in the
    claimed committee (REJECT)."""
    if not hasattr(attestation, "attester_index"):
        return attestation
    cfg = spec.config
    data = attestation.data
    if data.index != 0:
        return None     # electra data pins index to 0 (wire rule)
    committee = H.get_beacon_committee(cfg, state, data.slot,
                                       attestation.committee_index)
    if attestation.attester_index not in committee:
        return None
    from ..spec.electra.datastructures import get_electra_schemas
    S = get_electra_schemas(cfg)
    position = committee.index(attestation.attester_index)
    return S.Attestation(
        aggregation_bits=tuple(i == position
                               for i in range(len(committee))),
        data=data,
        signature=attestation.signature,
        committee_bits=tuple(
            i == attestation.committee_index
            for i in range(cfg.MAX_COMMITTEES_PER_SLOT)))


class AttestationValidator:
    """Single (unaggregated) attestation gossip rules + batched sig."""

    # single attestations are the bulk gossip class: sheddable under
    # level-2 brownout, behind aggregates in the priority drain
    verify_cls = VerifyClass.GOSSIP

    def __init__(self, spec: Spec, chain: RecentChainData,
                 verifier: AsyncSignatureVerifier):
        self.spec = spec
        self.chain = chain
        self.verifier = verifier
        # bounded like the reference's LimitedSet seen-caches
        self._seen: LimitedSet = LimitedSet(65536)

    @_traced_validate("attestation")
    async def validate(self, attestation) -> ValidationResult:
        cfg = self.spec.config
        data = attestation.data
        bits = attestation.aggregation_bits
        # exactly one bit set (gossip rule)
        if sum(1 for b in bits if b) != 1:
            return REJECT
        committee_index = _committee_index_of(attestation)
        if committee_index is None:
            return REJECT   # electra shape rules violated
        if data.target.epoch != H.compute_epoch_at_slot(cfg, data.slot):
            return REJECT
        # propagation slot window (with clock disparity handled by ticks)
        current_slot = self.chain.current_slot()
        if data.slot > current_slot:
            return SAVE_FOR_FUTURE
        if data.slot + cfg.ATTESTATION_PROPAGATION_SLOT_RANGE < current_slot:
            return IGNORE
        if not self.chain.contains_block(data.beacon_block_root):
            return SAVE_FOR_FUTURE
        try:
            target_state = self.chain.store.get_checkpoint_state(data.target)
        except Exception:
            return IGNORE
        if committee_index >= H.get_committee_count_per_slot(
                cfg, target_state, data.target.epoch):
            return REJECT
        committee = H.get_beacon_committee(cfg, target_state, data.slot,
                                           committee_index)
        if len(bits) != len(committee):
            return REJECT
        validator_index = committee[next(i for i, b in enumerate(bits) if b)]
        # first-seen per (validator, target epoch) dedupe (gossip rule)
        key = (data.target.epoch, validator_index)
        if key in self._seen:
            return IGNORE
        domain = H.get_domain(cfg, target_state, DOMAIN_BEACON_ATTESTER,
                              data.target.epoch)
        root = H.compute_signing_root(data, domain)
        pubkey = target_state.validators[validator_index].pubkey
        ok = await self.verifier.verify([pubkey], root,
                                        attestation.signature,
                                        cls=self.verify_cls)
        if not ok:
            return REJECT
        self._seen.add(key)
        return ACCEPT


class AggregateValidator:
    """SignedAggregateAndProof rules; the three signatures (selection
    proof, aggregator, aggregate) verify as ONE atomic batch task
    (reference AggregateAttestationValidator.java:124-126,242)."""

    # an aggregate carries a committee's worth of fork-choice weight:
    # it outranks single-attestation gossip and is never brownout-shed
    verify_cls = VerifyClass.SYNC_CRITICAL

    def __init__(self, spec: Spec, chain: RecentChainData,
                 verifier: AsyncSignatureVerifier):
        self.spec = spec
        self.chain = chain
        self.verifier = verifier
        self._seen_aggregators: LimitedSet = LimitedSet(16384)

    @_traced_validate("aggregate")
    async def validate(self, signed_aggregate) -> ValidationResult:
        cfg = self.spec.config
        msg = signed_aggregate.message
        aggregate = msg.aggregate
        data = aggregate.data
        current_slot = self.chain.current_slot()
        if data.slot > current_slot:
            return SAVE_FOR_FUTURE
        if data.slot + cfg.ATTESTATION_PROPAGATION_SLOT_RANGE < current_slot:
            return IGNORE    # stale: drop before any signature work
        if data.target.epoch != H.compute_epoch_at_slot(cfg, data.slot):
            return REJECT
        if not self.chain.contains_block(data.beacon_block_root):
            return SAVE_FOR_FUTURE
        key = (data.slot, msg.aggregator_index)
        if key in self._seen_aggregators:
            return IGNORE
        committee_index = _committee_index_of(aggregate)
        if committee_index is None:
            return REJECT
        try:
            state = self.chain.store.get_checkpoint_state(data.target)
        except Exception:
            return IGNORE
        if committee_index >= H.get_committee_count_per_slot(
                cfg, state, data.target.epoch):
            return REJECT   # out-of-range index would alias another slot
        committee = H.get_beacon_committee(cfg, state, data.slot,
                                           committee_index)
        if len(aggregate.aggregation_bits) != len(committee):
            return REJECT
        if msg.aggregator_index not in committee:
            return REJECT
        if not is_aggregator(cfg, state, data.slot, committee_index,
                             msg.selection_proof):
            return REJECT

        # three signatures, one atomic task
        batch = AsyncBatchSignatureVerifier(self.verifier,
                                            cls=self.verify_cls)
        agg_pubkey = state.validators[msg.aggregator_index].pubkey
        sel_root = H.selection_proof_signing_root(cfg, state, data.slot)
        batch.verify([agg_pubkey], sel_root, msg.selection_proof)

        proof_domain = H.get_domain(
            cfg, state, DOMAIN_AGGREGATE_AND_PROOF,
            H.compute_epoch_at_slot(cfg, data.slot))
        proof_root = H.compute_signing_root(msg, proof_domain)
        batch.verify([agg_pubkey], proof_root, signed_aggregate.signature)

        att_domain = H.get_domain(cfg, state, DOMAIN_BEACON_ATTESTER,
                                  data.target.epoch)
        att_root = H.compute_signing_root(data, att_domain)
        participants = [state.validators[v].pubkey
                        for v, b in zip(committee,
                                        aggregate.aggregation_bits) if b]
        if not participants:
            return REJECT
        batch.verify(participants, att_root, aggregate.signature)

        if not await batch.batch_verify():
            return REJECT
        self._seen_aggregators.add(key)
        return ACCEPT


class ContributionValidator:
    """SignedContributionAndProof gossip rules (reference
    statetransition/synccommittee/SignedContributionAndProofValidator):
    live slot, valid subcommittee, aggregator is a member, selection
    proof selects them — then the three signatures (selection proof,
    envelope, contribution aggregate) verify as ONE atomic batch
    through the batched device provider, accounted under the
    sync-committee demand stream."""

    # a contribution carries a whole subcommittee's sync weight toward
    # the next SyncAggregate — like attestation aggregates it outranks
    # bulk gossip and is never brownout-shed
    verify_cls = VerifyClass.SYNC_CRITICAL

    def __init__(self, spec: Spec, chain: RecentChainData,
                 verifier: AsyncSignatureVerifier):
        self.spec = spec
        self.chain = chain
        self.verifier = verifier
        self._seen: LimitedSet = LimitedSet(8192)

    @_traced_validate("sync_contribution")
    async def validate(self, signed) -> ValidationResult:
        from ..spec.altair import helpers as AH
        cfg = self.spec.config
        msg = signed.message
        contribution = msg.contribution
        slot = contribution.slot
        cur = self.chain.current_slot()
        if slot > cur:
            return SAVE_FOR_FUTURE
        if slot < cur - 1:
            return IGNORE
        if contribution.subcommittee_index \
                >= cfg.SYNC_COMMITTEE_SUBNET_COUNT:
            return REJECT
        if not any(contribution.aggregation_bits):
            return REJECT
        key = (slot, msg.aggregator_index,
               contribution.subcommittee_index)
        if key in self._seen:
            return IGNORE
        state = self.chain.head_state()
        if not hasattr(state, "current_sync_committee"):
            return IGNORE
        if msg.aggregator_index >= len(state.validators):
            return REJECT
        agg_pubkey = state.validators[msg.aggregator_index].pubkey
        positions, pubkeys = AH.sync_subcommittee_members(
            cfg, state, contribution.subcommittee_index)
        if agg_pubkey not in pubkeys:
            return REJECT
        if not AH.is_sync_committee_aggregator(cfg,
                                               msg.selection_proof):
            return REJECT

        triples = AH.contribution_signature_set(cfg, state, signed,
                                                pubkeys)
        if triples is None:
            return REJECT
        from ..infra.capacity import SOURCE_SYNC_COMMITTEE
        batch = AsyncBatchSignatureVerifier(
            self.verifier, cls=self.verify_cls,
            source=SOURCE_SYNC_COMMITTEE)
        for t_pks, t_root, t_sig in triples:
            batch.verify(t_pks, t_root, t_sig)
        if not await batch.batch_verify():
            return REJECT
        self._seen.add(key)
        return ACCEPT


class BlockGossipValidator:
    """Block gossip rules (reference BlockGossipValidator.java): slot
    not from the future/too old, first block per (slot, proposer),
    known parent, proposer signature against the parent's state."""

    # the proposer signature gates the whole slot's import: ONE
    # signature on the critical path — the VIP lane dispatches it
    # alone, ahead of every queued batch
    verify_cls = VerifyClass.VIP

    def __init__(self, spec: Spec, chain: RecentChainData,
                 verifier: AsyncSignatureVerifier):
        self.spec = spec
        self.chain = chain
        self.verifier = verifier
        self._seen: LimitedSet = LimitedSet(16384)

    @_traced_validate("block")
    async def validate(self, signed_block) -> ValidationResult:
        cfg = self.spec.config
        block = signed_block.message
        current_slot = self.chain.current_slot()
        if block.slot > current_slot:
            return SAVE_FOR_FUTURE
        finalized_slot = H.compute_start_slot_at_epoch(
            cfg, self.chain.finalized_checkpoint.epoch)
        if block.slot <= finalized_slot:
            return IGNORE
        key = (block.slot, block.proposer_index)
        if key in self._seen:
            return IGNORE
        parent_state = self.chain.get_state(block.parent_root)
        if parent_state is None:
            return SAVE_FOR_FUTURE
        if parent_state.slot >= block.slot:
            return REJECT
        try:
            pre = self.spec.process_slots(parent_state, block.slot)
            expected_proposer = H.get_beacon_proposer_index(cfg, pre)
        except Exception:
            return IGNORE
        if block.proposer_index != expected_proposer:
            return REJECT
        proposer = pre.validators[block.proposer_index]
        domain = H.get_domain(cfg, pre, DOMAIN_BEACON_PROPOSER)
        root = H.compute_signing_root(block, domain)
        if not await self.verifier.verify([proposer.pubkey], root,
                                          signed_block.signature,
                                          cls=self.verify_cls):
            return REJECT
        self._seen.add(key)
        return ACCEPT
