"""Deposit provider: the eth1 deposit merkle tree and block-production
proofs.

Equivalent of the reference's deposit plumbing (reference: beacon/
validator/.../coordinator/DepositProvider.java fed by beacon/pow's
deposit-log follower; the tree math matches the deposit contract's
incremental merkle tree): deposits observed on the execution chain
accumulate in a depth-32 merkle tree whose root (with the count mixed
in) is what eth1_data commits to; a proposer must include the next
`min(MAX_DEPOSITS, pending)` deposits WITH branches proving them into
that root, and process_deposit re-verifies each branch.

Post-electra (EIP-6110) deposit requests arrive straight from the
payload and this path winds down once the eth1 bridge drains.
"""

from typing import List, Optional

from ..spec.config import SpecConfig
from ..ssz import zero_hash
from ..ssz.hash import hash_pair

DEPOSIT_CONTRACT_TREE_DEPTH = 32


class DepositTree:
    """The deposit contract's accumulator, with proof generation."""

    def __init__(self):
        self._leaves: List[bytes] = []

    def push(self, deposit_data) -> int:
        """Append one DepositData; returns its index."""
        self._leaves.append(deposit_data.htr())
        return len(self._leaves) - 1

    @property
    def count(self) -> int:
        return len(self._leaves)

    def root(self, count: Optional[int] = None) -> bytes:
        """hash(merkle_root_over_2^32_leaves, count) — the deposit
        contract's get_deposit_root / spec deposit_root.  `count`
        snapshots the tree at an earlier length (the committed
        eth1_data may trail deposits the provider has already seen).
        Shares the per-snapshot level cache with proof()."""
        count = self.count if count is None else count
        # _levels runs all 32 contract levels (zero-padded), so the
        # top level holds the full virtual-tree root
        inner = self._levels(count)[-1][0]
        return hash_pair(inner, count.to_bytes(32, "little"))

    def _levels(self, count: int) -> List[List[bytes]]:
        """All populated tree levels over leaves[:count], cached per
        count: proofs for a whole block's deposits then cost O(log n)
        each instead of re-hashing the tree per proof."""
        cached = getattr(self, "_levels_cache", None)
        if cached is not None and cached[0] == count:
            return cached[1]
        from ..ssz.hash import _hash_level
        level = list(self._leaves[:count]) or [zero_hash(0)]
        levels = [level]
        for d in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            level = _hash_level(level, zero_hash(d))
            levels.append(level)
        self._levels_cache = (count, levels)
        return levels

    def proof(self, index: int, count: Optional[int] = None
              ) -> List[bytes]:
        """33-element branch proving leaf `index` into the tree
        SNAPSHOT at `count` leaves: 32 tree siblings + the count
        mix-in (the shape process_deposit verifies with depth+1).
        Proving against the live tree would break whenever deposits
        arrive after the eth1_data the state committed to."""
        count = self.count if count is None else count
        if index >= count:
            raise IndexError("deposit index beyond snapshot")
        levels = self._levels(count)
        branch = []
        idx = index
        for d in range(DEPOSIT_CONTRACT_TREE_DEPTH):
            level = levels[d]
            sib = idx ^ 1
            branch.append(level[sib] if sib < len(level)
                          else zero_hash(d))
            idx >>= 1
        return branch + [count.to_bytes(32, "little")]


class DepositProvider:
    """Serves the deposits a block at `state` must include (reference
    DepositProvider.getDeposits: from state.eth1_deposit_index up to
    eth1_data.deposit_count, capped at MAX_DEPOSITS)."""

    def __init__(self, cfg: SpecConfig):
        self.cfg = cfg
        self.tree = DepositTree()
        self._data: List[object] = []
        self._canonical: object = None
        self._rebuilding = False

    def on_deposit(self, deposit_data) -> int:
        """A new deposit observed on the execution chain."""
        self._data.append(deposit_data)
        return self.tree.push(deposit_data)

    def reset(self) -> None:
        """Discard the tree (eth1 reorg beyond the follow distance —
        the follower re-feeds everything from the canonical chain).
        Until the rebuild lands, eth1_data() abstains (returns None)
        rather than voting an empty-tree root."""
        self.tree = DepositTree()
        self._data = []
        self._canonical = None
        self._rebuilding = True

    def set_canonical_eth1_data(self, eth1_data) -> None:
        """The follower's voting view: the deposit root/count at the
        block ETH1_FOLLOW_DISTANCE behind head (reference
        Eth1DataCache feeding Eth1VotingPeriod)."""
        self._canonical = eth1_data
        self._rebuilding = False

    def eth1_data(self, block_hash: bytes = bytes(32)):
        from ..spec.datastructures import Eth1Data
        if self._canonical is not None:
            return self._canonical
        if self._rebuilding:
            return None      # abstain: caller repeats state.eth1_data
        # no follower wired (devnets): vote the live tree view
        return Eth1Data(deposit_root=self.tree.root(),
                        deposit_count=self.tree.count,
                        block_hash=block_hash)

    def get_deposits_for_block(self, state,
                               eth1_data=None) -> List[object]:
        """Proof-carrying deposits the next block MUST include.
        `eth1_data` is the eth1 vote the block will carry — if the vote
        reaches majority it adopts WITHIN the block, before
        process_operations counts expected deposits, so production must
        anticipate it (reference BlockOperationSelectorFactory passes
        the vote result into DepositProvider.getDeposits)."""
        eth1_data = state.eth1_data if eth1_data is None else eth1_data
        start = state.eth1_deposit_index
        # electra: the eth1 bridge stops at deposit_requests_start_index
        limit = eth1_data.deposit_count
        if hasattr(state, "deposit_requests_start_index"):
            limit = min(limit, state.deposit_requests_start_index)
        due = min(limit, start + self.cfg.MAX_DEPOSITS)
        end = min(due, self.tree.count)
        snapshot = eth1_data.deposit_count
        if end < due or snapshot > self.tree.count:
            # the consensus check will reject an under-filled block and
            # a truncated snapshot can't produce valid proofs — make
            # the data gap loud instead of a silent missed slot
            import logging
            logging.getLogger(__name__).warning(
                "deposit tree behind eth1_data: have %d, snapshot %d, "
                "block needs deposits %d..%d", self.tree.count,
                snapshot, start, due)
        if snapshot > self.tree.count or end <= start:
            return []
        from ..spec.milestones import build_fork_schedule
        S = build_fork_schedule(self.cfg).version_at_slot(
            state.slot).schemas
        # proofs prove into the SNAPSHOT the block's eth1_data commits
        # to, not the live tree
        out = []
        for i in range(start, end):
            out.append(S.Deposit(
                proof=tuple(self.tree.proof(i, snapshot)),
                data=self._data[i]))
        return out
