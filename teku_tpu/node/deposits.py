"""Deposit provider: the eth1 deposit merkle tree and block-production
proofs.

Equivalent of the reference's deposit plumbing (reference: beacon/
validator/.../coordinator/DepositProvider.java fed by beacon/pow's
deposit-log follower; the tree math matches the deposit contract's
incremental merkle tree): deposits observed on the execution chain
accumulate in a depth-32 merkle tree whose root (with the count mixed
in) is what eth1_data commits to; a proposer must include the next
`min(MAX_DEPOSITS, pending)` deposits WITH branches proving them into
that root, and process_deposit re-verifies each branch.

Post-electra (EIP-6110) deposit requests arrive straight from the
payload and this path winds down once the eth1 bridge drains.
"""

from typing import List, Optional

from ..spec.config import SpecConfig
from ..ssz import merkle_branch, merkleize, zero_hash
from ..ssz.hash import hash_pair

DEPOSIT_CONTRACT_TREE_DEPTH = 32


class DepositTree:
    """The deposit contract's accumulator, with proof generation."""

    def __init__(self):
        self._leaves: List[bytes] = []

    def push(self, deposit_data) -> int:
        """Append one DepositData; returns its index."""
        self._leaves.append(deposit_data.htr())
        return len(self._leaves) - 1

    @property
    def count(self) -> int:
        return len(self._leaves)

    def root(self) -> bytes:
        """hash(merkle_root_over_2^32_leaves, count) — the deposit
        contract's get_deposit_root / spec deposit_root."""
        inner = merkleize(self._leaves,
                          1 << DEPOSIT_CONTRACT_TREE_DEPTH) \
            if self._leaves else zero_hash(DEPOSIT_CONTRACT_TREE_DEPTH)
        return hash_pair(inner,
                         self.count.to_bytes(32, "little"))

    def proof(self, index: int) -> List[bytes]:
        """33-element branch: 32 tree siblings + the count mix-in (the
        shape process_deposit verifies with depth+1)."""
        branch = merkle_branch(self._leaves, index,
                               1 << DEPOSIT_CONTRACT_TREE_DEPTH)
        return branch + [self.count.to_bytes(32, "little")]


class DepositProvider:
    """Serves the deposits a block at `state` must include (reference
    DepositProvider.getDeposits: from state.eth1_deposit_index up to
    eth1_data.deposit_count, capped at MAX_DEPOSITS)."""

    def __init__(self, cfg: SpecConfig):
        self.cfg = cfg
        self.tree = DepositTree()
        self._data: List[object] = []

    def on_deposit(self, deposit_data) -> int:
        """A new deposit observed on the execution chain."""
        self._data.append(deposit_data)
        return self.tree.push(deposit_data)

    def eth1_data(self, block_hash: bytes = bytes(32)):
        from ..spec.datastructures import Eth1Data
        return Eth1Data(deposit_root=self.tree.root(),
                        deposit_count=self.tree.count,
                        block_hash=block_hash)

    def get_deposits_for_block(self, state) -> List[object]:
        """Proof-carrying deposits the next block MUST include."""
        start = state.eth1_deposit_index
        # electra: the eth1 bridge stops at deposit_requests_start_index
        limit = state.eth1_data.deposit_count
        if hasattr(state, "deposit_requests_start_index"):
            limit = min(limit, state.deposit_requests_start_index)
        due = min(limit, start + self.cfg.MAX_DEPOSITS)
        end = min(due, self.tree.count)
        if end < due:
            # the consensus check will reject an under-filled block —
            # make the data gap loud instead of a silent missed slot
            import logging
            logging.getLogger(__name__).warning(
                "deposit tree behind eth1_data: have %d, block needs "
                "deposits %d..%d", self.tree.count, start, due)
        if end <= start:
            return []
        from ..spec.milestones import build_fork_schedule
        S = build_fork_schedule(self.cfg).version_at_slot(
            state.slot).schemas
        out = []
        for i in range(start, end):
            out.append(S.Deposit(proof=tuple(self.tree.proof(i)),
                                 data=self._data[i]))
        return out
