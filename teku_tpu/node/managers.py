"""Attestation/Block managers: pending pools + fork-choice application.

Equivalent of the reference's AttestationManager and BlockManager
(reference: ethereum/statetransition/src/main/java/tech/pegasys/teku/
statetransition/attestation/AttestationManager.java:141-200 and
statetransition/block/BlockManager.java:99-191): gossip-validated items
flow into fork choice; items referencing unknown blocks wait in a
pending pool keyed by the missing root; future-slot items wait in a
future pool drained on slot ticks.
"""

import logging
from collections import defaultdict
from typing import Callable, Dict, List, Optional

from ..infra.events import BlockImportChannel, EventChannels
from ..spec import Spec
from ..storage.store import ForkChoiceError
from .chaindata import RecentChainData
from .gossip import ValidationResult

_LOG = logging.getLogger(__name__)


class AttestationManager:
    def __init__(self, spec: Spec, chain: RecentChainData,
                 pool=None, max_pending: int = 4096):
        self.spec = spec
        self.chain = chain
        self.pool = pool
        self._pending_by_block: Dict[bytes, List] = defaultdict(list)
        self._future_by_slot: Dict[int, List] = defaultdict(list)
        self._max_pending = max_pending
        self._n_pending = 0

    def add_attestation(self, attestation) -> None:
        """Apply a FULLY-VALIDATED attestation (signature settled by the
        gossip pipeline or locally produced) to the pool + fork choice;
        queue it if its block is unknown or its slot not yet reached.
        Unvalidated gossip (SAVE_FOR_FUTURE) must NOT come here — the
        node defers it for re-validation instead, or garbage signatures
        would poison block production."""
        data = attestation.data
        if self.pool is not None:
            self.pool.add(attestation)
        if data.slot + 1 > self.chain.current_slot():
            self._enqueue(self._future_by_slot[data.slot + 1], attestation)
            return
        if not self.chain.contains_block(data.beacon_block_root):
            self._enqueue(self._pending_by_block[data.beacon_block_root],
                          attestation)
            return
        self._apply(attestation)

    def _enqueue(self, bucket: List, attestation) -> None:
        if self._n_pending >= self._max_pending:
            return  # shed under pressure (reference pools are bounded)
        bucket.append(attestation)
        self._n_pending += 1

    def _apply(self, attestation) -> None:
        try:
            self.chain.store.on_attestation(attestation,
                                            signature_verified=True)
        except ForkChoiceError as exc:
            _LOG.debug("attestation dropped: %s", exc)

    def on_slot(self, slot: int) -> None:
        for s in [s for s in self._future_by_slot if s <= slot]:
            for att in self._future_by_slot.pop(s):
                self._n_pending -= 1
                self.add_attestation(att)

    def on_block_imported(self, block_root: bytes) -> None:
        for att in self._pending_by_block.pop(block_root, ()):
            self._n_pending -= 1
            self.add_attestation(att)


class BlockManager:
    def __init__(self, spec: Spec, chain: RecentChainData,
                 channels: Optional[EventChannels] = None,
                 max_pending: int = 256, blob_pool=None):
        self.spec = spec
        self.chain = chain
        self._channels = channels or EventChannels()
        self._pending_by_parent: Dict[bytes, List] = defaultdict(list)
        self._future_by_slot: Dict[int, List] = defaultdict(list)
        self._awaiting_blobs: Dict[bytes, object] = {}
        self._max_pending = max_pending
        self._n_pending = 0
        self.on_imported: List[Callable[[bytes], None]] = []
        self.blob_pool = blob_pool

    def import_block(self, signed_block) -> bool:
        """Import into fork choice; returns True if now in the store.
        Unknown-parent / future blocks queue for retry (reference
        BlockManager pending + futureBlocks pools)."""
        block = signed_block.message
        root = block.htr()
        if self.chain.contains_block(root):
            return True
        if block.slot > self.chain.current_slot():
            self._enqueue(self._future_by_slot[block.slot], signed_block)
            return False
        if not self.chain.contains_block(block.parent_root):
            self._enqueue(self._pending_by_parent[block.parent_root],
                          signed_block)
            return False
        # deneb availability gate (reference ForkChoice.onBlock →
        # BlobSidecarsAvailabilityChecker): a block whose commitments
        # lack proof-verified sidecars waits, an invalid set rejects
        commitments = getattr(block.body, "blob_kzg_commitments", ())
        if commitments and self.blob_pool is not None \
                and self._within_da_window(block.slot):
            from .blobs import AvailabilityResult
            verdict = self.blob_pool.check_availability(
                root, list(commitments))
            if verdict != AvailabilityResult.AVAILABLE:
                # absence is only ever PENDING (proof failures are
                # dropped at pool-add time, so "provably invalid"
                # cannot be observed here); parked blocks expire in
                # on_slot if the sidecars never arrive
                if root not in self._awaiting_blobs \
                        and self._n_pending < self._max_pending:
                    self._awaiting_blobs[root] = signed_block
                    self._n_pending += 1
                return False
        # step-timed like the reference's BlockImportPerformance
        # (invoked at ForkChoice.java:221,455,462)
        from ..infra.perf import StepTimer
        timer = StepTimer(f"block import slot {block.slot}",
                          threshold_ms=2000.0)
        try:
            post = self.chain.store.on_block(signed_block)
            timer.mark("transition+fork_choice")
        except ForkChoiceError as exc:
            _LOG.warning("block %s rejected: %s", root.hex()[:8], exc)
            return False
        self.chain.update_head()
        timer.mark("update_head")
        self._channels.publisher(BlockImportChannel).on_block_imported(
            signed_block, post)
        for cb in self.on_imported:
            cb(root)
        timer.complete()   # before recursing: children time themselves
        # unblock children waiting on us
        for child in self._pending_by_parent.pop(root, ()):
            self._n_pending -= 1
            self.import_block(child)
        return True

    def _within_da_window(self, slot: int) -> bool:
        """Blob data-availability is only required inside the retention
        window (spec is_data_available applies only within
        MIN_EPOCHS_FOR_BLOB_SIDECARS_REQUESTS of current).  Peers prune
        older sidecars, so gating historical blocks on availability
        would wedge any sync from >window behind (reference
        DataAvailabilityChecker's da-check horizon)."""
        cfg = self.spec.config
        block_epoch = slot // cfg.SLOTS_PER_EPOCH
        current_epoch = self.chain.current_slot() // cfg.SLOTS_PER_EPOCH
        return (block_epoch + cfg.MIN_EPOCHS_FOR_BLOB_SIDECARS_REQUESTS
                >= current_epoch)

    def _enqueue(self, bucket: List, signed_block) -> None:
        if self._n_pending >= self._max_pending:
            return
        bucket.append(signed_block)
        self._n_pending += 1

    def retry_pending_blobs(self) -> None:
        """Re-attempt blocks parked on blob availability (called when a
        new sidecar lands)."""
        for root in list(self._awaiting_blobs):
            signed = self._awaiting_blobs.pop(root)
            self._n_pending -= 1
            self.import_block(signed)

    def on_slot(self, slot: int) -> None:
        for s in [s for s in self._future_by_slot if s <= slot]:
            for blk in self._future_by_slot.pop(s):
                self._n_pending -= 1
                self.import_block(blk)
        # blob-parked blocks: retry each slot (sidecars may have come
        # in via sync RPC), and give up after an epoch of waiting so a
        # withheld sidecar can't pin the pending budget forever
        horizon = self.spec.config.SLOTS_PER_EPOCH
        for root in list(self._awaiting_blobs):
            signed = self._awaiting_blobs[root]
            if signed.message.slot + horizon < slot:
                del self._awaiting_blobs[root]
                self._n_pending -= 1
                _LOG.warning("block %s dropped: blobs never arrived",
                             root.hex()[:8])
            else:
                del self._awaiting_blobs[root]
                self._n_pending -= 1
                self.import_block(signed)
