"""Sync-committee message pool: per-slot signatures → SyncAggregate.

Equivalent of the reference's sync-committee pooling (reference:
ethereum/statetransition/src/main/java/tech/pegasys/teku/
statetransition/synccommittee/SyncCommitteeMessagePool.java +
SyncCommitteeContributionPool.java, reduced to the single-subnet
shape): committee members' signatures over a slot's block root
accumulate here; the next slot's proposer drains them into the block's
SyncAggregate.
"""

import logging
from typing import Dict, Optional, Tuple

from ..crypto import bls
from ..infra.collections import LimitedMap

_LOG = logging.getLogger(__name__)


class SyncCommitteeMessagePool:
    def __init__(self, cfg):
        self.cfg = cfg
        # (slot, block_root) -> {committee_position: signature}
        self._msgs: LimitedMap = LimitedMap(64)

    def add(self, slot: int, block_root: bytes, committee_position: int,
            signature: bytes) -> None:
        key = (slot, block_root)
        bucket = self._msgs.get(key)
        if bucket is None:
            bucket = {}
            self._msgs.put(key, bucket)
        bucket.setdefault(committee_position, signature)

    def add_contribution(self, contribution) -> None:
        """A validated per-subcommittee contribution (reference
        SyncCommitteeContributionPool): the best (most participation)
        contribution per (slot, root, subcommittee) wins."""
        key = ("contrib", contribution.slot,
               contribution.beacon_block_root)
        bucket = self._msgs.get(key)
        if bucket is None:
            bucket = {}
            self._msgs.put(key, bucket)
        held = bucket.get(contribution.subcommittee_index)
        if held is None or sum(contribution.aggregation_bits) \
                > sum(held.aggregation_bits):
            bucket[contribution.subcommittee_index] = contribution

    def build_contribution(self, slot: int, block_root: bytes,
                           subcommittee_index: int, schemas):
        """Aggregate THIS subcommittee's pooled messages (the sync
        aggregator duty's production shape)."""
        bucket = self._msgs.get((slot, block_root)) or {}
        cfg = self.cfg
        from ..spec.altair.helpers import sync_subcommittee_size
        sub_size = sync_subcommittee_size(cfg)
        start = subcommittee_index * sub_size
        positions = [p for p in bucket if start <= p < start + sub_size]
        if not positions:
            return None
        bits = tuple(start + i in bucket for i in range(sub_size))
        sig = bls.aggregate_signatures(
            [bucket[p] for p in sorted(positions)])
        return schemas.SyncCommitteeContribution(
            slot=slot, beacon_block_root=block_root,
            subcommittee_index=subcommittee_index,
            aggregation_bits=bits, signature=sig)

    def build_aggregate(self, slot: int, block_root: bytes, schemas):
        """SyncAggregate for (slot, root): contributions cover their
        whole subcommittee; the raw message pool fills subcommittees
        with no contribution.  A position must never be signed twice —
        the aggregate would then contain a key the bitfield names only
        once, and verification fails."""
        cfg = self.cfg
        size = cfg.SYNC_COMMITTEE_SIZE
        from ..spec.altair.helpers import sync_subcommittee_size
        sub_size = sync_subcommittee_size(cfg)
        contribs = self._msgs.get(("contrib", slot, block_root)) or {}
        bucket = self._msgs.get((slot, block_root)) or {}
        bits = [False] * size
        sigs = []
        for sub, contribution in sorted(contribs.items()):
            start = sub * sub_size
            any_bit = False
            for i, b in enumerate(contribution.aggregation_bits):
                if b:
                    bits[start + i] = True
                    any_bit = True
            if any_bit:
                sigs.append(contribution.signature)
        for position in sorted(bucket):
            if bits[position]:
                continue    # a contribution already carries this seat
            bits[position] = True
            sigs.append(bucket[position])
        if not sigs:
            from ..crypto.bls.pure_impl import G2_INFINITY
            sig = G2_INFINITY
        else:
            sig = sigs[0] if len(sigs) == 1 \
                else bls.aggregate_signatures(sigs)
        return schemas.SyncAggregate(sync_committee_bits=tuple(bits),
                                     sync_committee_signature=sig)
