"""Sync-committee message pool: per-slot signatures → SyncAggregate.

Equivalent of the reference's sync-committee pooling (reference:
ethereum/statetransition/src/main/java/tech/pegasys/teku/
statetransition/synccommittee/SyncCommitteeMessagePool.java +
SyncCommitteeContributionPool.java, reduced to the single-subnet
shape): committee members' signatures over a slot's block root
accumulate here; the next slot's proposer drains them into the block's
SyncAggregate.
"""

import logging
from typing import Dict, Optional, Tuple

from ..crypto import bls
from ..infra.collections import LimitedMap

_LOG = logging.getLogger(__name__)


class SyncCommitteeMessagePool:
    def __init__(self, cfg):
        self.cfg = cfg
        # (slot, block_root) -> {committee_position: signature}
        self._msgs: LimitedMap = LimitedMap(64)

    def add(self, slot: int, block_root: bytes, committee_position: int,
            signature: bytes) -> None:
        key = (slot, block_root)
        bucket = self._msgs.get(key)
        if bucket is None:
            bucket = {}
            self._msgs.put(key, bucket)
        bucket.setdefault(committee_position, signature)

    def build_aggregate(self, slot: int, block_root: bytes, schemas):
        """SyncAggregate over collected messages for (slot, root);
        empty participation carries the infinity signature."""
        bucket = self._msgs.get((slot, block_root)) or {}
        size = self.cfg.SYNC_COMMITTEE_SIZE
        bits = tuple(i in bucket for i in range(size))
        if not bucket:
            from ..crypto.bls.pure_impl import G2_INFINITY
            sig = G2_INFINITY
        else:
            sig = bls.aggregate_signatures(
                [bucket[i] for i in sorted(bucket)])
        return schemas.SyncAggregate(sync_committee_bits=bits,
                                     sync_committee_signature=sig)
