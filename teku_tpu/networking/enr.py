"""Ethereum Node Records (EIP-778) with the v4 identity scheme.

The spec-wire node identity the reference publishes via its discovery
library (reference: networking/p2p/.../discovery/discv5/
DiscV5Service.java — ENRs carry eth2 fork digest + attnets/syncnets):
RLP [signature, seq, k, v, ...] with keys sorted, signed with
secp256k1 over keccak256(content), node ID = keccak256(uncompressed
pubkey).  Textual form enr:<base64url-unpadded>.

Validated against the EIP-778 example record in tests (an
independently-published vector — the closest thing to foreign-client
interop available offline).
"""

import base64
from typing import Dict, Optional, Tuple

from . import rlp, secp256k1 as EC
from .keccak import keccak256

MAX_RECORD_SIZE = 300


class EnrError(ValueError):
    pass


class Enr:
    """Immutable decoded record."""

    def __init__(self, seq: int, pairs: Dict[bytes, bytes],
                 signature: bytes):
        self.seq = seq
        self.pairs = dict(pairs)
        self.signature = signature

    # -- content ------------------------------------------------------
    def get(self, key: str) -> Optional[bytes]:
        return self.pairs.get(key.encode())

    @property
    def public_key(self) -> Tuple[int, int]:
        raw = self.get("secp256k1")
        if raw is None:
            raise EnrError("record has no secp256k1 key")
        return EC.decompress(raw)

    @property
    def node_id(self) -> bytes:
        return keccak256(EC.uncompressed_xy(self.public_key))

    @property
    def ip(self) -> Optional[str]:
        raw = self.get("ip")
        return ".".join(str(b) for b in raw) if raw else None

    @property
    def udp(self) -> Optional[int]:
        raw = self.get("udp")
        return int.from_bytes(raw, "big") if raw else None

    # -- wire ---------------------------------------------------------
    def _content(self) -> list:
        items = [rlp.encode_uint(self.seq)]
        for k in sorted(self.pairs):
            items += [k, self.pairs[k]]
        return items

    def to_rlp(self) -> bytes:
        out = rlp.encode([self.signature] + self._content())
        if len(out) > MAX_RECORD_SIZE:
            raise EnrError("record exceeds 300 bytes")
        return out

    def to_text(self) -> str:
        return "enr:" + base64.urlsafe_b64encode(
            self.to_rlp()).rstrip(b"=").decode()

    def verify(self) -> bool:
        if self.get("id") != b"v4":
            return False
        digest = keccak256(rlp.encode(self._content()))
        try:
            return EC.verify(self.public_key, digest, self.signature)
        except (ValueError, EnrError):
            return False

    # -- constructors -------------------------------------------------
    @classmethod
    def create(cls, secret: int, seq: int = 1,
               ip: Optional[str] = None, udp: Optional[int] = None,
               extra: Optional[Dict[str, bytes]] = None) -> "Enr":
        pairs: Dict[bytes, bytes] = {
            b"id": b"v4",
            b"secp256k1": EC.compress(EC.pubkey(secret)),
        }
        if ip is not None:
            pairs[b"ip"] = bytes(int(p) for p in ip.split("."))
        if udp is not None:
            pairs[b"udp"] = udp.to_bytes(2, "big")
        for k, v in (extra or {}).items():
            pairs[k.encode()] = v
        record = cls(seq, pairs, b"")
        digest = keccak256(rlp.encode(record._content()))
        record.signature = EC.sign(secret, digest)
        return record

    @classmethod
    def from_rlp(cls, data: bytes) -> "Enr":
        if len(data) > MAX_RECORD_SIZE:
            raise EnrError("record exceeds 300 bytes")
        items = rlp.decode(data)
        if not isinstance(items, list) or len(items) < 2 \
                or len(items) % 2 != 0:
            raise EnrError("malformed record structure")
        signature, seq_raw = items[0], items[1]
        pairs = {}
        prev = None
        for i in range(2, len(items), 2):
            k, v = items[i], items[i + 1]
            if not isinstance(k, bytes) or not isinstance(v, bytes):
                raise EnrError("non-bytes key/value")
            if prev is not None and k <= prev:
                raise EnrError("keys not strictly sorted")
            prev = k
            pairs[k] = v
        record = cls(int.from_bytes(seq_raw, "big"), pairs, signature)
        if not record.verify():
            raise EnrError("invalid record signature")
        return record

    @classmethod
    def from_text(cls, text: str) -> "Enr":
        if not text.startswith("enr:"):
            raise EnrError("missing enr: prefix")
        raw = text[4:]
        raw += "=" * (-len(raw) % 4)
        return cls.from_rlp(base64.urlsafe_b64decode(raw))

    def __repr__(self) -> str:
        return (f"Enr(seq={self.seq}, "
                f"node_id={self.node_id.hex()[:16]}..., "
                f"ip={self.ip}, udp={self.udp})")
