"""Spec-conformant ssz_snappy req/resp stream encoding.

The consensus p2p spec encodes every req/resp payload as
  request : uvarint(len(ssz)) || snappy-FRAMED(ssz)
  response: chunks of [u8 result] || uvarint(len(ssz)) || snappy-FRAMED(ssz)
where "snappy-FRAMED" is the snappy framing format (stream identifier
chunk + compressed/uncompressed data chunks, each with a masked CRC32C
of the UNCOMPRESSED bytes) — distinct from gossip's raw snappy blocks.
(reference: networking/eth2/.../rpc/core/encodings/
RpcByteBufDecoder + SnappyFrameDecoder/Encoder + LengthPrefixedEncoding;
result byte semantics per RpcResponseStatus.)

This repo's transport multiplexes whole messages in frames rather than
libp2p streams, but the BYTES of each request/response body follow the
spec shapes above, validated down to checksum level.
"""

import struct
from typing import List, Optional, Tuple

from ..native import get_lib, snappyc

# snappy framing format chunk types
_STREAM_IDENT = b"\xff\x06\x00\x00sNaPpY"
_CHUNK_COMPRESSED = 0x00
_CHUNK_UNCOMPRESSED = 0x01
_MAX_FRAME_DATA = 65536          # framing format: uncompressed bytes/chunk

# response result codes (spec RpcResponseStatus)
RESULT_SUCCESS = 0
RESULT_INVALID_REQUEST = 1
RESULT_SERVER_ERROR = 2
RESULT_RESOURCE_UNAVAILABLE = 3

MAX_PAYLOAD = 1 << 27            # spec MAX_PAYLOAD_SIZE (128 MiB)


class EncodingError(ValueError):
    pass


# -- CRC32C -----------------------------------------------------------------

_CRC_TABLE = None


def _crc32c_py(data: bytes) -> int:
    global _CRC_TABLE
    if _CRC_TABLE is None:
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if c & 1 else c >> 1
            table.append(c)
        _CRC_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def crc32c(data: bytes) -> int:
    lib = get_lib()
    if lib is not None:
        return lib.teku_crc32c(data, len(data))
    return _crc32c_py(data)


def masked_crc32c(data: bytes) -> int:
    """The framing format masks checksums so CRCs of CRCs stay sane."""
    c = crc32c(data)
    return (((c >> 15) | (c << 17)) + 0xA282EAD8) & 0xFFFFFFFF


# -- uvarint (protobuf varint) ---------------------------------------------

def write_uvarint(value: int) -> bytes:
    if value < 0:
        raise EncodingError("uvarint is unsigned")
    out = bytearray()
    while value >= 0x80:
        out.append((value & 0x7F) | 0x80)
        value >>= 7
    out.append(value)
    return bytes(out)


def read_uvarint(data: bytes, pos: int = 0) -> Tuple[int, int]:
    """(value, next_pos); spec caps the length prefix at 10 bytes."""
    value = 0
    shift = 0
    for i in range(10):
        if pos + i >= len(data):
            raise EncodingError("truncated uvarint")
        byte = data[pos + i]
        value |= (byte & 0x7F) << shift
        if not byte & 0x80:
            return value, pos + i + 1
        shift += 7
    raise EncodingError("uvarint too long")


# -- snappy framing format --------------------------------------------------

def frame_compress(data: bytes) -> bytes:
    """Snappy framing format: stream identifier then <=64KiB chunks,
    each compressed (or stored) with a masked CRC32C of its
    uncompressed bytes."""
    out = [_STREAM_IDENT]
    for off in range(0, len(data), _MAX_FRAME_DATA):
        chunk = data[off:off + _MAX_FRAME_DATA]
        crc = masked_crc32c(chunk)
        comp = snappyc.compress(chunk)
        if len(comp) < len(chunk):
            body = struct.pack("<I", crc) + comp
            ctype = _CHUNK_COMPRESSED
        else:
            body = struct.pack("<I", crc) + chunk
            ctype = _CHUNK_UNCOMPRESSED
        out.append(struct.pack("<I", (len(body) << 8) | ctype)[:4])
        out.append(body)
    return b"".join(out)


def frame_uncompress(data: bytes, expected_len: Optional[int] = None
                     ) -> bytes:
    """Decode a framing-format stream, verifying every chunk checksum.
    `expected_len` (from the uvarint prefix) bounds the output."""
    if not data.startswith(_STREAM_IDENT):
        raise EncodingError("missing snappy stream identifier")
    pos = len(_STREAM_IDENT)
    out = []
    total = 0
    bound = expected_len if expected_len is not None else MAX_PAYLOAD
    while pos < len(data):
        if pos + 4 > len(data):
            raise EncodingError("truncated chunk header")
        head = struct.unpack("<I", data[pos:pos + 4])[0]
        ctype = head & 0xFF
        clen = head >> 8
        pos += 4
        if pos + clen > len(data):
            raise EncodingError("truncated chunk body")
        body = data[pos:pos + clen]
        pos += clen
        if ctype == _CHUNK_COMPRESSED or ctype == _CHUNK_UNCOMPRESSED:
            if clen < 4:
                raise EncodingError("chunk too short for checksum")
            (crc,) = struct.unpack("<I", body[:4])
            payload = body[4:]
            if ctype == _CHUNK_COMPRESSED:
                try:
                    payload = snappyc.uncompress(payload)
                except Exception as exc:
                    raise EncodingError(f"bad snappy block: {exc}")
            if len(payload) > _MAX_FRAME_DATA:
                raise EncodingError("chunk exceeds 64KiB limit")
            if masked_crc32c(payload) != crc:
                raise EncodingError("chunk checksum mismatch")
            total += len(payload)
            if total > bound:
                raise EncodingError("stream exceeds declared length")
            out.append(payload)
        elif ctype == 0xFF:
            if body != _STREAM_IDENT[4:]:
                raise EncodingError("bad repeated stream identifier")
        elif 0x80 <= ctype <= 0xFE:
            continue    # skippable per the format (0xFE = padding)
        else:
            raise EncodingError(f"unskippable unknown chunk {ctype:#x}")
    return b"".join(out)


# -- req/resp payload shapes ------------------------------------------------

def encode_payload(ssz_bytes: bytes) -> bytes:
    """uvarint length prefix + framed compression (spec request body
    and the per-chunk tail of responses)."""
    return write_uvarint(len(ssz_bytes)) + frame_compress(ssz_bytes)


def decode_payload(data: bytes, pos: int = 0,
                   max_len: int = MAX_PAYLOAD) -> Tuple[bytes, int]:
    """(ssz_bytes, next_pos).  The declared length is enforced both as
    a bound during decompression and exactly afterwards."""
    want, pos = read_uvarint(data, pos)
    if want > max_len:
        raise EncodingError(f"declared length {want} over limit")
    # the framed stream runs to the next chunk boundary; since callers
    # hand us the exact body, scan chunks until the declared size is
    # reached, tracking where the stream ends
    end = _frame_end(data, pos, want)
    ssz = frame_uncompress(data[pos:end], expected_len=want)
    if len(ssz) != want:
        raise EncodingError("length prefix does not match content")
    return ssz, end


def _frame_end(data: bytes, pos: int, want: int) -> int:
    """Find the end offset of a framed stream that decodes to exactly
    `want` bytes (chunk walk without decompression)."""
    if not data[pos:].startswith(_STREAM_IDENT):
        raise EncodingError("missing snappy stream identifier")
    cursor = pos + len(_STREAM_IDENT)
    produced = 0
    chunks = 0
    while produced < want:
        if cursor + 4 > len(data):
            raise EncodingError("truncated stream")
        # bound the walk: a crafted stream of produce-nothing chunks
        # must not be scanned unboundedly before frame_uncompress
        # rejects it (every data chunk produces >= 1 byte, so `want`
        # data chunks suffice; allow as many again for padding)
        chunks += 1
        if chunks > 2 * max(want, 1) + 64:
            raise EncodingError("chunk count exceeds stream bound")
        head = struct.unpack("<I", data[cursor:cursor + 4])[0]
        ctype = head & 0xFF
        clen = head >> 8
        cursor += 4 + clen
        if cursor > len(data):
            raise EncodingError("truncated chunk")
        if ctype in (_CHUNK_UNCOMPRESSED, _CHUNK_COMPRESSED):
            if clen < 4:
                # mirrors frame_uncompress's "chunk too short for
                # checksum" check: without it `produced` could go
                # NEGATIVE and walk the stream further than intended
                raise EncodingError("chunk too short for checksum")
            if ctype == _CHUNK_UNCOMPRESSED:
                produced += clen - 4
            else:
                body = data[cursor - clen + 4:cursor]
                produced += _snappy_uncompressed_len(body)
        # other chunk types (repeated ident, skippable/padding) produce
        # nothing; frame_uncompress validates them afterwards
    return cursor


def _snappy_uncompressed_len(block: bytes) -> int:
    value, _ = read_uvarint(block, 0)
    return value


def encode_response_chunk(ssz_bytes: bytes,
                          result: int = RESULT_SUCCESS) -> bytes:
    """Success chunks carry SSZ; error chunks carry an error message
    (possibly empty) — both use the same [result || payload] shape."""
    return bytes([result]) + encode_payload(ssz_bytes)


def decode_response(data: bytes) -> List[Tuple[int, bytes]]:
    """All chunks of a response body: [(result, ssz_bytes), ...]."""
    out = []
    pos = 0
    while pos < len(data):
        result = data[pos]
        ssz, pos = decode_payload(data, pos + 1)
        out.append((result, ssz))
    return out
