"""TCP p2p transport: framed streams, hello handshake, peer registry.

The transport role of the reference's libp2p stack (reference:
networking/p2p/src/main/java/tech/pegasys/teku/networking/p2p/libp2p/
LibP2PNetwork.java:46 — there TCP+yamux+noise via jvm-libp2p; here
asyncio TCP with u32-length frames and a hello handshake carrying
node id + fork digest + listen port).  Frames multiplex three planes:
gossip, request, response — the yamux-stream moral equivalent with a
fixed lane per plane.
"""

import asyncio
import logging
import secrets
import struct
from dataclasses import dataclass, field
from typing import Awaitable, Callable, Dict, List, Optional, Tuple

from .noise import NoiseError

_LOG = logging.getLogger(__name__)

KIND_HELLO = 0
KIND_GOSSIP = 1
KIND_REQUEST = 2
KIND_RESPONSE = 3
KIND_GOODBYE = 4

# goodbye reason codes (spec p2p-interface Goodbye reasons 1-3 plus
# the 128+ client-extension range real clients use)
GOODBYE_SHUTDOWN = 1
GOODBYE_IRRELEVANT_NETWORK = 2
GOODBYE_FAULT = 3
GOODBYE_BANNED = 128
GOODBYE_TOO_MANY_PEERS = 129

MAX_FRAME = 1 << 24


class Peer:
    def __init__(self, reader: asyncio.StreamReader,
                 writer: asyncio.StreamWriter, outbound: bool):
        self.reader = reader
        self.writer = writer
        self.outbound = outbound
        self.node_id: bytes = b""
        self.fork_digest: bytes = b""
        self.listen_port: int = 0
        self.status = None            # latest chain Status from them
        self._req_id = 0
        self._pending: Dict[int, asyncio.Future] = {}
        self.connected = True
        # egress accounting per wire lane (kind): the gossipsub O(D)
        # bandwidth property is asserted against these
        self.bytes_out: Dict[int, int] = {}

    async def send_frame(self, kind: int, payload: bytes) -> None:
        if not self.connected:
            return
        try:
            frame = (struct.pack("<IB", len(payload) + 1, kind)
                     + payload)
            self.bytes_out[kind] = (self.bytes_out.get(kind, 0)
                                    + len(frame))
            self.writer.write(frame)
            await self.writer.drain()
        except (ConnectionError, OSError):
            self.connected = False

    async def read_frame(self) -> Optional[Tuple[int, bytes]]:
        try:
            head = await self.reader.readexactly(4)
            (n,) = struct.unpack("<I", head)
            if not 1 <= n <= MAX_FRAME:
                return None
            body = await self.reader.readexactly(n)
            return body[0], body[1:]
        except (asyncio.IncompleteReadError, ConnectionError, OSError,
                NoiseError):
            # NoiseError = garbage/tampered ciphertext after a good
            # handshake: treat like a dead connection so the read loop
            # cleans the peer up instead of dying mid-task
            return None

    async def request(self, method: str, payload: bytes,
                      timeout: float = 10.0) -> bytes:
        """Round-trip on the request lane; responses matched by id."""
        self._req_id += 1
        rid = self._req_id
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        mb = method.encode()
        await self.send_frame(
            KIND_REQUEST,
            struct.pack("<IB", rid, len(mb)) + mb + payload)
        try:
            return await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(rid, None)

    def close(self) -> None:
        self.connected = False
        try:
            self.writer.close()
        except Exception:
            pass


@dataclass
class NetworkConfig:
    host: str = "127.0.0.1"
    port: int = 0                    # 0 = ephemeral
    max_peers: int = 32
    # noise XX encryption (reference LibP2PNetworkBuilder.java:219 —
    # the libp2p noise security upgrade); off only for tests that
    # inspect raw frames
    noise: bool = True


# the noise prologue binds both sides to the same protocol framing
_NOISE_PROLOGUE = b"teku-tpu/p2p/1"


class P2PNetwork:
    """Listens + dials; owns per-peer read loops; hands decoded frames
    to the gossip router and req/resp handler.  With noise enabled the
    node's identity IS its noise static key: node_id == the X25519
    static public key proven during the handshake."""

    def __init__(self, config: NetworkConfig, fork_digest: bytes,
                 node_id: Optional[bytes] = None, static_key=None,
                 reputation=None):
        from .reputation import ReputationManager
        self.config = config
        self.fork_digest = fork_digest
        self.reputation = reputation or ReputationManager()
        if config.noise:
            if node_id is not None:
                raise ValueError(
                    "with noise enabled the node id IS the static key;"
                    " pass static_key= to persist an identity")
            from .noise import generate_static_keypair
            if static_key is None:
                static_key, _ = generate_static_keypair()
            self.static_key = static_key
            self.node_id = static_key.public_key().public_bytes_raw()
        else:
            self.static_key = None
            self.node_id = node_id or secrets.token_bytes(32)
        self.peers: List[Peer] = []
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: int = config.port
        # plane handlers, wired by gossip router / rpc dispatcher
        self.on_gossip: Optional[Callable[[Peer, bytes],
                                          Awaitable[None]]] = None
        self.on_request: Optional[Callable[[Peer, str, bytes],
                                           Awaitable[bytes]]] = None
        self.on_peer_connected: Optional[Callable[[Peer],
                                                  Awaitable[None]]] = None
        self.on_peer_disconnected: Optional[
            Callable[[Peer], Awaitable[None]]] = None

    # -- lifecycle -----------------------------------------------------
    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._accept, self.config.host, self.config.port)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        for p in list(self.peers):
            await p.send_frame(KIND_GOODBYE, bytes([GOODBYE_SHUTDOWN]))
            p.close()
        self.peers.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # -- dialing / accepting ------------------------------------------
    async def connect(self, host: str, port: int) -> Optional[Peer]:
        if len(self.peers) >= self.config.max_peers:
            return None
        reader, writer = await asyncio.open_connection(host, port)
        noise_id = None
        if self.static_key is not None:
            try:
                reader, writer, noise_id = await self._secure(
                    reader, writer, initiator=True)
            except Exception:
                _LOG.info("noise handshake failed (dialing %s:%d)",
                          host, port)
                writer.close()
                return None
        peer = Peer(reader, writer, outbound=True)
        await self._handshake(peer, noise_id)
        if not peer.connected:
            return None
        if not self.reputation.is_connect_allowed(peer.node_id):
            _LOG.info("dialed a banned peer, dropping")
            peer.close()
            return None
        if not self._resolve_duplicate(peer):
            peer.close()
            return None
        self.peers.append(peer)
        asyncio.create_task(self._read_loop(peer))
        if self.on_peer_connected:
            await self.on_peer_connected(peer)
        return peer

    async def _accept(self, reader, writer) -> None:
        noise_id = None
        if self.static_key is not None:
            try:
                reader, writer, noise_id = await self._secure(
                    reader, writer, initiator=False)
            except Exception:
                # plaintext or malformed-handshake peer: reject
                _LOG.info("noise handshake failed (inbound)")
                writer.close()
                return
        peer = Peer(reader, writer, outbound=False)
        await self._handshake(peer, noise_id)
        if not peer.connected:
            return
        if not self.reputation.is_connect_allowed(peer.node_id):
            await peer.send_frame(KIND_GOODBYE,
                                  bytes([GOODBYE_BANNED]))
            peer.close()
            return
        if not self._resolve_duplicate(peer):
            peer.close()
            return
        if len(self.peers) >= self.config.max_peers:
            await peer.send_frame(KIND_GOODBYE,
                                  bytes([GOODBYE_TOO_MANY_PEERS]))
            peer.close()
            return
        self.peers.append(peer)
        asyncio.create_task(self._read_loop(peer))
        if self.on_peer_connected:
            await self.on_peer_connected(peer)

    async def _secure(self, reader, writer, initiator: bool):
        """Noise XX upgrade; returns (reader, writer, remote_static)
        with AEAD framing underneath."""
        from . import noise as N
        handshake = (N.initiator_handshake if initiator
                     else N.responder_handshake)
        tx, rx, remote_static = await asyncio.wait_for(
            handshake(reader, writer, self.static_key,
                      prologue=_NOISE_PROLOGUE),
            timeout=10.0)
        return N.NoiseReader(reader, rx), N.NoiseWriter(writer, tx), \
            remote_static

    def _resolve_duplicate(self, new_peer: Peer) -> bool:
        """Simultaneous-open tie-break: when two links to the same peer
        exist, BOTH sides keep the one initiated by the smaller
        node_id (each side sees the same link from opposite
        directions, so picking by initiator id is symmetric — naive
        keep-first lets each side keep a different link and close them
        both).  True = admit the new link."""
        old = [p for p in self.peers
               if p.connected and p.node_id == new_peer.node_id]
        if not old:
            return True
        keep_ours = self.node_id < new_peer.node_id
        new_wins = (new_peer.outbound == keep_ours)
        if new_wins:
            for p in old:
                p.close()
                if p in self.peers:
                    self.peers.remove(p)
        return new_wins

    async def _handshake(self, peer: Peer,
                         noise_id: Optional[bytes] = None) -> None:
        hello = (self.node_id + self.fork_digest
                 + struct.pack("<H", self.port))
        await peer.send_frame(KIND_HELLO, hello)
        try:
            # bounded: a peer speaking another protocol (e.g. noise to
            # our plaintext, or vice versa) must not hang the dial
            frame = await asyncio.wait_for(peer.read_frame(),
                                           timeout=10.0)
        except asyncio.TimeoutError:
            peer.close()
            return
        if frame is None or frame[0] != KIND_HELLO or len(frame[1]) < 38:
            peer.close()
            return
        data = frame[1]
        peer.node_id = data[:32]
        peer.fork_digest = data[32:36]
        (peer.listen_port,) = struct.unpack("<H", data[36:38])
        if noise_id is not None and peer.node_id != noise_id:
            # the hello id must BE the key the peer just proved —
            # otherwise ids are spoofable despite the encryption
            _LOG.info("peer hello id does not match noise identity")
            peer.close()
            return
        if peer.fork_digest != self.fork_digest:
            _LOG.info("peer on a different fork, disconnecting")
            await peer.send_frame(KIND_GOODBYE,
                                  bytes([GOODBYE_IRRELEVANT_NETWORK]))
            self.reputation.report_initiated_disconnect(
                peer.node_id, GOODBYE_IRRELEVANT_NETWORK)
            peer.close()
        if peer.node_id == self.node_id:
            peer.close()                                  # self-dial

    # -- read pump -----------------------------------------------------
    async def _read_loop(self, peer: Peer) -> None:
        while peer.connected:
            frame = await peer.read_frame()
            if frame is None:
                break
            kind, payload = frame
            try:
                if kind == KIND_GOSSIP and self.on_gossip:
                    await self.on_gossip(peer, payload)
                elif kind == KIND_REQUEST and self.on_request:
                    (rid, mlen) = struct.unpack("<IB", payload[:5])
                    method = payload[5:5 + mlen].decode()
                    body = payload[5 + mlen:]
                    resp = await self.on_request(peer, method, body)
                    await peer.send_frame(
                        KIND_RESPONSE, struct.pack("<I", rid) + resp)
                elif kind == KIND_RESPONSE:
                    (rid,) = struct.unpack("<I", payload[:4])
                    fut = peer._pending.get(rid)
                    if fut is not None and not fut.done():
                        fut.set_result(payload[4:])
                elif kind == KIND_GOODBYE:
                    # a fault-citing goodbye means redialing is useless
                    # for a while; remember that
                    self.reputation.report_received_goodbye(
                        peer.node_id, payload[0] if payload else None)
                    break
            except Exception:
                _LOG.exception("peer frame handling failed")
                break
        peer.close()
        if peer in self.peers:
            self.peers.remove(peer)
        if self.on_peer_disconnected is not None:
            try:
                await self.on_peer_disconnected(peer)
            except Exception:
                _LOG.exception("peer-disconnect hook failed")
