"""Req/resp RPC: status, ping/metadata, blocks by range/root.

The reference's beacon-chain RPC methods over spec ssz_snappy streams
(reference: networking/eth2/src/main/java/tech/pegasys/teku/networking/
eth2/rpc/beaconchain/methods/ — Status, Goodbye, Ping, Metadata,
BeaconBlocksByRange/RootMessageHandler; framing per
rpc/core/encodings/).  Every request body and response chunk follows
the spec byte shapes — uvarint length prefix + snappy FRAMING-format
stream, responses as [result byte || payload] chunks — validated down
to chunk checksums (encoding.py).  The transport multiplexes whole
messages where libp2p uses streams; the payload bytes are identical.
"""

import asyncio
import logging
import struct
from typing import List, Optional, Sequence

from ..infra.aio import retry_with_backoff
from ..infra.env import env_float
from ..spec import helpers as H
from ..spec.codec import (deserialize_signed_block,
                          serialize_signed_block)
from ..spec.datastructures import MetadataMessage, Ping, Status
from . import encoding as E

try:
    from .transport import P2PNetwork, Peer
except ModuleNotFoundError:      # pragma: no cover - optional crypto
    # the noise transport needs the `cryptography` package.  This guard
    # alone does not make `teku_tpu.networking` importable without it
    # (the package __init__ pulls the transport chain first), but it
    # lets THIS module load standalone — tests drive the client
    # retry/timeout logic in minimal containers by registering a stub
    # parent package and importing reqresp directly
    P2PNetwork = Peer = None

_LOG = logging.getLogger(__name__)

STATUS = "status"
PING = "ping"
METADATA = "metadata"
BLOCKS_BY_RANGE = "beacon_blocks_by_range"
BLOCKS_BY_ROOT = "beacon_blocks_by_root"
BLOB_SIDECARS_BY_RANGE = "blob_sidecars_by_range"
BLOB_SIDECARS_BY_ROOT = "blob_sidecars_by_root"

MAX_REQUEST_BLOCKS = 64


MAX_RESPONSE_BYTES = (1 << 24) - 4096     # fits one transport frame


def _pack_chunks(chunks: Sequence[bytes], ok: bool = True) -> bytes:
    """Spec response body: concatenated [result || uvarint || framed]
    chunks.  Truncates (never splits) at the frame budget: a shorter
    valid response lets the requester re-request the rest, an oversized
    frame would get the whole connection torn down."""
    if not ok:
        return E.encode_response_chunk(b"server error",
                                       result=E.RESULT_SERVER_ERROR)
    body = []
    total = 0
    for c in chunks:
        enc = E.encode_response_chunk(c)
        if total + len(enc) > MAX_RESPONSE_BYTES:
            break
        body.append(enc)
        total += len(enc)
    return b"".join(body)       # zero chunks = valid empty response


def _unpack_chunks(data: bytes) -> Optional[List[bytes]]:
    try:
        parsed = E.decode_response(data)
    except E.EncodingError:
        return None
    if any(result != E.RESULT_SUCCESS for result, _ in parsed):
        return None
    return [ssz for _, ssz in parsed]


class BeaconRpc:
    """Server + client for the beacon RPC methods, bound to a node's
    chain data.

    Client fetches carry a configurable per-request timeout (formerly
    four hard-coded 30 s literals) and transient failures — timeouts,
    connection resets — retry with bounded exponential backoff + jitter
    through `infra/aio.py:retry_with_backoff`.  A malformed response is
    NOT transient: it raises immediately so sync treats the peer as
    misbehaving instead of giving it three more chances."""

    def __init__(self, net: P2PNetwork, node,
                 request_timeout_s: Optional[float] = None,
                 request_attempts: int = 3):
        self.net = net
        self.node = node
        if request_timeout_s is None:
            request_timeout_s = env_float("TEKU_TPU_REQRESP_TIMEOUT_S",
                                          30.0, lo=0.1)
        self.request_timeout_s = request_timeout_s
        self.request_attempts = request_attempts
        self.seq_number = 0
        # chain, don't clobber: another protocol (e.g. discovery) may
        # already be installed — unknown methods fall through to it
        self._next_handler = net.on_request
        net.on_request = self._handle

    # -- server side ---------------------------------------------------
    def _local_status(self) -> Status:
        chain = self.node.chain
        spec = self.node.spec
        head_root = chain.head_root
        head_slot = chain.head_slot()
        fin = chain.finalized_checkpoint
        digest = H.compute_fork_digest(
            spec.config.GENESIS_FORK_VERSION,
            chain.head_state().genesis_validators_root)
        return Status(fork_digest=digest, finalized_root=fin.root,
                      finalized_epoch=fin.epoch, head_root=head_root,
                      head_slot=head_slot)

    async def _handle(self, peer: Peer, method: str, body: bytes) -> bytes:
        try:
            if method == STATUS:
                peer.status = Status.deserialize(E.decode_payload(body)[0])
                return _pack_chunks(
                    [Status.serialize(self._local_status())])
            if method == PING:
                return _pack_chunks(
                    [Ping.serialize(Ping(seq_number=self.seq_number))])
            if method == METADATA:
                return _pack_chunks([MetadataMessage.serialize(
                    MetadataMessage(seq_number=self.seq_number))])
            if method == BLOCKS_BY_RANGE:
                start, count = struct.unpack(
                    "<QQ", E.decode_payload(body)[0])
                count = min(count, MAX_REQUEST_BLOCKS)
                return _pack_chunks(
                    [serialize_signed_block(s)
                     for s in self._canonical_signed_in_range(start, count)])
            if method == BLOCKS_BY_ROOT:
                roots_blob = E.decode_payload(body)[0]
                roots = [roots_blob[i:i + 32]
                         for i in range(0, min(len(roots_blob),
                                               32 * MAX_REQUEST_BLOCKS), 32)]
                return _pack_chunks(self._blocks_by_root(roots))
            if method == BLOB_SIDECARS_BY_RANGE:
                start, count = struct.unpack(
                    "<QQ", E.decode_payload(body)[0])
                cfg = self.node.spec.config
                count = min(count, cfg.MAX_REQUEST_BLOCKS_DENEB)
                return _pack_chunks(
                    self._blob_sidecars_by_range(start, count))
            if method == BLOB_SIDECARS_BY_ROOT:
                ids_blob = E.decode_payload(body)[0]
                cap = self.node.spec.config.MAX_REQUEST_BLOB_SIDECARS
                ids = [(ids_blob[i:i + 32],
                        int.from_bytes(ids_blob[i + 32:i + 40], "little"))
                       for i in range(0, min(len(ids_blob), 40 * cap),
                                      40)]
                return _pack_chunks(self._blob_sidecars_by_root(ids))
            if self._next_handler is not None:
                return await self._next_handler(peer, method, body)
        except Exception:
            _LOG.exception("rpc %s failed", method)
        return _pack_chunks([], ok=False)

    def _canonical_roots_in_range(self, start: int,
                                  count: int) -> List[bytes]:
        """Canonical-chain block roots with slot in [start, start+count),
        ascending — the shared walk for blocks and blob sidecars."""
        store = self.node.store
        chain = []
        root = self.node.chain.head_root
        while root in store.blocks:
            blk = store.blocks[root]
            if blk.slot < start:
                break
            if blk.slot < start + count:
                chain.append(root)
            parent = blk.parent_root
            if parent == root or parent not in store.blocks:
                break
            root = parent
        chain.reverse()
        return chain

    def _canonical_signed_in_range(self, start: int, count: int) -> List:
        signed_blocks = self.node.store.signed_blocks
        return [s for r in self._canonical_roots_in_range(start, count)
                if (s := signed_blocks.get(r)) is not None]

    def _blocks_by_root(self, roots: Sequence[bytes]) -> List[bytes]:
        signed_blocks = self.node.store.signed_blocks
        return [serialize_signed_block(signed_blocks[r])
                for r in roots if r in signed_blocks]

    # -- blob sidecars (deneb req/resp; served from the tracking pool) --
    def _blob_pool(self):
        return getattr(self.node, "blob_pool", None)

    def _stored_sidecars(self, root: bytes) -> List[bytes]:
        """Serialized sidecars for `root`: the in-memory pool first,
        then the database (persisted imports outlive the pool's
        64-block horizon; pruned past the DA window)."""
        pool = self._blob_pool()
        if pool is not None:
            live = pool.wire_sidecars_for(root)
            if live:
                return [type(sc).serialize(sc) for sc in live]
        store = getattr(self.node, "blob_store", None)
        if store is not None:
            return store.get_blob_sidecars(root)
        return []

    def _blob_sidecars_by_range(self, start: int,
                                count: int) -> List[bytes]:
        cap = self.node.spec.config.MAX_REQUEST_BLOB_SIDECARS
        out = []
        for r in self._canonical_roots_in_range(start, count):
            for raw in self._stored_sidecars(r):
                out.append(raw)
                if len(out) >= cap:
                    return out
        return out

    def _blob_sidecars_by_root(self, ids) -> List[bytes]:
        schema = self._sidecar_schema()
        if schema is None:
            return []
        out = []
        for root, index in ids:
            for raw in self._stored_sidecars(root):
                if schema.deserialize(raw).index == index:
                    out.append(raw)
        return out

    # -- client side ---------------------------------------------------
    async def _fetch(self, peer: Peer, method: str, body: bytes) -> bytes:
        """One client request with per-request timeout and bounded
        retry (jittered backoff) on transient transport failures."""
        async def once():
            return await peer.request(method, body,
                                      timeout=self.request_timeout_s)
        return await retry_with_backoff(
            once, attempts=self.request_attempts, base_delay_s=0.25,
            jitter=0.5, what=f"reqresp {method}",
            retry_on=(asyncio.TimeoutError, ConnectionResetError,
                      BrokenPipeError, TimeoutError))

    async def exchange_status(self, peer: Peer) -> Optional[Status]:
        resp = await peer.request(
            STATUS,
            E.encode_payload(Status.serialize(self._local_status())))
        chunks = _unpack_chunks(resp)
        if not chunks:
            return None
        peer.status = Status.deserialize(chunks[0])
        return peer.status

    async def blocks_by_range(self, peer: Peer, start: int,
                              count: int) -> List:
        resp = await self._fetch(
            peer, BLOCKS_BY_RANGE,
            E.encode_payload(struct.pack("<QQ", start, count)))
        chunks = _unpack_chunks(resp)
        if chunks is None:
            # malformed/error responses must FAIL, not read as an empty
            # chain — sync treats an exception as peer misbehaviour and
            # backs the peer off, but an empty list as honest truth
            raise ConnectionError("malformed blocks_by_range response")
        cfg = self.node.spec.config
        return [deserialize_signed_block(cfg, c) for c in chunks]

    async def blocks_by_root(self, peer: Peer, roots: Sequence[bytes]
                             ) -> List:
        resp = await self._fetch(
            peer, BLOCKS_BY_ROOT, E.encode_payload(b"".join(roots)))
        chunks = _unpack_chunks(resp)
        if chunks is None:
            return []
        cfg = self.node.spec.config
        return [deserialize_signed_block(cfg, c) for c in chunks]

    def _sidecar_schema(self):
        from ..spec.deneb.datastructures import get_deneb_schemas
        return get_deneb_schemas(self.node.spec.config).BlobSidecar

    async def blob_sidecars_by_range(self, peer: Peer, start: int,
                                     count: int) -> List:
        resp = await self._fetch(
            peer, BLOB_SIDECARS_BY_RANGE,
            E.encode_payload(struct.pack("<QQ", start, count)))
        chunks = _unpack_chunks(resp)
        if chunks is None:
            return []
        schema = self._sidecar_schema()
        return [schema.deserialize(c) for c in chunks]

    async def blob_sidecars_by_root(self, peer: Peer, ids) -> List:
        """ids: (block_root, index) pairs (spec BlobIdentifier)."""
        body = b"".join(root + index.to_bytes(8, "little")
                        for root, index in ids)
        resp = await self._fetch(peer, BLOB_SIDECARS_BY_ROOT,
                                 E.encode_payload(body))
        chunks = _unpack_chunks(resp)
        if chunks is None:
            return []
        schema = self._sidecar_schema()
        return [schema.deserialize(c) for c in chunks]
