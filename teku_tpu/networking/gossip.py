"""Gossipsub router over the TCP transport: mesh, gossip, scoring.

The gossipsub v1.1 role (reference: networking/p2p/.../gossip/config/
GossipConfig.java:51-163 for the parameter set — D=8, D_low=6,
D_high=12, D_lazy=6, 700ms heartbeat, mcache 6 windows gossiping 3 —
and networking/eth2/.../gossip/encoding/SszSnappyEncoding.java for the
payload codec): each topic keeps a bounded MESH of peers receiving
full messages eagerly; everyone else hears message IDs via IHAVE
gossip and pulls what they miss with IWANT.  Egress per message is
O(D), not O(peers) — the property flood-publish lacks.

Message IDs follow the altair spec: SHA256(MESSAGE_DOMAIN_VALID_SNAPPY
++ uint64_le(len(topic)) ++ topic ++ uncompressed_data)[:20].

Control plane (SUBSCRIBE/GRAFT/PRUNE/IHAVE/IWANT) rides the same
KIND_GOSSIP transport lane with a leading envelope byte; data messages
are snappy block-compressed like the spec's gossip payloads.
"""

import asyncio
import hashlib
import logging
import random
import struct
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..infra.collections import LimitedSet
from ..native import snappyc
from ..node.gossip import GossipNetwork, TopicHandler, ValidationResult
from .scoring import GossipScoring
from .transport import GOODBYE_FAULT, KIND_GOSSIP, P2PNetwork, Peer

_LOG = logging.getLogger(__name__)

# reference GossipConfig.java defaults
D = 8
D_LOW = 6
D_HIGH = 12
D_LAZY = 6
HEARTBEAT_S = 0.7
MCACHE_LEN = 6           # history windows kept for IWANT serving
MCACHE_GOSSIP = 3        # windows advertised via IHAVE
MAX_IHAVE_PER_HEARTBEAT = 5000
MAX_IWANT_PER_CONTROL = 500

# mainnet does ~31k attestations/slot; the dedupe window must cover
# several slots of them (round 3's 65k cache was ~2 slots deep)
SEEN_CACHE_SIZE = 1 << 19

MESSAGE_DOMAIN_VALID_SNAPPY = b"\x01\x00\x00\x00"

# gossipsub v1.1 mesh admission: GRAFT only from peers with
# non-negative score (the graded thresholds live in scoring.py)
GRAFT_SCORE_FLOOR = 0.0
# gossipsub v1.1 PRUNE backoff: a pruned peer may not rejoin the mesh
# (either direction) until the backoff expires — without it a P3
# eviction re-grafts the same peer in the same heartbeat
PRUNE_BACKOFF_HEARTBEATS = 86          # ~60s at the 700ms heartbeat
# duplicates credit a mesh member's delivery duty only within this
# window after the first VALIDATED delivery (unbounded windows let a
# freeloader farm P3 credit by replaying one old message)
DELIVERY_WINDOW_HEARTBEATS = 2

ENV_DATA = 0
ENV_CONTROL = 1


def spec_msg_id(topic: str, data: bytes) -> bytes:
    """Altair gossip message-id over the UNCOMPRESSED payload."""
    tb = topic.encode()
    return hashlib.sha256(
        MESSAGE_DOMAIN_VALID_SNAPPY
        + struct.pack("<Q", len(tb)) + tb + data).digest()[:20]


# -- control-message codec --------------------------------------------------
#
# [u16 n_subs][{u8 subscribed, u8 tlen, topic}...]
# [u16 n_graft][{u8 tlen, topic}...]
# [u16 n_prune][{u8 tlen, topic}...]
# [u16 n_ihave][{u8 tlen, topic, u16 n_ids, 20B ids...}...]
# [u16 n_iwant][20B ids...]

def encode_control(subs: Sequence[Tuple[bool, str]] = (),
                   graft: Sequence[str] = (),
                   prune: Sequence[str] = (),
                   ihave: Sequence[Tuple[str, Sequence[bytes]]] = (),
                   iwant: Sequence[bytes] = ()) -> bytes:
    out = [struct.pack("<H", len(subs))]
    for on, topic in subs:
        tb = topic.encode()
        out.append(struct.pack("<BB", 1 if on else 0, len(tb)) + tb)
    for topics in (graft, prune):
        out.append(struct.pack("<H", len(topics)))
        for topic in topics:
            tb = topic.encode()
            out.append(struct.pack("<B", len(tb)) + tb)
    out.append(struct.pack("<H", len(ihave)))
    for topic, mids in ihave:
        tb = topic.encode()
        out.append(struct.pack("<B", len(tb)) + tb
                   + struct.pack("<H", len(mids)) + b"".join(mids))
    out.append(struct.pack("<H", len(iwant)) + b"".join(iwant))
    return bytes([ENV_CONTROL]) + b"".join(out)


def decode_control(payload: bytes):
    """payload WITHOUT the envelope byte → (subs, graft, prune, ihave,
    iwant); raises on malformed input (caller punishes)."""
    pos = 0

    def take(n):
        nonlocal pos
        if pos + n > len(payload):
            raise ValueError("truncated control")
        chunk = payload[pos:pos + n]
        pos += n
        return chunk

    def u16():
        return struct.unpack("<H", take(2))[0]

    def topic():
        (tlen,) = take(1)
        return take(tlen).decode()

    subs = []
    for _ in range(u16()):
        (on,) = take(1)
        subs.append((bool(on), topic()))
    graft = [topic() for _ in range(u16())]
    prune = [topic() for _ in range(u16())]
    ihave = []
    for _ in range(u16()):
        t = topic()
        n = u16()
        ihave.append((t, [take(20) for _ in range(n)]))
    iwant = [take(20) for _ in range(u16())]
    return subs, graft, prune, ihave, iwant


class MessageCache:
    """Sliding history of recent full messages (gossipsub mcache):
    IWANT is served from all MCACHE_LEN windows, IHAVE advertises the
    newest MCACHE_GOSSIP.  Windows are indexed per topic so the 700ms
    heartbeat's gossip_ids is O(ids in that topic), not O(topics x
    total cache) — at mainnet attestation rates the flat scan would
    stall the event loop."""

    def __init__(self, history: int = MCACHE_LEN,
                 gossip: int = MCACHE_GOSSIP):
        # window = {topic: {mid: data}}; plus a flat mid index for get()
        self._windows: List[Dict[str, Dict[bytes, bytes]]] = [
            {} for _ in range(history)]
        self._by_mid: List[Dict[bytes, Tuple[str, bytes]]] = [
            {} for _ in range(history)]
        self._gossip = gossip

    def put(self, mid: bytes, topic: str, data: bytes) -> None:
        self._windows[0].setdefault(topic, {})[mid] = data
        self._by_mid[0][mid] = (topic, data)

    def get(self, mid: bytes) -> Optional[Tuple[str, bytes]]:
        for w in self._by_mid:
            if mid in w:
                return w[mid]
        return None

    def gossip_ids(self, topic: str) -> List[bytes]:
        return [mid for w in self._windows[:self._gossip]
                for mid in w.get(topic, ())]

    def shift(self) -> None:
        self._windows.insert(0, {})
        self._windows.pop()
        self._by_mid.insert(0, {})
        self._by_mid.pop()


class TcpGossipNetwork(GossipNetwork):
    """GossipNetwork implementation the BeaconNode subscribes through —
    same interface as the in-memory devnet bus, gossipsub underneath."""

    def __init__(self, net: P2PNetwork, rng: Optional[random.Random] = None,
                 scoring: Optional[GossipScoring] = None):
        self.net = net
        self.net.on_gossip = self._on_gossip
        self.net.on_peer_disconnected = self._on_peer_gone
        self._handlers: Dict[str, TopicHandler] = {}
        self._seen: LimitedSet = LimitedSet(SEEN_CACHE_SIZE)
        # monotonic stamp of the last gossip frame received from ANY
        # peer — the health layer's staleness signal (None until the
        # first frame: silence during boot is not sickness)
        self.last_message_monotonic: Optional[float] = None
        self.scoring = scoring or GossipScoring()
        self._peer_topics: Dict[bytes, Set[str]] = {}
        self._mesh: Dict[str, Set[Peer]] = {}
        self._mcache = MessageCache()
        self._rng = rng or random.Random()
        self._heartbeat_task: Optional[asyncio.Task] = None
        # strong refs to in-flight control sends: asyncio holds tasks
        # weakly, and a GC'd task mid-drain = a GRAFT that never left
        self._control_tasks: set = set()
        # per-peer ids already served via IWANT (gossipsub v1.1 bounds
        # IWANT retries to stop bandwidth amplification)
        self._iwant_served: Dict[bytes, LimitedSet] = {}
        # (topic, node_id) -> heartbeat index when re-graft is allowed
        self._prune_backoff: Dict[Tuple[str, bytes], int] = {}
        # mid -> heartbeat expiry of the P3 duplicate-credit window
        self._delivery_window: Dict[bytes, int] = {}
        # mid -> heartbeat count when our own outstanding IWANT expires:
        # without this, every IHAVE advertiser is asked for the same
        # missing message and the payload arrives D_lazy times
        self._iwant_pending: Dict[bytes, int] = {}
        self._heartbeats = 0
        # observability (the O(D) egress assertion hangs off these)
        self.messages_forwarded = 0
        self.data_frames_sent = 0
        self.control_frames_sent = 0
        self.iwant_served = 0

    # -- lifecycle ------------------------------------------------------
    async def start(self) -> None:
        if self._heartbeat_task is None:
            self._heartbeat_task = asyncio.create_task(
                self._heartbeat_loop())

    async def stop(self) -> None:
        if self._heartbeat_task is not None:
            self._heartbeat_task.cancel()
            try:
                await self._heartbeat_task
            except asyncio.CancelledError:
                pass
            self._heartbeat_task = None

    # -- GossipNetwork interface ---------------------------------------
    def subscribe(self, topic: str, handler: TopicHandler) -> None:
        self._handlers[topic] = handler
        self._mesh.setdefault(topic, set())
        # announce to whoever is already connected; mesh fills via
        # heartbeat grafting (and peers grafting us)
        frame = encode_control(subs=[(True, topic)])
        for peer in list(self.net.peers):
            self._send_control(peer, frame)

    async def publish(self, topic: str, data: bytes) -> None:
        mid = spec_msg_id(topic, data)
        self._seen.add(mid)
        self._mcache.put(mid, topic, data)
        frame = self._encode_data(topic, data)
        targets = self._eager_targets(topic)
        await self._send_data(frame, targets, exclude=None)

    # -- peer bookkeeping ----------------------------------------------
    def announce_subscriptions(self, peer: Peer) -> None:
        """Tell a fresh peer which topics we're in (gossipsub sends the
        full subscription set on connect)."""
        if self._handlers:
            self._send_control(peer, encode_control(
                subs=[(True, t) for t in self._handlers]))

    async def _on_peer_gone(self, peer: Peer) -> None:
        self._peer_topics.pop(peer.node_id, None)
        self._iwant_served.pop(peer.node_id, None)
        for mesh in self._mesh.values():
            mesh.discard(peer)
        # retain the score book (no reconnect-washing); only end mesh
        # tenure, and only when no OTHER link to the same id survives
        # (duplicate-link teardown must not reset the live link)
        if not any(p.connected and p.node_id == peer.node_id
                   for p in self.net.peers if p is not peer):
            self.scoring.on_disconnect(peer.node_id)

    def _mesh_add(self, topic: str, peer: Peer) -> None:
        self._mesh.setdefault(topic, set()).add(peer)
        self.scoring.on_graft(peer.node_id, topic)

    def _mesh_drop(self, topic: str, peer: Peer,
                   backoff: bool = False) -> None:
        mesh = self._mesh.get(topic)
        if mesh is not None and peer in mesh:
            mesh.discard(peer)
            self.scoring.on_prune(peer.node_id, topic)
        if backoff:
            self._prune_backoff[(topic, peer.node_id)] = \
                self._heartbeats + PRUNE_BACKOFF_HEARTBEATS

    def _in_backoff(self, topic: str, node_id: bytes) -> bool:
        exp = self._prune_backoff.get((topic, node_id))
        return exp is not None and exp > self._heartbeats

    def _topic_peers(self, topic: str) -> List[Peer]:
        return [p for p in self.net.peers
                if topic in self._peer_topics.get(p.node_id, ())]

    def _eager_targets(self, topic: str) -> List[Peer]:
        """Mesh peers; if the mesh is empty (just subscribed, or we
        publish without subscribing) fall back to D random topic peers
        (gossipsub fanout), or — when nobody has announced the topic
        yet — all peers, so bootstrap-sized devnets still propagate."""
        mesh = [p for p in self._mesh.get(topic, ()) if p.connected]
        if mesh:
            return mesh
        floor = self.scoring.params.publish_threshold
        candidates = [p for p in self._topic_peers(topic)
                      if self.scoring.score(p.node_id) >= floor]
        if not candidates:
            candidates = [p for p in self.net.peers
                          if self.scoring.score(p.node_id) >= floor]
        self._rng.shuffle(candidates)
        return candidates[:D]

    # -- wire ----------------------------------------------------------
    @staticmethod
    def _encode_data(topic: str, data: bytes) -> bytes:
        tb = topic.encode()
        return (bytes([ENV_DATA]) + struct.pack("<B", len(tb)) + tb
                + snappyc.compress(data))

    async def _send_data(self, frame: bytes, targets: Sequence[Peer],
                         exclude) -> None:
        """Concurrent sends: one slow peer's TCP backpressure must not
        head-of-line-block propagation to the others."""
        sends = [peer.send_frame(KIND_GOSSIP, frame)
                 for peer in targets
                 if peer is not exclude and peer.connected]
        self.data_frames_sent += len(sends)
        if sends:
            await asyncio.gather(*sends, return_exceptions=True)

    def _send_control(self, peer: Peer, frame: bytes) -> None:
        if not peer.connected:
            return
        self.control_frames_sent += 1
        task = asyncio.ensure_future(peer.send_frame(KIND_GOSSIP, frame))
        self._control_tasks.add(task)
        task.add_done_callback(self._control_tasks.discard)

    # -- inbound -------------------------------------------------------
    async def _on_gossip(self, peer: Peer, payload: bytes) -> None:
        self.last_message_monotonic = time.monotonic()
        if self.scoring.score(peer.node_id) \
                < self.scoring.params.graylist_threshold:
            return                      # graylisted: drop everything
        if not payload:
            self._misbehave(peer)
            return
        kind = payload[0]
        if kind == ENV_DATA:
            await self._on_data(peer, payload[1:])
        elif kind == ENV_CONTROL:
            await self._on_control(peer, payload[1:])
        else:
            self._misbehave(peer)

    async def _on_data(self, peer: Peer, payload: bytes) -> None:
        try:
            tlen = payload[0]
            topic = payload[1:1 + tlen].decode()
            data = snappyc.uncompress(payload[1 + tlen:])
        except Exception:
            self._misbehave(peer)
            return
        mid = spec_msg_id(topic, data)
        self._iwant_pending.pop(mid, None)
        if not self._seen.add(mid):
            # duplicate: credits a mesh member's delivery duty ONLY
            # inside the post-validation delivery window
            exp = self._delivery_window.get(mid)
            if exp is not None and exp > self._heartbeats:
                self.scoring.on_duplicate_delivery(peer.node_id, topic)
            return
        handler = self._handlers.get(topic)
        if handler is None:
            return
        result = await handler.handle_message(data)
        if result is ValidationResult.ACCEPT:
            self.scoring.on_first_delivery(peer.node_id, topic)
            self._delivery_window[mid] = \
                self._heartbeats + DELIVERY_WINDOW_HEARTBEATS
            # eager-push into the mesh only after validation (gossipsub
            # propagation gating); everyone else learns the id via the
            # next heartbeat's IHAVE
            self.messages_forwarded += 1
            self._mcache.put(mid, topic, data)
            await self._send_data(self._encode_data(topic, data),
                                  self._eager_targets(topic),
                                  exclude=peer)
        elif result is ValidationResult.REJECT:
            self.scoring.on_invalid(peer.node_id, topic)
            self._maybe_graylist(peer)
        # IGNORE: no score change (gossipsub v1.1 — only REJECT counts
        # as an invalid delivery)

    async def _on_control(self, peer: Peer, payload: bytes) -> None:
        try:
            subs, graft, prune, ihave, iwant = decode_control(payload)
        except ValueError:
            self._misbehave(peer)
            return
        topics = self._peer_topics.setdefault(peer.node_id, set())
        for on, topic in subs:
            (topics.add if on else topics.discard)(topic)
            if not on:
                self._mesh_drop(topic, peer)
        prune_back = []
        for topic in graft:
            if self._in_backoff(topic, peer.node_id):
                # grafting during backoff is a protocol violation
                # (gossipsub v1.1) — costs behaviour score
                self.scoring.add_behaviour_penalty(peer.node_id, 0.5)
                prune_back.append(topic)
            elif (topic in self._handlers
                    and self.scoring.score(peer.node_id)
                    >= GRAFT_SCORE_FLOOR):
                self._mesh_add(topic, peer)
            else:
                prune_back.append(topic)
        for topic in prune:
            # peer-initiated PRUNE carries the backoff both ways
            self._mesh_drop(topic, peer, backoff=True)
        if prune_back:
            self._send_control(peer, encode_control(prune=prune_back))
        # IHAVE → IWANT for ids we miss — one outstanding request per
        # id (re-askable after the pending window expires), not one per
        # advertiser
        want = []
        for topic, mids in ihave:
            if topic not in self._handlers:
                continue
            for mid in mids:
                if mid in self._seen or len(want) >= \
                        MAX_IWANT_PER_CONTROL:
                    continue
                expiry = self._iwant_pending.get(mid)
                if expiry is not None and expiry > self._heartbeats:
                    continue        # already asked someone recently
                self._iwant_pending[mid] = self._heartbeats + 2
                want.append(mid)
        if want:
            self._send_control(peer, encode_control(iwant=want))
        # IWANT → serve full messages from the cache, once per peer per
        # id: repeat IWANTs are a bandwidth-amplification lever (spend
        # 20 bytes, receive a full block), so re-asks of DELIVERED ids
        # cost score instead.  Ids we no longer have (mcache evicted)
        # are not marked served — a retry for those is protocol-honest.
        served = 0
        already = self._iwant_served.setdefault(peer.node_id,
                                                LimitedSet(4096))
        for mid in iwant[:MAX_IWANT_PER_CONTROL]:
            if mid in already:
                # bandwidth-amplification probe: costs behaviour score
                self._misbehave(peer, n=0.2)
                continue
            entry = self._mcache.get(mid)
            if entry is not None:
                topic, data = entry
                await self._send_data(self._encode_data(topic, data),
                                      [peer], exclude=None)
                already.add(mid)
                served += 1
        self.iwant_served += served

    # -- heartbeat ------------------------------------------------------
    async def _heartbeat_loop(self) -> None:
        while True:
            await asyncio.sleep(HEARTBEAT_S)
            try:
                self.heartbeat()
            except Exception:
                _LOG.exception("gossip heartbeat failed")

    def heartbeat(self) -> None:
        """One mesh-maintenance pass (callable directly from tests —
        deterministic, no awaits: control sends are fire-and-forget)."""
        # one score snapshot per pass: scores change only via events,
        # and recomputing per (topic, peer) filter is O(topics^2*peers)
        scores = {p.node_id: self.scoring.score(p.node_id)
                  for p in self.net.peers}
        for topic in self._handlers:
            mesh = self._mesh.setdefault(topic, set())
            for p in [p for p in mesh if not p.connected]:
                self._mesh_drop(topic, p)
            # evict mesh members whose score went negative (gossipsub
            # v1.1 score-based pruning) — WITH backoff, else the
            # refill below re-grafts the same peer this same pass
            for p in [p for p in mesh
                      if scores.get(p.node_id, 0) < GRAFT_SCORE_FLOOR]:
                self._mesh_drop(topic, p, backoff=True)
                self._send_control(p, encode_control(prune=[topic]))
            if len(mesh) < D_LOW:
                candidates = [
                    p for p in self._topic_peers(topic)
                    if p not in mesh
                    and scores.get(p.node_id, 0) >= GRAFT_SCORE_FLOOR
                    and not self._in_backoff(topic, p.node_id)]
                self._rng.shuffle(candidates)
                for p in candidates[:D - len(mesh)]:
                    self._mesh_add(topic, p)
                    self._send_control(p, encode_control(graft=[topic]))
            elif len(mesh) > D_HIGH:
                excess = self._rng.sample(sorted(mesh, key=id),
                                          len(mesh) - D)
                for p in excess:
                    self._mesh_drop(topic, p, backoff=True)
                    self._send_control(p, encode_control(prune=[topic]))
            # gossip: IHAVE recent ids to D_lazy non-mesh topic peers
            # above the gossip threshold (below it they get nothing)
            mids = self._mcache.gossip_ids(topic)[
                :MAX_IHAVE_PER_HEARTBEAT]
            if mids:
                lazy = [p for p in self._topic_peers(topic)
                        if p not in mesh
                        and scores.get(p.node_id, 0)
                        >= self.scoring.params.gossip_threshold]
                self._rng.shuffle(lazy)
                for p in lazy[:D_LAZY]:
                    self._send_control(
                        p, encode_control(ihave=[(topic, mids)]))
        self._mcache.shift()
        self._heartbeats += 1
        if self._iwant_pending:
            self._iwant_pending = {
                mid: exp for mid, exp in self._iwant_pending.items()
                if exp > self._heartbeats}
        if self._delivery_window:
            self._delivery_window = {
                mid: exp for mid, exp in self._delivery_window.items()
                if exp > self._heartbeats}
        if self._prune_backoff:
            self._prune_backoff = {
                k: exp for k, exp in self._prune_backoff.items()
                if exp > self._heartbeats}
        # decaying counters (P2/P3/P4/P7) tick on the scoring module's
        # own interval, not per-heartbeat
        self.scoring.maybe_decay()

    # -- scoring --------------------------------------------------------
    def _misbehave(self, peer: Peer, n: float = 1.0) -> None:
        """Protocol violation (malformed frame, amplification probe):
        behaviour penalty (P7), squared above its tolerance."""
        self.scoring.add_behaviour_penalty(peer.node_id, n)
        self._maybe_graylist(peer)

    def _maybe_graylist(self, peer: Peer) -> None:
        if self.scoring.score(peer.node_id) \
                <= self.scoring.params.graylist_threshold:
            _LOG.warning("disconnecting graylisted peer")
            # record the for-cause disconnect in the transport-level
            # reputation book so the dialer won't immediately redial
            rep = getattr(self.net, "reputation", None)
            if rep is not None:
                rep.report_initiated_disconnect(peer.node_id,
                                                GOODBYE_FAULT)
            peer.close()
