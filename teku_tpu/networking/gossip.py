"""Gossip router over the TCP transport: topics, dedupe, forwarding.

The gossipsub role (reference: networking/p2p libp2p gossip +
networking/eth2/.../gossip/encoding/SszSnappyEncoding.java): messages
are ssz_snappy-encoded, identified by sha256(topic || data), seen-cache
suppressed, delivered to the local TopicHandler, and FORWARDED only on
ACCEPT (gossipsub validation gating).  Mesh = all connected peers
(flood-publish within the peer set; peer scoring trims misbehavers).
"""

import hashlib
import logging
import struct
from typing import Dict, Optional

from ..infra.collections import LimitedSet
from ..native import snappyc
from ..node.gossip import GossipNetwork, TopicHandler, ValidationResult
from .transport import KIND_GOSSIP, P2PNetwork, Peer

_LOG = logging.getLogger(__name__)

REJECT_SCORE = -10
IGNORE_SCORE = -1


class TcpGossipNetwork(GossipNetwork):
    """GossipNetwork implementation the BeaconNode subscribes through —
    same interface as the in-memory devnet bus, real wire underneath."""

    def __init__(self, net: P2PNetwork):
        self.net = net
        self.net.on_gossip = self._on_gossip
        self._handlers: Dict[str, TopicHandler] = {}
        self._seen: LimitedSet = LimitedSet(65536)
        self._scores: Dict[bytes, int] = {}
        self.messages_forwarded = 0

    # -- GossipNetwork interface --------------------------------------
    def subscribe(self, topic: str, handler: TopicHandler) -> None:
        self._handlers[topic] = handler

    async def publish(self, topic: str, data: bytes) -> None:
        frame = self._encode(topic, data)
        self._seen.add(self._msg_id(topic, data))
        await self._fanout(frame, exclude=None)

    async def _fanout(self, frame: bytes, exclude) -> None:
        """Concurrent sends: one slow peer's TCP backpressure must not
        head-of-line-block propagation to the others."""
        import asyncio
        sends = [peer.send_frame(KIND_GOSSIP, frame)
                 for peer in list(self.net.peers) if peer is not exclude]
        if sends:
            await asyncio.gather(*sends, return_exceptions=True)

    # -- wire ----------------------------------------------------------
    @staticmethod
    def _encode(topic: str, data: bytes) -> bytes:
        tb = topic.encode()
        return (struct.pack("<B", len(tb)) + tb
                + snappyc.compress(data))

    @staticmethod
    def _msg_id(topic: str, data: bytes) -> bytes:
        tb = topic.encode()
        # length-prefix the topic so (topic, data) boundaries can't be
        # shifted to forge a colliding id that poisons seen-caches
        return hashlib.sha256(
            len(tb).to_bytes(4, "little") + tb + data).digest()[:20]

    async def _on_gossip(self, peer: Peer, payload: bytes) -> None:
        try:
            tlen = payload[0]
            topic = payload[1:1 + tlen].decode()
            data = snappyc.uncompress(payload[1 + tlen:])
        except Exception:
            self._punish(peer, REJECT_SCORE)
            return
        mid = self._msg_id(topic, data)
        if not self._seen.add(mid):
            return                      # duplicate
        handler = self._handlers.get(topic)
        if handler is None:
            return
        result = await handler.handle_message(data)
        if result is ValidationResult.ACCEPT:
            # forward to everyone but the sender (gossipsub propagation
            # only after validation)
            self.messages_forwarded += 1
            await self._fanout(self._encode(topic, data), exclude=peer)
        elif result is ValidationResult.REJECT:
            self._punish(peer, REJECT_SCORE)
        elif result is ValidationResult.IGNORE:
            self._punish(peer, IGNORE_SCORE)

    def _punish(self, peer: Peer, delta: int) -> None:
        score = self._scores.get(peer.node_id, 0) + delta
        self._scores[peer.node_id] = score
        if score <= -100:
            _LOG.warning("disconnecting misbehaving peer")
            peer.close()
