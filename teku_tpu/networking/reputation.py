"""Peer reputation book: graded adjustments, disconnect thresholds,
and time-bounded bans.

The transport-level companion to gossip scoring (reference:
networking/p2p/src/main/java/tech/pegasys/teku/networking/p2p/
reputation/DefaultReputationManager.java and ReputationAdjustment.java
— score clamped to a max, LARGE/SMALL penalty and reward steps,
disconnect once the score crosses the floor, and ban-worthy goodbye
reason codes that suppress reconnects for a cooldown period).

Separation of duties: gossip scoring measures MESSAGE quality per
topic; this book measures CONNECTION behavior (handshake failures,
rate-limit violations, useless sync responses, rude goodbyes) and is
the thing consulted before dialing or admitting a peer.
"""

import time
from typing import Callable, Dict, Optional, Tuple

from ..infra.collections import LimitedMap

__all__ = ["Adjustment", "ReputationManager", "GOODBYE_BAN_WORTHY"]


class Adjustment:
    """Graded steps (reference ReputationAdjustment.java)."""
    LARGE_PENALTY = -10.0
    SMALL_PENALTY = -3.0
    SMALL_REWARD = 2.0
    LARGE_REWARD = 10.0


MAX_SCORE = 150.0
DISCONNECT_SCORE = -150.0

# goodbye reason codes whose SENDER is telling us we misbehaved in a
# way that makes an immediate redial pointless or rude (spec codes:
# 1=client shutdown, 2=irrelevant network, 3=fault/error, plus the
# 128+ banned/score range real clients use).  Transient conditions —
# client shutdown (1), too-many-peers (129) — are deliberately NOT
# here: banning over a full peer table turns one busy node into
# 10-minute mutual lockouts across a small devnet.
GOODBYE_BAN_WORTHY = frozenset({2, 3, 128, 250})

BAN_PERIOD_S = 600.0          # reference uses a cooldown of minutes
_BOOK_CAPACITY = 2048


class ReputationManager:
    """LRU-bounded score/ban book keyed by node id.  All reads are
    O(1); nothing here is async — callers close peers themselves on a
    True return from adjust()."""

    def __init__(self, time_fn: Callable[[], float] = time.monotonic,
                 capacity: int = _BOOK_CAPACITY,
                 ban_period_s: float = BAN_PERIOD_S):
        self._now = time_fn
        self._ban_period = ban_period_s
        self._scores: LimitedMap = LimitedMap(capacity)
        self._banned_until: LimitedMap = LimitedMap(capacity)

    # -- queries --------------------------------------------------------
    def score(self, node_id: bytes) -> float:
        return self._scores.get(node_id) or 0.0

    def is_connect_allowed(self, node_id: bytes) -> bool:
        """Consulted before dialing AND before admitting an inbound
        peer: banned ids wait out the cooldown."""
        until = self._banned_until.get(node_id)
        if until is None:
            return True
        if self._now() >= until:
            # ban expired: forgive the score too (the reference resets
            # on cooldown expiry so one old sin can't re-ban instantly)
            self._banned_until.pop(node_id, None)
            self._scores.pop(node_id, None)
            return True
        return False

    # -- mutations ------------------------------------------------------
    def adjust(self, node_id: bytes, delta: float) -> bool:
        """Apply a graded adjustment; True = the caller should
        disconnect (score crossed the floor, peer is now banned)."""
        s = min(self.score(node_id) + delta, MAX_SCORE)
        self._scores.put(node_id, s)
        if s <= DISCONNECT_SCORE:
            self._ban(node_id)
            return True
        return False

    def report_initiated_disconnect(self, node_id: bytes,
                                    reason: Optional[int]) -> None:
        """WE disconnected them for cause: ban-worthy reasons suppress
        redials for the cooldown."""
        if reason is not None and reason in GOODBYE_BAN_WORTHY:
            self._ban(node_id)

    def report_received_goodbye(self, node_id: bytes,
                                reason: Optional[int]) -> None:
        """THEY disconnected us citing a fault: don't redial into the
        same rejection for the cooldown."""
        if reason is not None and reason in GOODBYE_BAN_WORTHY:
            self._ban(node_id)

    def _ban(self, node_id: bytes) -> None:
        self._banned_until.put(node_id, self._now() + self._ban_period)
