"""Noise XX handshake + transport encryption for the TCP stack.

The role of the reference's libp2p noise security upgrade (reference:
networking/p2p/.../libp2p/LibP2PNetworkBuilder.java:219 — there
jvm-libp2p's Noise_XX_25519_ChaChaPoly_SHA256; here the same protocol
implemented directly per the Noise Protocol Framework spec rev 34):

    -> e
    <- e, ee, s, es
    -> s, se

Both sides authenticate with a static X25519 key transmitted
encrypted inside the handshake; the static public key IS the peer's
wire identity (libp2p derives peer ids from it the same way).  After
the handshake, split() yields one CipherState per direction and every
byte on the socket is ChaCha20-Poly1305 AEAD inside u16-length-
prefixed noise messages (<= 65535 bytes each, the noise cap).

AEAD/X25519/HMAC primitives come from the `cryptography` library; the
handshake state machine below is the Noise spec's, written against
its section 5 pseudocode.
"""

import hashlib
import hmac as _hmac
import struct
from typing import Optional, Tuple

from cryptography.hazmat.primitives.asymmetric.x25519 import (
    X25519PrivateKey, X25519PublicKey)
from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

PROTOCOL_NAME = b"Noise_XX_25519_ChaChaPoly_SHA256"
MAX_NOISE_MESSAGE = 65535
MAX_NOISE_PLAINTEXT = MAX_NOISE_MESSAGE - 16      # AEAD tag


class NoiseError(Exception):
    pass


def _hash(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def _hmac_sha256(key: bytes, data: bytes) -> bytes:
    return _hmac.new(key, data, hashlib.sha256).digest()


def _hkdf(chaining_key: bytes, ikm: bytes, n: int) -> Tuple[bytes, ...]:
    """Noise HKDF (spec 4.3): temp = HMAC(ck, ikm); out1 = HMAC(temp,
    0x01); out2 = HMAC(temp, out1 || 0x02); ..."""
    temp = _hmac_sha256(chaining_key, ikm)
    outputs = []
    prev = b""
    for i in range(1, n + 1):
        prev = _hmac_sha256(temp, prev + bytes([i]))
        outputs.append(prev)
    return tuple(outputs)


def generate_static_keypair() -> Tuple[X25519PrivateKey, bytes]:
    sk = X25519PrivateKey.generate()
    return sk, sk.public_key().public_bytes_raw()


class CipherState:
    """Noise spec 5.1: a ChaCha20-Poly1305 key and a nonce counter
    (96-bit nonce = 4 zero bytes || u64 little-endian n)."""

    def __init__(self, key: Optional[bytes] = None):
        self.k = key
        self.n = 0
        # key import happens once; encrypt/decrypt run per frame chunk
        self._cipher = None if key is None else ChaCha20Poly1305(key)

    def has_key(self) -> bool:
        return self.k is not None

    def _nonce(self) -> bytes:
        return bytes(4) + struct.pack("<Q", self.n)

    def encrypt_with_ad(self, ad: bytes, plaintext: bytes) -> bytes:
        if self._cipher is None:
            return plaintext
        if self.n >= 2 ** 64 - 1:
            raise NoiseError("nonce exhausted")
        ct = self._cipher.encrypt(self._nonce(), plaintext, ad)
        self.n += 1
        return ct

    def decrypt_with_ad(self, ad: bytes, ciphertext: bytes) -> bytes:
        if self._cipher is None:
            return ciphertext
        if self.n >= 2 ** 64 - 1:
            raise NoiseError("nonce exhausted")
        try:
            pt = self._cipher.decrypt(self._nonce(), ciphertext, ad)
        except Exception:
            raise NoiseError("AEAD decryption failed")
        self.n += 1
        return pt


class SymmetricState:
    """Noise spec 5.2: chaining key + handshake hash."""

    def __init__(self):
        if len(PROTOCOL_NAME) <= 32:
            self.h = PROTOCOL_NAME.ljust(32, b"\x00")
        else:
            self.h = _hash(PROTOCOL_NAME)
        self.ck = self.h
        self.cipher = CipherState()

    def mix_key(self, ikm: bytes) -> None:
        self.ck, temp_k = _hkdf(self.ck, ikm, 2)
        self.cipher = CipherState(temp_k)

    def mix_hash(self, data: bytes) -> None:
        self.h = _hash(self.h + data)

    def encrypt_and_hash(self, plaintext: bytes) -> bytes:
        ct = self.cipher.encrypt_with_ad(self.h, plaintext)
        self.mix_hash(ct)
        return ct

    def decrypt_and_hash(self, ciphertext: bytes) -> bytes:
        pt = self.cipher.decrypt_with_ad(self.h, ciphertext)
        self.mix_hash(ciphertext)
        return pt

    def split(self) -> Tuple[CipherState, CipherState]:
        k1, k2 = _hkdf(self.ck, b"", 2)
        return CipherState(k1), CipherState(k2)


class XXHandshake:
    """The three XX messages.  Drive with write_message_*/
    read_message_* in pattern order; `remote_static` is available
    after message 2 (initiator) / message 3 (responder)."""

    def __init__(self, initiator: bool,
                 static_key: X25519PrivateKey,
                 prologue: bytes = b""):
        self.initiator = initiator
        self.s = static_key
        self.s_pub = static_key.public_key().public_bytes_raw()
        self.e: Optional[X25519PrivateKey] = None
        self.re: Optional[bytes] = None
        self.rs: Optional[bytes] = None
        self.ss = SymmetricState()
        self.ss.mix_hash(prologue)

    # -- DH helpers ----------------------------------------------------
    def _dh(self, sk: X25519PrivateKey, pub: bytes) -> bytes:
        return sk.exchange(X25519PublicKey.from_public_bytes(pub))

    # -- message 1: -> e -----------------------------------------------
    def write_message_1(self) -> bytes:
        assert self.initiator
        self.e = X25519PrivateKey.generate()
        e_pub = self.e.public_key().public_bytes_raw()
        self.ss.mix_hash(e_pub)
        return e_pub + self.ss.encrypt_and_hash(b"")

    def read_message_1(self, msg: bytes) -> None:
        assert not self.initiator
        if len(msg) != 32:
            raise NoiseError("message 1 must be a bare ephemeral key")
        self.re = msg[:32]
        self.ss.mix_hash(self.re)
        self.ss.decrypt_and_hash(msg[32:])

    # -- message 2: <- e, ee, s, es --------------------------------------
    def write_message_2(self) -> bytes:
        assert not self.initiator
        self.e = X25519PrivateKey.generate()
        e_pub = self.e.public_key().public_bytes_raw()
        self.ss.mix_hash(e_pub)
        self.ss.mix_key(self._dh(self.e, self.re))          # ee
        s_ct = self.ss.encrypt_and_hash(self.s_pub)         # s
        self.ss.mix_key(self._dh(self.s, self.re))          # es
        payload_ct = self.ss.encrypt_and_hash(b"")
        return e_pub + s_ct + payload_ct

    def read_message_2(self, msg: bytes) -> None:
        assert self.initiator
        if len(msg) != 32 + 48 + 16:
            raise NoiseError("bad message 2 length")
        self.re = msg[:32]
        self.ss.mix_hash(self.re)
        self.ss.mix_key(self._dh(self.e, self.re))          # ee
        self.rs = self.ss.decrypt_and_hash(msg[32:80])      # s
        self.ss.mix_key(self._dh(self.e, self.rs))          # es
        self.ss.decrypt_and_hash(msg[80:])

    # -- message 3: -> s, se ---------------------------------------------
    def write_message_3(self) -> Tuple[bytes, CipherState, CipherState]:
        assert self.initiator
        s_ct = self.ss.encrypt_and_hash(self.s_pub)         # s
        self.ss.mix_key(self._dh(self.s, self.re))          # se
        payload_ct = self.ss.encrypt_and_hash(b"")
        tx, rx = self.ss.split()
        return s_ct + payload_ct, tx, rx

    def read_message_3(self, msg: bytes
                       ) -> Tuple[CipherState, CipherState]:
        assert not self.initiator
        if len(msg) != 48 + 16:
            raise NoiseError("bad message 3 length")
        self.rs = self.ss.decrypt_and_hash(msg[:48])        # s
        self.ss.mix_key(self._dh(self.e, self.rs))          # se
        self.ss.decrypt_and_hash(msg[48:])
        rx, tx = self.ss.split()
        return tx, rx


# -- asyncio stream integration ---------------------------------------------

async def _read_noise_message(reader) -> bytes:
    head = await reader.readexactly(2)
    (n,) = struct.unpack(">H", head)
    return await reader.readexactly(n)


def _write_noise_message(writer, msg: bytes) -> None:
    if len(msg) > MAX_NOISE_MESSAGE:
        raise NoiseError("noise message too large")
    writer.write(struct.pack(">H", len(msg)) + msg)


async def initiator_handshake(reader, writer,
                              static_key: X25519PrivateKey,
                              prologue: bytes = b""):
    """→ (tx, rx, remote_static_pub)."""
    hs = XXHandshake(True, static_key, prologue)
    _write_noise_message(writer, hs.write_message_1())
    await writer.drain()
    hs.read_message_2(await _read_noise_message(reader))
    msg3, tx, rx = hs.write_message_3()
    _write_noise_message(writer, msg3)
    await writer.drain()
    return tx, rx, hs.rs


async def responder_handshake(reader, writer,
                              static_key: X25519PrivateKey,
                              prologue: bytes = b""):
    """→ (tx, rx, remote_static_pub)."""
    hs = XXHandshake(False, static_key, prologue)
    hs.read_message_1(await _read_noise_message(reader))
    _write_noise_message(writer, hs.write_message_2())
    await writer.drain()
    tx, rx = hs.read_message_3(await _read_noise_message(reader))
    return tx, rx, hs.rs


class NoiseWriter:
    """Write side of the encrypted transport: plaintext is chunked to
    the noise cap and AEAD-sealed per chunk."""

    def __init__(self, writer, tx: CipherState):
        self._writer = writer
        self._tx = tx

    def write(self, data: bytes) -> None:
        for off in range(0, len(data), MAX_NOISE_PLAINTEXT):
            chunk = data[off:off + MAX_NOISE_PLAINTEXT]
            _write_noise_message(self._writer,
                                 self._tx.encrypt_with_ad(b"", chunk))

    async def drain(self) -> None:
        await self._writer.drain()

    def close(self) -> None:
        self._writer.close()

    def get_extra_info(self, *a, **kw):
        return self._writer.get_extra_info(*a, **kw)


class NoiseReader:
    """Read side: decrypts noise messages and re-buffers plaintext so
    readexactly() keeps its semantics."""

    def __init__(self, reader, rx: CipherState):
        self._reader = reader
        self._rx = rx
        self._buf = bytearray()

    async def readexactly(self, n: int) -> bytes:
        while len(self._buf) < n:
            ct = await _read_noise_message(self._reader)
            self._buf += self._rx.decrypt_with_ad(b"", ct)
        out = bytes(self._buf[:n])
        del self._buf[:n]
        return out
