"""Attestation subnet management: duty-driven + persistent subscriptions.

Equivalent of the reference's subnet machinery (reference: networking/
eth2/src/main/java/tech/pegasys/teku/networking/eth2/gossip/subnets/
AttestationTopicSubscriber.java + NodeBasedStableSubnetSubscriber): a
validator's committee assignment implies a subnet subscription window;
every node also holds a deterministic persistent subnet for mesh
health.  The manager tracks {subnet: unsubscribe_slot} and tells the
gossip layer which attestation topics to carry.
"""

import hashlib
import logging
from typing import Dict, Set

from ..spec.config import SpecConfig

_LOG = logging.getLogger(__name__)


class AttestationSubnetManager:
    def __init__(self, cfg: SpecConfig, node_id: bytes):
        self.cfg = cfg
        self.node_id = node_id
        self._until: Dict[int, int] = {}

    def persistent_subnets(self) -> Set[int]:
        """Node-stable subnets (reference NodeBasedStableSubnetSubscriber
        derives them from the node id).  Counter-hashed so any
        configured count works (a windowed digest silently zero-fills
        past 8 entries)."""
        return {
            int.from_bytes(
                hashlib.sha256(self.node_id
                               + i.to_bytes(4, "little")).digest()[:4],
                "little") % self.cfg.ATTESTATION_SUBNET_COUNT
            for i in range(self.cfg.RANDOM_SUBNETS_PER_VALIDATOR)}

    def subscribe_for_duty(self, subnet: int, until_slot: int) -> None:
        """reference AttestationTopicSubscriber.subscribeToCommitteeForAggregation"""
        self._until[subnet] = max(self._until.get(subnet, 0), until_slot)

    def on_slot(self, slot: int) -> Set[int]:
        """Active subnets after expiring stale duty subscriptions."""
        for subnet in [s for s, until in self._until.items()
                       if until < slot]:
            del self._until[subnet]
        return self.active_subnets()

    def active_subnets(self) -> Set[int]:
        return set(self._until) | self.persistent_subnets()
