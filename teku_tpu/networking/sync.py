"""Multipeer forward sync: batched parallel downloads, per-peer
backoff, stall detection with chain switching.

The reference's multipeer forward sync (reference: beacon/sync/src/
main/java/tech/pegasys/teku/beacon/sync/forward/multipeer/
BatchSync.java:43 — contiguous batches downloaded from several peers
in parallel, imported strictly in order through the standard block
pipeline; SyncStallDetector.java:34 — no-progress passes demote the
chain being followed so the node re-targets an honest head; peer
failures back the peer off rather than ending the sync).
"""

import asyncio
import logging
from typing import Dict, List, Optional, Tuple

from .reqresp import BeaconRpc, MAX_REQUEST_BLOCKS
from .transport import P2PNetwork, Peer

_LOG = logging.getLogger(__name__)

# passes a peer sits out after a failed/garbage response, doubling per
# repeat offense (reference peer scorer's cooldown role)
BACKOFF_BASE_PASSES = 2
MAX_PARALLEL_BATCHES = 4
STALL_PASSES_GIVE_UP = 3


class SyncService:
    def __init__(self, net: P2PNetwork, rpc: BeaconRpc, node,
                 parallelism: int = MAX_PARALLEL_BATCHES):
        self.net = net
        self.rpc = rpc
        self.node = node
        self.parallelism = parallelism
        self.syncing = False
        self.blocks_imported = 0
        self.batches_requested = 0
        self.stalls_detected = 0
        self.chain_switches = 0
        self._pass_no = 0
        # node_id -> (banned_until_pass, consecutive_failures)
        self._backoff: Dict[bytes, Tuple[int, int]] = {}

    # -- source selection ----------------------------------------------
    def _available(self, peer: Peer) -> bool:
        until, _ = self._backoff.get(peer.node_id, (0, 0))
        return peer.connected and self._pass_no >= until

    def _sync_sources(self) -> List[Peer]:
        """Peers claiming a head above ours, best claim first, backed
        off offenders excluded (reference chain selection: the target
        chain is the best claimed head with willing suppliers)."""
        ours = self.node.chain.head_slot()
        sources = [p for p in self.net.peers
                   if p.status is not None
                   and p.status.head_slot > ours
                   and self._available(p)]
        sources.sort(key=lambda p: p.status.head_slot, reverse=True)
        return sources

    def _best_peer(self):
        sources = self._sync_sources()
        return sources[0] if sources else None

    def _penalize(self, peer: Peer) -> None:
        until, fails = self._backoff.get(peer.node_id, (0, 0))
        fails += 1
        self._backoff[peer.node_id] = (
            self._pass_no + BACKOFF_BASE_PASSES * (2 ** (fails - 1)),
            fails)
        _LOG.info("sync: peer backed off (%d failures)", fails)

    def _reward(self, peer: Peer) -> None:
        self._backoff.pop(peer.node_id, None)

    # -- batched parallel download -------------------------------------
    async def _fetch_batch(self, peer: Peer, start: int, count: int):
        """(peer, start, count, blocks|None) — None = request failed;
        blocks are pre-screened to the requested window and ascending
        (a Byzantine peer cannot use the batch to smuggle other slots)."""
        self.batches_requested += 1
        try:
            blocks = await self.rpc.blocks_by_range(peer, start, count)
        except Exception as exc:
            _LOG.warning("range request failed: %s", exc)
            return peer, start, count, None
        kept = []
        last_slot = -1
        for signed in blocks:
            slot = signed.message.slot
            if not (start <= slot < start + count) or slot <= last_slot:
                return peer, start, count, None   # out-of-window/order
            kept.append(signed)
            last_slot = slot
        return peer, start, count, kept

    async def sync_once(self) -> bool:
        """One pass toward the best claimed head: contiguous batches
        fanned out across available peers in parallel, imported in
        order.  Returns True if any block was imported."""
        self._pass_no += 1
        sources = self._sync_sources()
        if not sources:
            return False
        self.syncing = True
        imported_any = False
        try:
            target = sources[0].status.head_slot
            cursor = self.node.chain.head_slot() + 1
            while cursor <= target:
                sources = [p for p in self._sync_sources()]
                if not sources:
                    break
                # up to `parallelism` contiguous batches in flight,
                # round-robin across the available source peers
                window = []
                s = cursor
                for i in range(self.parallelism):
                    if s > target:
                        break
                    count = min(MAX_REQUEST_BLOCKS, target - s + 1)
                    window.append((sources[i % len(sources)], s, count))
                    s += count
                results = await asyncio.gather(
                    *[self._fetch_batch(p, st, c)
                      for p, st, c in window])
                for peer, st, count, blocks in results:
                    if blocks is None:
                        # failed batch: back the peer off and re-pull
                        # this window from someone else next loop —
                        # later already-fetched batches still import
                        # via the pending-parent pool
                        self._penalize(peer)
                        continue
                    self._reward(peer)
                    await self._fetch_blobs_for(peer, blocks, st, count)
                    for signed in blocks:
                        if self.node.block_manager.import_block(signed):
                            self.blocks_imported += 1
                            imported_any = True
                # the cursor tracks actual chain progress, so garbage
                # batches (imports all fail) re-request the same window
                # from other peers instead of silently skipping it
                new_cursor = self.node.chain.head_slot() + 1
                if new_cursor <= cursor:
                    break    # no movement this window — pass stalls
                cursor = new_cursor
        finally:
            self.syncing = False
        return imported_any

    async def _fetch_blobs_for(self, peer, blocks, start: int,
                               count: int) -> None:
        """Pull the sidecars a batch of blocks needs BEFORE importing,
        so the availability gate passes (reference BatchDataRequester
        requests blocks and blobs together).  Sidecars are pool-added
        with full verification (inclusion proof + KZG)."""
        need = [s for s in blocks
                if getattr(s.message.body, "blob_kzg_commitments", ())]
        if not need:
            return
        cfg = self.node.spec.config
        pool = getattr(self.node, "blob_pool", None)
        if pool is None:
            return
        try:
            sidecars = await self.rpc.blob_sidecars_by_range(
                peer, start, count)
        except Exception as exc:
            _LOG.warning("blob range request failed: %s", exc)
            return
        for sc in sidecars:
            pool.add_spec_sidecar(cfg, sc)

    # -- historical backfill (reference beacon/sync/historical/) -------
    def _oldest_known(self):
        store = self.node.store
        root = min(store.blocks, key=lambda r: store.blocks[r].slot)
        return root, store.blocks[root]

    async def backfill_once(self, peer=None, batch: int = 32,
                            frontier=None) -> int:
        """Extend the chain BACKWARD from the oldest known block: fetch
        the preceding range, authenticate purely by parent-root hash
        linkage up to the trusted anchor, batch-verify proposer
        signatures against the anchor validator set, and retain the
        blocks for serving.  Returns blocks accepted (0 = done/stuck).
        `frontier` (a block) skips the oldest-block rescan when the
        caller already tracks it."""
        peer = peer or self._best_peer() or next(
            iter(self.net.peers), None)
        if peer is None:
            return 0
        store = self.node.store
        oldest = frontier if frontier is not None \
            else self._oldest_known()[1]
        if oldest.slot == 0:
            return 0
        expected_parent = oldest.parent_root
        accepted = []
        bottom = oldest.slot
        # walk the request window downward past empty-slot gaps: an
        # empty chunk means the parent lives further back; a non-empty
        # chunk that doesn't link means forked/corrupt data (the break
        # below covers both that and success)
        while bottom > 0:
            start = max(0, bottom - batch)
            try:
                blocks = await self.rpc.blocks_by_range(
                    peer, start, bottom - start)
            except Exception as exc:
                _LOG.warning("backfill range request failed: %s", exc)
                return 0
            for signed in reversed(blocks):
                block = signed.message
                root = block.htr()
                if root != expected_parent:
                    continue
                accepted.append((root, signed))
                expected_parent = block.parent_root
            if blocks or start == 0:
                break
            bottom = start
        if not accepted:
            return 0
        if not self._verify_backfill_signatures(
                [s for _, s in accepted]):
            _LOG.warning("backfill batch signature check failed")
            return 0
        for root, signed in accepted:
            store.blocks[root] = signed.message
            store.signed_blocks[root] = signed
        # the deepest block accepted = the next round's frontier
        self._last_accepted = accepted[-1][1].message
        self.blocks_imported += len(accepted)
        return len(accepted)

    def _verify_backfill_signatures(self, signed_blocks) -> bool:
        """Proposer signatures in one batch: pubkeys from the anchor
        state (the registry is append-only, so every historical
        proposer is present), domains from the fork schedule."""
        from ..crypto import bls
        from ..spec import helpers as H
        from ..spec.config import DOMAIN_BEACON_PROPOSER
        from ..spec.milestones import build_fork_schedule
        cfg = self.node.spec.config
        state = self.node.chain.head_state()
        schedule = build_fork_schedule(cfg)
        triples = []
        for signed in signed_blocks:
            block = signed.message
            if block.slot == 0:
                # the genesis block is unsigned (zero-sig anchor
                # envelope); hash linkage alone authenticates it
                continue
            if block.proposer_index >= len(state.validators):
                return False
            epoch = block.slot // cfg.SLOTS_PER_EPOCH
            version = schedule.version_for(
                schedule.milestone_at_epoch(epoch))
            domain = H.compute_domain(DOMAIN_BEACON_PROPOSER,
                                      version.fork_version,
                                      state.genesis_validators_root)
            root = H.compute_signing_root(block, domain)
            triples.append((
                [state.validators[block.proposer_index].pubkey],
                root, signed.signature))
        return bls.batch_verify(triples)

    async def backfill_to_genesis(self, max_rounds: int = 100000) -> int:
        total = 0
        frontier = self._oldest_known()[1]
        for _ in range(max_rounds):
            n = await self.backfill_once(frontier=frontier)
            if n == 0:
                break
            total += n
            # the deepest block just accepted is the new frontier —
            # no O(chain) rescan per round
            frontier = self._last_accepted
        return total

    async def run_until_synced(self, max_rounds: int = 50) -> None:
        """Sync passes until a pass makes no progress AND no credible
        better head remains.  A pass that stalls (peers claim more than
        we can import) demotes the best claimant — reference
        SyncStallDetector.java:34 switching target chains — so a peer
        advertising a phantom head cannot pin the node below the
        honest chain."""
        stalled_passes = 0
        for _ in range(max_rounds):
            # refresh statuses so the target tracks the peer's progress
            for peer in list(self.net.peers):
                try:
                    await self.rpc.exchange_status(peer)
                except Exception:
                    continue
            before = self.node.chain.head_slot()
            imported = await self.sync_once()
            if imported and self.node.chain.head_slot() > before:
                stalled_passes = 0
                continue
            best = self._best_peer()
            if best is None:
                return               # nobody claims better — synced
            # someone still claims a higher head but the pass moved
            # nothing: stall — demote the claimant and re-target
            self.stalls_detected += 1
            stalled_passes += 1
            self._penalize(best)
            self.chain_switches += 1
            _LOG.warning("sync stalled below claimed head %d; "
                         "switching source chains", best.status.head_slot)
            if stalled_passes >= STALL_PASSES_GIVE_UP:
                return
