"""Forward sync: catch up to the best peer via blocks-by-range.

The reference's multipeer forward sync, reduced to its spine
(reference: beacon/sync/src/main/java/tech/pegasys/teku/beacon/sync/
forward/multipeer/ — chain selection by peer-claimed head, batched
range requests, import through the standard block pipeline): pick the
peer claiming the highest head above ours, pull batches, import each
through the BlockManager (full verification), repeat until caught up.
"""

import asyncio
import logging
from typing import Optional

from .reqresp import BeaconRpc, MAX_REQUEST_BLOCKS
from .transport import P2PNetwork

_LOG = logging.getLogger(__name__)


class SyncService:
    def __init__(self, net: P2PNetwork, rpc: BeaconRpc, node):
        self.net = net
        self.rpc = rpc
        self.node = node
        self.syncing = False
        self.blocks_imported = 0

    def _best_peer(self):
        best, best_slot = None, self.node.chain.head_slot()
        for peer in self.net.peers:
            if peer.status is not None and peer.status.head_slot > best_slot:
                best, best_slot = peer, peer.status.head_slot
        return best

    async def sync_once(self) -> bool:
        """One pass: returns True if any block was imported (the driver
        loops until a pass imports nothing — caught up)."""
        peer = self._best_peer()
        if peer is None:
            return False
        self.syncing = True
        start = self.node.chain.head_slot() + 1
        target = peer.status.head_slot
        imported_any = False
        try:
            while start <= target:
                count = min(MAX_REQUEST_BLOCKS, target - start + 1)
                try:
                    blocks = await self.rpc.blocks_by_range(
                        peer, start, count)
                except Exception as exc:
                    # one bad/silent peer must not kill the service
                    _LOG.warning("range request failed: %s", exc)
                    break
                if not blocks:
                    break
                await self._fetch_blobs_for(peer, blocks, start, count)
                for signed in blocks:
                    if self.node.block_manager.import_block(signed):
                        self.blocks_imported += 1
                        imported_any = True
                # the cursor must STRICTLY advance regardless of what
                # slots the peer claims, or a Byzantine peer replaying
                # old blocks pins the loop forever
                start = max(start + 1, blocks[-1].message.slot + 1)
        finally:
            self.syncing = False
        return imported_any

    async def _fetch_blobs_for(self, peer, blocks, start: int,
                               count: int) -> None:
        """Pull the sidecars a batch of blocks needs BEFORE importing,
        so the availability gate passes (reference BatchDataRequester
        requests blocks and blobs together).  Sidecars are pool-added
        with full verification (inclusion proof + KZG)."""
        need = [s for s in blocks
                if getattr(s.message.body, "blob_kzg_commitments", ())]
        if not need:
            return
        cfg = self.node.spec.config
        pool = getattr(self.node, "blob_pool", None)
        if pool is None:
            return
        try:
            sidecars = await self.rpc.blob_sidecars_by_range(
                peer, start, count)
        except Exception as exc:
            _LOG.warning("blob range request failed: %s", exc)
            return
        for sc in sidecars:
            pool.add_spec_sidecar(cfg, sc)

    async def run_until_synced(self, max_rounds: int = 50) -> None:
        for _ in range(max_rounds):
            # refresh statuses so the target tracks the peer's progress
            for peer in list(self.net.peers):
                try:
                    await self.rpc.exchange_status(peer)
                except Exception:
                    continue
            if not await self.sync_once():
                return
