"""Forward sync: catch up to the best peer via blocks-by-range.

The reference's multipeer forward sync, reduced to its spine
(reference: beacon/sync/src/main/java/tech/pegasys/teku/beacon/sync/
forward/multipeer/ — chain selection by peer-claimed head, batched
range requests, import through the standard block pipeline): pick the
peer claiming the highest head above ours, pull batches, import each
through the BlockManager (full verification), repeat until caught up.
"""

import asyncio
import logging
from typing import Optional

from .reqresp import BeaconRpc, MAX_REQUEST_BLOCKS
from .transport import P2PNetwork

_LOG = logging.getLogger(__name__)


class SyncService:
    def __init__(self, net: P2PNetwork, rpc: BeaconRpc, node):
        self.net = net
        self.rpc = rpc
        self.node = node
        self.syncing = False
        self.blocks_imported = 0

    def _best_peer(self):
        best, best_slot = None, self.node.chain.head_slot()
        for peer in self.net.peers:
            if peer.status is not None and peer.status.head_slot > best_slot:
                best, best_slot = peer, peer.status.head_slot
        return best

    async def sync_once(self) -> bool:
        """One pass: returns True if any block was imported (the driver
        loops until a pass imports nothing — caught up)."""
        peer = self._best_peer()
        if peer is None:
            return False
        self.syncing = True
        start = self.node.chain.head_slot() + 1
        target = peer.status.head_slot
        imported_any = False
        try:
            while start <= target:
                count = min(MAX_REQUEST_BLOCKS, target - start + 1)
                try:
                    blocks = await self.rpc.blocks_by_range(
                        peer, start, count)
                except Exception as exc:
                    # one bad/silent peer must not kill the service
                    _LOG.warning("range request failed: %s", exc)
                    break
                if not blocks:
                    break
                await self._fetch_blobs_for(peer, blocks, start, count)
                for signed in blocks:
                    if self.node.block_manager.import_block(signed):
                        self.blocks_imported += 1
                        imported_any = True
                # the cursor must STRICTLY advance regardless of what
                # slots the peer claims, or a Byzantine peer replaying
                # old blocks pins the loop forever
                start = max(start + 1, blocks[-1].message.slot + 1)
        finally:
            self.syncing = False
        return imported_any

    async def _fetch_blobs_for(self, peer, blocks, start: int,
                               count: int) -> None:
        """Pull the sidecars a batch of blocks needs BEFORE importing,
        so the availability gate passes (reference BatchDataRequester
        requests blocks and blobs together).  Sidecars are pool-added
        with full verification (inclusion proof + KZG)."""
        need = [s for s in blocks
                if getattr(s.message.body, "blob_kzg_commitments", ())]
        if not need:
            return
        cfg = self.node.spec.config
        pool = getattr(self.node, "blob_pool", None)
        if pool is None:
            return
        try:
            sidecars = await self.rpc.blob_sidecars_by_range(
                peer, start, count)
        except Exception as exc:
            _LOG.warning("blob range request failed: %s", exc)
            return
        for sc in sidecars:
            pool.add_spec_sidecar(cfg, sc)

    # -- historical backfill (reference beacon/sync/historical/) -------
    def _oldest_known(self):
        store = self.node.store
        root = min(store.blocks, key=lambda r: store.blocks[r].slot)
        return root, store.blocks[root]

    async def backfill_once(self, peer=None, batch: int = 32,
                            frontier=None) -> int:
        """Extend the chain BACKWARD from the oldest known block: fetch
        the preceding range, authenticate purely by parent-root hash
        linkage up to the trusted anchor, batch-verify proposer
        signatures against the anchor validator set, and retain the
        blocks for serving.  Returns blocks accepted (0 = done/stuck).
        `frontier` (a block) skips the oldest-block rescan when the
        caller already tracks it."""
        peer = peer or self._best_peer() or next(
            iter(self.net.peers), None)
        if peer is None:
            return 0
        store = self.node.store
        oldest = frontier if frontier is not None \
            else self._oldest_known()[1]
        if oldest.slot == 0:
            return 0
        expected_parent = oldest.parent_root
        accepted = []
        bottom = oldest.slot
        # walk the request window downward past empty-slot gaps: an
        # empty chunk means the parent lives further back; a non-empty
        # chunk that doesn't link means forked/corrupt data (the break
        # below covers both that and success)
        while bottom > 0:
            start = max(0, bottom - batch)
            try:
                blocks = await self.rpc.blocks_by_range(
                    peer, start, bottom - start)
            except Exception as exc:
                _LOG.warning("backfill range request failed: %s", exc)
                return 0
            for signed in reversed(blocks):
                block = signed.message
                root = block.htr()
                if root != expected_parent:
                    continue
                accepted.append((root, signed))
                expected_parent = block.parent_root
            if blocks or start == 0:
                break
            bottom = start
        if not accepted:
            return 0
        if not self._verify_backfill_signatures(
                [s for _, s in accepted]):
            _LOG.warning("backfill batch signature check failed")
            return 0
        for root, signed in accepted:
            store.blocks[root] = signed.message
            store.signed_blocks[root] = signed
        # the deepest block accepted = the next round's frontier
        self._last_accepted = accepted[-1][1].message
        self.blocks_imported += len(accepted)
        return len(accepted)

    def _verify_backfill_signatures(self, signed_blocks) -> bool:
        """Proposer signatures in one batch: pubkeys from the anchor
        state (the registry is append-only, so every historical
        proposer is present), domains from the fork schedule."""
        from ..crypto import bls
        from ..spec import helpers as H
        from ..spec.config import DOMAIN_BEACON_PROPOSER
        from ..spec.milestones import build_fork_schedule
        cfg = self.node.spec.config
        state = self.node.chain.head_state()
        schedule = build_fork_schedule(cfg)
        triples = []
        for signed in signed_blocks:
            block = signed.message
            if block.slot == 0:
                # the genesis block is unsigned (zero-sig anchor
                # envelope); hash linkage alone authenticates it
                continue
            if block.proposer_index >= len(state.validators):
                return False
            epoch = block.slot // cfg.SLOTS_PER_EPOCH
            version = schedule.version_for(
                schedule.milestone_at_epoch(epoch))
            domain = H.compute_domain(DOMAIN_BEACON_PROPOSER,
                                      version.fork_version,
                                      state.genesis_validators_root)
            root = H.compute_signing_root(block, domain)
            triples.append((
                [state.validators[block.proposer_index].pubkey],
                root, signed.signature))
        return bls.batch_verify(triples)

    async def backfill_to_genesis(self, max_rounds: int = 100000) -> int:
        total = 0
        frontier = self._oldest_known()[1]
        for _ in range(max_rounds):
            n = await self.backfill_once(frontier=frontier)
            if n == 0:
                break
            total += n
            # the deepest block just accepted is the new frontier —
            # no O(chain) rescan per round
            frontier = self._last_accepted
        return total

    async def run_until_synced(self, max_rounds: int = 50) -> None:
        for _ in range(max_rounds):
            # refresh statuses so the target tracks the peer's progress
            for peer in list(self.net.peers):
                try:
                    await self.rpc.exchange_status(peer)
                except Exception:
                    continue
            if not await self.sync_once():
                return
