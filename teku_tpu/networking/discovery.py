"""Peer discovery: peer-exchange over the transport + target-count
maintenance.

The role of the reference's discv5 stack (reference: networking/p2p/
src/main/java/tech/pegasys/teku/networking/p2p/discovery/discv5/
DiscV5Service.java + DiscoveryNetwork composing discovery with the
connection manager): there UDP Kademlia walks global ENRs; here — the
deployment target being single-host/ICI-pod meshes with zero external
egress — peers gossip their peer tables over the existing TCP lanes
("discovery_peers" RPC), and the service dials newly-learned addresses
until the target peer count holds.  The seam (`lookup()` + periodic
maintenance) matches, so a UDP walker can replace the backend without
callers changing.
"""

import asyncio
import logging
import struct
from typing import List, Optional, Set, Tuple

from ..infra.aio import RepeatingTask
from .reqresp import _pack_chunks, _unpack_chunks
from .transport import P2PNetwork, Peer

_LOG = logging.getLogger(__name__)

DISCOVERY_METHOD = "discovery_peers"


class DiscoveryService:
    def __init__(self, net: P2PNetwork, target_peers: int = 8,
                 interval_s: float = 30.0):
        self.net = net
        self.target_peers = target_peers
        self.known: Set[Tuple[str, int]] = set()
        self._task = RepeatingTask(interval_s, self._round, "discovery")
        self._prev_on_request = None

    # -- wiring --------------------------------------------------------
    def install(self) -> None:
        """Chain onto the rpc dispatcher: answer discovery requests,
        delegate everything else to the existing handler."""
        self._prev_on_request = self.net.on_request

        async def handle(peer: Peer, method: str, body: bytes) -> bytes:
            if method == DISCOVERY_METHOD:
                return _pack_chunks([self._encode_peers()])
            if self._prev_on_request is not None:
                return await self._prev_on_request(peer, method, body)
            return _pack_chunks([], ok=False)
        self.net.on_request = handle

    def start(self) -> None:
        self._task.start()

    async def stop(self) -> None:
        await self._task.stop()

    # -- peer table exchange ------------------------------------------
    def _encode_peers(self) -> bytes:
        out = []
        for peer in self.net.peers:
            if peer.connected and peer.listen_port:
                host = peer.writer.get_extra_info("peername")
                if host:
                    addr = f"{host[0]}:{peer.listen_port}"
                    out.append(struct.pack("<B", len(addr))
                               + addr.encode())
        return b"".join(out)

    @staticmethod
    def _decode_peers(blob: bytes) -> List[Tuple[str, int]]:
        out, pos = [], 0
        while pos < len(blob):
            n = blob[pos]
            pos += 1
            addr = blob[pos:pos + n].decode(errors="replace")
            pos += n
            host, _, port = addr.rpartition(":")
            try:
                out.append((host, int(port)))
            except ValueError:
                continue
        return out

    async def lookup(self) -> List[Tuple[str, int]]:
        """One peer-table sweep, all peers queried CONCURRENTLY so dead
        peers cost one timeout, not one each."""
        async def ask(peer):
            try:
                return await peer.request(DISCOVERY_METHOD, b"",
                                          timeout=5.0)
            except Exception:
                return None
        responses = await asyncio.gather(
            *(ask(p) for p in list(self.net.peers)))
        found = []
        for resp in responses:
            if resp is None:
                continue
            chunks = _unpack_chunks(resp)
            if chunks:
                found.extend(self._decode_peers(chunks[0]))
        return found

    def _connected_addrs(self) -> Set[Tuple[str, int]]:
        out = set()
        for peer in self.net.peers:
            info = peer.writer.get_extra_info("peername")
            if info and peer.listen_port:
                out.add((info[0], peer.listen_port))
        return out

    async def _round(self) -> None:
        if len(self.net.peers) >= self.target_peers:
            return
        connected = self._connected_addrs()
        for host, port in await self.lookup():
            if (host, port) in connected:
                continue          # already have this peer
            # loopback self-dial guard; cross-host same-port is legal
            # (multi-host meshes commonly share one listen port) and the
            # handshake's node-id check catches any remaining self-dial
            if port == self.net.port and host in ("127.0.0.1",
                                                  "localhost", "::1"):
                continue
            if len(self.net.peers) >= self.target_peers:
                break
            try:
                peer = await asyncio.wait_for(
                    self.net.connect(host, port), timeout=5.0)
            except (OSError, asyncio.TimeoutError):
                continue          # retried naturally next round
            if peer is not None and peer.connected:
                self.known.add((host, port))
