"""Gossipsub v1.1 peer scoring: per-topic weighted parameters with
decaying counters.

Replaces the r4 scalar score with the spec's score function (reference:
networking/p2p/src/main/java/tech/pegasys/teku/networking/p2p/gossip/
config/GossipScoringConfig.java and networking/eth2/src/main/java/tech/
pegasys/teku/networking/eth2/gossip/config/GossipScoringConfigurator.java
— there the per-topic params are derived from spec constants; here the
same component shapes with values scaled to this router's traffic):

    score(p) = sum_topic tw_t * ( w1*P1 + w2*P2 + w3*P3 + w4*P4 )
               [positive topic sum capped at topic_score_cap]
             + w7 * max(0, behaviour_penalty - threshold)^2

  P1 time in mesh          (capped, rewards stable mesh members)
  P2 first-message deliveries      (decaying counter, capped)
  P3 mesh-message-delivery deficit (squared; active only after the
     mesh membership is older than the activation window)
  P4 invalid message deliveries    (squared penalty)
  P7 behaviour penalty    (protocol violations: malformed frames,
     broken IWANT promises; squared above a tolerance threshold)

An adversary who alternates valid and invalid traffic — the attack the
r4 scalar counter was gameable by — now carries the *squared* P4
penalty per topic while the linear P2 credit is capped, so the score
goes monotonically down under any mix with a nonzero invalid rate.

Counters decay multiplicatively every DECAY_INTERVAL_S (the spec slot
time) and snap to zero below `decay_to_zero`, which also garbage-
collects drained records.  Disconnects RETAIN the counters
(`on_disconnect` only ends mesh tenure — spec retainScore): a peer
cannot wash a negative score by dropping and redialing.
"""

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, Optional

__all__ = [
    "TopicScoreParams", "PeerScoreParams", "GossipScoring",
    "eth2_topic_params",
]


@dataclass(frozen=True)
class TopicScoreParams:
    """Weights for one topic (gossipsub v1.1 §score-function)."""
    topic_weight: float = 0.5
    # P1: time in mesh
    time_in_mesh_weight: float = 0.033
    time_in_mesh_quantum_s: float = 12.0
    time_in_mesh_cap: float = 300.0
    # P2: first message deliveries
    first_message_weight: float = 1.0
    first_message_decay: float = 0.86
    first_message_cap: float = 40.0
    # P3: mesh message delivery deficit (weight must be <= 0)
    mesh_delivery_weight: float = -1.0
    mesh_delivery_decay: float = 0.93
    mesh_delivery_cap: float = 20.0
    mesh_delivery_threshold: float = 4.0
    mesh_delivery_activation_s: float = 60.0
    # P4: invalid message deliveries (weight must be <= 0)
    invalid_message_weight: float = -50.0
    invalid_message_decay: float = 0.93


@dataclass(frozen=True)
class PeerScoreParams:
    """Peer-global weights and thresholds."""
    topic_score_cap: float = 100.0
    behaviour_penalty_weight: float = -10.0
    behaviour_penalty_decay: float = 0.86
    behaviour_penalty_threshold: float = 6.0
    decay_interval_s: float = 12.0
    decay_to_zero: float = 0.01
    # thresholds (gossipsub v1.1 §thresholds)
    gossip_threshold: float = -40.0     # below: no IHAVE/IWANT exchange
    publish_threshold: float = -80.0    # below: not a publish target
    graylist_threshold: float = -160.0  # below: drop everything / close


def eth2_topic_params(topic: str) -> TopicScoreParams:
    """Reference-shaped per-topic families (GossipScoringConfigurator
    derives block/aggregate/subnet params from spec constants; the
    relative weighting here mirrors its structure: blocks score high
    and slow, subnets low and fast)."""
    if "beacon_attestation" in topic:
        # 64 subnets: each carries 1/64 of the weight, fast decay
        return TopicScoreParams(
            topic_weight=0.015, first_message_cap=120.0,
            first_message_decay=0.68, mesh_delivery_threshold=2.0,
            invalid_message_weight=-99.0)
    if "beacon_aggregate_and_proof" in topic:
        return TopicScoreParams(topic_weight=0.5,
                                first_message_decay=0.68)
    if "beacon_block" in topic:
        return TopicScoreParams(topic_weight=0.5,
                                first_message_cap=23.0,
                                mesh_delivery_threshold=1.0)
    if "sync_committee" in topic:
        return TopicScoreParams(topic_weight=0.015,
                                first_message_decay=0.68)
    # voluntary_exit / slashings / bls_to_execution_change: rare
    # messages — no mesh-delivery duty (threshold 0 disables P3)
    return TopicScoreParams(topic_weight=0.05,
                            mesh_delivery_weight=0.0,
                            mesh_delivery_threshold=0.0)


@dataclass
class _TopicCounters:
    mesh_since: Optional[float] = None   # None = not in our mesh
    first_deliveries: float = 0.0
    mesh_deliveries: float = 0.0
    invalid: float = 0.0


@dataclass
class _PeerRecord:
    topics: Dict[str, _TopicCounters] = field(default_factory=dict)
    behaviour_penalty: float = 0.0


class GossipScoring:
    """Per-peer score book.  All methods are O(1) except score()
    (O(active topics for that peer)) and decay() (O(peers x topics),
    run once per decay interval)."""

    def __init__(self,
                 params: Optional[PeerScoreParams] = None,
                 topic_params: Optional[Callable[
                     [str], TopicScoreParams]] = None,
                 time_fn: Callable[[], float] = time.monotonic):
        self.params = params or PeerScoreParams()
        self._topic_params = topic_params or eth2_topic_params
        self._now = time_fn
        self._peers: Dict[bytes, _PeerRecord] = {}
        self._tp_cache: Dict[str, TopicScoreParams] = {}
        self._last_decay = time_fn()

    # -- params ---------------------------------------------------------
    def topic_params(self, topic: str) -> TopicScoreParams:
        tp = self._tp_cache.get(topic)
        if tp is None:
            tp = self._tp_cache[topic] = self._topic_params(topic)
        return tp

    # -- event intake ---------------------------------------------------
    def _counters(self, peer_id: bytes, topic: str) -> _TopicCounters:
        rec = self._peers.setdefault(peer_id, _PeerRecord())
        tc = rec.topics.get(topic)
        if tc is None:
            tc = rec.topics[topic] = _TopicCounters()
        return tc

    def on_graft(self, peer_id: bytes, topic: str) -> None:
        tc = self._counters(peer_id, topic)
        if tc.mesh_since is None:
            tc.mesh_since = self._now()

    def on_prune(self, peer_id: bytes, topic: str) -> None:
        rec = self._peers.get(peer_id)
        tc = rec.topics.get(topic) if rec else None
        if tc is not None:
            tc.mesh_since = None
            tc.mesh_deliveries = 0.0

    def on_first_delivery(self, peer_id: bytes, topic: str) -> None:
        tp = self.topic_params(topic)
        tc = self._counters(peer_id, topic)
        tc.first_deliveries = min(tc.first_deliveries + 1,
                                  tp.first_message_cap)
        if tc.mesh_since is not None:
            tc.mesh_deliveries = min(tc.mesh_deliveries + 1,
                                     tp.mesh_delivery_cap)

    def on_duplicate_delivery(self, peer_id: bytes, topic: str) -> None:
        """A duplicate from a mesh member still counts toward its
        mesh-delivery duty (it IS delivering, just not first)."""
        tc = self._counters(peer_id, topic)
        if tc.mesh_since is not None:
            tp = self.topic_params(topic)
            tc.mesh_deliveries = min(tc.mesh_deliveries + 1,
                                     tp.mesh_delivery_cap)

    def on_invalid(self, peer_id: bytes, topic: str) -> None:
        self._counters(peer_id, topic).invalid += 1

    def add_behaviour_penalty(self, peer_id: bytes,
                              n: float = 1.0) -> None:
        rec = self._peers.setdefault(peer_id, _PeerRecord())
        rec.behaviour_penalty += n

    def on_disconnect(self, peer_id: bytes) -> None:
        """Connection teardown ends mesh tenure but RETAINS the decay
        counters (gossipsub retainScore): a peer cannot wash a negative
        score by reconnecting — the record lives until decay drains it."""
        rec = self._peers.get(peer_id)
        if rec is None:
            return
        for tc in rec.topics.values():
            tc.mesh_since = None

    # -- score ----------------------------------------------------------
    def score(self, peer_id: bytes) -> float:
        rec = self._peers.get(peer_id)
        if rec is None:
            return 0.0
        now = self._now()
        topic_sum = 0.0
        for topic, tc in rec.topics.items():
            tp = self.topic_params(topic)
            s = 0.0
            if tc.mesh_since is not None:
                in_mesh = now - tc.mesh_since
                s += tp.time_in_mesh_weight * min(
                    in_mesh / tp.time_in_mesh_quantum_s,
                    tp.time_in_mesh_cap)
            s += tp.first_message_weight * tc.first_deliveries
            if (tp.mesh_delivery_weight != 0.0
                    and tc.mesh_since is not None
                    and now - tc.mesh_since
                    >= tp.mesh_delivery_activation_s
                    and tc.mesh_deliveries < tp.mesh_delivery_threshold):
                deficit = tp.mesh_delivery_threshold - tc.mesh_deliveries
                s += tp.mesh_delivery_weight * deficit * deficit
            s += tp.invalid_message_weight * tc.invalid * tc.invalid
            topic_sum += tp.topic_weight * s
        total = min(topic_sum, self.params.topic_score_cap)
        excess = rec.behaviour_penalty \
            - self.params.behaviour_penalty_threshold
        if excess > 0:
            total += self.params.behaviour_penalty_weight \
                * excess * excess
        return total

    # -- decay ----------------------------------------------------------
    def maybe_decay(self) -> None:
        """Apply one decay pass if a decay interval has elapsed —
        callers invoke this from their heartbeat, cadence-free."""
        now = self._now()
        if now - self._last_decay < self.params.decay_interval_s:
            return
        self._last_decay = now
        self.decay()

    def decay(self) -> None:
        zero = self.params.decay_to_zero
        dead = []
        for peer_id, rec in self._peers.items():
            rec.behaviour_penalty *= self.params.behaviour_penalty_decay
            if rec.behaviour_penalty < zero:
                rec.behaviour_penalty = 0.0
            empty = rec.behaviour_penalty == 0.0
            for topic, tc in rec.topics.items():
                tp = self.topic_params(topic)
                tc.first_deliveries *= tp.first_message_decay
                if tc.first_deliveries < zero:
                    tc.first_deliveries = 0.0
                tc.mesh_deliveries *= tp.mesh_delivery_decay
                if tc.mesh_deliveries < zero:
                    tc.mesh_deliveries = 0.0
                tc.invalid *= tp.invalid_message_decay
                if tc.invalid < zero:
                    tc.invalid = 0.0
                if (tc.mesh_since is not None or tc.first_deliveries
                        or tc.mesh_deliveries or tc.invalid):
                    empty = False
            if empty:
                dead.append(peer_id)
        for peer_id in dead:
            del self._peers[peer_id]
