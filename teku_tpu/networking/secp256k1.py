"""Minimal secp256k1: ECDSA (RFC 6979 deterministic nonces) + ECDH.

The identity curve of Ethereum's discovery layer (EIP-778 ENRs sign
with it; discv5's handshake needs the COMPRESSED shared ECDH point,
which OpenSSL-backed APIs don't expose).  Pure Python — identity
operations are per-handshake, not per-message, so correctness and
auditability beat speed here (the reference's equivalent dependency
is Bouncy Castle via jvm-libp2p / the discovery library).
"""

import hashlib
import hmac
from typing import Optional, Tuple

P = 2 ** 256 - 2 ** 32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8

Point = Optional[Tuple[int, int]]     # None = infinity


def _inv(a: int, m: int) -> int:
    return pow(a, -1, m)


def point_add(a: Point, b: Point) -> Point:
    if a is None:
        return b
    if b is None:
        return a
    if a[0] == b[0] and (a[1] + b[1]) % P == 0:
        return None
    if a == b:
        lam = (3 * a[0] * a[0]) * _inv(2 * a[1], P) % P
    else:
        lam = (b[1] - a[1]) * _inv(b[0] - a[0], P) % P
    x = (lam * lam - a[0] - b[0]) % P
    return (x, (lam * (a[0] - x) - a[1]) % P)


def point_mul(k: int, pt: Point) -> Point:
    acc: Point = None
    add = pt
    while k:
        if k & 1:
            acc = point_add(acc, add)
        add = point_add(add, add)
        k >>= 1
    return acc


def pubkey(secret: int) -> Tuple[int, int]:
    if not 0 < secret < N:
        raise ValueError("secret key out of range")
    pt = point_mul(secret, (GX, GY))
    assert pt is not None
    return pt


def compress(pt: Tuple[int, int]) -> bytes:
    return bytes([2 + (pt[1] & 1)]) + pt[0].to_bytes(32, "big")


def decompress(data: bytes) -> Tuple[int, int]:
    if len(data) != 33 or data[0] not in (2, 3):
        raise ValueError("bad compressed point")
    x = int.from_bytes(data[1:], "big")
    if x >= P:
        raise ValueError("x out of range")
    y2 = (pow(x, 3, P) + 7) % P
    y = pow(y2, (P + 1) // 4, P)
    if y * y % P != y2:
        raise ValueError("not on curve")
    if (y & 1) != (data[0] & 1):
        y = P - y
    return (x, y)


def uncompressed_xy(pt: Tuple[int, int]) -> bytes:
    """64-byte x||y (the EIP-778 node-id preimage)."""
    return pt[0].to_bytes(32, "big") + pt[1].to_bytes(32, "big")


# -- ECDSA (RFC 6979 nonce, raw r||s signatures, low-s normalized) ----------

def _rfc6979_k(secret: int, digest: bytes) -> int:
    key = secret.to_bytes(32, "big")
    v = b"\x01" * 32
    k = b"\x00" * 32
    k = hmac.new(k, v + b"\x00" + key + digest, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    k = hmac.new(k, v + b"\x01" + key + digest, hashlib.sha256).digest()
    v = hmac.new(k, v, hashlib.sha256).digest()
    while True:
        v = hmac.new(k, v, hashlib.sha256).digest()
        cand = int.from_bytes(v, "big")
        if 0 < cand < N:
            return cand
        k = hmac.new(k, v + b"\x00", hashlib.sha256).digest()
        v = hmac.new(k, v, hashlib.sha256).digest()


def sign(secret: int, digest: bytes) -> bytes:
    """64-byte r||s over a 32-byte message digest."""
    z = int.from_bytes(digest, "big") % N
    while True:
        k = _rfc6979_k(secret, digest)
        pt = point_mul(k, (GX, GY))
        r = pt[0] % N
        if r == 0:
            digest = hashlib.sha256(digest).digest()
            continue
        s = _inv(k, N) * (z + r * secret) % N
        if s == 0:
            digest = hashlib.sha256(digest).digest()
            continue
        if s > N // 2:
            s = N - s
        return r.to_bytes(32, "big") + s.to_bytes(32, "big")


def verify(pub: Tuple[int, int], digest: bytes, signature: bytes) -> bool:
    if len(signature) != 64:
        return False
    r = int.from_bytes(signature[:32], "big")
    s = int.from_bytes(signature[32:], "big")
    if not (0 < r < N and 0 < s < N):
        return False
    z = int.from_bytes(digest, "big") % N
    w = _inv(s, N)
    u1 = z * w % N
    u2 = r * w % N
    pt = point_add(point_mul(u1, (GX, GY)), point_mul(u2, pub))
    if pt is None:
        return False
    return pt[0] % N == r


def ecdh(secret: int, peer_pub: Tuple[int, int]) -> bytes:
    """discv5 key agreement: the COMPRESSED 33-byte shared point."""
    shared = point_mul(secret, peer_pub)
    if shared is None:
        raise ValueError("degenerate ECDH result")
    return compress(shared)
