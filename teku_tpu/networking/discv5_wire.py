"""discv5 v5.1 wire protocol: packet masking, WHOAREYOU handshake,
session keys, and the PING/PONG/FINDNODE/NODES message codec.

The spec wire format of Ethereum's discovery layer (reference:
networking/p2p/.../discovery/discv5/DiscV5Service.java delegates to
the discovery library; this module implements the protocol itself):

  packet        = masking-iv || masked(header) || message
  masked(x)     = AES-128-CTR(key=dest-node-id[:16], iv=masking-iv, x)
  header        = "discv5" || 0x0001 || flag || nonce(12) || authdata-size
  message       = AES-128-GCM(session-key, nonce, type||RLP,
                              ad=masking-iv||header)

Flags: 0 ordinary, 1 WHOAREYOU (authdata = id-nonce || enr-seq),
2 handshake (authdata = src-id || sig-size || eph-key-size ||
id-signature || eph-pubkey || [record]).  Session keys derive from
ECDH over secp256k1 via HKDF-SHA256 with the WHOAREYOU challenge data
as salt; the id-signature proves the static identity over
sha256("discovery v5 identity proof" || challenge-data ||
eph-pubkey || dest-node-id).

Messages: PING(0x01) PONG(0x02) FINDNODE(0x03) NODES(0x04), RLP
bodies per the spec.
"""

import hashlib
import hmac
import os
import secrets
from typing import Dict, List, Optional, Tuple

from . import rlp, secp256k1 as EC
from .enr import Enr

PROTOCOL_ID = b"discv5"
VERSION = b"\x00\x01"
FLAG_MESSAGE = 0
FLAG_WHOAREYOU = 1
FLAG_HANDSHAKE = 2

ID_SIGNATURE_TEXT = b"discovery v5 identity proof"
KDF_INFO = b"discovery v5 key agreement"

MSG_PING = 0x01
MSG_PONG = 0x02
MSG_FINDNODE = 0x03
MSG_NODES = 0x04


class WireError(ValueError):
    pass


def _aes_ctr(key: bytes, iv: bytes, data: bytes) -> bytes:
    from cryptography.hazmat.primitives.ciphers import (Cipher,
                                                        algorithms,
                                                        modes)
    enc = Cipher(algorithms.AES(key), modes.CTR(iv)).encryptor()
    return enc.update(data) + enc.finalize()


def _aes_gcm_encrypt(key: bytes, nonce: bytes, pt: bytes,
                     ad: bytes) -> bytes:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    return AESGCM(key).encrypt(nonce, pt, ad)


def _aes_gcm_decrypt(key: bytes, nonce: bytes, ct: bytes,
                     ad: bytes) -> bytes:
    from cryptography.hazmat.primitives.ciphers.aead import AESGCM
    return AESGCM(key).decrypt(nonce, ct, ad)


def _hkdf_extract_expand(salt: bytes, ikm: bytes, info: bytes,
                         length: int) -> bytes:
    prk = hmac.new(salt, ikm, hashlib.sha256).digest()
    out = b""
    block = b""
    counter = 1
    while len(out) < length:
        block = hmac.new(prk, block + info + bytes([counter]),
                         hashlib.sha256).digest()
        out += block
        counter += 1
    return out[:length]


# --------------------------------------------------------------------------
# Header / packet codec
# --------------------------------------------------------------------------

def _build_header(flag: int, nonce: bytes, authdata: bytes) -> bytes:
    return (PROTOCOL_ID + VERSION + bytes([flag]) + nonce
            + len(authdata).to_bytes(2, "big") + authdata)


def encode_packet(dest_node_id: bytes, flag: int, nonce: bytes,
                  authdata: bytes, message: bytes = b"",
                  masking_iv: Optional[bytes] = None) -> bytes:
    header = _build_header(flag, nonce, authdata)
    iv = masking_iv if masking_iv is not None else os.urandom(16)
    return iv + _aes_ctr(dest_node_id[:16], iv, header) + message


def decode_packet(local_node_id: bytes, datagram: bytes
                  ) -> Tuple[int, bytes, bytes, bytes, bytes]:
    """(flag, nonce, authdata, message_ciphertext, ad) — `ad` is the
    AES-GCM associated data (masking-iv || unmasked header)."""
    if len(datagram) < 16 + 23:
        raise WireError("datagram too short")
    iv = datagram[:16]
    # unmask the static header first to learn the authdata size
    static = _aes_ctr(local_node_id[:16], iv, datagram[16:16 + 23])
    if static[:6] != PROTOCOL_ID or static[6:8] != VERSION:
        raise WireError("bad protocol id")
    flag = static[8]
    nonce = static[9:21]
    authdata_size = int.from_bytes(static[21:23], "big")
    end = 16 + 23 + authdata_size
    if len(datagram) < end:
        raise WireError("truncated authdata")
    # re-run the CTR stream over header+authdata in one pass
    header = _aes_ctr(local_node_id[:16], iv,
                      datagram[16:end])
    authdata = header[23:]
    return flag, nonce, authdata, datagram[end:], iv + header


# --------------------------------------------------------------------------
# WHOAREYOU + handshake
# --------------------------------------------------------------------------

def whoareyou_authdata(id_nonce: bytes, enr_seq: int) -> bytes:
    return id_nonce + enr_seq.to_bytes(8, "big")


def challenge_data(masking_iv: bytes, dest_node_id: bytes,
                   nonce: bytes, authdata: bytes) -> bytes:
    """masking-iv || static-header || authdata of the WHOAREYOU
    packet, exactly as transmitted (pre-masking)."""
    return masking_iv + _build_header(FLAG_WHOAREYOU, nonce, authdata)


def derive_session_keys(ecdh_secret: bytes, node_id_a: bytes,
                        node_id_b: bytes,
                        challenge: bytes) -> Tuple[bytes, bytes]:
    """(initiator_key, recipient_key) per the spec KDF."""
    info = KDF_INFO + node_id_a + node_id_b
    out = _hkdf_extract_expand(challenge, ecdh_secret, info, 32)
    return out[:16], out[16:]


def id_signature(static_secret: int, challenge: bytes,
                 eph_pubkey: bytes, dest_node_id: bytes) -> bytes:
    digest = hashlib.sha256(ID_SIGNATURE_TEXT + challenge + eph_pubkey
                            + dest_node_id).digest()
    return EC.sign(static_secret, digest)


def verify_id_signature(signer_pub, challenge: bytes,
                        eph_pubkey: bytes, dest_node_id: bytes,
                        signature: bytes) -> bool:
    digest = hashlib.sha256(ID_SIGNATURE_TEXT + challenge + eph_pubkey
                            + dest_node_id).digest()
    return EC.verify(signer_pub, digest, signature)


def handshake_authdata(src_node_id: bytes, signature: bytes,
                       eph_pubkey: bytes,
                       record: Optional[bytes] = None) -> bytes:
    return (src_node_id + bytes([len(signature)])
            + bytes([len(eph_pubkey)]) + signature + eph_pubkey
            + (record or b""))


def parse_handshake_authdata(authdata: bytes
                             ) -> Tuple[bytes, bytes, bytes,
                                        Optional[bytes]]:
    if len(authdata) < 34:
        raise WireError("handshake authdata too short")
    src_id = authdata[:32]
    sig_size = authdata[32]
    key_size = authdata[33]
    need = 34 + sig_size + key_size
    if len(authdata) < need:
        raise WireError("truncated handshake authdata")
    sig = authdata[34:34 + sig_size]
    eph = authdata[34 + sig_size:need]
    record = authdata[need:] or None
    return src_id, sig, eph, record


# --------------------------------------------------------------------------
# Messages
# --------------------------------------------------------------------------

def encode_ping(request_id: bytes, enr_seq: int) -> bytes:
    return bytes([MSG_PING]) + rlp.encode(
        [request_id, rlp.encode_uint(enr_seq)])


def encode_pong(request_id: bytes, enr_seq: int, ip: str,
                port: int) -> bytes:
    return bytes([MSG_PONG]) + rlp.encode(
        [request_id, rlp.encode_uint(enr_seq),
         bytes(int(p) for p in ip.split(".")),
         rlp.encode_uint(port)])


def encode_findnode(request_id: bytes, distances: List[int]) -> bytes:
    return bytes([MSG_FINDNODE]) + rlp.encode(
        [request_id, [rlp.encode_uint(d) for d in distances]])


def encode_nodes(request_id: bytes, total: int,
                 records: List[Enr]) -> bytes:
    return bytes([MSG_NODES]) + rlp.encode(
        [request_id, rlp.encode_uint(total),
         [rlp.decode(r.to_rlp()) for r in records]])


def decode_message(data: bytes):
    """(type, decoded fields dict)."""
    if not data:
        raise WireError("empty message")
    mtype = data[0]
    body = rlp.decode(data[1:])
    if not isinstance(body, list) or not body:
        raise WireError("malformed message body")
    if mtype == MSG_PING:
        return mtype, {"request_id": body[0],
                       "enr_seq": int.from_bytes(body[1], "big")}
    if mtype == MSG_PONG:
        return mtype, {"request_id": body[0],
                       "enr_seq": int.from_bytes(body[1], "big"),
                       "ip": ".".join(str(b) for b in body[2]),
                       "port": int.from_bytes(body[3], "big")}
    if mtype == MSG_FINDNODE:
        return mtype, {"request_id": body[0],
                       "distances": [int.from_bytes(d, "big")
                                     for d in body[1]]}
    if mtype == MSG_NODES:
        records = []
        for item in body[2]:
            records.append(Enr.from_rlp(rlp.encode(item)))
        return mtype, {"request_id": body[0],
                       "total": int.from_bytes(body[1], "big"),
                       "records": records}
    raise WireError(f"unknown message type {mtype:#x}")


def log2_distance(a: bytes, b: bytes) -> int:
    x = int.from_bytes(a, "big") ^ int.from_bytes(b, "big")
    return x.bit_length()


# --------------------------------------------------------------------------
# Protocol driver (session state machine)
# --------------------------------------------------------------------------

class Session:
    __slots__ = ("send_key", "recv_key")

    def __init__(self, send_key: bytes, recv_key: bytes):
        self.send_key = send_key
        self.recv_key = recv_key


class Discv5Wire:
    """Per-node protocol state: encode/decode datagrams, run the
    WHOAREYOU handshake, manage sessions.  Transport-agnostic — the
    caller moves datagrams (tests use real UDP sockets)."""

    def __init__(self, secret: int, enr: Enr):
        self.secret = secret
        self.enr = enr
        self.node_id = enr.node_id
        self.sessions: Dict[bytes, Session] = {}
        # nonce -> (dest_node_id, pending message plaintext)
        self._awaiting_whoareyou: Dict[bytes, Tuple[bytes, bytes]] = {}
        # node_id -> challenge data we issued
        self._issued_challenges: Dict[bytes, bytes] = {}

    # -- sending ------------------------------------------------------
    def initial_packet(self, dest: Enr, message: bytes) -> bytes:
        """First contact: an ordinary packet under a RANDOM key (the
        recipient cannot decrypt and answers WHOAREYOU — spec
        first-contact flow)."""
        nonce = os.urandom(12)
        self._awaiting_whoareyou[nonce] = (dest.node_id, message)
        junk = os.urandom(max(len(message) + 16, 32))
        return encode_packet(dest.node_id, FLAG_MESSAGE, nonce,
                             self.node_id, junk)

    def message_packet(self, dest_node_id: bytes,
                       message: bytes) -> bytes:
        session = self.sessions.get(dest_node_id)
        if session is None:
            raise WireError("no session with peer")
        nonce = os.urandom(12)
        iv = os.urandom(16)
        header = _build_header(FLAG_MESSAGE, nonce, self.node_id)
        ct = _aes_gcm_encrypt(session.send_key, nonce, message,
                              iv + header)
        return iv + _aes_ctr(dest_node_id[:16], iv, header) + ct

    def whoareyou_packet(self, request_nonce: bytes, src_node_id: bytes,
                         enr_seq: int = 0) -> bytes:
        """Challenge an undecryptable packet; remembers the challenge
        data for the handshake verification."""
        id_nonce = os.urandom(16)
        authdata = whoareyou_authdata(id_nonce, enr_seq)
        iv = os.urandom(16)
        self._issued_challenges[src_node_id] = challenge_data(
            iv, src_node_id, request_nonce, authdata)
        return encode_packet(src_node_id, FLAG_WHOAREYOU,
                             request_nonce, authdata, b"",
                             masking_iv=iv)

    # -- receiving ----------------------------------------------------
    def handle_datagram(self, datagram: bytes, peer_enr_hint=None):
        """Returns one of:
        ("whoareyou_needed", reply_datagram)    — first contact seen
        ("handshake", reply_datagram)           — we must handshake
        ("message", src_node_id, mtype, fields) — decrypted message
        ("none", None)                          — dropped
        `peer_enr_hint`: known Enr of the peer (needed to answer a
        WHOAREYOU; real deployments look it up from the table)."""
        flag, nonce, authdata, ct, ad = decode_packet(self.node_id,
                                                      datagram)
        if flag == FLAG_WHOAREYOU:
            return self._on_whoareyou(nonce, authdata, ad,
                                      peer_enr_hint)
        if flag == FLAG_HANDSHAKE:
            return self._on_handshake(nonce, authdata, ct, ad)
        if flag == FLAG_MESSAGE:
            src_id = authdata
            if len(src_id) != 32:
                raise WireError("bad ordinary authdata")
            session = self.sessions.get(src_id)
            if session is not None:
                try:
                    pt = _aes_gcm_decrypt(session.recv_key, nonce, ct,
                                          ad)
                    mtype, fields = decode_message(pt)
                    return ("message", src_id, mtype, fields)
                except Exception:
                    pass            # stale keys: fall through, re-key
            return ("whoareyou_needed",
                    self.whoareyou_packet(nonce, src_id))
        raise WireError(f"unknown flag {flag}")

    def _on_whoareyou(self, nonce, authdata, ad, peer_enr):
        pending = self._awaiting_whoareyou.pop(nonce, None)
        if pending is None or peer_enr is None:
            return ("none", None)
        dest_node_id, message = pending
        id_nonce, enr_seq = authdata[:16], authdata[16:24]
        challenge = ad     # masking-iv || header, exactly as received
        eph_secret = int.from_bytes(secrets.token_bytes(32), "big") \
            % EC.N or 1
        eph_pub = EC.compress(EC.pubkey(eph_secret))
        ecdh_secret = EC.ecdh(eph_secret, peer_enr.public_key)
        init_key, recp_key = derive_session_keys(
            ecdh_secret, self.node_id, dest_node_id, challenge)
        self.sessions[dest_node_id] = Session(send_key=init_key,
                                              recv_key=recp_key)
        sig = id_signature(self.secret, challenge, eph_pub,
                           dest_node_id)
        record = self.enr.to_rlp() \
            if int.from_bytes(enr_seq, "big") < self.enr.seq else None
        authdata_out = handshake_authdata(self.node_id, sig, eph_pub,
                                          record)
        out_nonce = os.urandom(12)
        iv = os.urandom(16)
        header = _build_header(FLAG_HANDSHAKE, out_nonce, authdata_out)
        ct = _aes_gcm_encrypt(init_key, out_nonce, message,
                              iv + header)
        return ("handshake",
                iv + _aes_ctr(dest_node_id[:16], iv, header) + ct)

    def _on_handshake(self, nonce, authdata, ct, ad):
        src_id, sig, eph_pub, record = parse_handshake_authdata(
            authdata)
        challenge = self._issued_challenges.pop(src_id, None)
        if challenge is None:
            return ("none", None)
        peer_enr = Enr.from_rlp(record) if record else None
        if peer_enr is None:
            return ("none", None)   # no cached records in this driver
        if peer_enr.node_id != src_id:
            raise WireError("handshake record/node-id mismatch")
        if not verify_id_signature(peer_enr.public_key, challenge,
                                   eph_pub, self.node_id, sig):
            raise WireError("bad id signature")
        ecdh_secret = EC.ecdh(self.secret, EC.decompress(eph_pub))
        init_key, recp_key = derive_session_keys(
            ecdh_secret, src_id, self.node_id, challenge)
        self.sessions[src_id] = Session(send_key=recp_key,
                                        recv_key=init_key)
        pt = _aes_gcm_decrypt(init_key, nonce, ct, ad)
        mtype, fields = decode_message(pt)
        return ("message", src_id, mtype, fields)
