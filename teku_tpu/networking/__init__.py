"""Networking: TCP transport, gossip router, req/resp RPC, sync.

Reference: /root/reference/networking/ (p2p, eth2) and
/root/reference/beacon/sync/.
"""

from .gossip import TcpGossipNetwork
from .reqresp import BeaconRpc
from .sync import SyncService
from .transport import NetworkConfig, P2PNetwork, Peer


class NetworkedNode:
    """Convenience bundle: BeaconNode + TCP network + RPC + sync,
    mirroring the reference's Eth2P2PNetworkBuilder composition."""

    def __init__(self, spec, genesis_state, host: str = "127.0.0.1",
                 port: int = 0, name: str = "node", store=None):
        from ..spec import helpers as H
        from ..node.node import BeaconNode
        digest = H.compute_fork_digest(
            spec.config.GENESIS_FORK_VERSION,
            genesis_state.genesis_validators_root)
        self.net = P2PNetwork(NetworkConfig(host=host, port=port), digest)
        self.gossip = TcpGossipNetwork(self.net)
        self.node = BeaconNode(spec, genesis_state, self.gossip,
                               name=name, store=store)
        self.rpc = BeaconRpc(self.net, self.node)
        self.sync = SyncService(self.net, self.rpc, self.node)

        async def _on_connect(peer):
            # gossipsub sends the full subscription set on connect so
            # the peer can graft us into topic meshes
            self.gossip.announce_subscriptions(peer)
            try:
                await self.rpc.exchange_status(peer)
            except Exception:
                pass
        self.net.on_peer_connected = _on_connect

    async def start(self) -> None:
        await self.net.start()
        await self.gossip.start()
        await self.node.start()

    async def stop(self) -> None:
        await self.node.stop()
        await self.gossip.stop()
        await self.net.stop()

    async def connect(self, other: "NetworkedNode"):
        return await self.net.connect("127.0.0.1", other.net.port)
