"""Networking: TCP transport, gossip router, req/resp RPC, sync.

Reference: /root/reference/networking/ (p2p, eth2) and
/root/reference/beacon/sync/.
"""

from typing import Optional

from .gossip import TcpGossipNetwork
from .reqresp import BeaconRpc
from .sync import SyncService
from .transport import NetworkConfig, P2PNetwork, Peer


class NetworkedNode:
    """Convenience bundle: BeaconNode + TCP network + RPC + sync,
    mirroring the reference's Eth2P2PNetworkBuilder composition."""

    def __init__(self, spec, genesis_state, host: str = "127.0.0.1",
                 port: int = 0, name: str = "node", store=None,
                 udp_discovery_port: Optional[int] = None,
                 bootnodes=(), target_peers: int = 8):
        from ..spec import helpers as H
        from ..node.node import BeaconNode
        self._host = host
        digest = H.compute_fork_digest(
            spec.config.GENESIS_FORK_VERSION,
            genesis_state.genesis_validators_root)
        self._udp_discovery_port = udp_discovery_port
        self._bootnodes = list(bootnodes)
        self._target_peers = target_peers
        self.discv5 = None
        self._discv5_task = None
        self.net = P2PNetwork(NetworkConfig(host=host, port=port), digest)
        self.gossip = TcpGossipNetwork(self.net)
        self.node = BeaconNode(spec, genesis_state, self.gossip,
                               name=name, store=store)
        self.rpc = BeaconRpc(self.net, self.node)
        self.sync = SyncService(self.net, self.rpc, self.node)
        from .subnets import AttestationSubnetManager
        self.subnets = AttestationSubnetManager(spec.config,
                                                self.net.node_id)
        # spec node record (EIP-778, secp256k1 v4 identity) advertising
        # the eth2 fork digest — what /eth/v1/node/identity publishes
        # (reference: ENRs from DiscV5Service.java)
        import secrets as _secrets
        from . import secp256k1 as _ec
        from .enr import Enr as _Enr
        self._enr_secret = (int.from_bytes(_secrets.token_bytes(32),
                                           "big") % _ec.N) or 1
        # ENRForkID (p2p spec): fork_digest || next_fork_version ||
        # next_fork_epoch, with next = current/FAR_FUTURE when no fork
        # is scheduled — anything else makes conformant peers treat us
        # as on an incompatible fork
        from ..spec.config import FAR_FUTURE_EPOCH
        enr_fork_id = (digest + spec.config.GENESIS_FORK_VERSION
                       + FAR_FUTURE_EPOCH.to_bytes(8, "little"))
        self.enr = _Enr.create(
            self._enr_secret, seq=1, ip=host if host[0].isdigit()
            else "127.0.0.1",
            udp=udp_discovery_port or 0,
            extra={"eth2": enr_fork_id, "attnets": bytes(8)})
        # expire duty-driven subnet windows with the chain clock (the
        # manager's active set also feeds /eth/v1/node/identity
        # attnets); the manager itself satisfies the channel's on_slot
        # shape
        from ..infra.events import SlotEventsChannel
        self.node.channels.subscribe(SlotEventsChannel, self.subnets)

        async def _on_connect(peer):
            # gossipsub sends the full subscription set on connect so
            # the peer can graft us into topic meshes
            self.gossip.announce_subscriptions(peer)
            try:
                await self.rpc.exchange_status(peer)
            except Exception:
                pass
        self.net.on_peer_connected = _on_connect
        self._register_health_checks()

    def _register_health_checks(self) -> None:
        """Networking-layer checks into the node's HealthRegistry —
        peer count, sync status, gossip staleness (the node itself
        registers its subsystem checks; only the layer that OWNS the
        network can judge it)."""
        from ..infra.health import (CheckResult, HealthStatus,
                                    staleness_check)

        def peers_check() -> CheckResult:
            connected = sum(1 for p in self.net.peers if p.connected)
            if connected == 0:
                return CheckResult(HealthStatus.DEGRADED,
                                   "no connected peers")
            return CheckResult(HealthStatus.UP,
                               f"{connected} peer(s) connected")

        def sync_check() -> CheckResult:
            if self.sync.syncing:
                head = self.node.chain.head_slot()
                return CheckResult(HealthStatus.DEGRADED,
                                   f"syncing (head slot {head})")
            return CheckResult(HealthStatus.UP, "in sync")

        self.node.health.register("peers", peers_check)
        self.node.health.register("sync", sync_check)
        # gossip silence only counts once a first frame has arrived
        # AND peers are connected — a peerless node is the peers
        # check's finding, not a staleness one
        base = staleness_check(
            lambda: self.gossip.last_message_monotonic,
            degraded_s=60.0, what="gossip message")

        def gossip_check() -> CheckResult:
            if not any(p.connected for p in self.net.peers):
                return CheckResult(HealthStatus.UP,
                                   "no peers (staleness n/a)")
            return base()

        self.node.health.register("gossip", gossip_check)

    async def start(self) -> None:
        import asyncio
        await self.net.start()
        await self.gossip.start()
        await self.node.start()
        if self._udp_discovery_port is not None:
            # UDP walker: discovered fork-matched records feed the TCP
            # dialer until the peer target holds (reference
            # DiscoveryNetwork composing discv5 + ConnectionManager)
            from .discv5 import UdpDiscoveryService

            dial_tasks = set()   # strong refs: tasks held weakly

            def _dial(record):
                if record.noise_pub == self.net.node_id:
                    return
                if len(self.net.peers) >= self._target_peers:
                    return
                task = asyncio.ensure_future(
                    self.net.connect(record.ip, record.tcp_port))
                dial_tasks.add(task)
                task.add_done_callback(dial_tasks.discard)
            self.discv5 = UdpDiscoveryService(
                noise_pub=self.net.node_id,
                fork_digest=self.net.fork_digest,
                ip=self._host,
                udp_port=self._udp_discovery_port,
                tcp_port=self.net.port,
                on_discovered=_dial)
            await self.discv5.start()
            if self._bootnodes:
                await self.discv5.bootstrap(
                    [(h, int(p)) for h, p in
                     (addr.rsplit(":", 1) for addr in self._bootnodes)])
            self._discv5_task = asyncio.create_task(self.discv5.run())
        elif self._bootnodes:
            raise ValueError("bootnodes given but UDP discovery is "
                             "disabled (set udp_discovery_port)")

    async def stop(self) -> None:
        import asyncio
        if self._discv5_task is not None:
            # cancel the handle we hold: discv5.stop()'s own handle is
            # registered from inside run(), which may not have started
            self._discv5_task.cancel()
            try:
                await self._discv5_task
            except asyncio.CancelledError:
                pass
            self._discv5_task = None
        if self.discv5 is not None:
            await self.discv5.stop()
        await self.node.stop()
        await self.gossip.stop()
        await self.net.stop()

    async def connect(self, other: "NetworkedNode"):
        return await self.net.connect("127.0.0.1", other.net.port)
