"""UDP node discovery: signed records, Kademlia routing, random-walk
lookups — the discv5 role, natively.

Equivalent of the reference's discv5 stack (reference: networking/p2p/
.../discovery/discv5/DiscV5Service.java:57 wrapping a discv5 walker;
DiscoveryNetwork.java composing it with the connection manager): nodes
carry SIGNED, sequence-numbered records (the ENR role) and answer
PING/PONG (liveness + record exchange) and FINDNODE/NODES (peers close
to a target id) over UDP; a periodic random-target lookup walks the
DHT and hands live, fork-matched endpoints to the TCP dialer.

Simplifications vs wire-discv5, chosen deliberately: records are
Ed25519-signed (no secp256k1 in this stack) and datagrams carry
whole records rather than discv5's encrypted session envelopes — a
record is self-authenticating, and transport security lives in the
noise layer where the real traffic flows.  node_id =
sha256(ed25519_pub), XOR-distance buckets, k=16, alpha=3.
"""

import asyncio
import hashlib
import logging
import secrets
import socket
import struct
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

from cryptography.hazmat.primitives.asymmetric.ed25519 import (
    Ed25519PrivateKey, Ed25519PublicKey)

_LOG = logging.getLogger(__name__)

K_BUCKET = 16
ALPHA = 3
MSG_PING = 1
MSG_PONG = 2
MSG_FINDNODE = 3
MSG_NODES = 4
MAX_RECORD = 512
MAX_DATAGRAM = 1400          # stay under typical MTU


@dataclass(frozen=True)
class NodeRecord:
    """The ENR role: everything needed to contact and authenticate a
    node, signed by its discovery identity."""
    seq: int
    ed_pub: bytes            # 32B identity key
    noise_pub: bytes         # 32B transport identity (dial target id)
    fork_digest: bytes       # 4B network filter
    ip: str
    udp_port: int
    tcp_port: int
    signature: bytes = b""

    @property
    def node_id(self) -> bytes:
        return hashlib.sha256(self.ed_pub).digest()

    def _signing_body(self) -> bytes:
        ip = self.ip.encode()
        return (struct.pack("<Q", self.seq) + self.ed_pub
                + self.noise_pub + self.fork_digest
                + struct.pack("<HHB", self.udp_port, self.tcp_port,
                              len(ip)) + ip)

    def encode(self) -> bytes:
        return self._signing_body() + self.signature

    @classmethod
    def decode(cls, raw: bytes) -> "NodeRecord":
        if len(raw) < 8 + 32 + 32 + 4 + 5 + 64:
            raise ValueError("record too short")
        (seq,) = struct.unpack("<Q", raw[:8])
        ed_pub = raw[8:40]
        noise_pub = raw[40:72]
        fork_digest = raw[72:76]
        udp_port, tcp_port, ip_len = struct.unpack("<HHB", raw[76:81])
        ip = raw[81:81 + ip_len].decode()
        signature = raw[81 + ip_len:81 + ip_len + 64]
        record = cls(seq=seq, ed_pub=ed_pub, noise_pub=noise_pub,
                     fork_digest=fork_digest, ip=ip,
                     udp_port=udp_port, tcp_port=tcp_port,
                     signature=signature)
        record.verify()
        return record

    def verify(self) -> None:
        try:
            Ed25519PublicKey.from_public_bytes(self.ed_pub).verify(
                self.signature, self._signing_body())
        except Exception:
            raise ValueError("bad record signature")


def make_record(identity: Ed25519PrivateKey, noise_pub: bytes,
                fork_digest: bytes, ip: str, udp_port: int,
                tcp_port: int, seq: int = 1) -> NodeRecord:
    record = NodeRecord(seq=seq,
                        ed_pub=identity.public_key().public_bytes_raw(),
                        noise_pub=noise_pub, fork_digest=fork_digest,
                        ip=ip, udp_port=udp_port, tcp_port=tcp_port)
    sig = identity.sign(record._signing_body())
    return NodeRecord(**{**record.__dict__, "signature": sig})


def _distance(a: bytes, b: bytes) -> int:
    return int.from_bytes(a, "big") ^ int.from_bytes(b, "big")


class RoutingTable:
    """XOR-metric buckets (log-distance), k entries each, LRU within a
    bucket; liveness evicts via the service's ping cycle."""

    def __init__(self, own_id: bytes, k: int = K_BUCKET):
        self.own_id = own_id
        self.k = k
        self._buckets: Dict[int, List[NodeRecord]] = {}
        self._by_id: Dict[bytes, NodeRecord] = {}

    def _bucket_of(self, node_id: bytes) -> int:
        d = _distance(self.own_id, node_id)
        return d.bit_length()        # 0 only for self

    def add(self, record: NodeRecord) -> bool:
        nid = record.node_id
        if nid == self.own_id:
            return False
        existing = self._by_id.get(nid)
        if existing is not None and existing.seq >= record.seq:
            return False             # stale or same
        idx = self._bucket_of(nid)
        bucket = self._buckets.setdefault(idx, [])
        if existing is not None:
            bucket[:] = [r for r in bucket if r.node_id != nid]
        elif len(bucket) >= self.k:
            return False             # full: keep the tested residents
        bucket.append(record)
        self._by_id[nid] = record
        return True

    def remove(self, node_id: bytes) -> None:
        record = self._by_id.pop(node_id, None)
        if record is not None:
            idx = self._bucket_of(node_id)
            self._buckets[idx] = [r for r in self._buckets.get(idx, [])
                                  if r.node_id != node_id]

    def closest(self, target: bytes, n: int = K_BUCKET
                ) -> List[NodeRecord]:
        return sorted(self._by_id.values(),
                      key=lambda r: _distance(r.node_id, target))[:n]

    def __len__(self) -> int:
        return len(self._by_id)

    def records(self) -> List[NodeRecord]:
        return list(self._by_id.values())


class UdpDiscoveryService(asyncio.DatagramProtocol):
    """The walker: answers PING/FINDNODE, pings for liveness, runs
    random-target lookups, and reports live fork-matched records to
    `on_discovered` (the connection manager's dial feed)."""

    def __init__(self, identity: Optional[Ed25519PrivateKey] = None,
                 noise_pub: bytes = bytes(32),
                 fork_digest: bytes = bytes(4),
                 ip: str = "127.0.0.1", udp_port: int = 0,
                 tcp_port: int = 0,
                 on_discovered: Optional[
                     Callable[[NodeRecord], None]] = None):
        self.identity = identity or Ed25519PrivateKey.generate()
        self.noise_pub = noise_pub
        self.fork_digest = fork_digest
        self._ip = ip
        self._udp_port = udp_port
        self._tcp_port = tcp_port
        self.on_discovered = on_discovered
        self.record: Optional[NodeRecord] = None
        self.table: Optional[RoutingTable] = None
        self._transport = None
        self._pending_pong: Dict[Tuple[str, int],
                                 asyncio.Future] = {}
        self._pending_nodes: Dict[Tuple[str, int],
                                  asyncio.Future] = {}
        self._task: Optional[asyncio.Task] = None
        self.port: int = udp_port

    # -- lifecycle ------------------------------------------------------
    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._transport, _ = await loop.create_datagram_endpoint(
            lambda: self, local_addr=(self._ip, self._udp_port))
        self.port = self._transport.get_extra_info("sockname")[1]
        self.record = make_record(self.identity, self.noise_pub,
                                  self.fork_digest, self._ip,
                                  self.port, self._tcp_port)
        self.table = RoutingTable(self.record.node_id)

    async def run(self, interval_s: float = 10.0) -> None:
        self._task = asyncio.current_task()
        while True:
            try:
                await self.lookup(secrets.token_bytes(32))
                await self._liveness_round()
            except asyncio.CancelledError:
                raise
            except Exception:
                _LOG.exception("discovery round failed")
            await asyncio.sleep(interval_s)

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
        if self._transport is not None:
            self._transport.close()

    # -- datagram handling ---------------------------------------------
    def datagram_received(self, data: bytes, addr) -> None:
        try:
            self._handle(data, addr)
        except Exception:
            _LOG.debug("bad discovery datagram from %s", addr)

    def _handle(self, data: bytes, addr) -> None:
        if not data:
            return
        kind = data[0]
        if kind in (MSG_PING, MSG_PONG):
            record = NodeRecord.decode(data[1:])
            if record.fork_digest != self.fork_digest:
                return          # other network: no pong, no table entry
            self._admit(record)
            if kind == MSG_PING:
                self._send(addr, MSG_PONG, self.record.encode())
            else:
                fut = self._pending_pong.pop(addr, None)
                if fut is not None and not fut.done():
                    fut.set_result(record)
        elif kind == MSG_FINDNODE:
            target = data[1:33]
            asker = NodeRecord.decode(data[33:])
            self._admit(asker)
            body = bytearray()
            count = 0
            for rec in self.table.closest(target):
                enc = rec.encode()
                if len(body) + len(enc) + 3 > MAX_DATAGRAM:
                    break
                body += struct.pack("<H", len(enc)) + enc
                count += 1
            self._send(addr, MSG_NODES,
                       bytes([count]) + bytes(body))
        elif kind == MSG_NODES:
            count = data[1]
            pos = 2
            found = []
            for _ in range(count):
                (n,) = struct.unpack("<H", data[pos:pos + 2])
                pos += 2
                found.append(NodeRecord.decode(data[pos:pos + n]))
                pos += n
            for rec in found:
                self._admit(rec)
            fut = self._pending_nodes.pop(addr, None)
            if fut is not None and not fut.done():
                fut.set_result(found)

    def _admit(self, record: NodeRecord) -> None:
        """Signed + fork-matched records enter the table and the dial
        feed (the DiscoveryNetwork composition point)."""
        if record.fork_digest != self.fork_digest:
            return
        if self.table.add(record) and self.on_discovered is not None:
            try:
                self.on_discovered(record)
            except Exception:
                _LOG.exception("on_discovered failed")

    def _send(self, addr, kind: int, payload: bytes) -> None:
        if self._transport is not None:
            self._transport.sendto(bytes([kind]) + payload, addr)

    # -- client ops -----------------------------------------------------
    async def ping(self, addr: Tuple[str, int],
                   timeout: float = 2.0) -> Optional[NodeRecord]:
        """PING an endpoint; returns its (verified) record on PONG."""
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending_pong[addr] = fut
        self._send(addr, MSG_PING, self.record.encode())
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return None
        finally:
            self._pending_pong.pop(addr, None)

    async def find_node(self, record: NodeRecord, target: bytes,
                        timeout: float = 2.0) -> List[NodeRecord]:
        addr = (record.ip, record.udp_port)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending_nodes[addr] = fut
        self._send(addr, MSG_FINDNODE, target + self.record.encode())
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            return []
        finally:
            self._pending_nodes.pop(addr, None)

    async def bootstrap(self, addrs: List[Tuple[str, int]]) -> int:
        """PING the seed endpoints; returns how many answered."""
        results = await asyncio.gather(
            *(self.ping(a) for a in addrs))
        return sum(1 for r in results if r is not None)

    async def lookup(self, target: bytes) -> List[NodeRecord]:
        """Iterative Kademlia lookup: query ALPHA closest, merge NODES,
        repeat while the closest set improves."""
        queried = set()
        while True:
            frontier = [r for r in self.table.closest(target)
                        if r.node_id not in queried][:ALPHA]
            if not frontier:
                break
            for r in frontier:
                queried.add(r.node_id)
            before = len(self.table)
            await asyncio.gather(
                *(self.find_node(r, target) for r in frontier))
            if len(self.table) == before and len(queried) >= ALPHA:
                break
        return self.table.closest(target)

    async def _liveness_round(self) -> None:
        """Ping the table; evict the dead (the k-bucket 'tested
        residents' rule's other half)."""
        for record in self.table.records():
            pong = await self.ping((record.ip, record.udp_port),
                                   timeout=1.0)
            if pong is None:
                self.table.remove(record.node_id)
