"""RLP (recursive length prefix) encoding — the serialization ENRs and
discv5 messages use (Ethereum's devp2p format; EIP-778 records are
signed RLP lists).  Values are bytes; lists nest arbitrarily.
Integers encode as minimal big-endian byte strings (no leading zero,
zero = empty string) — callers convert.
"""

from typing import List, Tuple, Union

Item = Union[bytes, List["Item"]]


class RlpError(ValueError):
    pass


def encode_uint(v: int) -> bytes:
    if v == 0:
        return b""
    return v.to_bytes((v.bit_length() + 7) // 8, "big")


def decode_uint(b: bytes) -> int:
    if b[:1] == b"\x00":
        raise RlpError("leading zero in integer")
    return int.from_bytes(b, "big")


def _encode_length(n: int, short_base: int) -> bytes:
    if n <= 55:
        return bytes([short_base + n])
    n_bytes = encode_uint(n)
    return bytes([short_base + 55 + len(n_bytes)]) + n_bytes


def encode(item: Item) -> bytes:
    if isinstance(item, (bytes, bytearray)):
        item = bytes(item)
        if len(item) == 1 and item[0] < 0x80:
            return item
        return _encode_length(len(item), 0x80) + item
    if isinstance(item, (list, tuple)):
        payload = b"".join(encode(i) for i in item)
        return _encode_length(len(payload), 0xC0) + payload
    raise RlpError(f"cannot RLP-encode {type(item).__name__}")


def _decode_at(data: bytes, pos: int) -> Tuple[Item, int]:
    if pos >= len(data):
        raise RlpError("truncated item")
    b0 = data[pos]
    if b0 < 0x80:
        return bytes([b0]), pos + 1
    if b0 <= 0xBF:
        if b0 <= 0xB7:
            n, pos = b0 - 0x80, pos + 1
        else:
            ln = b0 - 0xB7
            n = decode_uint(data[pos + 1:pos + 1 + ln])
            if n <= 55:
                raise RlpError("non-canonical long length")
            pos += 1 + ln
        if pos + n > len(data):
            raise RlpError("truncated string")
        out = data[pos:pos + n]
        if n == 1 and out[0] < 0x80:
            raise RlpError("non-canonical single byte")
        return out, pos + n
    if b0 <= 0xF7:
        n, pos = b0 - 0xC0, pos + 1
    else:
        ln = b0 - 0xF7
        n = decode_uint(data[pos + 1:pos + 1 + ln])
        if n <= 55:
            raise RlpError("non-canonical long length")
        pos += 1 + ln
    end = pos + n
    if end > len(data):
        raise RlpError("truncated list")
    items: List[Item] = []
    while pos < end:
        item, pos = _decode_at(data, pos)
        items.append(item)
    if pos != end:
        raise RlpError("list payload overrun")
    return items, pos


def decode(data: bytes) -> Item:
    item, end = _decode_at(data, 0)
    if end != len(data):
        raise RlpError("trailing bytes after item")
    return item
