"""Validator client: epoch duty schedulers driving signed duties
through the ValidatorApiChannel.

Equivalent of the reference's validator client (reference: validator/
client/src/main/java/tech/pegasys/teku/validator/client/
ValidatorClientService.java, AttestationDutyScheduler.java,
BlockDutyScheduler.java, duties/attestations/AttestationProductionDuty
.java, AggregationDuty.java): duties are queried once per epoch,
executed at their slot phases, and every signature flows through the
(slashing-protected) DutySigner — the client never touches raw keys or
the node's internals, only the API channel.
"""

import logging
from typing import Dict, List, Optional

from ..spec import helpers as H
from ..spec import Spec
from ..spec.builder import is_aggregator_by_size
from .api import (AttesterDuty, ProposerDuty, SyncDuty,
                  ValidatorApiChannel)
from .signer import DutySigner, SigningError

_LOG = logging.getLogger(__name__)


class ValidatorClient:
    """One client managing a set of validator indices."""

    def __init__(self, spec: Spec, api: ValidatorApiChannel,
                 signer: DutySigner, validator_indices: List[int],
                 graffiti: bytes = bytes(32)):
        self.spec = spec
        self.api = api
        self.signer = signer
        self.indices = list(validator_indices)
        self.graffiti = graffiti
        self._proposer_duties: Dict[int, List[ProposerDuty]] = {}
        self._attester_duties: Dict[int, List[AttesterDuty]] = {}
        self._sync_duties: Dict[int, List[SyncDuty]] = {}
        self.blocks_proposed = 0
        self.attestations_sent = 0
        self.aggregates_sent = 0

    # -- duty loading (once per epoch, reference RetryingDutyLoader) ---
    def _duties_for_epoch(self, epoch: int) -> None:
        if epoch not in self._proposer_duties:
            mine = set(self.indices)
            self._proposer_duties[epoch] = [
                d for d in self.api.get_proposer_duties(epoch)
                if d.validator_index in mine]
            self._attester_duties[epoch] = self.api.get_attester_duties(
                epoch, self.indices)
            try:
                self._sync_duties[epoch] = self.api.get_sync_duties(
                    epoch, self.indices)
            except NotImplementedError:
                self._sync_duties[epoch] = []
            for old in [e for e in self._proposer_duties if e < epoch - 1]:
                del self._proposer_duties[old]
                del self._attester_duties[old]
                self._sync_duties.pop(old, None)

    # -- slot phases ---------------------------------------------------
    async def on_slot_start(self, slot: int) -> None:
        cfg = self.spec.config
        epoch = H.compute_epoch_at_slot(cfg, slot)
        self._duties_for_epoch(epoch)
        for duty in self._proposer_duties[epoch]:
            if duty.slot != slot:
                continue
            state = self.api.duty_state(slot)
            try:
                reveal = self.signer.sign_randao_reveal(
                    cfg, state, epoch, duty.validator_index)
                block, pre = await self.api.produce_unsigned_block(
                    slot, reveal, self.graffiti)
                signature = self.signer.sign_block(cfg, pre, block)
            except SigningError as exc:
                _LOG.warning("block duty refused: %s", exc)
                continue
            except Exception:
                # a failed proposal must never kill the duty driver
                # (reference duties log-and-continue via SafeFuture)
                _LOG.exception("block production failed at slot %d", slot)
                continue
            signed = self.spec.at_slot(slot).schemas.SignedBeaconBlock(
                message=block, signature=signature)
            await self.api.publish_signed_block(signed)
            self.blocks_proposed += 1

    def _slot_version(self, slot: int):
        from ..spec.milestones import SpecMilestone
        version = self.spec.at_slot(slot)
        return version, version.milestone >= SpecMilestone.ELECTRA

    async def on_attestation_due(self, slot: int) -> None:
        cfg = self.spec.config
        epoch = H.compute_epoch_at_slot(cfg, slot)
        self._duties_for_epoch(epoch)
        version, electra = self._slot_version(slot)
        S = version.schemas
        data_by_committee = {}
        for duty in self._attester_duties[epoch]:
            if duty.slot != slot:
                continue
            if duty.committee_index not in data_by_committee:
                data_by_committee[duty.committee_index] = (
                    self.api.get_attestation_data(slot,
                                                  duty.committee_index))
            data = data_by_committee[duty.committee_index]
            state = self.api.duty_state(slot)
            try:
                sig = self.signer.sign_attestation_data(
                    cfg, state, data, duty.validator_index)
            except SigningError as exc:
                _LOG.warning("attestation duty refused: %s", exc)
                continue
            if electra:
                # EIP-7549 wire shape for subnets: SingleAttestation
                att = S.SingleAttestation(
                    committee_index=duty.committee_index,
                    attester_index=duty.validator_index,
                    data=data, signature=sig)
            else:
                bits = tuple(i == duty.committee_position
                             for i in range(duty.committee_size))
                att = S.Attestation(aggregation_bits=bits, data=data,
                                    signature=sig)
            await self.api.publish_attestation(att)
            self.attestations_sent += 1

    async def on_sync_committee_due(self, slot: int) -> None:
        """Altair sync-committee duty: members sign the head root at
        the current slot (reference: validator/client/duties/
        synccommittee/SyncCommitteeProductionDuty).  Membership comes
        from the sync-duties query — no state needed."""
        cfg = self.spec.config
        epoch = H.compute_epoch_at_slot(cfg, slot)
        self._duties_for_epoch(epoch)
        members = {d.validator_index for d in self._sync_duties[epoch]}
        if not members:
            return
        state = self.api.duty_state(slot)
        # sign the CURRENT head (the slot's block): it is included by
        # the next proposer as previous-slot root — and remembered so
        # the aggregation phase targets the SAME root even if the head
        # moves mid-slot
        head_root = self.api.head_root()
        self._sync_duty_root = (slot, head_root)
        version = self.spec.at_slot(slot)
        msgs = []
        for vi in members:
            try:
                sig = self.signer.sign_sync_committee_message(
                    cfg, state, slot, head_root, vi)
            except SigningError:
                continue
            msgs.append(version.schemas.SyncCommitteeMessage(
                slot=slot, beacon_block_root=head_root,
                validator_index=vi, signature=sig))
        if msgs:
            await self.api.publish_sync_committee_messages(msgs)

    async def on_sync_aggregation_due(self, slot: int) -> None:
        """Sync-committee contribution duty (reference duties/
        synccommittee/SyncCommitteeAggregationDuty): members with a
        winning selection proof aggregate their subcommittee's pooled
        messages and broadcast a SignedContributionAndProof.
        Subcommittee assignment comes from the sync duty's committee
        positions — no state needed."""
        cfg = self.spec.config
        epoch = H.compute_epoch_at_slot(cfg, slot)
        self._duties_for_epoch(epoch)
        duties = self._sync_duties[epoch]
        if not duties:
            return
        from ..spec.altair.helpers import is_sync_committee_aggregator
        build = getattr(self.api, "build_sync_contribution", None)
        publish = getattr(self.api, "publish_contribution_and_proof",
                          None)
        if build is None or publish is None:
            return      # channel without the contribution surface
        state = self.api.duty_state(slot)
        from ..spec.altair.helpers import sync_subcommittee_size
        sub_size = sync_subcommittee_size(cfg)
        # aggregate the root the slot's messages actually signed — a
        # mid-slot head change must not orphan the pooled messages
        duty = getattr(self, "_sync_duty_root", None)
        head_root = (duty[1] if duty is not None and duty[0] == slot
                     else self.api.head_root())
        version = self.spec.at_slot(slot)
        # EVERY validator with a winning selection proof broadcasts its
        # own contribution (the redundancy is the point of selecting
        # ~TARGET aggregators per subcommittee); dedupe only per
        # (validator, subcommittee) across duplicate committee seats
        done: set = set()
        for sync_duty in duties:
            vi = sync_duty.validator_index
            subs = {pos // sub_size for pos in sync_duty.positions}
            await self._contribute_for(
                cfg, state, slot, vi, subs, done, head_root, version,
                build, publish, is_sync_committee_aggregator)

    async def _contribute_for(self, cfg, state, slot, vi, subs, done,
                              head_root, version, build, publish,
                              is_sync_committee_aggregator) -> None:
        for sub in sorted(subs):
            if (vi, sub) in done:
                continue
            done.add((vi, sub))
            try:
                proof = self.signer.sign_sync_selection_proof(
                    cfg, state, slot, sub, vi)
            except SigningError:
                continue
            if not is_sync_committee_aggregator(cfg, proof):
                continue
            contribution = build(slot, head_root, sub)
            if contribution is None:
                continue
            msg = version.schemas.ContributionAndProof(
                aggregator_index=vi, contribution=contribution,
                selection_proof=proof)
            try:
                sig = self.signer.sign_contribution_and_proof(
                    cfg, state, msg)
            except SigningError:
                continue
            await publish(version.schemas.SignedContributionAndProof(
                message=msg, signature=sig))

    async def on_aggregation_due(self, slot: int) -> None:
        cfg = self.spec.config
        epoch = H.compute_epoch_at_slot(cfg, slot)
        self._duties_for_epoch(epoch)
        try:
            await self.on_sync_aggregation_due(slot)
        except Exception:
            # a failed sync contribution must never take down the
            # attestation aggregation below (or the whole duty loop)
            _LOG.exception("sync aggregation duty failed at slot %d",
                           slot)
        version, electra = self._slot_version(slot)
        S = version.schemas
        aggregated_committees = set()
        for duty in self._attester_duties[epoch]:
            if duty.slot != slot:
                continue
            if duty.committee_index in aggregated_committees:
                continue
            state = self.api.duty_state(slot)
            try:
                proof = self.signer.sign_selection_proof(
                    cfg, state, slot, duty.validator_index)
            except SigningError:
                continue
            # the duty carries committee_length so this needs no
            # shuffling (what lets a remote VC skip state downloads)
            if not is_aggregator_by_size(cfg, duty.committee_size, proof):
                continue
            data = self.api.get_attestation_data(slot, duty.committee_index)
            aggregate = self.api.get_aggregate(
                data, duty.committee_index) if electra \
                else self.api.get_aggregate(data)
            if aggregate is None:
                continue
            msg = S.AggregateAndProof(
                aggregator_index=duty.validator_index,
                aggregate=aggregate, selection_proof=proof)
            try:
                sig = self.signer.sign_aggregate_and_proof(cfg, state, msg)
            except SigningError:
                continue
            signed = S.SignedAggregateAndProof(message=msg, signature=sig)
            await self.api.publish_aggregate_and_proof(signed)
            self.aggregates_sent += 1
            aggregated_committees.add(duty.committee_index)
