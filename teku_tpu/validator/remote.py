"""Remote validator client mode: the ValidatorApiChannel over the
beacon REST API, so a VC process can drive duties against any beacon
node it can reach over HTTP.

Equivalent of the reference's remote VC (reference: validator/remote/
src/main/java/tech/pegasys/teku/validator/remote/
RemoteValidatorApiHandler.java over the typedef OkHttp client; the
in-process path is validator/eventadapter/InProcessBeaconNodeApi.java):
duties and attestation data come from the standard JSON duty endpoints,
productions/submissions ride SSZ octet-stream bodies, and the signing
context is a light DutyContext built from /eth/v1/beacon/genesis plus
the fork schedule — the remote VC NEVER downloads a beacon state
(mainnet states are hundreds of MB; the duty endpoints exist precisely
so it doesn't have to).

The HTTP client is deliberately synchronous (urllib over localhost/LAN,
millisecond round trips): duty_state and the duty queries are sync on
the channel interface, and a VC process has nothing else to run while
its one duty blocks.
"""

import json
import logging
import urllib.error
import urllib.request
from typing import List, Optional

from ..spec import helpers as H
from ..spec import Spec
from ..spec.codec import serialize_signed_block
from ..spec.datastructures import Fork
from ..spec.milestones import build_fork_schedule
from .api import (AttesterDuty, ProposerDuty, SyncDuty,
                  ValidatorApiChannel)

_LOG = logging.getLogger(__name__)


class DutyContext:
    """Everything the signers consume from a 'state' — slot, fork,
    genesis_validators_root (H.get_domain's full read set) — in a few
    dozen bytes instead of a downloaded BeaconState."""

    __slots__ = ("slot", "fork", "genesis_validators_root")

    def __init__(self, slot: int, fork: Fork,
                 genesis_validators_root: bytes):
        self.slot = slot
        self.fork = fork
        self.genesis_validators_root = genesis_validators_root


class RemoteValidatorApi(ValidatorApiChannel):
    def __init__(self, spec: Spec, base_url: str, timeout: float = 10.0):
        self.spec = spec
        self.base = base_url.rstrip("/")
        self.timeout = timeout
        self._genesis_root: Optional[bytes] = None

    # -- transport -----------------------------------------------------
    def _get_json(self, path: str) -> dict:
        with urllib.request.urlopen(self.base + path,
                                    timeout=self.timeout) as resp:
            return json.loads(resp.read())

    def _get_bytes(self, path: str) -> bytes:
        with urllib.request.urlopen(self.base + path,
                                    timeout=self.timeout) as resp:
            return resp.read()

    def _post(self, path: str, data: bytes,
              ctype: str = "application/octet-stream") -> None:
        req = urllib.request.Request(
            self.base + path, data=data, method="POST",
            headers={"Content-Type": ctype})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            resp.read()

    # -- duties --------------------------------------------------------
    def get_proposer_duties(self, epoch: int) -> List[ProposerDuty]:
        out = self._get_json(f"/eth/v1/validator/duties/proposer/{epoch}")
        return [ProposerDuty(validator_index=int(d["validator_index"]),
                             slot=int(d["slot"]))
                for d in out["data"]]

    def get_attester_duties(self, epoch: int,
                            indices: List[int]) -> List[AttesterDuty]:
        body = json.dumps([str(i) for i in indices]).encode()
        req = urllib.request.Request(
            self.base + f"/eth/v1/validator/duties/attester/{epoch}",
            data=body, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            out = json.loads(resp.read())
        return [AttesterDuty(
            validator_index=int(d["validator_index"]),
            slot=int(d["slot"]),
            committee_index=int(d["committee_index"]),
            committee_position=int(d["validator_committee_index"]),
            committee_size=int(d["committee_length"]),
            committees_at_slot=int(d["committees_at_slot"]))
            for d in out["data"]]

    def get_sync_duties(self, epoch: int,
                        indices: List[int]) -> List[SyncDuty]:
        body = json.dumps([str(i) for i in indices]).encode()
        req = urllib.request.Request(
            self.base + f"/eth/v1/validator/duties/sync/{epoch}",
            data=body, method="POST",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            out = json.loads(resp.read())
        return [SyncDuty(
            validator_index=int(d["validator_index"]),
            pubkey=bytes.fromhex(d["pubkey"][2:]),
            positions=tuple(
                int(p) for p in d["validator_sync_committee_indices"]))
            for d in out["data"]]

    # -- chain context -------------------------------------------------
    def head_root(self) -> bytes:
        out = self._get_json("/eth/v1/beacon/headers/head")
        return bytes.fromhex(out["data"]["root"][2:])

    def genesis_validators_root(self) -> bytes:
        if self._genesis_root is None:
            out = self._get_json("/eth/v1/beacon/genesis")
            self._genesis_root = bytes.fromhex(
                out["data"]["genesis_validators_root"][2:])
        return self._genesis_root

    def duty_state(self, slot: int):
        """Signing context WITHOUT a state download: genesis root from
        the genesis endpoint (cached forever — it never changes), fork
        from the locally-known schedule.  The debug-state pull this
        replaces moved hundreds of MB per epoch at mainnet scale."""
        cfg = self.spec.config
        epoch = H.compute_epoch_at_slot(cfg, slot)
        prev, cur, fork_epoch = build_fork_schedule(cfg).fork_at_epoch(
            epoch)
        return DutyContext(
            slot=slot,
            fork=Fork(previous_version=prev, current_version=cur,
                      epoch=fork_epoch),
            genesis_validators_root=self.genesis_validators_root())

    def get_attestation_data(self, slot: int, committee_index: int):
        from ..spec.datastructures import (AttestationData, Checkpoint)
        out = self._get_json(
            f"/eth/v1/validator/attestation_data?slot={slot}"
            f"&committee_index={committee_index}")["data"]
        return AttestationData(
            slot=int(out["slot"]), index=int(out["index"]),
            beacon_block_root=bytes.fromhex(
                out["beacon_block_root"][2:]),
            source=Checkpoint(epoch=int(out["source"]["epoch"]),
                              root=bytes.fromhex(
                                  out["source"]["root"][2:])),
            target=Checkpoint(epoch=int(out["target"]["epoch"]),
                              root=bytes.fromhex(
                                  out["target"]["root"][2:])))

    # -- production / submission ---------------------------------------
    async def produce_unsigned_block(self, slot: int, randao_reveal: bytes,
                                     graffiti: bytes = bytes(32)):
        raw = self._get_bytes(
            f"/eth/v3/validator/blocks/{slot}"
            f"?randao_reveal=0x{randao_reveal.hex()}"
            f"&graffiti=0x{graffiti.hex()}")
        version = build_fork_schedule(self.spec.config).version_at_slot(
            slot)
        block = version.schemas.BeaconBlock.deserialize(raw)
        # the signing context: same head state the node built against
        pre = self.duty_state(slot)
        return block, pre

    async def publish_signed_block(self, signed_block) -> None:
        self._post("/eth/v2/beacon/blocks",
                   serialize_signed_block(signed_block))

    async def publish_attestation(self, attestation) -> None:
        self._post("/eth/v1/beacon/pool/attestations",
                   type(attestation).serialize(attestation))

    def get_aggregate(self, data, committee_index=None):
        root = data.htr()
        extra = (f"&committee_index={committee_index}"
                 if committee_index is not None else "")
        try:
            raw = self._get_bytes(
                f"/eth/v1/validator/aggregate_attestation"
                f"?attestation_data_root=0x{root.hex()}"
                f"&slot={data.slot}" + extra)
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            raise
        version = build_fork_schedule(self.spec.config).version_at_slot(
            data.slot)
        return version.schemas.Attestation.deserialize(raw)

    async def publish_aggregate_and_proof(self, signed_aggregate) -> None:
        self._post("/eth/v1/validator/aggregate_and_proofs",
                   type(signed_aggregate).serialize(signed_aggregate))

    def build_sync_contribution(self, slot: int, block_root: bytes,
                                subcommittee_index: int):
        try:
            raw = self._get_bytes(
                f"/eth/v1/validator/sync_committee_contribution"
                f"?slot={slot}&subcommittee_index={subcommittee_index}"
                f"&beacon_block_root=0x{block_root.hex()}")
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                return None
            raise
        S = build_fork_schedule(self.spec.config).version_at_slot(
            slot).schemas
        return S.SyncCommitteeContribution.deserialize(raw)

    async def publish_contribution_and_proof(self, signed) -> None:
        self._post("/eth/v1/validator/contribution_and_proofs",
                   type(signed).serialize(signed))

    async def publish_sync_committee_message(self, msg) -> None:
        await self.publish_sync_committee_messages([msg])

    async def publish_sync_committee_messages(self, msgs) -> None:
        """One POST per slot, not per validator: the endpoint takes the
        whole batch."""
        body = json.dumps([{
            "slot": str(m.slot),
            "beacon_block_root": "0x" + m.beacon_block_root.hex(),
            "validator_index": str(m.validator_index),
            "signature": "0x" + m.signature.hex()}
            for m in msgs]).encode()
        self._post("/eth/v1/beacon/pool/sync_committees", body,
                   ctype="application/json")
