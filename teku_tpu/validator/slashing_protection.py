"""Local slashing protection: signing records + EIP-3076 interchange.

Equivalent of the reference's slashing protection (reference:
ethereum/spec/src/main/java/tech/pegasys/teku/spec/signatures/
LocalSlashingProtector.java, data/dataexchange/ for the EIP-3076
import/export): before any block or attestation signature, the signing
record for that validator must admit it — blocks strictly ascend by
slot, attestation sources/targets never regress or surround.
"""

import json
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Optional, Union


@dataclass
class SigningRecord:
    """reference: ethereum/signingrecord ValidatorSigningRecord."""
    block_slot: int = 0
    source_epoch: Optional[int] = None
    target_epoch: Optional[int] = None

    def may_sign_block(self, slot: int) -> bool:
        return slot > self.block_slot

    def may_sign_attestation(self, source: int, target: int) -> bool:
        if self.source_epoch is None and self.target_epoch is None:
            return source <= target
        if source > target:
            return False
        if self.source_epoch is not None and source < self.source_epoch:
            return False
        if self.target_epoch is not None and target <= self.target_epoch:
            return False
        return True


class SlashingProtector:
    """Per-pubkey records, persisted as one JSON file per validator
    (the reference stores YAML per validator in the data dir)."""

    def __init__(self, data_dir: Optional[Union[str, Path]] = None):
        self._dir = Path(data_dir) if data_dir else None
        self._records: Dict[bytes, SigningRecord] = {}
        if self._dir is not None:
            self._dir.mkdir(parents=True, exist_ok=True)
            for f in self._dir.glob("*.json"):
                d = json.loads(f.read_text())
                self._records[bytes.fromhex(f.stem)] = SigningRecord(
                    block_slot=d.get("block_slot", 0),
                    source_epoch=d.get("source_epoch"),
                    target_epoch=d.get("target_epoch"))

    def _get(self, pubkey: bytes) -> SigningRecord:
        rec = self._records.get(pubkey)
        if rec is None:
            rec = self._records[pubkey] = SigningRecord()
        return rec

    def _persist(self, pubkey: bytes) -> None:
        if self._dir is None:
            return
        rec = self._records[pubkey]
        (self._dir / f"{pubkey.hex()}.json").write_text(json.dumps({
            "block_slot": rec.block_slot,
            "source_epoch": rec.source_epoch,
            "target_epoch": rec.target_epoch}))

    # -- the two checks, record-before-sign ---------------------------
    def may_sign_block(self, pubkey: bytes, slot: int) -> bool:
        rec = self._get(pubkey)
        if not rec.may_sign_block(slot):
            return False
        rec.block_slot = slot
        self._persist(pubkey)
        return True

    def may_sign_attestation(self, pubkey: bytes, source_epoch: int,
                             target_epoch: int) -> bool:
        rec = self._get(pubkey)
        if not rec.may_sign_attestation(source_epoch, target_epoch):
            return False
        rec.source_epoch = source_epoch
        rec.target_epoch = target_epoch
        self._persist(pubkey)
        return True

    # -- EIP-3076 interchange -----------------------------------------
    def export_interchange(self, genesis_validators_root: bytes) -> dict:
        return {
            "metadata": {
                "interchange_format_version": "5",
                "genesis_validators_root":
                    "0x" + genesis_validators_root.hex(),
            },
            "data": [
                {
                    "pubkey": "0x" + pk.hex(),
                    "signed_blocks": (
                        [{"slot": str(rec.block_slot)}]
                        if rec.block_slot else []),
                    "signed_attestations": (
                        [{"source_epoch": str(rec.source_epoch),
                          "target_epoch": str(rec.target_epoch)}]
                        if rec.target_epoch is not None else []),
                }
                for pk, rec in sorted(self._records.items())
            ],
        }

    def import_interchange(self, doc: dict,
                           genesis_validators_root: bytes) -> int:
        meta_root = doc["metadata"]["genesis_validators_root"]
        if bytes.fromhex(meta_root[2:]) != genesis_validators_root:
            raise ValueError("interchange for a different chain")
        n = 0
        for entry in doc["data"]:
            pk = bytes.fromhex(entry["pubkey"][2:])
            rec = self._get(pk)
            for sb in entry.get("signed_blocks", ()):
                rec.block_slot = max(rec.block_slot, int(sb["slot"]))
            for sa in entry.get("signed_attestations", ()):
                src, tgt = int(sa["source_epoch"]), int(sa["target_epoch"])
                if rec.source_epoch is None or src > rec.source_epoch:
                    rec.source_epoch = src
                if rec.target_epoch is None or tgt > rec.target_epoch:
                    rec.target_epoch = tgt
            self._persist(pk)
            n += 1
        return n
