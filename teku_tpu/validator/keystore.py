"""EIP-2335 BLS keystores: scrypt/pbkdf2 KDF + AES-128-CTR + sha256.

Equivalent of the reference's bls-keystore module (reference:
infrastructure/bls-keystore/src/main/java/tech/pegasys/teku/bls/
keystore/KeyStore.java, KeyStoreLoader.java): load/decrypt/create the
standard encrypted keystore JSON the validator client and key-manager
API exchange.  Validated against the reference's own test vectors
(infrastructure/bls-keystore/src/test/resources/).
"""

import hashlib
import json
import secrets
import unicodedata
import uuid as uuid_mod
from pathlib import Path
from typing import Optional, Union

from cryptography.hazmat.primitives.ciphers import (algorithms, Cipher,
                                                    modes)


class KeystoreError(ValueError):
    """Malformed keystore or wrong password."""


def _normalize_password(password: str) -> bytes:
    """EIP-2335: NFKD normalize, strip C0/C1 control codes + DEL."""
    norm = unicodedata.normalize("NFKD", password)
    stripped = "".join(
        c for c in norm
        if not (ord(c) < 0x20 or 0x7F <= ord(c) <= 0x9F))
    return stripped.encode("utf-8")


def _kdf(crypto: dict, password: bytes) -> bytes:
    kdf = crypto["kdf"]
    params = kdf["params"]
    salt = bytes.fromhex(params["salt"])
    if kdf["function"] == "scrypt":
        return hashlib.scrypt(
            password, salt=salt, n=params["n"], r=params["r"],
            p=params["p"], dklen=params["dklen"],
            maxmem=2 ** 31 - 1)
    if kdf["function"] == "pbkdf2":
        if params.get("prf", "hmac-sha256") != "hmac-sha256":
            raise KeystoreError(f"unsupported prf {params.get('prf')}")
        return hashlib.pbkdf2_hmac("sha256", password, salt,
                                   params["c"], dklen=params["dklen"])
    raise KeystoreError(f"unsupported kdf {kdf['function']!r}")


def _checksum(dk: bytes, cipher_message: bytes) -> bytes:
    return hashlib.sha256(dk[16:32] + cipher_message).digest()


def decrypt(keystore: Union[dict, str, Path], password: str) -> bytes:
    """Returns the 32-byte secret, raising on bad password/format."""
    if isinstance(keystore, (str, Path)):
        keystore = json.loads(Path(keystore).read_text())
    if keystore.get("version") != 4:
        raise KeystoreError(f"unsupported version {keystore.get('version')}")
    crypto = keystore["crypto"]
    if crypto["checksum"]["function"] != "sha256":
        raise KeystoreError("unsupported checksum function")
    if crypto["cipher"]["function"] != "aes-128-ctr":
        raise KeystoreError("unsupported cipher function")
    dk = _kdf(crypto, _normalize_password(password))
    cipher_message = bytes.fromhex(crypto["cipher"]["message"])
    if _checksum(dk, cipher_message) != bytes.fromhex(
            crypto["checksum"]["message"]):
        raise KeystoreError("checksum mismatch (wrong password?)")
    iv = bytes.fromhex(crypto["cipher"]["params"]["iv"])
    decryptor = Cipher(algorithms.AES(dk[:16]),
                       modes.CTR(iv)).decryptor()
    return decryptor.update(cipher_message) + decryptor.finalize()


def encrypt(secret: bytes, password: str, *,
            kdf: str = "scrypt", path: str = "",
            pubkey: Optional[bytes] = None,
            description: str = "") -> dict:
    """Create a version-4 keystore dict for the 32-byte secret."""
    assert len(secret) == 32
    salt = secrets.token_bytes(32)
    pw = _normalize_password(password)
    if kdf == "scrypt":
        kdf_obj = {"function": "scrypt",
                   "params": {"dklen": 32, "n": 262144, "r": 8, "p": 1,
                              "salt": salt.hex()},
                   "message": ""}
        dk = hashlib.scrypt(pw, salt=salt, n=262144, r=8, p=1, dklen=32,
                            maxmem=2 ** 31 - 1)
    elif kdf == "pbkdf2":
        kdf_obj = {"function": "pbkdf2",
                   "params": {"dklen": 32, "c": 262144,
                              "prf": "hmac-sha256", "salt": salt.hex()},
                   "message": ""}
        dk = hashlib.pbkdf2_hmac("sha256", pw, salt, 262144, dklen=32)
    else:
        raise KeystoreError(f"unsupported kdf {kdf!r}")
    iv = secrets.token_bytes(16)
    encryptor = Cipher(algorithms.AES(dk[:16]), modes.CTR(iv)).encryptor()
    cipher_message = encryptor.update(secret) + encryptor.finalize()
    return {
        "crypto": {
            "kdf": kdf_obj,
            "checksum": {"function": "sha256", "params": {},
                         "message": _checksum(dk, cipher_message).hex()},
            "cipher": {"function": "aes-128-ctr",
                       "params": {"iv": iv.hex()},
                       "message": cipher_message.hex()},
        },
        "description": description,
        "pubkey": pubkey.hex() if pubkey else "",
        "path": path,
        "uuid": str(uuid_mod.uuid4()),
        "version": 4,
    }


def load_directory(keys_dir: Union[str, Path],
                   passwords_dir: Union[str, Path]) -> dict:
    """Load every keystore in `keys_dir`, password file of the same stem
    in `passwords_dir` (the reference's --validator-keys dir:dir layout,
    validator/client/loader/).  Returns {pubkey_bytes: secret_int}."""
    out = {}
    keys_dir, passwords_dir = Path(keys_dir), Path(passwords_dir)
    for ks_path in sorted(keys_dir.glob("*.json")):
        pw_path = passwords_dir / (ks_path.stem + ".txt")
        password = pw_path.read_text().strip()
        ks = json.loads(ks_path.read_text())
        secret = decrypt(ks, password)
        secret_int = int.from_bytes(secret, "big")
        pubkey = bytes.fromhex(ks.get("pubkey") or "")
        if not pubkey:
            # EIP-2335 allows an absent pubkey — derive it, or every
            # such keystore would collide on b"" and be dropped
            from ..crypto import bls
            pubkey = bls.secret_to_public_key(secret_int)
        out[pubkey] = secret_int
    return out
