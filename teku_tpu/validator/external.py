"""External (Web3Signer-style) remote signing + multi-BN failover.

Equivalent of the reference's remote-signing and failover stack
(reference: validator/client/src/main/java/tech/pegasys/teku/validator/
client/signer/ExternalSigner.java:68 — HTTP POST
/api/v1/eth2/sign/{pubkey} with a typed body and the locally-computed
signing root; validator/remote/.../FailoverValidatorApiHandler.java:69
— an ordered list of beacon nodes, requests start at the last healthy
one and fail over on error, sticky until the next failure).

The signing ROOT is always computed locally (the same SigningRootUtil
math as LocalSigner), so a compromised signer service can be detected
by verifying returned signatures and can never trick the VC into
signing a different message than its duty.
"""

import json
import logging
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence

from ..spec import helpers as H
from ..ssz.json import _hex
from ..spec.config import (DOMAIN_AGGREGATE_AND_PROOF,
                           DOMAIN_BEACON_ATTESTER,
                           DOMAIN_BEACON_PROPOSER, SpecConfig)
from .api import ValidatorApiChannel
from .signer import DutySigner, SigningError

_LOG = logging.getLogger(__name__)


class ExternalSigner(DutySigner):
    """Signs duties through a Web3Signer-compatible HTTP API.

    `pubkeys_by_index` maps validator indices to the BLS public keys
    the signing service holds; every response signature is verified
    against the locally-computed root before it is used."""

    def __init__(self, base_url: str,
                 pubkeys_by_index: Dict[int, bytes],
                 timeout: float = 10.0, verify: bool = True):
        self.base = base_url.rstrip("/")
        self.pubkeys = dict(pubkeys_by_index)
        self.timeout = timeout
        self.verify = verify

    # -- HTTP ----------------------------------------------------------
    def _sign(self, validator_index: int, root: bytes, duty_type: str,
              extra: Optional[Dict] = None) -> bytes:
        pubkey = self.pubkeys.get(validator_index)
        if pubkey is None:
            raise SigningError(f"no pubkey for validator "
                               f"{validator_index}")
        # a conforming Web3Signer requires fork_info + the typed duty
        # payload (it reads the slot/epoch for its own slashing
        # protection); signingRoot alone is rejected (reference:
        # ExternalSigner.java request bodies)
        payload = {"type": duty_type,
                   "signingRoot": "0x" + root.hex()}
        if extra:
            payload.update(extra)
        body = json.dumps(payload).encode()
        req = urllib.request.Request(
            f"{self.base}/api/v1/eth2/sign/0x{pubkey.hex()}",
            data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                out = json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                raise SigningError("signer does not hold this key")
            if exc.code == 412:
                # Web3Signer's own slashing protection refused
                raise SigningError("external signer refused "
                                   "(slashing risk)")
            raise SigningError(f"external signer HTTP {exc.code}")
        except OSError as exc:
            raise SigningError(f"external signer unreachable: {exc}")
        try:
            raw = out["signature"]
            signature = bytes.fromhex(
                raw[2:] if raw.startswith("0x") else raw)
            if len(signature) != 96:
                raise ValueError("wrong signature length")
        except (KeyError, ValueError, TypeError, AttributeError) as exc:
            raise SigningError(f"malformed signer response: {exc}")
        if self.verify:
            from ..crypto import bls
            if not bls.verify(pubkey, root, signature):
                raise SigningError(
                    "external signer returned an invalid signature")
        return signature

    def upcheck(self) -> bool:
        try:
            with urllib.request.urlopen(f"{self.base}/upcheck",
                                        timeout=self.timeout) as resp:
                return resp.status == 200
        except OSError:
            return False

    def public_keys(self) -> List[bytes]:
        with urllib.request.urlopen(
                f"{self.base}/api/v1/eth2/publicKeys",
                timeout=self.timeout) as resp:
            return [bytes.fromhex(k[2:]) for k in
                    json.loads(resp.read())]

    # -- DutySigner surface (roots computed locally) -------------------
    def sign_block(self, cfg: SpecConfig, state, block) -> bytes:
        domain = H.get_domain(cfg, state, DOMAIN_BEACON_PROPOSER,
                              H.compute_epoch_at_slot(cfg, block.slot))
        header = {"slot": str(block.slot),
                  "proposer_index": str(block.proposer_index),
                  "parent_root": _hex(block.parent_root),
                  "state_root": _hex(block.state_root),
                  "body_root": _hex(block.body.htr())}
        return self._sign(
            block.proposer_index,
            H.compute_signing_root(block, domain), "BLOCK_V2",
            {"fork_info": _fork_info(state),
             "beacon_block": {"version": _milestone_name(cfg, block.slot),
                              "block_header": header}})

    def sign_attestation_data(self, cfg, state, data,
                              validator_index) -> bytes:
        domain = H.get_domain(cfg, state, DOMAIN_BEACON_ATTESTER,
                              data.target.epoch)
        return self._sign(validator_index,
                          H.compute_signing_root(data, domain),
                          "ATTESTATION",
                          {"fork_info": _fork_info(state),
                           "attestation": _container_json(data)})

    def sign_randao_reveal(self, cfg, state, epoch,
                           validator_index) -> bytes:
        return self._sign(validator_index,
                          H.randao_signing_root(cfg, state, epoch),
                          "RANDAO_REVEAL",
                          {"fork_info": _fork_info(state),
                           "randao_reveal": {"epoch": str(epoch)}})

    def sign_aggregate_and_proof(self, cfg, state, msg) -> bytes:
        domain = H.get_domain(
            cfg, state, DOMAIN_AGGREGATE_AND_PROOF,
            H.compute_epoch_at_slot(cfg, msg.aggregate.data.slot))
        return self._sign(msg.aggregator_index,
                          H.compute_signing_root(msg, domain),
                          "AGGREGATE_AND_PROOF",
                          {"fork_info": _fork_info(state),
                           "aggregate_and_proof": _container_json(msg)})

    def sign_selection_proof(self, cfg, state, slot,
                             validator_index) -> bytes:
        return self._sign(
            validator_index,
            H.selection_proof_signing_root(cfg, state, slot),
            "AGGREGATION_SLOT",
            {"fork_info": _fork_info(state),
             "aggregation_slot": {"slot": str(slot)}})

    def sign_sync_committee_message(self, cfg, state, slot, block_root,
                                    validator_index) -> bytes:
        from ..spec.altair.helpers import sync_message_signing_root
        return self._sign(validator_index,
                          sync_message_signing_root(cfg, state, slot,
                                                    block_root),
                          "SYNC_COMMITTEE_MESSAGE",
                          {"fork_info": _fork_info(state),
                           "sync_committee_message": {
                               "beacon_block_root": _hex(block_root),
                               "slot": str(slot)}})

    def sign_sync_selection_proof(self, cfg, state, slot,
                                  subcommittee_index,
                                  validator_index) -> bytes:
        from ..spec.altair.helpers import (
            sync_selection_proof_signing_root)
        return self._sign(
            validator_index,
            sync_selection_proof_signing_root(cfg, state, slot,
                                              subcommittee_index),
            "SYNC_COMMITTEE_SELECTION_PROOF",
            {"fork_info": _fork_info(state),
             "sync_aggregator_selection_data": {
                 "slot": str(slot),
                 "subcommittee_index": str(subcommittee_index)}})

    def sign_contribution_and_proof(self, cfg, state, msg) -> bytes:
        from ..spec.altair.helpers import (
            contribution_and_proof_signing_root)
        return self._sign(
            msg.aggregator_index,
            contribution_and_proof_signing_root(cfg, state, msg),
            "SYNC_COMMITTEE_CONTRIBUTION_AND_PROOF",
            {"fork_info": _fork_info(state),
             "contribution_and_proof": _container_json(msg)})


def _container_json(obj):
    """SSZ container -> Web3Signer JSON shape: the schema-driven walk
    (bitfields MUST serialize as hex strings, not bool arrays — a
    conforming Web3Signer rejects the latter)."""
    from ..ssz.json import ssz_to_json
    return ssz_to_json(type(obj), obj)


def _fork_info(state) -> Dict:
    f = state.fork
    return {"fork": {"previous_version": _hex(f.previous_version),
                     "current_version": _hex(f.current_version),
                     "epoch": str(f.epoch)},
            "genesis_validators_root":
                _hex(state.genesis_validators_root)}


def _milestone_name(cfg, slot) -> str:
    epoch = H.compute_epoch_at_slot(cfg, slot)
    names = (("ELECTRA_FORK_EPOCH", "ELECTRA"),
             ("DENEB_FORK_EPOCH", "DENEB"),
             ("CAPELLA_FORK_EPOCH", "CAPELLA"),
             ("BELLATRIX_FORK_EPOCH", "BELLATRIX"),
             ("ALTAIR_FORK_EPOCH", "ALTAIR"))
    for attr, name in names:
        if epoch >= getattr(cfg, attr, 2 ** 63):
            return name
    return "PHASE0"


class FailoverError(Exception):
    pass


class FailoverValidatorApi(ValidatorApiChannel):
    """Wraps an ordered list of ValidatorApiChannels: requests go to
    the last-known-healthy node first and fail over in order on ANY
    error, sticky until the next failure (reference
    FailoverValidatorApiHandler.java:69)."""

    def __init__(self, channels: Sequence[ValidatorApiChannel]):
        assert channels, "need at least one beacon node"
        self.channels = list(channels)
        self._current = 0
        self.failovers = 0

    def _iter(self):
        # snapshot: a concurrent request's failover mid-iteration must
        # not make THIS request revisit a node it already saw (and
        # never reach the healthy one)
        start = self._current
        n = len(self.channels)
        for k in range(n):
            yield (start + k) % n

    def _sync(self, name, *args, **kw):
        errors = []
        for idx in self._iter():
            try:
                out = getattr(self.channels[idx], name)(*args, **kw)
                if idx != self._current:
                    _LOG.warning("failover: switched to beacon node %d",
                                 idx)
                    self.failovers += 1
                    self._current = idx
                return out
            except Exception as exc:
                errors.append((idx, exc))
        raise FailoverError(f"{name} failed on every beacon node: "
                            f"{errors}")

    async def _async(self, name, *args, **kw):
        errors = []
        for idx in self._iter():
            try:
                out = await getattr(self.channels[idx], name)(*args,
                                                              **kw)
                if idx != self._current:
                    _LOG.warning("failover: switched to beacon node %d",
                                 idx)
                    self.failovers += 1
                    self._current = idx
                return out
            except Exception as exc:
                errors.append((idx, exc))
        raise FailoverError(f"{name} failed on every beacon node: "
                            f"{errors}")

    # -- sync surface --------------------------------------------------
    def get_proposer_duties(self, epoch):
        return self._sync("get_proposer_duties", epoch)

    def get_attester_duties(self, epoch, indices):
        return self._sync("get_attester_duties", epoch, indices)

    def get_sync_duties(self, epoch, indices):
        return self._sync("get_sync_duties", epoch, indices)

    def get_attestation_data(self, slot, committee_index):
        return self._sync("get_attestation_data", slot, committee_index)

    def get_aggregate(self, data, committee_index=None):
        return self._sync("get_aggregate", data, committee_index)

    def duty_state(self, slot):
        return self._sync("duty_state", slot)

    def head_root(self):
        return self._sync("head_root")

    def build_sync_contribution(self, slot, block_root,
                                subcommittee_index):
        return self._sync("build_sync_contribution", slot, block_root,
                          subcommittee_index)

    # -- async surface -------------------------------------------------
    async def produce_unsigned_block(self, slot, randao_reveal,
                                     graffiti=bytes(32)):
        return await self._async("produce_unsigned_block", slot,
                                 randao_reveal, graffiti)

    async def publish_signed_block(self, signed_block):
        return await self._async("publish_signed_block", signed_block)

    async def publish_attestation(self, attestation):
        return await self._async("publish_attestation", attestation)

    async def publish_aggregate_and_proof(self, signed_aggregate):
        return await self._async("publish_aggregate_and_proof",
                                 signed_aggregate)

    async def publish_sync_committee_messages(self, msgs):
        return await self._async("publish_sync_committee_messages",
                                 msgs)

    async def publish_sync_committee_message(self, msg):
        return await self._async("publish_sync_committee_message", msg)

    async def publish_contribution_and_proof(self, signed):
        return await self._async("publish_contribution_and_proof",
                                 signed)
