"""External (Web3Signer-style) remote signing + multi-BN failover.

Equivalent of the reference's remote-signing and failover stack
(reference: validator/client/src/main/java/tech/pegasys/teku/validator/
client/signer/ExternalSigner.java:68 — HTTP POST
/api/v1/eth2/sign/{pubkey} with a typed body and the locally-computed
signing root; validator/remote/.../FailoverValidatorApiHandler.java:69
— an ordered list of beacon nodes, requests start at the last healthy
one and fail over on error, sticky until the next failure).

The signing ROOT is always computed locally (the same SigningRootUtil
math as LocalSigner), so a compromised signer service can be detected
by verifying returned signatures and can never trick the VC into
signing a different message than its duty.
"""

import json
import logging
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Sequence

from ..spec import helpers as H
from ..spec.config import (DOMAIN_AGGREGATE_AND_PROOF,
                           DOMAIN_BEACON_ATTESTER,
                           DOMAIN_BEACON_PROPOSER, SpecConfig)
from .api import ValidatorApiChannel
from .signer import DutySigner, SigningError

_LOG = logging.getLogger(__name__)


class ExternalSigner(DutySigner):
    """Signs duties through a Web3Signer-compatible HTTP API.

    `pubkeys_by_index` maps validator indices to the BLS public keys
    the signing service holds; every response signature is verified
    against the locally-computed root before it is used."""

    def __init__(self, base_url: str,
                 pubkeys_by_index: Dict[int, bytes],
                 timeout: float = 10.0, verify: bool = True):
        self.base = base_url.rstrip("/")
        self.pubkeys = dict(pubkeys_by_index)
        self.timeout = timeout
        self.verify = verify

    # -- HTTP ----------------------------------------------------------
    def _sign(self, validator_index: int, root: bytes,
              duty_type: str) -> bytes:
        pubkey = self.pubkeys.get(validator_index)
        if pubkey is None:
            raise SigningError(f"no pubkey for validator "
                               f"{validator_index}")
        body = json.dumps({"type": duty_type,
                           "signingRoot": "0x" + root.hex()}).encode()
        req = urllib.request.Request(
            f"{self.base}/api/v1/eth2/sign/0x{pubkey.hex()}",
            data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout) as resp:
                out = json.loads(resp.read())
        except urllib.error.HTTPError as exc:
            if exc.code == 404:
                raise SigningError("signer does not hold this key")
            if exc.code == 412:
                # Web3Signer's own slashing protection refused
                raise SigningError("external signer refused "
                                   "(slashing risk)")
            raise SigningError(f"external signer HTTP {exc.code}")
        except OSError as exc:
            raise SigningError(f"external signer unreachable: {exc}")
        try:
            raw = out["signature"]
            signature = bytes.fromhex(
                raw[2:] if raw.startswith("0x") else raw)
            if len(signature) != 96:
                raise ValueError("wrong signature length")
        except (KeyError, ValueError, TypeError, AttributeError) as exc:
            raise SigningError(f"malformed signer response: {exc}")
        if self.verify:
            from ..crypto import bls
            if not bls.verify(pubkey, root, signature):
                raise SigningError(
                    "external signer returned an invalid signature")
        return signature

    def upcheck(self) -> bool:
        try:
            with urllib.request.urlopen(f"{self.base}/upcheck",
                                        timeout=self.timeout) as resp:
                return resp.status == 200
        except OSError:
            return False

    def public_keys(self) -> List[bytes]:
        with urllib.request.urlopen(
                f"{self.base}/api/v1/eth2/publicKeys",
                timeout=self.timeout) as resp:
            return [bytes.fromhex(k[2:]) for k in
                    json.loads(resp.read())]

    # -- DutySigner surface (roots computed locally) -------------------
    def sign_block(self, cfg: SpecConfig, state, block) -> bytes:
        domain = H.get_domain(cfg, state, DOMAIN_BEACON_PROPOSER,
                              H.compute_epoch_at_slot(cfg, block.slot))
        return self._sign(block.proposer_index,
                          H.compute_signing_root(block, domain),
                          "BLOCK_V2")

    def sign_attestation_data(self, cfg, state, data,
                              validator_index) -> bytes:
        domain = H.get_domain(cfg, state, DOMAIN_BEACON_ATTESTER,
                              data.target.epoch)
        return self._sign(validator_index,
                          H.compute_signing_root(data, domain),
                          "ATTESTATION")

    def sign_randao_reveal(self, cfg, state, epoch,
                           validator_index) -> bytes:
        return self._sign(validator_index,
                          H.randao_signing_root(cfg, state, epoch),
                          "RANDAO_REVEAL")

    def sign_aggregate_and_proof(self, cfg, state, msg) -> bytes:
        domain = H.get_domain(
            cfg, state, DOMAIN_AGGREGATE_AND_PROOF,
            H.compute_epoch_at_slot(cfg, msg.aggregate.data.slot))
        return self._sign(msg.aggregator_index,
                          H.compute_signing_root(msg, domain),
                          "AGGREGATE_AND_PROOF")

    def sign_selection_proof(self, cfg, state, slot,
                             validator_index) -> bytes:
        return self._sign(
            validator_index,
            H.selection_proof_signing_root(cfg, state, slot),
            "AGGREGATION_SLOT")

    def sign_sync_committee_message(self, cfg, state, slot, block_root,
                                    validator_index) -> bytes:
        from ..spec.altair.helpers import sync_message_signing_root
        return self._sign(validator_index,
                          sync_message_signing_root(cfg, state, slot,
                                                    block_root),
                          "SYNC_COMMITTEE_MESSAGE")

    def sign_sync_selection_proof(self, cfg, state, slot,
                                  subcommittee_index,
                                  validator_index) -> bytes:
        from ..spec.altair.helpers import (
            sync_selection_proof_signing_root)
        return self._sign(
            validator_index,
            sync_selection_proof_signing_root(cfg, state, slot,
                                              subcommittee_index),
            "SYNC_COMMITTEE_SELECTION_PROOF")

    def sign_contribution_and_proof(self, cfg, state, msg) -> bytes:
        from ..spec.altair.helpers import (
            contribution_and_proof_signing_root)
        return self._sign(
            msg.aggregator_index,
            contribution_and_proof_signing_root(cfg, state, msg),
            "SYNC_COMMITTEE_CONTRIBUTION_AND_PROOF")


class FailoverError(Exception):
    pass


class FailoverValidatorApi(ValidatorApiChannel):
    """Wraps an ordered list of ValidatorApiChannels: requests go to
    the last-known-healthy node first and fail over in order on ANY
    error, sticky until the next failure (reference
    FailoverValidatorApiHandler.java:69)."""

    def __init__(self, channels: Sequence[ValidatorApiChannel]):
        assert channels, "need at least one beacon node"
        self.channels = list(channels)
        self._current = 0
        self.failovers = 0

    def _iter(self):
        # snapshot: a concurrent request's failover mid-iteration must
        # not make THIS request revisit a node it already saw (and
        # never reach the healthy one)
        start = self._current
        n = len(self.channels)
        for k in range(n):
            yield (start + k) % n

    def _sync(self, name, *args, **kw):
        errors = []
        for idx in self._iter():
            try:
                out = getattr(self.channels[idx], name)(*args, **kw)
                if idx != self._current:
                    _LOG.warning("failover: switched to beacon node %d",
                                 idx)
                    self.failovers += 1
                    self._current = idx
                return out
            except Exception as exc:
                errors.append((idx, exc))
        raise FailoverError(f"{name} failed on every beacon node: "
                            f"{errors}")

    async def _async(self, name, *args, **kw):
        errors = []
        for idx in self._iter():
            try:
                out = await getattr(self.channels[idx], name)(*args,
                                                              **kw)
                if idx != self._current:
                    _LOG.warning("failover: switched to beacon node %d",
                                 idx)
                    self.failovers += 1
                    self._current = idx
                return out
            except Exception as exc:
                errors.append((idx, exc))
        raise FailoverError(f"{name} failed on every beacon node: "
                            f"{errors}")

    # -- sync surface --------------------------------------------------
    def get_proposer_duties(self, epoch):
        return self._sync("get_proposer_duties", epoch)

    def get_attester_duties(self, epoch, indices):
        return self._sync("get_attester_duties", epoch, indices)

    def get_sync_duties(self, epoch, indices):
        return self._sync("get_sync_duties", epoch, indices)

    def get_attestation_data(self, slot, committee_index):
        return self._sync("get_attestation_data", slot, committee_index)

    def get_aggregate(self, data, committee_index=None):
        return self._sync("get_aggregate", data, committee_index)

    def duty_state(self, slot):
        return self._sync("duty_state", slot)

    def head_root(self):
        return self._sync("head_root")

    def build_sync_contribution(self, slot, block_root,
                                subcommittee_index):
        return self._sync("build_sync_contribution", slot, block_root,
                          subcommittee_index)

    # -- async surface -------------------------------------------------
    async def produce_unsigned_block(self, slot, randao_reveal,
                                     graffiti=bytes(32)):
        return await self._async("produce_unsigned_block", slot,
                                 randao_reveal, graffiti)

    async def publish_signed_block(self, signed_block):
        return await self._async("publish_signed_block", signed_block)

    async def publish_attestation(self, attestation):
        return await self._async("publish_attestation", attestation)

    async def publish_aggregate_and_proof(self, signed_aggregate):
        return await self._async("publish_aggregate_and_proof",
                                 signed_aggregate)

    async def publish_sync_committee_messages(self, msgs):
        return await self._async("publish_sync_committee_messages",
                                 msgs)

    async def publish_sync_committee_message(self, msg):
        return await self._async("publish_sync_committee_message", msg)

    async def publish_contribution_and_proof(self, signed):
        return await self._async("publish_contribution_and_proof",
                                 signed)
