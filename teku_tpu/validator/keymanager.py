"""Key-manager REST API: list/import/delete validator keystores.

Equivalent of the reference's EIP-3076-aware key-manager API on the
validator client (reference: validator/client/restapi/ — the standard
keymanager endpoints on :5052): keystores live in a directory, imports
decrypt + register with the running client, deletes export the
validator's slashing-protection record alongside.
"""

import json
import logging
from pathlib import Path
from typing import Dict, Optional

from ..crypto import bls
from ..infra.restapi import HttpError, RestApi
from .keystore import decrypt, KeystoreError

_LOG = logging.getLogger(__name__)


class KeyManagerApi(RestApi):
    def __init__(self, keys_dir, protector=None, on_key_added=None,
                 on_key_removed=None, host: str = "127.0.0.1",
                 port: int = 0):
        super().__init__(host, port)
        self.keys_dir = Path(keys_dir)
        self.keys_dir.mkdir(parents=True, exist_ok=True)
        self.protector = protector
        self.on_key_added = on_key_added
        self.on_key_removed = on_key_removed
        # pubkey hex (no 0x) -> secret int, for keys loaded this session
        self.active: Dict[str, int] = {}
        self.get("/eth/v1/keystores", self._list)
        self.post("/eth/v1/keystores", self._import)
        self.route("DELETE", "/eth/v1/keystores", self._delete)

    async def _list(self):
        out = []
        for f in sorted(self.keys_dir.glob("*.json")):
            try:
                ks = json.loads(f.read_text())
            except json.JSONDecodeError:
                continue
            out.append({"validating_pubkey": "0x" + ks.get("pubkey", ""),
                        "derivation_path": ks.get("path", ""),
                        "readonly": False})
        return {"data": out}

    async def _import(self, body=None):
        if not isinstance(body, dict):
            raise HttpError(400, "expected an import request object")
        keystores = body.get("keystores", [])
        passwords = body.get("passwords", [])
        if len(keystores) != len(passwords):
            raise HttpError(400, "keystores/passwords length mismatch")
        statuses = []
        for ks_json, password in zip(keystores, passwords):
            try:
                ks = (json.loads(ks_json) if isinstance(ks_json, str)
                      else ks_json)
                secret = decrypt(ks, password)
                secret_int = int.from_bytes(secret, "big")
                pubkey = ks.get("pubkey") or bls.secret_to_public_key(
                    secret_int).hex()
                (self.keys_dir / f"{pubkey[:16]}.json").write_text(
                    json.dumps(ks))
                self.active[pubkey] = secret_int
                if self.on_key_added:
                    self.on_key_added(bytes.fromhex(pubkey), secret_int)
                statuses.append({"status": "imported", "message": ""})
            except (KeystoreError, ValueError, KeyError) as exc:
                statuses.append({"status": "error", "message": str(exc)})
        return {"data": statuses}

    async def _delete(self, body=None):
        if not isinstance(body, dict):
            raise HttpError(400, "expected a delete request object")
        statuses = []
        interchange = {"metadata": {
            "interchange_format_version": "5",
            "genesis_validators_root": "0x" + "00" * 32}, "data": []}
        for pk_hex in body.get("pubkeys", []):
            pk_hex = pk_hex.removeprefix("0x")
            found = False
            for f in self.keys_dir.glob("*.json"):
                try:
                    ks = json.loads(f.read_text())
                except json.JSONDecodeError:
                    continue
                if ks.get("pubkey") == pk_hex:
                    f.unlink()
                    found = True
                    break
            self.active.pop(pk_hex, None)
            if self.on_key_removed and found:
                self.on_key_removed(bytes.fromhex(pk_hex))
            if found and self.protector is not None:
                doc = self.protector.export_interchange(b"\x00" * 32)
                interchange["data"] = [
                    e for e in doc["data"]
                    if e["pubkey"] == "0x" + pk_hex]
            statuses.append({"status": "deleted" if found
                             else "not_found", "message": ""})
        return {"data": statuses,
                "slashing_protection": json.dumps(interchange)}
