"""ValidatorApiChannel: the BN↔VC seam.

Equivalent of the reference's ValidatorApiChannel + ValidatorApiHandler
(reference: validator/api/src/main/java/tech/pegasys/teku/validator/api/
ValidatorApiChannel.java:52 and beacon/validator/.../coordinator/
ValidatorApiHandler.java): duties queries, unsigned production,
submission.  The in-process implementation binds directly to a
BeaconNode (reference InProcessBeaconNodeApi); a remote implementation
can speak the REST API instead without the client changing.
"""

import logging
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..spec import helpers as H
from ..spec.builder import attestation_data_for, build_unsigned_block
from ..node.gossip import (AGGREGATE_TOPIC, attestation_subnet_topic,
                           BEACON_BLOCK_TOPIC)
from ..node.node import BeaconNode, compute_subnet_for_attestation

_LOG = logging.getLogger(__name__)


@dataclass
class AttesterDuty:
    validator_index: int
    slot: int
    committee_index: int
    committee_position: int
    committee_size: int
    committees_at_slot: int


@dataclass
class ProposerDuty:
    validator_index: int
    slot: int


@dataclass
class SyncDuty:
    """One validator's sync-committee membership for an epoch
    (reference validator/api SyncCommitteeDuty /
    PostSyncDuties.java:43): the committee positions double as the
    subcommittee assignment (position // subcommittee_size)."""
    validator_index: int
    pubkey: bytes
    positions: tuple          # indices into the sync committee


class ValidatorApiChannel:
    """The full duty surface the VC consumes."""

    def get_proposer_duties(self, epoch: int) -> List[ProposerDuty]:
        raise NotImplementedError

    def get_attester_duties(self, epoch: int,
                            indices: Sequence[int]) -> List[AttesterDuty]:
        raise NotImplementedError

    def get_sync_duties(self, epoch: int,
                        indices: Sequence[int]) -> List[SyncDuty]:
        raise NotImplementedError

    def get_attestation_data(self, slot: int, committee_index: int):
        raise NotImplementedError

    async def produce_unsigned_block(self, slot: int, randao_reveal: bytes,
                                     graffiti: bytes):
        raise NotImplementedError

    async def publish_signed_block(self, signed_block) -> None:
        raise NotImplementedError

    async def publish_attestation(self, attestation) -> None:
        raise NotImplementedError

    def get_aggregate(self, data, committee_index=None):
        """Best pooled aggregate for `data` (electra duties pass their
        committee_index — the data alone no longer names one)."""
        raise NotImplementedError

    async def publish_sync_committee_messages(self, msgs) -> None:
        """One slot's sync messages as a batch; the default fans out to
        the singular publish (remote implementations override to send
        ONE request per slot instead of one per validator)."""
        for msg in msgs:
            await self.publish_sync_committee_message(msg)

    async def publish_aggregate_and_proof(self, signed_aggregate) -> None:
        raise NotImplementedError

    def duty_state(self, slot: int):
        """Head state advanced to `slot` (signing context)."""
        raise NotImplementedError

    def head_root(self) -> bytes:
        raise NotImplementedError


class BeaconNodeValidatorApi(ValidatorApiChannel):
    """In-process binding to one BeaconNode."""

    def __init__(self, node: BeaconNode):
        self.node = node
        self.spec = node.spec

    # -- duties --------------------------------------------------------
    def get_proposer_duties(self, epoch: int) -> List[ProposerDuty]:
        cfg = self.spec.config
        out = []
        first = H.compute_start_slot_at_epoch(cfg, epoch)
        # advance ONE state incrementally across the epoch's slots: the
        # expensive epoch-boundary transition runs once, not per slot
        from ..spec.transition import process_slots
        state = self.node.advanced_head_state(max(first, 1))
        for slot in range(max(first, 1), first + cfg.SLOTS_PER_EPOCH):
            if state.slot < slot:
                state = process_slots(cfg, state, slot)
            out.append(ProposerDuty(
                validator_index=H.get_beacon_proposer_index(cfg, state),
                slot=slot))
        return out

    def get_attester_duties(self, epoch: int,
                            indices: Sequence[int]) -> List[AttesterDuty]:
        cfg = self.spec.config
        wanted = set(indices)
        out = []
        first = H.compute_start_slot_at_epoch(cfg, epoch)
        state = self.node.advanced_head_state(max(first, 1))
        committees = H.get_committee_count_per_slot(cfg, state, epoch)
        for slot in range(first, first + cfg.SLOTS_PER_EPOCH):
            for ci in range(committees):
                committee = H.get_beacon_committee(cfg, state, slot, ci)
                for pos, vi in enumerate(committee):
                    if vi in wanted:
                        out.append(AttesterDuty(
                            validator_index=vi, slot=slot,
                            committee_index=ci, committee_position=pos,
                            committee_size=len(committee),
                            committees_at_slot=committees))
        return out

    def get_sync_duties(self, epoch: int,
                        indices: Sequence[int]) -> List[SyncDuty]:
        """Membership in the sync committee covering `epoch`
        (reference ValidatorApiHandler.getSyncCommitteeDuties)."""
        cfg = self.spec.config
        first = H.compute_start_slot_at_epoch(cfg, epoch)
        state = self.node.advanced_head_state(max(first, 1))
        if not hasattr(state, "current_sync_committee"):
            return []
        wanted = set(indices)
        by_pubkey: Dict[bytes, int] = {}
        for vi in wanted:
            if vi < len(state.validators):
                by_pubkey[state.validators[vi].pubkey] = vi
        positions: Dict[int, list] = {}
        for pos, pk in enumerate(state.current_sync_committee.pubkeys):
            vi = by_pubkey.get(pk)
            if vi is not None:
                positions.setdefault(vi, []).append(pos)
        return [SyncDuty(validator_index=vi,
                         pubkey=state.validators[vi].pubkey,
                         positions=tuple(pos_list))
                for vi, pos_list in sorted(positions.items())]

    # -- production ----------------------------------------------------
    def duty_state(self, slot: int):
        return self.node.advanced_head_state(slot)

    def head_root(self) -> bytes:
        return self.node.chain.head_root

    def get_attestation_data(self, slot: int, committee_index: int):
        state = self.node.advanced_head_state(slot)
        from ..spec.milestones import SpecMilestone
        # EIP-7549: electra attestation data pins index to 0 (the
        # committee rides in committee_bits)
        if self.spec.milestone_at_slot(slot) >= SpecMilestone.ELECTRA:
            committee_index = 0
        return attestation_data_for(self.spec.config, state, slot,
                                    committee_index,
                                    self.node.chain.head_root)

    async def produce_unsigned_block(self, slot: int, randao_reveal: bytes,
                                     graffiti: bytes = bytes(32)):
        """(unsigned block with state_root, pre_state) — the caller
        signs.  Mirrors ValidatorApiHandler.createUnsignedBlock."""
        cfg = self.spec.config
        pre = self.node.advanced_head_state(slot)
        from ..spec.milestones import SpecMilestone
        att_limit = (cfg.MAX_ATTESTATIONS_ELECTRA
                     if self.spec.milestone_at_slot(slot)
                     >= SpecMilestone.ELECTRA else cfg.MAX_ATTESTATIONS)
        atts = self.node.pool.get_attestations_for_block(pre, att_limit)
        pools = self.node.operation_pools
        sync_aggregate = None
        if hasattr(pre, "current_sync_committee"):
            # drain the sync pool: messages signed the PREVIOUS slot's
            # head root (reference SyncCommitteeContributionPool →
            # block production)
            from ..spec.milestones import build_fork_schedule
            version = build_fork_schedule(cfg).version_at_slot(slot)
            prev_root = H.get_block_root_at_slot(cfg, pre,
                                                 max(slot, 1) - 1)
            sync_aggregate = self.node.sync_pool.build_aggregate(
                max(slot, 1) - 1, prev_root, version.schemas)
        deposit_provider = getattr(self.node, "deposit_provider", None)
        eth1_vote = None
        deposits = ()
        if deposit_provider is not None:
            # vote the provider's deposit-chain view; if THIS vote
            # reaches the period majority it adopts inside the block,
            # so the deposit list must be computed against the outcome
            from ..spec.block import eth1_vote_outcome
            eth1_vote = deposit_provider.eth1_data()
            if eth1_vote is None:
                # provider rebuilding after an eth1 reorg: abstain by
                # repeating the committed data instead of voting an
                # empty-tree root
                eth1_vote = pre.eth1_data
            effective = eth1_vote_outcome(cfg, pre, eth1_vote)
            deposits = deposit_provider.get_deposits_for_block(
                pre, effective)
        # blob source seam (reference: the EL's getPayload blobs
        # bundle): blobs ride as sidecars, only commitments in-body
        commitments: tuple = ()
        blob_source = getattr(self.node, "blob_source", None)
        if blob_source is not None:
            from ..spec.milestones import SpecMilestone
            if self.spec.milestone_at_slot(slot) >= SpecMilestone.DENEB:
                bundle = blob_source(slot)
                if bundle is not None:
                    blobs, commitments, proofs = bundle
                    self._pending_blob_bundles = getattr(
                        self, "_pending_blob_bundles", {})
                    self._pending_blob_bundles = {
                        k: v for k, v in
                        self._pending_blob_bundles.items()
                        if v[0] >= slot - 2}   # keep only fresh ones
        # prepare_beacon_proposer fee recipients land in the payload
        proposer = H.get_beacon_proposer_index(cfg, pre)
        fee_recipient = getattr(self.node, "proposer_preparations",
                                {}).get(proposer)
        block, _post = build_unsigned_block(
            cfg, pre, slot, randao_reveal, attestations=atts,
            deposits=deposits, eth1_vote=eth1_vote,
            proposer_index=proposer, fee_recipient=fee_recipient,
            blob_kzg_commitments=commitments,
            proposer_slashings=pools["proposer_slashings"].get_for_block(
                cfg.MAX_PROPOSER_SLASHINGS, pre),
            attester_slashings=pools["attester_slashings"].get_for_block(
                cfg.MAX_ATTESTER_SLASHINGS, pre),
            voluntary_exits=pools["voluntary_exits"].get_for_block(
                cfg.MAX_VOLUNTARY_EXITS, pre),
            bls_to_execution_changes=(
                pools["bls_to_execution_changes"].get_for_block(
                    cfg.MAX_BLS_TO_EXECUTION_CHANGES, pre)
                if hasattr(pre, "next_withdrawal_index") else ()),
            graffiti=graffiti, sync_aggregate=sync_aggregate)
        if commitments:
            # keyed by body root: the signed envelope isn't known yet
            self._pending_blob_bundles[block.body.htr()] = (
                slot, blobs, proofs)
        return block, pre

    # -- submission ----------------------------------------------------
    async def publish_signed_block(self, signed_block) -> None:
        # a blob-carrying block's sidecars go out FIRST (they embed the
        # signed header, buildable only now) so peers' availability
        # gates can admit the block (reference publishes sidecars and
        # block together from BlockPublisherDeneb)
        bundle = getattr(self, "_pending_blob_bundles", {}).pop(
            signed_block.message.body.htr(), None)
        if bundle is not None:
            await self._publish_blob_sidecars(signed_block, bundle)
        self.node.block_manager.import_block(signed_block)
        from ..spec.codec import serialize_signed_block
        await self.node.gossip.publish(
            BEACON_BLOCK_TOPIC, serialize_signed_block(signed_block))

    async def _publish_blob_sidecars(self, signed_block, bundle) -> None:
        from ..node.gossip import blob_sidecar_topic
        from ..spec.deneb.datastructures import make_blob_sidecars
        _slot, blobs, proofs = bundle
        cfg = self.spec.config
        sidecars = make_blob_sidecars(cfg, signed_block, blobs, proofs)
        for sc in sidecars:
            # own sidecars: pool directly (proofs are ours), gossip out
            self.node.blob_pool.add_spec_sidecar(cfg, sc)
            await self.node.gossip.publish(
                blob_sidecar_topic(sc.index), type(sc).serialize(sc))

    async def publish_attestation(self, attestation) -> None:
        """Locally-produced attestations run the SAME gossip validation
        as remote ones before touching the pool or fork choice (the
        reference marks them producedLocally but still validates) — a
        signer bug or stale duty must not poison block production."""
        from ..node.gossip import ValidationResult
        wire = attestation
        if hasattr(attestation, "attester_index"):
            # electra single attestation: normalize for local
            # validation/pooling, publish the wire shape
            from ..node.validators import normalize_attestation
            try:
                state = self.node.advanced_head_state(
                    min(attestation.data.slot,
                        self.node.chain.current_slot()))
            except Exception:
                _LOG.warning("no state to normalize own attestation")
                return
            attestation = normalize_attestation(self.spec, state,
                                                attestation)
            if attestation is None:
                _LOG.warning("own single attestation malformed")
                return
        result = await self.node.attestation_validator.validate(attestation)
        if result is ValidationResult.ACCEPT:
            self.node.attestation_manager.add_attestation(attestation)
        elif result is ValidationResult.SAVE_FOR_FUTURE:
            # transient timing skew (node a hair behind the duty timer):
            # defer locally for re-validation, but still broadcast —
            # peers judge for themselves (the message is honestly ours)
            self.node._defer("att", attestation)
        else:
            _LOG.warning("own attestation failed validation: %s", result)
            return
        cfg = self.spec.config
        data = attestation.data
        state = self.node.advanced_head_state(max(data.slot, 1))
        committees = H.get_committee_count_per_slot(cfg, state,
                                                    data.target.epoch)
        from ..node.validators import _committee_index_of
        ci = _committee_index_of(attestation)
        subnet = compute_subnet_for_attestation(
            cfg, committees, data.slot, ci if ci is not None else 0)
        await self.node.gossip.publish(
            attestation_subnet_topic(subnet),
            type(wire).serialize(wire))

    def get_aggregate(self, data, committee_index=None):
        return self.node.pool.get_aggregate(data, committee_index)

    async def publish_sync_committee_message(self, msg) -> None:
        """Own sync message: same validation as gossip, then pool +
        broadcast (reference SyncCommitteeMessageValidator feed)."""
        from ..node.gossip import SYNC_COMMITTEE_TOPIC, ValidationResult
        result = await self.node._process_sync_message(msg)
        if result is not ValidationResult.ACCEPT:
            _LOG.warning("own sync message failed validation: %s", result)
            return
        await self.node.gossip.publish(
            SYNC_COMMITTEE_TOPIC, type(msg).serialize(msg))

    def build_sync_contribution(self, slot: int, block_root: bytes,
                                subcommittee_index: int):
        """This subcommittee's pooled messages as a contribution (the
        sync aggregator duty's getter)."""
        from ..spec.milestones import build_fork_schedule
        S = build_fork_schedule(self.spec.config).version_at_slot(
            slot).schemas
        return self.node.sync_pool.build_contribution(
            slot, block_root, subcommittee_index, S)

    async def publish_contribution_and_proof(self, signed) -> None:
        """Own contribution: same validation as gossip, then pool +
        broadcast."""
        from ..node.gossip import SYNC_CONTRIBUTION_TOPIC, \
            ValidationResult
        result = await self.node._process_sync_contribution(signed)
        if result is not ValidationResult.ACCEPT:
            _LOG.warning("own sync contribution failed validation: %s",
                         result)
            return
        await self.node.gossip.publish(
            SYNC_CONTRIBUTION_TOPIC, type(signed).serialize(signed))

    async def publish_aggregate_and_proof(self, signed_aggregate) -> None:
        from ..node.gossip import ValidationResult
        result = await self.node.aggregate_validator.validate(
            signed_aggregate)
        if result is ValidationResult.ACCEPT:
            self.node.attestation_manager.add_attestation(
                signed_aggregate.message.aggregate)
        elif result is ValidationResult.SAVE_FOR_FUTURE:
            self.node._defer("agg", signed_aggregate)
        else:
            _LOG.warning("own aggregate failed validation: %s", result)
            return
        await self.node.gossip.publish(
            AGGREGATE_TOPIC,
            self.spec.schemas.SignedAggregateAndProof.serialize(
                signed_aggregate))
