"""Validator stack: keystores, signers, slashing protection, duties.

Reference: /root/reference/validator/ (client, api, remote) and
/root/reference/infrastructure/bls-keystore/.
"""

from .api import (AttesterDuty, BeaconNodeValidatorApi, ProposerDuty,
                  ValidatorApiChannel)
from .client import ValidatorClient
from .external import (ExternalSigner, FailoverError,
                       FailoverValidatorApi)
from .remote import RemoteValidatorApi
from .signer import (DutySigner, LocalSigner, SigningError,
                     SlashingProtectedSigner)
from .slashing_protection import SigningRecord, SlashingProtector
