"""Duty signers: local keys + slashing-protected wrapper.

Equivalent of the reference's signature stack (reference: ethereum/
spec/src/main/java/tech/pegasys/teku/spec/signatures/Signer.java,
LocalSigner.java, SlashingProtectedSigner.java, SigningRootUtil.java):
a Signer turns duty payloads into BLS signatures; the slashing-protected
wrapper consults the protector BEFORE the key touches anything.
"""

from typing import Dict, Optional

from ..crypto import bls
from ..spec import helpers as H
from ..spec.config import (DOMAIN_AGGREGATE_AND_PROOF,
                           DOMAIN_BEACON_ATTESTER, DOMAIN_BEACON_PROPOSER,
                           SpecConfig)
from .slashing_protection import SlashingProtector


class SigningError(Exception):
    """Refused (slashing risk) or impossible (unknown key)."""


class DutySigner:
    """Typed duty-signing API (reference Signer.java)."""

    def sign_block(self, cfg: SpecConfig, state, block) -> bytes:
        raise NotImplementedError

    def sign_attestation_data(self, cfg: SpecConfig, state, data,
                              validator_index: int) -> bytes:
        raise NotImplementedError

    def sign_randao_reveal(self, cfg: SpecConfig, state, epoch: int,
                           validator_index: int) -> bytes:
        raise NotImplementedError

    def sign_aggregate_and_proof(self, cfg: SpecConfig, state, msg) -> bytes:
        raise NotImplementedError

    def sign_selection_proof(self, cfg: SpecConfig, state, slot: int,
                             validator_index: int) -> bytes:
        raise NotImplementedError

    def sign_sync_committee_message(self, cfg: SpecConfig, state,
                                    slot: int, block_root: bytes,
                                    validator_index: int) -> bytes:
        raise NotImplementedError

    def sign_sync_selection_proof(self, cfg: SpecConfig, state,
                                  slot: int, subcommittee_index: int,
                                  validator_index: int) -> bytes:
        raise NotImplementedError

    def sign_contribution_and_proof(self, cfg: SpecConfig, state,
                                    msg) -> bytes:
        raise NotImplementedError


class LocalSigner(DutySigner):
    def __init__(self, secret_keys_by_index: Dict[int, int],
                 pubkeys_by_index: Optional[Dict[int, bytes]] = None):
        self.keys = dict(secret_keys_by_index)
        self.pubkeys = pubkeys_by_index or {
            i: bls.secret_to_public_key(sk) for i, sk in self.keys.items()}

    def _sign(self, validator_index: int, root: bytes) -> bytes:
        sk = self.keys.get(validator_index)
        if sk is None:
            raise SigningError(f"no key for validator {validator_index}")
        return bls.sign(sk, root)

    def sign_block(self, cfg, state, block) -> bytes:
        domain = H.get_domain(cfg, state, DOMAIN_BEACON_PROPOSER,
                              H.compute_epoch_at_slot(cfg, block.slot))
        return self._sign(block.proposer_index,
                          H.compute_signing_root(block, domain))

    def sign_attestation_data(self, cfg, state, data,
                              validator_index) -> bytes:
        domain = H.get_domain(cfg, state, DOMAIN_BEACON_ATTESTER,
                              data.target.epoch)
        return self._sign(validator_index,
                          H.compute_signing_root(data, domain))

    def sign_randao_reveal(self, cfg, state, epoch,
                           validator_index) -> bytes:
        return self._sign(validator_index,
                          H.randao_signing_root(cfg, state, epoch))

    def sign_aggregate_and_proof(self, cfg, state, msg) -> bytes:
        domain = H.get_domain(
            cfg, state, DOMAIN_AGGREGATE_AND_PROOF,
            H.compute_epoch_at_slot(cfg, msg.aggregate.data.slot))
        return self._sign(msg.aggregator_index,
                          H.compute_signing_root(msg, domain))

    def sign_selection_proof(self, cfg, state, slot,
                             validator_index) -> bytes:
        return self._sign(validator_index,
                          H.selection_proof_signing_root(cfg, state, slot))

    def sign_sync_committee_message(self, cfg, state, slot, block_root,
                                    validator_index) -> bytes:
        from ..spec.altair.helpers import sync_message_signing_root
        return self._sign(validator_index, sync_message_signing_root(
            cfg, state, slot, block_root))

    def sign_sync_selection_proof(self, cfg, state, slot,
                                  subcommittee_index,
                                  validator_index) -> bytes:
        from ..spec.altair.helpers import (
            sync_selection_proof_signing_root)
        return self._sign(validator_index,
                          sync_selection_proof_signing_root(
                              cfg, state, slot, subcommittee_index))

    def sign_contribution_and_proof(self, cfg, state, msg) -> bytes:
        from ..spec.altair.helpers import (
            contribution_and_proof_signing_root)
        return self._sign(msg.aggregator_index,
                          contribution_and_proof_signing_root(
                              cfg, state, msg))


class SlashingProtectedSigner(DutySigner):
    """Wraps a signer; block + attestation signatures consult the
    protector first (reference SlashingProtectedSigner.java).  RANDAO,
    selection proofs and aggregates carry no slashing risk and pass
    through."""

    def __init__(self, inner: LocalSigner, protector: SlashingProtector):
        self.inner = inner
        self.protector = protector

    def _pubkey(self, validator_index: int) -> bytes:
        return self.inner.pubkeys[validator_index]

    def sign_block(self, cfg, state, block) -> bytes:
        if not self.protector.may_sign_block(
                self._pubkey(block.proposer_index), block.slot):
            raise SigningError(
                f"slashing protection refused block at slot {block.slot}")
        return self.inner.sign_block(cfg, state, block)

    def sign_attestation_data(self, cfg, state, data,
                              validator_index) -> bytes:
        if not self.protector.may_sign_attestation(
                self._pubkey(validator_index), data.source.epoch,
                data.target.epoch):
            raise SigningError(
                f"slashing protection refused attestation "
                f"{data.source.epoch}->{data.target.epoch}")
        return self.inner.sign_attestation_data(cfg, state, data,
                                                validator_index)

    def sign_randao_reveal(self, cfg, state, epoch, validator_index):
        return self.inner.sign_randao_reveal(cfg, state, epoch,
                                             validator_index)

    def sign_aggregate_and_proof(self, cfg, state, msg):
        return self.inner.sign_aggregate_and_proof(cfg, state, msg)

    def sign_selection_proof(self, cfg, state, slot, validator_index):
        return self.inner.sign_selection_proof(cfg, state, slot,
                                               validator_index)

    def sign_sync_committee_message(self, cfg, state, slot, block_root,
                                    validator_index):
        # sync messages carry no slashing risk
        return self.inner.sign_sync_committee_message(
            cfg, state, slot, block_root, validator_index)

    def sign_sync_selection_proof(self, cfg, state, slot,
                                  subcommittee_index, validator_index):
        return self.inner.sign_sync_selection_proof(
            cfg, state, slot, subcommittee_index, validator_index)

    def sign_contribution_and_proof(self, cfg, state, msg):
        return self.inner.sign_contribution_and_proof(cfg, state, msg)
