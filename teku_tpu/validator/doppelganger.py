"""Doppelganger detection: refuse to start duties if our keys are
already attesting elsewhere.

Equivalent of the reference's doppelganger detector (reference:
validator/client/src/main/java/tech/pegasys/teku/validator/client/
doppelganger/DoppelgangerDetector.java + slashingriskactions/
DoppelgangerDetectionShutDown.java): watch the chain for N epochs; any
attestation carrying one of our validator indices means another
instance is live with our keys — abort before we equivocate.
"""

import logging
from typing import Callable, Iterable, Optional, Set

_LOG = logging.getLogger(__name__)


class DoppelgangerDetected(RuntimeError):
    pass


class DoppelgangerDetector:
    def __init__(self, watched_indices: Iterable[int],
                 detection_epochs: int = 2,
                 on_detected: Optional[Callable[[int], None]] = None):
        self.watched: Set[int] = set(watched_indices)
        self.detection_epochs = detection_epochs
        self.on_detected = on_detected
        self._start_epoch: Optional[int] = None
        self.cleared = False
        self.detected: Set[int] = set()

    def begin(self, current_epoch: int) -> None:
        self._start_epoch = current_epoch
        self.cleared = not self.watched or self.detection_epochs == 0

    def observe_attesters(self, attesting_indices: Iterable[int]) -> None:
        """Feed every indexed attestation seen on gossip/in blocks."""
        if self.cleared or self._start_epoch is None:
            return
        hits = self.watched & set(attesting_indices)
        for index in hits:
            self.detected.add(index)
            _LOG.error("DOPPELGANGER: validator %d is attesting "
                       "elsewhere — refusing duties", index)
            if self.on_detected:
                self.on_detected(index)
        if hits:
            raise DoppelgangerDetected(
                f"validators {sorted(self.detected)} active elsewhere")

    def on_epoch(self, epoch: int) -> bool:
        """Returns True when the watch window completed cleanly and
        duties may start."""
        if self._start_epoch is None or self.detected:
            return False
        if epoch >= self._start_epoch + self.detection_epochs:
            self.cleared = True
        return self.cleared
