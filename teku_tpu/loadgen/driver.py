"""Scenario driver: replay a traffic model against the REAL pipeline.

Like ``services/overload_sim.py`` (whose virtual-clock technique this
extends), the control plane under test is PRODUCTION CODE, unmodified:
the real ``AggregatingSignatureVerificationService`` (priority queue,
coalescing, bisect, flush deadlines) and the real
``AdmissionController`` (adaptive batching, brownout) with the real
``CapacityTelemetry`` — all on one injected virtual clock, so a
scenario replays deterministically in milliseconds of wall time.

What stands in for hardware is the DEVICE MODEL, and it is
dedup-AWARE: a dispatch costs
``overhead + padded_unique_messages * h2c_cost + padded_lanes *
lane_cost`` virtual seconds — the cost model PERF.md measured for the
unique-message pipeline — so committee-duplicated traffic is genuinely
cheaper per lane than a dup-collapse flood, and the capacity model
sees exactly the shape-dependent latency it sees in production.  A
triple whose signature carries ``INVALID_SIG_PREFIX`` fails its whole
batch, which forces the service's real bisect path.  Blob-batch events
dispatch through ``crypto/kzg.py``'s REAL facade with a model backend
installed, so the ``source="kzg"`` arrival accounting and the guarded
fallback seams are the production code paths.

Per-scenario evidence (the ``cli loadgen`` report and bench's
``mainnet`` phase): sigs/sec, per-class p50/p99 and shed counts,
dedup ratio, coalesced/bisect counts, and every brownout transition.
"""

import asyncio
from typing import Dict, List, Optional, Sequence, Tuple, Union

from ..crypto import bls, kzg
from ..infra import capacity as capacity_mod
from ..infra import flightrecorder
from ..infra.metrics import GLOBAL_REGISTRY, MetricsRegistry
from ..services.admission import AdmissionController, VerifyClass
from ..services.overload_sim import VirtualClock, _next_pow2
from ..services.signatures import (AggregatingSignatureVerificationService,
                                   ServiceCapacityExceededError)
from . import model as model_mod
from . import scenarios as scenarios_mod
from .model import INVALID_SIG_PREFIX, generate_events
from .scenarios import Scenario

# process-global loadgen evidence (closed label vocabularies: scenario
# names from the registry, kinds from the model, classes from the enum)
_M_EVENTS = GLOBAL_REGISTRY.labeled_counter(
    "loadgen_events_total",
    "traffic-model events replayed, by scenario and event kind",
    labelnames=("scenario", "kind"))
_M_SHEDS = GLOBAL_REGISTRY.labeled_counter(
    "loadgen_sheds_total",
    "loadgen submissions shed by the service, by scenario and class",
    labelnames=("scenario", "class"))
_M_DEDUP = GLOBAL_REGISTRY.labeled_gauge(
    "loadgen_dedup_ratio",
    "measured lane-duplication ratio of the last run per scenario "
    "(1 - unique messages / lanes at the device)",
    labelnames=("scenario",))


class DedupAwareDevice:
    """Model BLS implementation on the virtual clock with the
    unique-message cost model; verdicts honor the invalid-signature
    marker so failed batches exercise the real bisect recursion."""

    def __init__(self, clock: VirtualClock,
                 telemetry: capacity_mod.CapacityTelemetry,
                 lane_sigs_per_sec: float = 3000.0,
                 h2c_msgs_per_sec: float = 1500.0,
                 overhead_s: float = 0.002, min_pad: int = 8):
        self.clock = clock
        self.telemetry = telemetry
        self.lane_s = 1.0 / lane_sigs_per_sec
        self.h2c_s = 1.0 / h2c_msgs_per_sec
        self.overhead_s = overhead_s
        self.min_pad = min_pad
        self.dispatches = 0
        self.lanes_total = 0
        self.unique_total = 0
        self.completed_at: Dict[tuple, float] = {}

    def batch_verify(self, triples) -> bool:
        n = len(triples)
        uniques = len({msg for _pks, msg, _sig in triples})
        padded = max(_next_pow2(n), self.min_pad)
        padded_u = max(_next_pow2(uniques), 1)
        dt = (self.overhead_s + padded_u * self.h2c_s
              + padded * self.lane_s)
        t0 = self.clock()
        self.clock.advance(dt)
        self.telemetry.record_dispatch(f"{padded}x1", "sim", n, t0,
                                       self.clock())
        self.dispatches += 1
        self.lanes_total += n
        self.unique_total += uniques
        ok = True
        now = self.clock()
        for _pks, msg, sig in triples:
            self.completed_at[(msg, sig)] = now
            if sig.startswith(INVALID_SIG_PREFIX):
                ok = False
        return ok

    def fast_aggregate_verify(self, pks, msg, sig) -> bool:
        return self.batch_verify([(pks, msg, sig)])

    def dedup_ratio(self) -> float:
        if not self.lanes_total:
            return 0.0
        return 1.0 - self.unique_total / self.lanes_total


class ModelKzgBackend:
    """Stand-in KZG device: one virtual-time dispatch per blob batch,
    fed through the REAL ``crypto/kzg.py`` facade so its arrival
    accounting and guarded-fallback seams are exercised."""

    name = "loadgen-model"

    def __init__(self, clock: VirtualClock,
                 telemetry: capacity_mod.CapacityTelemetry,
                 blob_s: float = 0.004, overhead_s: float = 0.002):
        self.clock = clock
        self.telemetry = telemetry
        self.blob_s = blob_s
        self.overhead_s = overhead_s
        self.batches = 0
        self.blobs = 0

    def verify_blob_kzg_proof_batch(self, blobs, commitments, proofs,
                                    setup) -> bool:
        n = len(blobs)
        t0 = self.clock()
        self.clock.advance(self.overhead_s + n * self.blob_s)
        self.telemetry.record_dispatch(f"kzg{_next_pow2(n)}", "sim",
                                       n, t0, self.clock())
        self.batches += 1
        self.blobs += n
        return True


def _percentiles(lats: List[float]) -> Tuple[float, float]:
    if not lats:
        return 0.0, 0.0
    ordered = sorted(lats)

    def pct(q):
        return ordered[min(len(ordered) - 1,
                           int(q * len(ordered)))] * 1e3
    return round(pct(0.50), 3), round(pct(0.99), 3)


async def _run_scenario(scenario: Scenario, seed: int, slots: int,
                        validators: Optional[int]) -> dict:
    model = scenario.model
    if validators is not None:
        model = model.with_overrides(validators=validators)
    events = generate_events(model, seed=seed, slots=slots)
    stats = model_mod.stream_stats(events)

    clock = VirtualClock()
    registry = MetricsRegistry()
    recorder = flightrecorder.FlightRecorder(capacity=2048,
                                             registry=registry)
    telemetry = capacity_mod.CapacityTelemetry(
        registry=registry, window_s=2.5, clock=clock, recorder=recorder)
    # dedup-aware device scaled so the scenario's offered rate is a
    # meaningful fraction of capacity (storms overload, steady holds)
    device = DedupAwareDevice(
        clock, telemetry,
        lane_sigs_per_sec=scenario.capacity_sigs_per_sec * 2,
        h2c_msgs_per_sec=scenario.capacity_sigs_per_sec)
    kzg_backend = ModelKzgBackend(clock, telemetry)
    controller = AdmissionController(
        telemetry=telemetry, min_bucket=8, max_batch=256,
        slo_p50_s=0.1, tick_s=0.02, hold_ticks=25, clock=clock,
        registry=registry, recorder=recorder,
        name=f"loadgen_{scenario.name}")
    svc = AggregatingSignatureVerificationService(
        num_workers=1, queue_capacity=4000, max_batch_size=256,
        registry=registry, name="loadgen", overlap=False,
        controller=controller, telemetry=telemetry, recorder=recorder,
        clock=clock)

    submitted: Dict[str, int] = {c.label: 0 for c in VerifyClass}
    sheds: Dict[str, int] = {c.label: 0 for c in VerifyClass}
    pending: List[tuple] = []      # (event, future)
    by_class: Dict[str, List[float]] = {}
    kzg_setup = kzg.TrustedSetup(g1_lagrange=None,
                                 g2_monomial=[None, None])

    def observe_latency(fut, key, t_sub, cls_label):
        """Resolution-time latency capture: reading the device stamp
        when THIS future settles, not after the whole run — a later
        re-delivery of the same triple re-dispatches and would
        overwrite the stamp, inflating every earlier submission."""
        def _cb(f):
            if f.cancelled() or f.exception() is not None:
                return
            done_at = device.completed_at.get(key)
            if done_at is not None:
                by_class.setdefault(cls_label, []).append(
                    done_at - t_sub)
        fut.add_done_callback(_cb)

    t_start = clock()
    horizon = t_start + slots * model_mod.SECONDS_PER_SLOT

    bls.set_implementation(device)
    kzg_prev_backend = kzg.get_backend()
    kzg.set_backend(kzg_backend)
    telemetry_prev = capacity_mod.swap_default(telemetry)
    try:
        await svc.start()
        idx = 0
        idle_tick = 0.02
        while True:
            if idx < len(events):
                ev = events[idx]
                t_ev = t_start + ev.t
                if clock() < t_ev:
                    # advance to the next arrival (bounded tick so the
                    # controller and flush deadlines stay live)
                    clock.advance(min(t_ev - clock(), idle_tick))
                    await asyncio.sleep(0)
                    continue
                idx += 1
                _M_EVENTS.labels(scenario=scenario.name,
                                 kind=ev.kind).inc()
                if ev.kind == "blob_batch":
                    # through the REAL kzg facade: arrival accounting
                    # (source="kzg") + the installed model backend
                    kzg.verify_blob_kzg_proof_batch(
                        [b"blob"] * ev.blobs, [b"c"] * ev.blobs,
                        [b"p"] * ev.blobs, kzg_setup)
                    continue
                submitted[ev.cls.label] += 1
                t_sub = clock()
                try:
                    if len(ev.triples) == 1:
                        pks, msg, sig = ev.triples[0]
                        fut = svc.verify(pks, msg, sig, cls=ev.cls,
                                         source=ev.source)
                        key = (msg, sig)
                    else:
                        fut = svc.verify_multi(list(ev.triples),
                                               cls=ev.cls,
                                               source=ev.source)
                        key = (ev.triples[0][1], ev.triples[0][2])
                except ServiceCapacityExceededError:
                    sheds[ev.cls.label] += 1
                    _M_SHEDS.labels(scenario=scenario.name,
                                    **{"class": ev.cls.label}).inc()
                    continue
                observe_latency(fut, key, t_sub, ev.cls.label)
                pending.append((ev, fut))
                await asyncio.sleep(0)
                continue
            # stream exhausted: drain the queue in virtual time (the
            # horizon guard bounds the drain — a wedged future must
            # fail the run loudly, not hang the harness)
            if svc._queue.qsize() == 0 and all(
                    f.done() for _, f in pending):
                break
            if clock() >= horizon + 120:
                raise RuntimeError(
                    "loadgen drain did not settle within the virtual "
                    "horizon (wedged task?)")
            clock.advance(idle_tick)
            await asyncio.sleep(0)

        # throughput window ends when the load drains — the brownout
        # cool-down below advances the clock further and must not
        # dilute sigs/sec on exactly the scenarios that browned out
        duration = clock() - t_start
        completed = 0
        failed_verdicts = 0
        for ev, fut in pending:
            try:
                ok = await fut
            except ServiceCapacityExceededError:
                sheds[ev.cls.label] += 1
                _M_SHEDS.labels(scenario=scenario.name,
                                **{"class": ev.cls.label}).inc()
                continue
            if ok:
                completed += len(ev.triples)
            else:
                failed_verdicts += 1
        # cool down through the brownout exit hysteresis so the report
        # shows the full enter→exit episode
        for _ in range(controller.hold_ticks + 20):
            if controller.brownout_level == 0:
                break
            clock.advance(max(telemetry.window_s / 4,
                              controller.tick_s))
            controller.tick()
        await svc.stop()
    finally:
        capacity_mod.swap_default(telemetry_prev)
        kzg.set_backend(kzg_prev_backend)
        bls.reset_implementation()

    all_lats = [lat for ls in by_class.values() for lat in ls]
    p50, p99 = _percentiles(all_lats)
    per_class = {}
    for c in VerifyClass:
        ls = by_class.get(c.label, [])
        c50, c99 = _percentiles(ls)
        per_class[c.label] = {
            "submitted": submitted[c.label],
            "completed": len(ls),
            "shed": sheds[c.label],
            "p50_ms": c50, "p99_ms": c99}
    dispatch_counter = registry.metrics()["loadgen_dispatch_total"]
    dispatches = {kind: int(child.value) for (kind,), child
                  in dispatch_counter._items()}
    coalesced = int(
        registry.metrics()["loadgen_coalesced_total"].value)
    b_events = [e for e in recorder.snapshot()
                if e["kind"].startswith("brownout_")]
    _M_DEDUP.labels(scenario=scenario.name).set(
        round(device.dedup_ratio(), 4))
    return {
        "scenario": scenario.name,
        "seed": seed,
        "slots": slots,
        "validators": model.validators,
        "committee_shaped": scenario.committee_shaped,
        "adversarial": scenario.adversarial,
        "classes_declared": list(scenario.classes),
        "stream": stats,
        "duration_s": round(duration, 3),
        "sigs_per_sec": round(completed / duration, 1) if duration
        else 0.0,
        "completed_triples": completed,
        "failed_verdicts": failed_verdicts,
        "p50_ms": p50, "p99_ms": p99,
        "by_class": per_class,
        "sheds": sheds,
        "shed_total": sum(sheds.values()),
        "dedup_ratio": round(device.dedup_ratio(), 4),
        "coalesced": coalesced,
        "dispatches": dispatches,
        "bisect_dispatches": dispatches.get("bisect", 0),
        "device": {"dispatches": device.dispatches,
                   "lanes": device.lanes_total,
                   "unique": device.unique_total},
        "kzg": {"batches": kzg_backend.batches,
                "blobs": kzg_backend.blobs,
                "source_accounted": capacity_mod.SOURCE_KZG in
                telemetry.snapshot()["arrival_rate_per_second"]},
        "arrival_sources": sorted(
            telemetry.snapshot()["arrival_rate_per_second"]),
        "brownout": {
            "enters": sum(1 for e in b_events
                          if e["kind"] == "brownout_enter"
                          and e.get("from_level", 0) == 0),
            "exits": sum(1 for e in b_events
                         if e["kind"] == "brownout_exit"),
            "final_level": controller.brownout_level,
            "transitions": [
                {k: e.get(k) for k in ("kind", "level", "from_level",
                                       "utilization")}
                for e in b_events[:16]],
        },
    }


def run_scenario(scenario: Union[str, Scenario], seed: int = 1,
                 slots: int = 2,
                 validators: Optional[int] = None) -> dict:
    """One scenario end-to-end; returns the evidence dict."""
    if isinstance(scenario, str):
        scenario = scenarios_mod.get(scenario)
    return asyncio.run(_run_scenario(scenario, seed=seed, slots=slots,
                                     validators=validators))


def run_scenarios(names: Optional[Sequence[str]] = None, seed: int = 1,
                  slots: int = 2,
                  validators: Optional[int] = None) -> dict:
    """The sweep bench's ``mainnet`` phase embeds: every named (default
    all) scenario under the same seed, with a cross-scenario summary."""
    names = list(names or scenarios_mod.DEFAULT_SWEEP)
    out: dict = {"seed": seed, "slots": slots, "scenarios": {}}
    for name in names:
        out["scenarios"][name] = run_scenario(name, seed=seed,
                                              slots=slots,
                                              validators=validators)
    out["summary"] = summarize(out["scenarios"])
    return out


def summarize(scenarios: Dict[str, dict]) -> dict:
    """Cross-scenario acceptance view (what the bench gate reads)."""
    worst_block_import = 0
    worst_critical_p50 = 0.0
    dedup_floor = None
    for rep in scenarios.values():
        if not isinstance(rep, dict) or "by_class" not in rep:
            continue
        worst_block_import = max(
            worst_block_import,
            rep["sheds"].get("block_import", 0)
            + rep["sheds"].get("vip", 0))
        if not rep.get("adversarial"):
            # the critical-p50 bound holds on every PRODUCTION shape;
            # adversarial floods (deep bisect recursion) stress other
            # properties — their gate is sheds==0, not latency
            for cls in ("vip", "block_import"):
                worst_critical_p50 = max(
                    worst_critical_p50,
                    rep["by_class"][cls]["p50_ms"])
        if rep.get("committee_shaped"):
            d = rep.get("dedup_ratio", 0.0)
            dedup_floor = d if dedup_floor is None \
                else min(dedup_floor, d)
    return {
        "scenarios_run": len(scenarios),
        "block_import_sheds_worst": worst_block_import,
        "critical_p50_ms_worst": round(worst_critical_p50, 3),
        "committee_dedup_ratio_min": (round(dedup_floor, 4)
                                      if dedup_floor is not None
                                      else None),
    }
