"""Scenario driver: replay a traffic model against the REAL pipeline.

Like ``services/overload_sim.py`` (whose virtual-clock technique this
extends), the control plane under test is PRODUCTION CODE, unmodified:
the real ``AggregatingSignatureVerificationService`` (priority queue,
coalescing, bisect, flush deadlines) and the real
``AdmissionController`` (adaptive batching, brownout) with the real
``CapacityTelemetry`` — all on one injected virtual clock, so a
scenario replays deterministically in milliseconds of wall time.

What stands in for hardware is the DEVICE MODEL, and it is
dedup-AWARE: a dispatch costs
``overhead + padded_unique_messages * h2c_cost + padded_lanes *
lane_cost`` virtual seconds — the cost model PERF.md measured for the
unique-message pipeline — so committee-duplicated traffic is genuinely
cheaper per lane than a dup-collapse flood, and the capacity model
sees exactly the shape-dependent latency it sees in production.  A
triple whose signature carries ``INVALID_SIG_PREFIX`` fails its whole
batch, which forces the service's real bisect path.  Blob-batch events
dispatch through ``crypto/kzg.py``'s REAL facade with a model backend
installed, so the ``source="kzg"`` arrival accounting and the guarded
fallback seams are the production code paths.

Per-scenario evidence (the ``cli loadgen`` report and bench's
``mainnet`` phase): sigs/sec, per-class p50/p99 and shed counts,
dedup ratio, coalesced/bisect counts, and every brownout transition.

CHAOS scenarios (``Scenario.mesh_devices`` + a ``chaos`` schedule)
route the model through the REAL supervisor machinery —
``GuardedBls12381`` + breaker + ``parallel/selfheal.MeshHealer`` over
a model mesh — and arm timed device-keyed ``bls.mesh_shard`` faults
mid-run, so eject/reshape/readmit runs under traffic and the report
carries the full recovery evidence (``rep["chaos"]``).

VIRTUAL-CLOCK DISCIPLINE: the driver advances the clock ONLY while
the service is quiescent at the thread boundary
(``svc.inflight_dispatches == 0``).  Advancing while a dispatch
crossed into ``asyncio.to_thread`` charged GIL-scheduling wall time
to virtual latency — on a 1-core box each thread handoff costs a
~5 ms GIL switch interval of driver spinning, which at 20 ms of
virtual time per spin inflated the r10/r11 block-import p50 to
~3.6 s.  With the gate, virtual latency is queue wait + modeled
device time on any host.
"""

import asyncio
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

from ..crypto import bls, kzg
from ..crypto.bls.loader import GuardedBls12381
from ..infra import capacity as capacity_mod
from ..infra import faults, flightrecorder, timeline
from ..infra.metrics import GLOBAL_REGISTRY, MetricsRegistry
from ..infra.supervisor import CircuitBreaker
from ..parallel import selfheal
from ..services.admission import AdmissionController, VerifyClass
from ..services.overload_sim import VirtualClock, _next_pow2
from ..services.signatures import (AggregatingSignatureVerificationService,
                                   ServiceCapacityExceededError)
from . import model as model_mod
from . import scenarios as scenarios_mod
from .model import INVALID_SIG_PREFIX, generate_events
from .scenarios import Scenario

# process-global loadgen evidence (closed label vocabularies: scenario
# names from the registry, kinds from the model, classes from the enum)
_M_EVENTS = GLOBAL_REGISTRY.labeled_counter(
    "loadgen_events_total",
    "traffic-model events replayed, by scenario and event kind",
    labelnames=("scenario", "kind"))
_M_SHEDS = GLOBAL_REGISTRY.labeled_counter(
    "loadgen_sheds_total",
    "loadgen submissions shed by the service, by scenario and class",
    labelnames=("scenario", "class"))
_M_DEDUP = GLOBAL_REGISTRY.labeled_gauge(
    "loadgen_dedup_ratio",
    "measured lane-duplication ratio of the last run per scenario "
    "(1 - unique messages / lanes at the device)",
    labelnames=("scenario",))


class DedupAwareDevice:
    """Model BLS implementation on the virtual clock with the
    unique-message cost model; verdicts honor the invalid-signature
    marker so failed batches exercise the real bisect recursion."""

    def __init__(self, clock: VirtualClock,
                 telemetry: capacity_mod.CapacityTelemetry,
                 lane_sigs_per_sec: float = 3000.0,
                 h2c_msgs_per_sec: float = 1500.0,
                 overhead_s: float = 0.002, min_pad: int = 8,
                 completed_at: Optional[Dict[tuple, float]] = None):
        self.clock = clock
        self.telemetry = telemetry
        self.lane_s = 1.0 / lane_sigs_per_sec
        self.h2c_s = 1.0 / h2c_msgs_per_sec
        self.overhead_s = overhead_s
        self.min_pad = min_pad
        self.dispatches = 0
        self.lanes_total = 0
        self.unique_total = 0
        # shareable across backends: the chaos scenario swaps model
        # backends mid-run (eject/reshape) and the latency stamps must
        # land in ONE dict the driver's callbacks read
        self.completed_at: Dict[tuple, float] = (
            completed_at if completed_at is not None else {})

    def batch_verify(self, triples) -> bool:
        n = len(triples)
        uniques = len({msg for _pks, msg, _sig in triples})
        padded = max(_next_pow2(n), self.min_pad)
        padded_u = max(_next_pow2(uniques), 1)
        dt = (self.overhead_s + padded_u * self.h2c_s
              + padded * self.lane_s)
        t0 = self.clock()
        self.clock.advance(dt)
        self.telemetry.record_dispatch(f"{padded}x1", "sim", n, t0,
                                       self.clock())
        self.dispatches += 1
        self.lanes_total += n
        self.unique_total += uniques
        ok = True
        now = self.clock()
        for _pks, msg, sig in triples:
            self.completed_at[(msg, sig)] = now
            if sig.startswith(INVALID_SIG_PREFIX):
                ok = False
        return ok

    def fast_aggregate_verify(self, pks, msg, sig) -> bool:
        return self.batch_verify([(pks, msg, sig)])

    def dedup_ratio(self) -> float:
        if not self.lanes_total:
            return 0.0
        return 1.0 - self.unique_total / self.lanes_total


class MeshModelDevice(DedupAwareDevice):
    """Model MESH: the dedup-aware cost model scaled by the live
    device subset (losing a chip costs 1/N of throughput), with every
    dispatch passing the REAL ``bls.mesh_shard`` fault site keyed by
    the live device names — the production seam the chaos schedule
    arms, so a keyed wedge fails the collective exactly while the
    sick device is in the live set and stops once it is ejected."""

    def __init__(self, clock: VirtualClock,
                 telemetry: capacity_mod.CapacityTelemetry,
                 live: Sequence[int], total: int,
                 lane_sigs_per_sec: float, h2c_msgs_per_sec: float,
                 completed_at: Optional[Dict[tuple, float]] = None):
        frac = len(live) / max(total, 1)
        super().__init__(clock, telemetry,
                         lane_sigs_per_sec=lane_sigs_per_sec * frac,
                         h2c_msgs_per_sec=h2c_msgs_per_sec * frac,
                         completed_at=completed_at)
        self.live_names = tuple(f"vdev{i}" for i in live)
        self.mesh_info = {"devices": list(self.live_names),
                          "n_devices": len(live), "axis": "dp"}

    def batch_verify(self, triples) -> bool:
        faults.check(selfheal.FAULT_SITE, keys=self.live_names)
        return super().batch_verify(triples)


class ModelKzgBackend:
    """Stand-in KZG device: one virtual-time dispatch per blob batch,
    fed through the REAL ``crypto/kzg.py`` facade so its arrival
    accounting and guarded-fallback seams are exercised."""

    name = "loadgen-model"

    def __init__(self, clock: VirtualClock,
                 telemetry: capacity_mod.CapacityTelemetry,
                 blob_s: float = 0.004, overhead_s: float = 0.002):
        self.clock = clock
        self.telemetry = telemetry
        self.blob_s = blob_s
        self.overhead_s = overhead_s
        self.batches = 0
        self.blobs = 0

    def verify_blob_kzg_proof_batch(self, blobs, commitments, proofs,
                                    setup) -> bool:
        n = len(blobs)
        t0 = self.clock()
        self.clock.advance(self.overhead_s + n * self.blob_s)
        self.telemetry.record_dispatch(f"kzg{_next_pow2(n)}", "sim",
                                       n, t0, self.clock())
        self.batches += 1
        self.blobs += n
        return True


def _percentiles(lats: List[float]) -> Tuple[float, float]:
    if not lats:
        return 0.0, 0.0
    ordered = sorted(lats)

    def pct(q):
        return ordered[min(len(ordered) - 1,
                           int(q * len(ordered)))] * 1e3
    return round(pct(0.50), 3), round(pct(0.99), 3)


async def _run_scenario(scenario: Scenario, seed: int, slots: int,
                        validators: Optional[int]) -> dict:
    model = scenario.model
    if validators is not None:
        model = model.with_overrides(validators=validators)
    events = generate_events(model, seed=seed, slots=slots)
    stats = model_mod.stream_stats(events)

    clock = VirtualClock()
    registry = MetricsRegistry()
    recorder = flightrecorder.FlightRecorder(capacity=2048,
                                             registry=registry)
    telemetry = capacity_mod.CapacityTelemetry(
        registry=registry, window_s=2.5, clock=clock, recorder=recorder)
    # dedup-aware device scaled so the scenario's offered rate is a
    # meaningful fraction of capacity (storms overload, steady holds)
    base_lane = scenario.capacity_sigs_per_sec * 2
    base_h2c = scenario.capacity_sigs_per_sec
    completed_at: Dict[tuple, float] = {}
    backends: List[DedupAwareDevice] = []
    guarded = healer = breaker = None
    if scenario.mesh_devices:
        # chaos wiring: the model mesh behind the REAL supervisor
        # machinery — GuardedBls12381 (oracle-model fallback, breaker)
        # + parallel/selfheal.MeshHealer — so a timed bls.mesh_shard
        # wedge exercises production eject/reshape/readmit, measured
        # under traffic
        total = scenario.mesh_devices

        def make_backend(live):
            if not live:
                return None
            be = MeshModelDevice(clock, telemetry, live, total,
                                 base_lane, base_h2c,
                                 completed_at=completed_at)
            backends.append(be)
            return be

        device = make_backend(tuple(range(total)))
        # the last-resort cliff a wedged dispatch falls to mid-heal:
        # same verdict rule, oracle (~CPU) speed — the very cliff
        # self-healing exists to avoid paying for the whole mesh
        oracle = DedupAwareDevice(
            clock, telemetry, lane_sigs_per_sec=base_lane / 20,
            h2c_msgs_per_sec=base_h2c / 20, completed_at=completed_at)
        breaker = CircuitBreaker(
            failure_threshold=6, deadline_s=5.0, cooldown_s=0.5,
            name="loadgen_mesh", registry=registry)
        guarded = GuardedBls12381(device, breaker, oracle=oracle,
                                  registry=registry)

        def heal_install(be, live, epoch):
            if be is None:
                return        # zero healthy: oracle stays last resort
            guarded.swap_device(be)
            # production wiring parity (loader.make_mesh_healer): the
            # reshaped backend is known-good, so serving resumes now
            breaker.record_success()

        healer = selfheal.MeshHealer(
            [f"vdev{i}" for i in range(total)],
            probe=lambda i: faults.check(selfheal.FAULT_SITE,
                                         keys=(f"vdev{i}",)),
            make_backend=make_backend, install=heal_install,
            trip_threshold=1, probe_deadline_s=1.0, reprobe_s=0.05,
            registry=registry, recorder=recorder)
        guarded.healer = healer
        impl = guarded
    else:
        device = DedupAwareDevice(
            clock, telemetry, lane_sigs_per_sec=base_lane,
            h2c_msgs_per_sec=base_h2c, completed_at=completed_at)
        backends.append(device)
        impl = device
    kzg_backend = ModelKzgBackend(clock, telemetry)
    controller = AdmissionController(
        telemetry=telemetry, min_bucket=8, max_batch=256,
        slo_p50_s=0.1, tick_s=0.02, hold_ticks=25, clock=clock,
        registry=registry, recorder=recorder,
        name=f"loadgen_{scenario.name}")
    svc = AggregatingSignatureVerificationService(
        num_workers=1, queue_capacity=4000, max_batch_size=256,
        registry=registry, name="loadgen", overlap=False,
        controller=controller, telemetry=telemetry, recorder=recorder,
        clock=clock)

    submitted: Dict[str, int] = {c.label: 0 for c in VerifyClass}
    sheds: Dict[str, int] = {c.label: 0 for c in VerifyClass}
    pending: List[tuple] = []      # (event, future)
    by_class: Dict[str, List[float]] = {}
    kzg_setup = kzg.TrustedSetup(g1_lagrange=None,
                                 g2_monomial=[None, None])

    def observe_latency(fut, key, t_sub, cls_label):
        """Resolution-time latency capture: reading the device stamp
        when THIS future settles, not after the whole run — a later
        re-delivery of the same triple re-dispatches and would
        overwrite the stamp, inflating every earlier submission."""
        def _cb(f):
            if f.cancelled() or f.exception() is not None:
                return
            done_at = completed_at.get(key)
            if done_at is not None:
                by_class.setdefault(cls_label, []).append(
                    done_at - t_sub)
        fut.add_done_callback(_cb)

    t_start = clock()
    horizon = t_start + slots * model_mod.SECONDS_PER_SLOT
    # the PER-DISPATCH real-time bound: virtual progress is gated on
    # service quiescence below, so a genuinely wedged dispatch must
    # fail the harness by wall clock, not hang it.  PROGRESS-BASED —
    # reset whenever the service goes quiescent — so a long healthy
    # run (many slots, slow box) can never trip it cumulatively
    wall_stall_s = 120.0
    wall_deadline = time.monotonic() + wall_stall_s
    chaos = sorted(scenario.chaos, key=lambda c: c.t)
    chaos_idx = 0
    chaos_log: List[dict] = []

    def fire_chaos():
        """Arm/clear the schedule's faults as virtual time reaches
        them — the timed bls.mesh_shard wedge mid-steady-state."""
        nonlocal chaos_idx
        while chaos_idx < len(chaos) \
                and clock() - t_start >= chaos[chaos_idx].t:
            ce = chaos[chaos_idx]
            chaos_idx += 1
            if ce.action == "wedge":
                faults.inject(selfheal.FAULT_SITE, faults.Raise(
                    RuntimeError(f"chaos: vdev{ce.device} wedged"),
                    times=ce.times, key=f"vdev{ce.device}"))
            else:
                faults.clear(selfheal.FAULT_SITE)
            chaos_log.append({"t": round(clock() - t_start, 3),
                              "action": ce.action,
                              "device": ce.device})

    async def park_for_dispatch():
        """A dispatch is crossing the thread boundary: hold the
        VIRTUAL clock and park in a real sleep so the executor thread
        gets the GIL immediately.  Spinning sleep(0) here while
        advancing the clock was the r10/r11 block-import p50
        inflation: on a 1-core box the driver keeps the GIL for the
        full switch interval (~5 ms) per thread handoff, and every
        spin charged idle_tick VIRTUAL seconds to whatever was in
        flight — ~3.6 s p50 from pure scheduler wall time.  Holding
        the clock makes virtual latency what the model says it is
        (queue wait + modeled device time), on any core count."""
        if time.monotonic() > wall_deadline:
            raise RuntimeError(
                f"loadgen made no dispatch progress for "
                f"{wall_stall_s:.0f}s of wall time (wedged executor "
                "thread?)")
        await asyncio.sleep(0.0005)

    def note_progress():
        nonlocal wall_deadline
        wall_deadline = time.monotonic() + wall_stall_s

    bls.set_implementation(impl)
    kzg_prev_backend = kzg.get_backend()
    kzg.set_backend(kzg_backend)
    telemetry_prev = capacity_mod.swap_default(telemetry)
    # causal-timeline window: ring events are stamped on the REAL
    # monotonic clock even while scenario time is virtual, so the
    # attribution below reads real-wall overlap (model backends emit
    # no device-busy events — those metrics honestly come back
    # None/zero, the skip-if-missing contract)
    ring_mark = timeline.RING.mark()
    t_real0 = time.perf_counter()
    try:
        await svc.start()
        idx = 0
        idle_tick = 0.02
        while True:
            fire_chaos()
            if idx < len(events):
                ev = events[idx]
                t_ev = t_start + ev.t
                if clock() < t_ev:
                    if svc.inflight_dispatches:
                        await park_for_dispatch()
                        continue
                    note_progress()
                    # advance to the next arrival (bounded tick so the
                    # controller and flush deadlines stay live)
                    clock.advance(min(t_ev - clock(), idle_tick))
                    await asyncio.sleep(0)
                    continue
                idx += 1
                _M_EVENTS.labels(scenario=scenario.name,
                                 kind=ev.kind).inc()
                if ev.kind == "blob_batch":
                    # through the REAL kzg facade: arrival accounting
                    # (source="kzg") + the installed model backend
                    kzg.verify_blob_kzg_proof_batch(
                        [b"blob"] * ev.blobs, [b"c"] * ev.blobs,
                        [b"p"] * ev.blobs, kzg_setup)
                    continue
                submitted[ev.cls.label] += 1
                t_sub = clock()
                try:
                    if len(ev.triples) == 1:
                        pks, msg, sig = ev.triples[0]
                        fut = svc.verify(pks, msg, sig, cls=ev.cls,
                                         source=ev.source)
                        key = (msg, sig)
                    else:
                        fut = svc.verify_multi(list(ev.triples),
                                               cls=ev.cls,
                                               source=ev.source)
                        key = (ev.triples[0][1], ev.triples[0][2])
                except ServiceCapacityExceededError:
                    sheds[ev.cls.label] += 1
                    _M_SHEDS.labels(scenario=scenario.name,
                                    **{"class": ev.cls.label}).inc()
                    continue
                observe_latency(fut, key, t_sub, ev.cls.label)
                pending.append((ev, fut))
                await asyncio.sleep(0)
                continue
            # stream exhausted: drain the queue in virtual time (the
            # horizon guard bounds the drain — a wedged future must
            # fail the run loudly, not hang the harness)
            if svc._queue.qsize() == 0 and all(
                    f.done() for _, f in pending):
                break
            if clock() >= horizon + 120:
                raise RuntimeError(
                    "loadgen drain did not settle within the virtual "
                    "horizon (wedged task?)")
            if svc.inflight_dispatches:
                await park_for_dispatch()
                continue
            note_progress()
            clock.advance(idle_tick)
            await asyncio.sleep(0)

        # throughput window ends when the load drains — the brownout
        # cool-down below advances the clock further and must not
        # dilute sigs/sec on exactly the scenarios that browned out
        duration = clock() - t_start
        completed = 0
        failed_verdicts = 0
        for ev, fut in pending:
            try:
                ok = await fut
            except ServiceCapacityExceededError:
                sheds[ev.cls.label] += 1
                _M_SHEDS.labels(scenario=scenario.name,
                                **{"class": ev.cls.label}).inc()
                continue
            if ok:
                completed += len(ev.triples)
            else:
                failed_verdicts += 1
        # cool down through the brownout exit hysteresis so the report
        # shows the full enter→exit episode
        for _ in range(controller.hold_ticks + 20):
            if controller.brownout_level == 0:
                break
            clock.advance(max(telemetry.window_s / 4,
                              controller.tick_s))
            controller.tick()
        if healer is not None and chaos_idx >= len(chaos):
            # the schedule cleared its faults: give the background
            # reprobe (real time) a bounded window to readmit and grow
            # the mesh back, so the report shows the full cycle.  The
            # gate is the LIVE width (the grow INSTALL), not the
            # ledger — readmit precedes the grow reshape in the
            # reprobe loop, and exiting between the two would build
            # the report with reshapes.grow still 0
            total = scenario.mesh_devices
            t_wait = time.monotonic() + 5.0
            while (healer.ledger.ejected()
                   or len(healer.live_devices) < total) \
                    and time.monotonic() < t_wait:
                await asyncio.sleep(0.02)
        await svc.stop()
    finally:
        if scenario.chaos:
            faults.clear(selfheal.FAULT_SITE)
        if healer is not None:
            healer.close()
        capacity_mod.swap_default(telemetry_prev)
        kzg.set_backend(kzg_prev_backend)
        bls.reset_implementation()

    t_real1 = time.perf_counter()
    attribution = timeline.attribution(
        timeline.RING.snapshot(since_seq=ring_mark), t_real0, t_real1)

    # aggregate device evidence across every backend that served (the
    # chaos scenario swaps model backends on eject/readmit; counting
    # only the last would hide the wedge-window work)
    dev_dispatches = sum(b.dispatches for b in backends)
    dev_lanes = sum(b.lanes_total for b in backends)
    dev_unique = sum(b.unique_total for b in backends)
    dedup_ratio = (1.0 - dev_unique / dev_lanes) if dev_lanes else 0.0

    all_lats = [lat for ls in by_class.values() for lat in ls]
    p50, p99 = _percentiles(all_lats)
    per_class = {}
    for c in VerifyClass:
        ls = by_class.get(c.label, [])
        c50, c99 = _percentiles(ls)
        per_class[c.label] = {
            "submitted": submitted[c.label],
            "completed": len(ls),
            "shed": sheds[c.label],
            "p50_ms": c50, "p99_ms": c99}
    dispatch_counter = registry.metrics()["loadgen_dispatch_total"]
    dispatches = {kind: int(child.value) for (kind,), child
                  in dispatch_counter._items()}
    coalesced = int(
        registry.metrics()["loadgen_coalesced_total"].value)
    b_events = [e for e in recorder.snapshot()
                if e["kind"].startswith("brownout_")]
    _M_DEDUP.labels(scenario=scenario.name).set(round(dedup_ratio, 4))
    chaos_block = None
    if healer is not None:
        mesh_events = [e for e in recorder.snapshot()
                       if e["kind"].startswith("mesh_")]
        req = registry.metrics().get("bls_verify_requests_total")
        served = {}
        if req is not None:
            for (backend, reason), child in req._items():
                served[f"{backend}:{reason}"] = int(child.value)
        chaos_block = {
            "schedule": chaos_log,
            "mesh": healer.snapshot(),
            "ejects": sum(1 for e in mesh_events
                          if e["kind"] == "mesh_eject"),
            "readmits": sum(1 for e in mesh_events
                            if e["kind"] == "mesh_readmit"),
            "reshapes": dict(healer.reshapes),
            "recovery_s": healer.last_recovery_s,
            "recovered": not healer.ledger.ejected(),
            # no invalid signatures in this mix: every failed verdict
            # during device loss would be a WRONG verdict — the
            # zero-wrong-verdict chaos gate reads this
            "wrong_verdicts": failed_verdicts,
            "served": served,
            "events": [{k: e.get(k) for k in
                        ("kind", "device", "direction",
                         "from_devices", "to_devices", "epoch",
                         "trace_id")}
                       for e in mesh_events[:24]],
        }
    return {
        "scenario": scenario.name,
        "seed": seed,
        "slots": slots,
        "validators": model.validators,
        "committee_shaped": scenario.committee_shaped,
        "adversarial": scenario.adversarial,
        "classes_declared": list(scenario.classes),
        "stream": stats,
        "duration_s": round(duration, 3),
        "sigs_per_sec": round(completed / duration, 1) if duration
        else 0.0,
        "completed_triples": completed,
        "failed_verdicts": failed_verdicts,
        "p50_ms": p50, "p99_ms": p99,
        "by_class": per_class,
        "sheds": sheds,
        "shed_total": sum(sheds.values()),
        "dedup_ratio": round(dedup_ratio, 4),
        "coalesced": coalesced,
        "attribution": attribution,
        "dispatches": dispatches,
        "bisect_dispatches": dispatches.get("bisect", 0),
        "device": {"dispatches": dev_dispatches,
                   "lanes": dev_lanes,
                   "unique": dev_unique},
        **({"chaos": chaos_block} if chaos_block is not None else {}),
        "kzg": {"batches": kzg_backend.batches,
                "blobs": kzg_backend.blobs,
                "source_accounted": capacity_mod.SOURCE_KZG in
                telemetry.snapshot()["arrival_rate_per_second"]},
        "arrival_sources": sorted(
            telemetry.snapshot()["arrival_rate_per_second"]),
        "brownout": {
            "enters": sum(1 for e in b_events
                          if e["kind"] == "brownout_enter"
                          and e.get("from_level", 0) == 0),
            "exits": sum(1 for e in b_events
                         if e["kind"] == "brownout_exit"),
            "final_level": controller.brownout_level,
            "transitions": [
                {k: e.get(k) for k in ("kind", "level", "from_level",
                                       "utilization")}
                for e in b_events[:16]],
        },
    }


def run_scenario(scenario: Union[str, Scenario], seed: int = 1,
                 slots: int = 2,
                 validators: Optional[int] = None) -> dict:
    """One scenario end-to-end; returns the evidence dict."""
    if isinstance(scenario, str):
        scenario = scenarios_mod.get(scenario)
    return asyncio.run(_run_scenario(scenario, seed=seed, slots=slots,
                                     validators=validators))


def run_scenarios(names: Optional[Sequence[str]] = None, seed: int = 1,
                  slots: int = 2,
                  validators: Optional[int] = None) -> dict:
    """The sweep bench's ``mainnet`` phase embeds: every named (default
    all) scenario under the same seed, with a cross-scenario summary."""
    names = list(names or scenarios_mod.DEFAULT_SWEEP)
    out: dict = {"seed": seed, "slots": slots, "scenarios": {}}
    for name in names:
        out["scenarios"][name] = run_scenario(name, seed=seed,
                                              slots=slots,
                                              validators=validators)
    out["summary"] = summarize(out["scenarios"])
    return out


def summarize(scenarios: Dict[str, dict]) -> dict:
    """Cross-scenario acceptance view (what the bench gate reads)."""
    worst_block_import = 0
    worst_critical_p50 = 0.0
    dedup_floor = None
    for rep in scenarios.values():
        if not isinstance(rep, dict) or "by_class" not in rep:
            continue
        worst_block_import = max(
            worst_block_import,
            rep["sheds"].get("block_import", 0)
            + rep["sheds"].get("vip", 0))
        if not rep.get("adversarial"):
            # the critical-p50 bound holds on every PRODUCTION shape;
            # adversarial floods (deep bisect recursion) stress other
            # properties — their gate is sheds==0, not latency
            for cls in ("vip", "block_import"):
                worst_critical_p50 = max(
                    worst_critical_p50,
                    rep["by_class"][cls]["p50_ms"])
        if rep.get("committee_shaped"):
            d = rep.get("dedup_ratio", 0.0)
            dedup_floor = d if dedup_floor is None \
                else min(dedup_floor, d)
    return {
        "scenarios_run": len(scenarios),
        "block_import_sheds_worst": worst_block_import,
        "critical_p50_ms_worst": round(worst_critical_p50, 3),
        "committee_dedup_ratio_min": (round(dedup_floor, 4)
                                      if dedup_floor is not None
                                      else None),
    }
