"""Named traffic scenarios: the closed mix vocabulary loadgen runs.

Each scenario is a parameterization of the traffic model plus the
declared ``VerifyClass`` mix it exercises — declared, because the
point of a scenario is not just throughput: the priority/shed behavior
under each shape is part of what the driver measures and the bench
gates pin (BLOCK_IMPORT sheds must be zero under EVERY scenario,
committee-shaped mixes must hold the dedup-ratio floor).

The registry is a CLOSED vocabulary on purpose: scenario names are
also metric label values (``loadgen_*{scenario=...}``), and the
exposition's cardinality must stay bounded.
"""

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from .model import TrafficModel


@dataclass(frozen=True)
class ChaosEvent:
    """One timed fault action on the scenario's virtual clock.

    ``wedge`` arms a device-keyed Raise at the ``bls.mesh_shard``
    site (infra/faults.py) — the model mesh's collective dispatch AND
    that device's isolation probe fail, driving the REAL
    GuardedBls12381 + MeshHealer eject/reshape path; ``clear`` removes
    the faults so the background reprobe re-admits the device."""

    t: float                 # virtual seconds into the run
    action: str              # "wedge" | "clear"
    device: int = 0          # sick device index (wedge)
    times: Optional[int] = None   # fault budget (None = until clear)


@dataclass(frozen=True)
class Scenario:
    """One named mix: model overrides + what it is meant to exercise."""

    name: str
    description: str
    model: TrafficModel
    # the classes this mix submits (declared, asserted by tests so a
    # scenario exercises priority handling, not just throughput)
    classes: Tuple[str, ...]
    # committee-shaped mixes must hold the dedup-ratio floor in the
    # bench gate; adversarial dup-collapse opts out
    committee_shaped: bool = True
    adversarial: bool = False
    # offered-load scale: multiplies the modeled device's capacity
    # deficit (1.0 = the default driver capacity)
    capacity_sigs_per_sec: float = 1500.0
    # > 0: route the model through the REAL supervisor machinery —
    # GuardedBls12381 + breaker + parallel/selfheal.MeshHealer over a
    # model mesh of this many devices — so the chaos schedule below
    # exercises production eject/reshape/readmit, not a stub
    mesh_devices: int = 0
    # timed fault schedule on the virtual clock (requires mesh_devices)
    chaos: Tuple[ChaosEvent, ...] = ()


def _m(**kw) -> TrafficModel:
    return TrafficModel(**kw)


SCENARIOS: Dict[str, Scenario] = {}


def _register(s: Scenario) -> Scenario:
    SCENARIOS[s.name] = s
    return s


STEADY_STATE = _register(Scenario(
    name="steady_state",
    description="mid-epoch mainnet shape: committee-duplicated "
                "attestation subnets, aggregation waves, sync "
                "committee, a few blobs per block",
    model=_m(),
    classes=("vip", "block_import", "sync_critical", "gossip"),
))

EPOCH_BOUNDARY_STORM = _register(Scenario(
    name="epoch_boundary_storm",
    description="epoch-boundary slot: 3x attestation volume plus an "
                "OPTIMISTIC deferred-revalidation burst — the shape "
                "that drives brownout entry",
    model=_m(first_slot=992,       # slot 992 % 32 == 0 in-window
             storm_factor=3.0),
    classes=("vip", "block_import", "sync_critical", "gossip",
             "optimistic"),
    # tight capacity: the boundary storm must actually OVERLOAD the
    # modeled device so brownout entry + shed-by-class are exercised,
    # not just higher queue depths
    capacity_sigs_per_sec=300.0,
))

INVALID_SIG_FLOOD = _register(Scenario(
    name="invalid_sig_flood",
    description="adversarial forged-signature flood: failed batches "
                "force the service's bisect recursion to isolate the "
                "bad lanes",
    model=_m(invalid_rate=0.25, blobs_per_block=0.0,
             sync_message_visibility=0.0,
             sync_contribution_visibility=0.0),
    classes=("vip", "block_import", "sync_critical", "gossip"),
    adversarial=True,
))

EQUIVOCATION_REPLAY = _register(Scenario(
    name="equivocation_replay",
    description="adversarial replay storm: identical triples "
                "re-delivered in-flight (coalescing fan-out), some "
                "replicas claiming a higher class (lane promotion)",
    model=_m(equivocation_rate=0.4, redelivery=0.3,
             blobs_per_block=0.0),
    classes=("vip", "block_import", "sync_critical", "gossip"),
    adversarial=True,
))

DUP_COLLAPSE = _register(Scenario(
    name="dup_collapse",
    description="adversarial dup-collapse: every lane a fresh "
                "message, starving the H(m) cache and the "
                "unique-message pipeline of all reuse",
    model=_m(dup_collapse=True, blobs_per_block=0.0),
    classes=("vip", "block_import", "sync_critical", "gossip"),
    committee_shaped=False,
    adversarial=True,
))

BLOB_STORM = _register(Scenario(
    name="blob_storm",
    description="deneb blob waves at the spec maximum through the "
                "guarded KZG backend alongside the signature load — "
                "blob demand must be visible as its own source",
    model=_m(blobs_per_block=6.0),
    classes=("vip", "block_import", "sync_critical", "gossip"),
))

CHAOS_DEVICE_LOSS = _register(Scenario(
    name="chaos_device_loss",
    description="mid-steady-state device loss: a timed bls.mesh_shard "
                "wedge kills one chip of the 8-device model mesh; the "
                "REAL healer must eject it, reshape to 4 and keep "
                "serving with ZERO protected-class sheds and zero "
                "wrong verdicts, then grow back on the clear",
    model=_m(),
    classes=("vip", "block_import", "sync_critical", "gossip"),
    # adversarial: the p50 bound is waived (capacity deliberately
    # halves mid-run) — the gates are sheds==0 and wrong verdicts==0;
    # the committee shape itself is unchanged, so the dedup floor holds
    committee_shaped=True,
    adversarial=True,
    mesh_devices=8,
    chaos=(ChaosEvent(t=4.0, action="wedge", device=3),
           ChaosEvent(t=14.0, action="clear")),
))

# names in registration order — the default `cli loadgen --scenario
# all` / bench `mainnet` phase sweep
DEFAULT_SWEEP = tuple(SCENARIOS)


def get(name: str) -> Scenario:
    try:
        return SCENARIOS[name]
    except KeyError:
        raise KeyError(
            f"unknown scenario {name!r}; known: {', '.join(SCENARIOS)}"
        ) from None
