"""Mainnet-shape load generator (ROADMAP 4).

Bench has always measured synthetic uniform batches; production
traffic is bursty and committee-shaped — the exact regime the
committee-consensus and bursty-arrival papers (PAPERS.md) measure, and
the regime every PR-5..8 win is a function of.  This package generates
that shape and replays it against the REAL verify pipeline:

- ``model``     — seeded-deterministic gossip-replay traffic model of
                  a 1M-validator network: 64 attestation subnets,
                  committee-size/duplication curves derived from the
                  validator count, slot-aligned aggregation waves,
                  sync-committee messages + contributions, deneb blob
                  waves, epoch-boundary storms;
- ``scenarios`` — named traffic mixes, including adversarial shapes
                  (invalid-signature floods, equivocation replays,
                  dup-collapse) with declared VerifyClass mixes;
- ``driver``    — replays a scenario against the real
                  ``AggregatingSignatureVerificationService`` +
                  ``AdmissionController`` under the injectable virtual
                  clock, emitting per-scenario/per-class evidence
                  (``cli loadgen`` and bench's ``mainnet`` phase).
"""

from . import model, scenarios, driver  # noqa: F401

__all__ = ["model", "scenarios", "driver"]
