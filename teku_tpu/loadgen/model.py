"""Gossip-replay traffic model of a mainnet-scale validator set.

Everything is DERIVED from the validator count the way the consensus
spec derives it (reference: spec get_committee_count_per_slot /
compute_subnet_for_attestation; SyncCommitteeUtil subcommittees), so a
1M-validator model produces the real mainnet shape — 64 committees per
slot across 64 attestation subnets, ~490-member committees whose
members all sign the SAME AttestationData (the duplication curve the
dedup pipeline exploits), slot-aligned aggregation waves (3-signature
atomic sets, one of which re-uses the committee's message), a
512-member sync committee whose members all sign the slot's head root,
deneb blob batches, and epoch-boundary storms.

Determinism contract (the bench reproducibility rule): event streams
are a pure function of ``(model, seed, slots)`` — one ``random.Random``
seeded from the arguments, NO wall clock, no process state.  The same
seed replays bit-identical traffic on any host, so a regression gate
can cite a scenario run the way it cites a bench shape.

Synthetic crypto material: the device model under the virtual clock
costs dispatches by SHAPE (lanes, unique messages), not by field
arithmetic, so keys/signatures are compact deterministic tokens.
Invalid signatures (adversarial floods) carry ``INVALID_SIG_PREFIX``
so the device model — like a real device — fails the whole batch and
forces the service's bisect path.
"""

import hashlib
import random
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Tuple

from ..services.admission import VerifyClass

# closed event-kind vocabulary (also a metric label set — bounded)
EVENT_KINDS = ("block", "block_import", "attestation", "aggregate",
               "sync_message", "sync_contribution", "blob_batch")

# a signature with this prefix fails device verification (the model
# device's stand-in for a forged signature)
INVALID_SIG_PREFIX = b"!BAD"

# mainnet constants the shape derives from (spec values)
SLOTS_PER_EPOCH = 32
SECONDS_PER_SLOT = 12.0
MAX_COMMITTEES_PER_SLOT = 64
TARGET_COMMITTEE_SIZE = 128
ATTESTATION_SUBNET_COUNT = 64
TARGET_AGGREGATORS_PER_COMMITTEE = 16
SYNC_COMMITTEE_SIZE = 512
SYNC_COMMITTEE_SUBNET_COUNT = 4
TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE = 16
MAX_BLOBS_PER_BLOCK = 6


def committees_per_slot(validators: int) -> int:
    """Spec get_committee_count_per_slot."""
    return max(1, min(MAX_COMMITTEES_PER_SLOT,
                      validators // SLOTS_PER_EPOCH
                      // TARGET_COMMITTEE_SIZE))


def committee_size(validators: int) -> int:
    """Members per committee at this validator count (the duplication
    factor of one AttestationData's gossip)."""
    return max(1, validators // SLOTS_PER_EPOCH
               // committees_per_slot(validators))


def subnet_for(validators: int, slot: int, committee: int) -> int:
    """Spec compute_subnet_for_attestation."""
    since_epoch_start = (committees_per_slot(validators)
                         * (slot % SLOTS_PER_EPOCH))
    return (since_epoch_start + committee) % ATTESTATION_SUBNET_COUNT


@dataclass(frozen=True)
class Event:
    """One gossip arrival: a verification task (or blob batch) at a
    virtual time offset from the window start."""

    t: float                       # seconds from window start
    kind: str                      # EVENT_KINDS member
    cls: VerifyClass
    triples: Tuple = ()            # ((pks, msg, sig), ...)
    valid: bool = True
    source: Optional[str] = None   # capacity arrival stream override
    blobs: int = 0                 # blob_batch only
    subnet: Optional[int] = None   # attestation events
    committee: Optional[int] = None


@dataclass(frozen=True)
class TrafficModel:
    """Shape parameters; everything else derives from ``validators``.

    The visibility fractions model ONE node's view: it subscribes to
    ``local_subnets`` of the 64 attestation subnets (every committee
    member's single attestation on those arrives), while the global
    topics (blocks, aggregates, sync contributions) arrive from every
    committee — sampled by the visibility fractions to keep one node's
    stream at one node's volume."""

    validators: int = 1_000_000
    local_subnets: int = 2
    participation: float = 0.95
    # fraction of singles re-delivered by gossip (in-flight duplicate
    # pressure on the coalescing layer even in the steady state)
    redelivery: float = 0.10
    # fraction of the global aggregate/sync-contribution waves one
    # node's mesh actually delivers
    aggregate_visibility: float = 0.25
    sync_message_visibility: float = 0.25
    sync_contribution_visibility: float = 0.5
    # mean blobs per block (Poisson-ish, capped at the spec max)
    blobs_per_block: float = 3.0
    # epoch-boundary storm: multiplier on the boundary slot's
    # attestation volume (late prev-epoch votes + re-broadcast) plus an
    # OPTIMISTIC deferred-revalidation burst of the same size
    storm_factor: float = 1.0
    # adversarial knobs (scenario layer sets these)
    invalid_rate: float = 0.0       # fraction of forged signatures
    equivocation_rate: float = 0.0  # fraction of singles replayed
    equivocation_copies: int = 3    # replays per equivocated message
    dup_collapse: bool = False      # every lane's message unique
    # first slot of the window (slot % 32 == 0 puts the epoch boundary
    # inside the window)
    first_slot: int = 1000

    def with_overrides(self, **kw) -> "TrafficModel":
        return replace(self, **kw)


def _pk(validator_index: int) -> bytes:
    return b"pk" + validator_index.to_bytes(6, "big")


def _sig(msg: bytes, validator_index: int, valid: bool = True) -> bytes:
    body = hashlib.blake2b(msg + validator_index.to_bytes(6, "big"),
                           digest_size=12).digest()
    return (INVALID_SIG_PREFIX if not valid else b"sig:") + body


def _spread(rng: random.Random, mean: float) -> float:
    """Propagation delay: exponential, bounded (a gossip mesh delivers
    within a couple of seconds or not at all)."""
    return min(rng.expovariate(1.0 / mean), 6 * mean)


class _Counters:
    """Mutable generation state threaded through the per-slot
    emitters (member sampling without replacement per committee)."""

    def __init__(self, rng: random.Random):
        self.rng = rng
        self.uniq = 0

    def nonce(self) -> int:
        self.uniq += 1
        return self.uniq


def generate_events(model: TrafficModel, seed: int,
                    slots: int) -> List[Event]:
    """The deterministic event stream: ``slots`` consecutive slots of
    one node's gossip arrivals, sorted by arrival time."""
    rng = random.Random(f"loadgen:{seed}:{model.validators}")
    st = _Counters(rng)
    events: List[Event] = []
    n_committees = committees_per_slot(model.validators)
    c_size = committee_size(model.validators)
    sync_sub_size = SYNC_COMMITTEE_SIZE // SYNC_COMMITTEE_SUBNET_COUNT
    for s in range(slots):
        slot = model.first_slot + s
        t0 = s * SECONDS_PER_SLOT
        is_boundary = slot % SLOTS_PER_EPOCH == 0
        storm = model.storm_factor if is_boundary else 1.0
        events.extend(_slot_block(model, st, slot, t0, n_committees,
                                  c_size))
        events.extend(_slot_attestations(model, st, slot, t0,
                                         n_committees, c_size, storm))
        events.extend(_slot_aggregates(model, st, slot, t0,
                                       n_committees, c_size, storm))
        events.extend(_slot_sync(model, st, slot, t0, sync_sub_size))
        events.extend(_slot_blobs(model, st, slot, t0))
    events.sort(key=lambda e: e.t)
    return events


def _slot_block(model, st, slot, t0, n_committees,
                c_size) -> List[Event]:
    msg = b"block-%d" % slot
    proposer = st.rng.randrange(model.validators)
    block = Event(t=t0 + 0.05 + _spread(st.rng, 0.1), kind="block",
                  cls=VerifyClass.VIP,
                  triples=(((_pk(proposer),), msg,
                            _sig(msg, proposer)),))
    # the block's IMPORT signature batch follows: the body carries the
    # previous slot's packed aggregates, re-verified as one
    # BLOCK_IMPORT task — messages are the previous slot's committee
    # AttestationData (duplication reaches across the import boundary)
    import_triples = []
    for c in range(min(8, n_committees)):
        m = _att_msg(model, st, slot - 1, c)
        signer = c * c_size + st.rng.randrange(c_size)
        participants = tuple(
            _pk(c * c_size + i)
            for i in range(0, c_size, max(1, c_size // 16)))
        import_triples.append((participants, m, _sig(m, signer)))
    block_import = Event(
        t=block.t + 0.15 + _spread(st.rng, 0.1), kind="block_import",
        cls=VerifyClass.BLOCK_IMPORT, triples=tuple(import_triples))
    return [block, block_import]


def _att_msg(model, st, slot, committee) -> bytes:
    base = b"att-%d-%d" % (slot, committee)
    if model.dup_collapse:
        # adversarial dup-collapse: every lane a fresh message — the
        # H(m) cache and the unique-message pipeline get zero reuse
        return base + b"/%d" % st.nonce()
    return base


def _slot_attestations(model, st, slot, t0, n_committees, c_size,
                       storm) -> List[Event]:
    """Single attestations on the locally-subscribed subnets: every
    participating member of each local committee signs the committee's
    ONE AttestationData — the duplication curve is the committee
    size."""
    rng = st.rng
    out: List[Event] = []
    due = t0 + SECONDS_PER_SLOT / 3
    # one committee per locally-subscribed subnet; the spec mapping
    # rotates which SUBNET each committee lands on as slots advance
    local = list(range(min(model.local_subnets, n_committees)))
    for committee in local:
        subnet = subnet_for(model.validators, slot, committee)
        msg = None if model.dup_collapse else _att_msg(
            model, st, slot, committee)
        base = committee * c_size
        n_members = int(c_size * model.participation * storm)
        for j in range(n_members):
            member = base + (j % c_size)
            m = (_att_msg(model, st, slot, committee)
                 if model.dup_collapse else msg)
            valid = rng.random() >= model.invalid_rate
            triple = ((_pk(member),), m, _sig(m, member, valid))
            t = due + _spread(rng, 0.25)
            out.append(Event(t=t, kind="attestation",
                             cls=VerifyClass.GOSSIP, triples=(triple,),
                             valid=valid, subnet=subnet,
                             committee=committee))
            if rng.random() < model.redelivery:
                # gossip re-delivery: the identical triple again while
                # likely still in flight (coalescing pressure)
                out.append(Event(t=t + _spread(rng, 0.05),
                                 kind="attestation",
                                 cls=VerifyClass.GOSSIP,
                                 triples=(triple,), valid=valid,
                                 subnet=subnet, committee=committee))
            if rng.random() < model.equivocation_rate:
                # equivocation replay storm: the same triple hammered
                # several times, one replica claiming a HIGHER class —
                # exercises coalescing fan-out and lane promotion
                for k in range(model.equivocation_copies):
                    cls = (VerifyClass.SYNC_CRITICAL if k == 0
                           else VerifyClass.GOSSIP)
                    out.append(Event(
                        t=t + 0.01 + _spread(rng, 0.03), cls=cls,
                        kind="attestation", triples=(triple,),
                        valid=valid, subnet=subnet,
                        committee=committee))
        if storm > 1.0:
            # boundary storm rider: deferred prev-epoch votes
            # re-entering as OPTIMISTIC revalidation
            prev_msg = _att_msg(model, st, slot - 1, committee)
            for j in range(int(n_members * (storm - 1.0) / storm)):
                member = base + (j % c_size)
                m = (_att_msg(model, st, slot - 1, committee)
                     if model.dup_collapse else prev_msg)
                out.append(Event(
                    t=t0 + _spread(rng, 0.4), kind="attestation",
                    cls=VerifyClass.OPTIMISTIC,
                    triples=(((_pk(member),), m, _sig(m, member)),),
                    subnet=subnet, committee=committee))
    return out


def _slot_aggregates(model, st, slot, t0, n_committees, c_size,
                     storm) -> List[Event]:
    """The aggregation wave at 2/3 slot: aggregates arrive from EVERY
    committee (global topic), each a 3-signature atomic set whose third
    message is the committee's AttestationData — committee duplication
    reaches across the single/aggregate boundary."""
    rng = st.rng
    out: List[Event] = []
    due = t0 + 2 * SECONDS_PER_SLOT / 3
    n_aggs = int(n_committees * TARGET_AGGREGATORS_PER_COMMITTEE
                 * model.aggregate_visibility * storm)
    for a in range(n_aggs):
        committee = a % n_committees
        aggregator = committee * c_size + rng.randrange(c_size)
        att_msg = _att_msg(model, st, slot, committee)
        sel_msg = b"sel-%d-%d-%d" % (slot, committee, aggregator)
        proof_msg = b"agg-%d-%d-%d" % (slot, committee, aggregator)
        participants = tuple(
            _pk(committee * c_size + i)
            for i in range(0, c_size,
                           max(1, c_size // 16)))  # compact pk set
        valid = rng.random() >= model.invalid_rate
        out.append(Event(
            t=due + _spread(rng, 0.3), kind="aggregate",
            cls=VerifyClass.SYNC_CRITICAL, valid=valid,
            committee=committee,
            subnet=subnet_for(model.validators, slot, committee),
            triples=(
                ((_pk(aggregator),), sel_msg,
                 _sig(sel_msg, aggregator)),
                ((_pk(aggregator),), proof_msg,
                 _sig(proof_msg, aggregator)),
                (participants, att_msg,
                 _sig(att_msg, aggregator, valid)),
            )))
    return out


def _slot_sync(model, st, slot, t0, sub_size) -> List[Event]:
    """Sync-committee wave: every participating member signs the SAME
    head root (maximum duplication — the second device verb's natural
    shape), then per-subcommittee contributions aggregate it."""
    rng = st.rng
    out: List[Event] = []
    msg = b"sync-%d" % slot
    due = t0 + SECONDS_PER_SLOT / 3
    n_msgs = int(SYNC_COMMITTEE_SIZE * model.participation
                 * model.sync_message_visibility)
    for j in range(n_msgs):
        member = 7_000_000 + (slot * SYNC_COMMITTEE_SIZE
                              + j) % model.validators
        out.append(Event(
            t=due + _spread(rng, 0.25), kind="sync_message",
            cls=VerifyClass.GOSSIP, source="sync_committee",
            triples=(((_pk(member),), msg, _sig(msg, member)),)))
    n_contrib = int(SYNC_COMMITTEE_SUBNET_COUNT
                    * TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE
                    * model.sync_contribution_visibility)
    contrib_due = t0 + 2 * SECONDS_PER_SLOT / 3
    for c in range(n_contrib):
        sub = c % SYNC_COMMITTEE_SUBNET_COUNT
        aggregator = 7_000_000 + (slot * 64 + c) % model.validators
        sel_msg = b"synsel-%d-%d-%d" % (slot, sub, aggregator)
        env_msg = b"synenv-%d-%d-%d" % (slot, sub, aggregator)
        participants = tuple(
            _pk(7_000_000 + (slot * SYNC_COMMITTEE_SIZE + sub
                             * sub_size + i) % model.validators)
            for i in range(0, sub_size, max(1, sub_size // 16)))
        out.append(Event(
            t=contrib_due + _spread(rng, 0.3),
            kind="sync_contribution", cls=VerifyClass.SYNC_CRITICAL,
            source="sync_committee",
            triples=(
                ((_pk(aggregator),), sel_msg,
                 _sig(sel_msg, aggregator)),
                ((_pk(aggregator),), env_msg,
                 _sig(env_msg, aggregator)),
                (participants, msg, _sig(msg, aggregator)),
            )))
    return out


def _slot_blobs(model, st, slot, t0) -> List[Event]:
    if model.blobs_per_block <= 0:
        return []
    rng = st.rng
    # Poisson-shaped count via the seeded rng, capped at the spec max
    n = 0
    lam = model.blobs_per_block
    while rng.random() < lam / (lam + 1) and n < MAX_BLOBS_PER_BLOCK:
        n += 1
    if n == 0:
        return []
    # blob verification's class is declared where the verb lives
    # (crypto/kzg.py): DA checks gate import/sync, never sheddable
    from ..crypto.kzg import KZG_ARRIVAL_SOURCE, kzg_verify_class
    return [Event(t=t0 + 0.3 + _spread(st.rng, 0.2),
                  kind="blob_batch", cls=kzg_verify_class(),
                  source=KZG_ARRIVAL_SOURCE, blobs=n)]


# --------------------------------------------------------------------------
# Stream introspection (tests + reports)
# --------------------------------------------------------------------------

def stream_stats(events: Sequence[Event]) -> dict:
    """Structural summary of a generated stream: per-kind/per-class
    counts, lane/unique-message totals, the attestation duplication
    curve, and subnet coverage."""
    by_kind: Dict[str, int] = {k: 0 for k in EVENT_KINDS}
    by_class: Dict[str, int] = {c.label: 0 for c in VerifyClass}
    lanes = 0
    blobs = 0
    msgs: Dict[bytes, int] = {}
    att_msgs: Dict[bytes, int] = {}
    subnets = set()
    for e in events:
        by_kind[e.kind] += 1
        by_class[e.cls.label] += len(e.triples) or e.blobs
        lanes += len(e.triples)
        blobs += e.blobs
        if e.subnet is not None:
            subnets.add(e.subnet)
        for _pks, m, _sig_ in e.triples:
            msgs[m] = msgs.get(m, 0) + 1
            if e.kind == "attestation":
                att_msgs[m] = att_msgs.get(m, 0) + 1
    dup_curve = (sorted(att_msgs.values()) if att_msgs else [])
    return {
        "events": len(events),
        "lanes": lanes,
        "unique_messages": len(msgs),
        "dedup_ratio": round(1.0 - len(msgs) / lanes, 4) if lanes
        else 0.0,
        "by_kind": by_kind,
        "by_class": by_class,
        "blobs": blobs,
        "subnets_seen": sorted(subnets),
        "attestation_dup_mean": (round(sum(dup_curve)
                                       / len(dup_curve), 2)
                                 if dup_curve else 0.0),
        "attestation_dup_max": dup_curve[-1] if dup_curve else 0,
    }
