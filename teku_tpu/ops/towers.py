"""BLS12-381 extension-field towers on TPU limb arithmetic (JAX).

Fq2 = Fq[u]/(u^2+1) as a tuple (c0, c1) of limb arrays; Fq6 = Fq2[v]/(v^3-xi)
with xi = 1+u as a 3-tuple of Fq2; Fq12 = Fq6[w]/(w^2-v) as a 2-tuple of Fq6.
Tuples are JAX pytrees, so every op broadcasts over leading batch dims and
composes with jit/scan/shard_map untouched.

Algorithms mirror the pure-Python oracle (teku_tpu/crypto/bls/fields.py) —
Karatsuba Fq2/Fq6/Fq12 mul, Chung-Hasan Fq6 squaring, Granger-Scott
cyclotomic squaring, computed Frobenius constants — on the lazy-reduction
limb layer (see limbs.py):

- additive ops and conjugation are free (elementwise, no carries);
- each tower op gathers its independent base-field multiplies into ONE
  wide fp.mont_mul call (same multiply count as the oracle's Karatsuba,
  ~20x smaller XLA graphs, wide lanes for the TPU VPU);
- Fq12-level ops compress their outputs back to one "unit" so values
  stay inside the limb layer's operand-magnitude contract; Fq2/Fq6
  results may be lazy (a few units) and call sites track that.

The reference client gets this layer from native blst (reference:
infrastructure/bls/src/main/java/tech/pegasys/teku/bls/impl/blst/
BlstBLS12381.java).  Validation: tests/test_ops_towers.py checks every op
against the oracle.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..crypto.bls import fields as F
from ..crypto.bls.constants import P
from . import limbs as fp

# --------------------------------------------------------------------------
# Constants (host-computed, Montgomery form)
# --------------------------------------------------------------------------


def fq2_const(c) -> tuple:
    """Host: oracle Fq2 tuple of ints -> Montgomery limb constant pair."""
    return (np.asarray(fp.int_to_mont(c[0])), np.asarray(fp.int_to_mont(c[1])))


FQ2_ZERO_NP = fq2_const((0, 0))
FQ2_ONE_NP = fq2_const((1, 0))

FROB6_C1 = fq2_const(F.FROB6_C1)
FROB6_C2 = fq2_const(F.FROB6_C2)
FROB12_C1 = fq2_const(F.FROB12_C1)

# sqrt constants for q = P^2 ≡ 9 (mod 16): c1 = sqrt(-1), c2 = sqrt(c1),
# c3 = sqrt(-c1); all four of {cand, c1*cand, c2*cand, c3*cand} are tried
# branch-free (RFC 9380 appendix I.3 constant-time sqrt shape).
_SQRT_M1 = F.fq2_sqrt((P - 1, 0))
_SQRT_C2 = F.fq2_sqrt(_SQRT_M1)
_SQRT_C3 = F.fq2_sqrt(F.fq2_neg(_SQRT_M1))
assert _SQRT_M1 and _SQRT_C2 and _SQRT_C3
SQRT_EXP = (P * P + 7) // 16
assert (P * P) % 16 == 9


def _bcast2(c, like):
    """Broadcast an Fq2 numpy constant to the batch shape of `like`."""
    shape = like[0].shape
    return (jnp.broadcast_to(jnp.asarray(c[0]), shape),
            jnp.broadcast_to(jnp.asarray(c[1]), shape))


# --------------------------------------------------------------------------
# Lane stacking helpers
# --------------------------------------------------------------------------

def _stk(*xs):
    return jnp.stack(xs, axis=-2)


def _fq2s(elems):
    """Stack fq2 tuples along a new -2 lane axis."""
    return (jnp.stack([e[0] for e in elems], axis=-2),
            jnp.stack([e[1] for e in elems], axis=-2))


def _fq2u(s):
    """Unstack the -2 lane axis back to a list of fq2 tuples."""
    n = s[0].shape[-2]
    return [(s[0][..., i, :], s[1][..., i, :]) for i in range(n)]


def tree_stack(elems):
    """Stack arbitrary pytrees along a new LEADING axis."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0), *elems)


def tree_unstack(t, n):
    return [jax.tree_util.tree_map(lambda x: x[i], t) for i in range(n)]


def fq2_compress(a):
    t = fp.compress(_stk(a[0], a[1]))
    return (t[..., 0, :], t[..., 1, :])


def fq6_compress(a):
    t = fp.compress(_stk(a[0][0], a[0][1], a[1][0], a[1][1],
                         a[2][0], a[2][1]))
    return ((t[..., 0, :], t[..., 1, :]), (t[..., 2, :], t[..., 3, :]),
            (t[..., 4, :], t[..., 5, :]))


def fq12_compress(a):
    comps = [c for six in a for two in six for c in two]
    t = fp.compress(jnp.stack(comps, axis=-2))
    out = [t[..., i, :] for i in range(12)]
    return (((out[0], out[1]), (out[2], out[3]), (out[4], out[5])),
            ((out[6], out[7]), (out[8], out[9]), (out[10], out[11])))


def fq12_reduce_value(a):
    """Re-bound the integer VALUE of every component to (-P, 2P) without
    changing residues: one wide Montgomery multiply by R (x*R*R^-1 = x).

    compress() bounds limb magnitudes but leaves values untouched; ops
    whose output includes an additive copy of their input (cyclotomic
    squaring's conjugate terms) would otherwise double their value every
    iteration until the product columns overflow int64.
    """
    comps = [c for six in a for two in six for c in two]
    t = fp.mont_mul(jnp.stack(comps, axis=-2), jnp.asarray(fp.ONE_MONT))
    out = [t[..., i, :] for i in range(12)]
    return (((out[0], out[1]), (out[2], out[3]), (out[4], out[5])),
            ((out[6], out[7]), (out[8], out[9]), (out[10], out[11])))


# --------------------------------------------------------------------------
# Fq2 — additive ops are lazy/free; results of mul/sqr are <= 3 units
# --------------------------------------------------------------------------

def fq2_add(a, b):
    return (fp.add(a[0], b[0]), fp.add(a[1], b[1]))


def fq2_sub(a, b):
    return (fp.sub(a[0], b[0]), fp.sub(a[1], b[1]))


def fq2_neg(a):
    return (fp.neg(a[0]), fp.neg(a[1]))


def fq2_double(a):
    return fq2_add(a, a)


def fq2_mul(a, b):
    # Karatsuba, 3 base muls in one width-3 call; output <= 3 units
    t = fp.mont_mul(_stk(a[0], a[1], fp.add(a[0], a[1])),
                    _stk(b[0], b[1], fp.add(b[0], b[1])))
    t0, t1, t2 = t[..., 0, :], t[..., 1, :], t[..., 2, :]
    return (fp.sub(t0, t1), fp.sub(fp.sub(t2, t0), t1))


def fq2_sqr(a):
    # (a0+a1)(a0-a1), a0*a1 — one width-2 call; output <= 2 units
    t = fp.mont_mul(_stk(fp.add(a[0], a[1]), a[0]),
                    _stk(fp.sub(a[0], a[1]), a[1]))
    return (t[..., 0, :], fp.double(t[..., 1, :]))


def fq2_mul_fp(a, s):
    """Multiply both components by an Fq (Montgomery) scalar."""
    t = fp.mont_mul(_stk(a[0], a[1]), s[..., None, :])
    return (t[..., 0, :], t[..., 1, :])


def fq2_conj(a):
    return (a[0], fp.neg(a[1]))


def fq2_mul_by_xi(a):
    # a * (1 + u) = (a0 - a1) + (a0 + a1) u  — doubles the unit count
    return (fp.sub(a[0], a[1]), fp.add(a[0], a[1]))


def fq2_inv(a):
    """Branch-free inverse; inv(0) = 0 (callers select around zero).
    Input may be lazy up to ~5 units.  The underlying Fq inversion of
    the norm is batched across the whole batch shape (ONE Fermat
    exponentiation per call via limbs.inv_many)."""
    sq = fp.mont_sqr(_stk(a[0], a[1]))
    norm = fp.compress(fp.add(sq[..., 0, :], sq[..., 1, :]))
    ninv = fp.inv_many(norm)
    t = fp.mont_mul(_stk(a[0], a[1]), ninv[..., None, :])
    return (t[..., 0, :], fp.neg(t[..., 1, :]))


def fq2_is_zero(a):
    c = fp.canonical(_stk(a[0], a[1]))
    return jnp.all(c == 0, axis=(-2, -1))


def fq2_eq(a, b):
    return fq2_is_zero(fq2_sub(a, b))


def fq2_select(cond, a, b):
    return (fp.select(cond, a[0], b[0]), fp.select(cond, a[1], b[1]))


def fq2_pow_static(a, e: int):
    """a^e for a static exponent via scan (1 sqr + 1 selected mul / bit).
    `a` may be lazy up to ~4 units (the scan state stays <= 3 units)."""
    assert e > 0
    bits = np.array([(e >> i) & 1 for i in range(e.bit_length())][::-1],
                    dtype=np.int64)
    a = fq2_compress(a)   # both the init and the per-bit multiplier

    def body(acc, bit):
        acc = fq2_sqr(acc)
        acc = fq2_select(bit != 0, fq2_mul(acc, a), acc)
        return acc, None

    acc, _ = lax.scan(body, a, jnp.asarray(bits[1:]))
    return acc


def fq2_sqrt(a):
    """Branch-free square root in Fq2 (q ≡ 9 mod 16).

    Returns (ok, root): ok is False where `a` is a non-residue (root lanes
    are then garbage and must be selected away by the caller).
    `a` may be lazy (a few units).
    """
    a = fq2_compress(a)
    cand = fq2_pow_static(a, SQRT_EXP)   # a = 0 -> cand = 0, matches below
    consts = [fq2_const(c) for c in (_SQRT_M1, _SQRT_C2, _SQRT_C3)]
    cands = [cand] + [fq2_mul(_bcast2(c, cand), cand) for c in consts]
    # all four squares and the four differences checked in ONE canonical map
    sq = fq2_sqr(_fq2s(cands))
    d = fq2_sub(sq, (a[0][..., None, :], a[1][..., None, :]))
    zc = fp.canonical(jnp.stack([d[0], d[1]], axis=-2))  # (..., 4?, 2, L)
    matches = jnp.all(zc == 0, axis=(-2, -1))            # (..., 4)
    found = jnp.zeros(matches.shape[:-1], dtype=bool)
    root = cand
    for i in range(4):
        m = matches[..., i] & ~found
        root = fq2_select(m, cands[i], root)
        found = found | m
    return found, root


def fq2_is_large(a_plain):
    """Lexicographic 'y is the larger root' on CANONICAL PLAIN limbs
    (wire-format sign bit; oracle curve.py _fq2_is_large)."""
    half = jnp.asarray(fp.int_to_limbs((P - 1) // 2))
    zero1 = jnp.all(a_plain[1] == 0, axis=-1)
    large1 = fp.gt(a_plain[1], half)
    return large1 | (zero1 & fp.gt(a_plain[0], half))


def fq2_from_mont(a):
    """Montgomery (possibly lazy) -> canonical plain limbs."""
    t = fp.canonical_plain(_stk(a[0], a[1]))
    return (t[..., 0, :], t[..., 1, :])


# --------------------------------------------------------------------------
# Fq6 — outputs lazy (<= 7 units); unit inputs required for mul/sqr
# --------------------------------------------------------------------------

def fq6_add(a, b):
    return tuple(fq2_add(x, y) for x, y in zip(a, b))


def fq6_sub(a, b):
    return tuple(fq2_sub(x, y) for x, y in zip(a, b))


def fq6_neg(a):
    return tuple(fq2_neg(x) for x in a)


def fq6_mul(a, b):
    # Toom-style 6-mul Karatsuba, all six fq2 muls in one wide call.
    # Inputs must be <= 2 units per component.
    a0, a1, a2 = a
    b0, b1, b2 = b
    A = _fq2s([a0, a1, a2, fq2_add(a1, a2), fq2_add(a0, a1), fq2_add(a0, a2)])
    B = _fq2s([b0, b1, b2, fq2_add(b1, b2), fq2_add(b0, b1), fq2_add(b0, b2)])
    t0, t1, t2, s12, s01, s02 = _fq2u(fq2_mul(A, B))
    c0 = fq2_add(t0, fq2_mul_by_xi(fq2_sub(fq2_sub(s12, t1), t2)))
    c1 = fq2_add(fq2_sub(fq2_sub(s01, t0), t1), fq2_mul_by_xi(t2))
    c2 = fq2_add(fq2_sub(fq2_sub(s02, t0), t2), t1)
    return (c0, c1, c2)


def fq6_sqr(a):
    # Chung-Hasan SQR2, five fq2 muls in one wide call
    a0, a1, a2 = a
    m = fq2_add(fq2_sub(a0, a1), a2)
    A = _fq2s([a0, a0, m, a1, a2])
    B = _fq2s([a0, a1, m, a2, a2])
    s0, s1, s2, s3, s4 = _fq2u(fq2_mul(A, B))
    s1 = fq2_add(s1, s1)
    s3 = fq2_add(s3, s3)
    c0 = fq2_add(s0, fq2_mul_by_xi(s3))
    c1 = fq2_add(s1, fq2_mul_by_xi(s4))
    c2 = fq2_sub(fq2_add(fq2_add(s1, s2), s3), fq2_add(s0, s4))
    return (c0, c1, c2)


def fq6_mul_by_v(a):
    return (fq2_mul_by_xi(a[2]), a[0], a[1])


def fq6_mul_by_fq2(a, s):
    t = _fq2u(fq2_mul(_fq2s([a[0], a[1], a[2]]), _fq2s([s, s, s])))
    return (t[0], t[1], t[2])


def fq6_inv(a):
    """Input <= 2 units per component."""
    a0, a1, a2 = a
    p6 = _fq2u(fq2_mul(_fq2s([a0, a2, a1, a1, a0, a0]),
                       _fq2s([a0, a2, a1, a2, a1, a2])))
    sq0, sq2, sq1, m12, m01, m02 = p6
    t0 = fq2_sub(sq0, fq2_mul_by_xi(m12))
    t1 = fq2_sub(fq2_mul_by_xi(sq2), m01)
    t2 = fq2_sub(sq1, m02)
    n3 = _fq2u(fq2_mul(_fq2s([a0, a2, a1]), _fq2s([t0, t1, t2])))
    norm = fq2_add(n3[0], fq2_mul_by_xi(fq2_add(n3[1], n3[2])))
    ninv = fq2_compress(fq2_inv(norm))
    out = _fq2u(fq2_mul(_fq2s([t0, t1, t2]), _fq2s([ninv, ninv, ninv])))
    return (out[0], out[1], out[2])


def fq6_eq(a, b):
    d = fq6_sub(a, b)
    c = fp.canonical(_stk(d[0][0], d[0][1], d[1][0], d[1][1],
                          d[2][0], d[2][1]))
    return jnp.all(c == 0, axis=(-2, -1))


def fq6_select(cond, a, b):
    return tuple(fq2_select(cond, x, y) for x, y in zip(a, b))


def fq6_frobenius(a):
    t = _fq2u(fq2_mul(_fq2s([fq2_conj(a[1]), fq2_conj(a[2])]),
                      _fq2s([_bcast2(FROB6_C1, a[1]),
                             _bcast2(FROB6_C2, a[2])])))
    return (fq2_conj(a[0]), t[0], t[1])


# --------------------------------------------------------------------------
# Fq12 — all ops take unit inputs and return COMPRESSED (unit) outputs
# --------------------------------------------------------------------------

def fq12_ones(batch_shape=()):
    """FQ12 one broadcast to a batch shape."""
    one = _bcast2(FQ2_ONE_NP, (jnp.zeros(batch_shape + (fp.L,),
                                         dtype=jnp.int64),) * 2)
    zero2 = _bcast2(FQ2_ZERO_NP, one)
    z6 = (zero2, zero2, zero2)
    return ((one, zero2, zero2), z6)


def fq12_mul(a, b):
    # Karatsuba over Fq6: all 3 fq6 muls as one call on a leading axis,
    # i.e. 18 base-field multiplies in a single wide mont_mul.
    a0, a1 = a
    b0, b1 = b
    A = tree_stack([a0, a1, fq6_add(a0, a1)])
    B = tree_stack([b0, b1, fq6_add(b0, b1)])
    t0, t1, t2 = tree_unstack(fq6_mul(A, B), 3)
    c0 = fq6_add(t0, fq6_mul_by_v(t1))
    c1 = fq6_sub(fq6_sub(t2, t0), t1)
    return fq12_compress((c0, c1))


def fq12_sqr(a):
    # complex squaring: both fq6 muls in one call
    a0, a1 = a
    A = tree_stack([a0, fq6_add(a0, a1)])
    B = tree_stack([a1, fq6_add(a0, fq6_mul_by_v(a1))])
    t, u = tree_unstack(fq6_mul(A, B), 2)
    c0 = fq6_sub(u, fq6_add(t, fq6_mul_by_v(t)))
    c1 = fq6_add(t, t)
    return fq12_compress((c0, c1))


def fq12_conj(a):
    return (a[0], fq6_neg(a[1]))


def fq12_cyclo_sqr(a):
    """Granger-Scott squaring for cyclotomic-subgroup elements (mirrors
    oracle fields.fq12_cyclo_sqr): three Fq4 squarings whose nine fq2
    multiplies run as one wide call."""
    (g0, g1, g2), (h0, h1, h2) = a
    A = _fq2s([g0, g0, h1, h0, h0, g2, g1, g1, h2])
    B = _fq2s([h1, g0, h1, g2, h0, g2, h2, g1, h2])
    ta, sa, sb, tb, sc, sd, tc, se, sf = _fq2u(fq2_mul(A, B))

    def fp4(t, s_hi, s_lo):
        return (fq2_add(s_hi, fq2_mul_by_xi(s_lo)), fq2_add(t, t))

    a0, a1 = fp4(ta, sa, sb)
    b0, b1 = fp4(tb, sc, sd)
    c0, c1 = fp4(tc, se, sf)
    sc0, sc1 = fq2_mul_by_xi(c1), c0

    def triple(x):
        return fq2_add(fq2_add(x, x), x)

    def comb(s0, s1, o0, o1, sign):
        t0, t1 = triple(s0), triple(s1)
        d0 = fq2_add(o0, o0)
        d1 = fq2_add(o1, o1)
        if sign > 0:
            return (fq2_add(t0, d0), fq2_sub(t1, d1))
        return (fq2_sub(t0, d0), fq2_add(t1, d1))

    B0 = comb(a0, a1, g0, h1, -1)
    B1 = comb(sc0, sc1, h0, g2, +1)
    B2 = comb(b0, b1, g1, h2, -1)
    # value-reduce, not just compress: the ±2*conj(input) terms otherwise
    # compound the component values across squaring chains
    return fq12_reduce_value(((B0[0], B2[0], B1[1]), (B1[0], B0[1], B2[1])))


def fq12_inv(a):
    a0, a1 = a
    s0, s1 = tree_unstack(fq6_sqr(tree_stack([a0, a1])), 2)
    norm = fq6_compress(fq6_sub(s0, fq6_mul_by_v(s1)))
    ninv = fq6_compress(fq6_inv(norm))
    m0, m1 = tree_unstack(
        fq6_mul(tree_stack([a0, a1]), tree_stack([ninv, ninv])), 2)
    return fq12_compress((m0, fq6_neg(m1)))


def fq12_frobenius(a, power: int = 1):
    result = a
    for _ in range(power % 12):
        c0 = fq6_frobenius(result[0])
        c1 = fq6_frobenius(result[1])
        c1 = fq6_mul_by_fq2(c1, _bcast2(FROB12_C1, c1[0]))
        result = fq12_compress((c0, c1))
    return result


def fq12_eq(a, b):
    d0 = fq6_sub(a[0], b[0])
    d1 = fq6_sub(a[1], b[1])
    comps = [c for six in (d0, d1) for two in six for c in two]
    c = fp.canonical(jnp.stack(comps, axis=-2))
    return jnp.all(c == 0, axis=(-2, -1))


def fq12_is_one(a):
    return fq12_eq(a, fq12_ones(a[0][0][0].shape[:-1]))


def fq12_select(cond, a, b):
    return tuple(fq6_select(cond, x, y) for x, y in zip(a, b))


# --------------------------------------------------------------------------
# Host conversions (tests / boundaries)
# --------------------------------------------------------------------------

def fq2_to_device(c):
    """Oracle Fq2 (int pair) -> Montgomery limb arrays (unbatched)."""
    return (jnp.asarray(fp.int_to_mont(c[0])), jnp.asarray(fp.int_to_mont(c[1])))


def fq2_from_device(a, index=()) -> tuple:
    """Montgomery limb arrays -> oracle Fq2 int pair at a batch index."""
    return (fp.mont_to_int(np.asarray(a[0])[index]),
            fp.mont_to_int(np.asarray(a[1])[index]))


def fq6_to_device(c):
    return tuple(fq2_to_device(x) for x in c)


def fq6_from_device(a, index=()):
    return tuple(fq2_from_device(x, index) for x in a)


def fq12_to_device(c):
    return tuple(fq6_to_device(x) for x in c)


def fq12_from_device(a, index=()):
    return tuple(fq6_from_device(x, index) for x in a)
