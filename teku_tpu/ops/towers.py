"""BLS12-381 extension-field towers on TPU limb arithmetic (JAX).

Fq2 = Fq[u]/(u^2+1) as a tuple (c0, c1) of limb arrays; Fq6 = Fq2[v]/(v^3-xi)
with xi = 1+u as a 3-tuple of Fq2; Fq12 = Fq6[w]/(w^2-v) as a 2-tuple of Fq6.
Tuples are JAX pytrees, so every op broadcasts over leading batch dims and
composes with jit/scan/shard_map untouched.

Algorithms mirror the pure-Python oracle (teku_tpu/crypto/bls/fields.py) —
Karatsuba Fq2/Fq6/Fq12 mul, Chung-Hasan Fq6 squaring, Granger-Scott
cyclotomic squaring, computed Frobenius constants — re-expressed branch-free
on Montgomery limbs.  The reference client gets this layer from native blst
(reference: infrastructure/bls/src/main/java/tech/pegasys/teku/bls/impl/
blst/BlstBLS12381.java, SWIG classes P1/P2/Pairing).

Validation: tests/test_ops_towers.py checks every op against the oracle.
"""

import numpy as np

import jax.numpy as jnp
from jax import lax

from ..crypto.bls import fields as F
from ..crypto.bls.constants import P
from . import limbs as fp

# --------------------------------------------------------------------------
# Constants (host-computed, Montgomery form)
# --------------------------------------------------------------------------


def fq2_const(c) -> tuple:
    """Host: oracle Fq2 tuple of ints -> Montgomery limb constant pair."""
    return (np.asarray(fp.int_to_mont(c[0])), np.asarray(fp.int_to_mont(c[1])))


FQ2_ZERO_NP = fq2_const((0, 0))
FQ2_ONE_NP = fq2_const((1, 0))

FROB6_C1 = fq2_const(F.FROB6_C1)
FROB6_C2 = fq2_const(F.FROB6_C2)
FROB12_C1 = fq2_const(F.FROB12_C1)

# sqrt constants for q = P^2 ≡ 9 (mod 16): c1 = sqrt(-1), c2 = sqrt(c1),
# c3 = sqrt(-c1); all four of {cand, c1*cand, c2*cand, c3*cand} are tried
# branch-free (RFC 9380 appendix I.3 constant-time sqrt shape).
_SQRT_M1 = F.fq2_sqrt((P - 1, 0))
_SQRT_C2 = F.fq2_sqrt(_SQRT_M1)
_SQRT_C3 = F.fq2_sqrt(F.fq2_neg(_SQRT_M1))
assert _SQRT_M1 and _SQRT_C2 and _SQRT_C3
SQRT_EXP = (P * P + 7) // 16
assert (P * P) % 16 == 9


def _bcast2(c, like):
    """Broadcast an Fq2 numpy constant to the batch shape of `like`."""
    shape = like[0].shape
    return (jnp.broadcast_to(jnp.asarray(c[0]), shape),
            jnp.broadcast_to(jnp.asarray(c[1]), shape))


# --------------------------------------------------------------------------
# Fq2
# --------------------------------------------------------------------------

def fq2_add(a, b):
    return (fp.add(a[0], b[0]), fp.add(a[1], b[1]))


def fq2_sub(a, b):
    return (fp.sub(a[0], b[0]), fp.sub(a[1], b[1]))


def fq2_neg(a):
    return (fp.neg(a[0]), fp.neg(a[1]))


def fq2_double(a):
    return fq2_add(a, a)


def fq2_mul(a, b):
    # Karatsuba: 3 base muls
    t0 = fp.mont_mul(a[0], b[0])
    t1 = fp.mont_mul(a[1], b[1])
    t2 = fp.mont_mul(fp.add(a[0], a[1]), fp.add(b[0], b[1]))
    return (fp.sub(t0, t1), fp.sub(fp.sub(t2, t0), t1))


def fq2_sqr(a):
    # (a0+a1)(a0-a1), 2 a0 a1
    c0 = fp.mont_mul(fp.add(a[0], a[1]), fp.sub(a[0], a[1]))
    t = fp.mont_mul(a[0], a[1])
    return (c0, fp.add(t, t))


def fq2_mul_fp(a, s):
    """Multiply both components by an Fq (Montgomery) scalar."""
    return (fp.mont_mul(a[0], s), fp.mont_mul(a[1], s))


def fq2_conj(a):
    return (a[0], fp.neg(a[1]))


def fq2_mul_by_xi(a):
    # a * (1 + u) = (a0 - a1) + (a0 + a1) u
    return (fp.sub(a[0], a[1]), fp.add(a[0], a[1]))


def fq2_inv(a):
    """Branch-free inverse; inv(0) = 0 (callers select around zero)."""
    norm = fp.add(fp.mont_sqr(a[0]), fp.mont_sqr(a[1]))
    ninv = fp.inv(norm)
    return (fp.mont_mul(a[0], ninv), fp.neg(fp.mont_mul(a[1], ninv)))


def fq2_is_zero(a):
    return fp.is_zero(a[0]) & fp.is_zero(a[1])


def fq2_eq(a, b):
    return fp.eq(a[0], b[0]) & fp.eq(a[1], b[1])


def fq2_select(cond, a, b):
    return (fp.select(cond, a[0], b[0]), fp.select(cond, a[1], b[1]))


def fq2_pow_static(a, e: int):
    """a^e for a static exponent via scan (1 sqr + 1 selected mul per bit)."""
    assert e > 0
    bits = np.array([(e >> i) & 1 for i in range(e.bit_length())][::-1],
                    dtype=np.int64)

    def body(acc, bit):
        acc = fq2_sqr(acc)
        acc = fq2_select(bit != 0, fq2_mul(acc, a), acc)
        return acc, None

    acc, _ = lax.scan(body, a, jnp.asarray(bits[1:]))
    return acc


def fq2_sqrt(a):
    """Branch-free square root in Fq2 (q ≡ 9 mod 16).

    Returns (ok, root): ok is False where `a` is a non-residue (root lanes
    are then garbage and must be selected away by the caller).
    """
    cand = fq2_pow_static(a, SQRT_EXP)   # a = 0 -> cand = 0, matches below
    root = cand
    found = jnp.zeros(fq2_is_zero(a).shape, dtype=bool)
    for c in (None, _SQRT_M1, _SQRT_C2, _SQRT_C3):
        t = cand if c is None else fq2_mul(_bcast2(fq2_const(c), cand), cand)
        match = fq2_eq(fq2_sqr(t), a) & ~found
        root = fq2_select(match, t, root)
        found = found | match
    return found, root


def fq2_is_large(a_plain):
    """Lexicographic 'y is the larger root' on PLAIN-form limbs
    (wire-format sign bit; oracle curve.py _fq2_is_large)."""
    half = jnp.asarray(fp.int_to_limbs((P - 1) // 2))
    large1 = fp.gt(a_plain[1], half)
    return large1 | (fp.is_zero(a_plain[1]) & fp.gt(a_plain[0], half))


def fq2_from_mont(a):
    return (fp.from_mont(a[0]), fp.from_mont(a[1]))


# --------------------------------------------------------------------------
# Fq6
# --------------------------------------------------------------------------

def fq6_add(a, b):
    return tuple(fq2_add(x, y) for x, y in zip(a, b))


def fq6_sub(a, b):
    return tuple(fq2_sub(x, y) for x, y in zip(a, b))


def fq6_neg(a):
    return tuple(fq2_neg(x) for x in a)


def fq6_mul(a, b):
    a0, a1, a2 = a
    b0, b1, b2 = b
    t0 = fq2_mul(a0, b0)
    t1 = fq2_mul(a1, b1)
    t2 = fq2_mul(a2, b2)
    c0 = fq2_add(t0, fq2_mul_by_xi(fq2_sub(fq2_sub(
        fq2_mul(fq2_add(a1, a2), fq2_add(b1, b2)), t1), t2)))
    c1 = fq2_add(fq2_sub(fq2_sub(
        fq2_mul(fq2_add(a0, a1), fq2_add(b0, b1)), t0), t1),
        fq2_mul_by_xi(t2))
    c2 = fq2_add(fq2_sub(fq2_sub(
        fq2_mul(fq2_add(a0, a2), fq2_add(b0, b2)), t0), t2), t1)
    return (c0, c1, c2)


def fq6_sqr(a):
    # Chung-Hasan SQR2
    a0, a1, a2 = a
    s0 = fq2_sqr(a0)
    s1 = fq2_mul(a0, a1)
    s1 = fq2_add(s1, s1)
    s2 = fq2_sqr(fq2_add(fq2_sub(a0, a1), a2))
    s3 = fq2_mul(a1, a2)
    s3 = fq2_add(s3, s3)
    s4 = fq2_sqr(a2)
    c0 = fq2_add(s0, fq2_mul_by_xi(s3))
    c1 = fq2_add(s1, fq2_mul_by_xi(s4))
    c2 = fq2_sub(fq2_add(fq2_add(s1, s2), s3), fq2_add(s0, s4))
    return (c0, c1, c2)


def fq6_mul_by_v(a):
    return (fq2_mul_by_xi(a[2]), a[0], a[1])


def fq6_mul_by_fq2(a, s):
    return tuple(fq2_mul(x, s) for x in a)


def fq6_inv(a):
    a0, a1, a2 = a
    t0 = fq2_sub(fq2_sqr(a0), fq2_mul_by_xi(fq2_mul(a1, a2)))
    t1 = fq2_sub(fq2_mul_by_xi(fq2_sqr(a2)), fq2_mul(a0, a1))
    t2 = fq2_sub(fq2_sqr(a1), fq2_mul(a0, a2))
    norm = fq2_add(fq2_mul(a0, t0),
                   fq2_mul_by_xi(fq2_add(fq2_mul(a2, t1), fq2_mul(a1, t2))))
    ninv = fq2_inv(norm)
    return (fq2_mul(t0, ninv), fq2_mul(t1, ninv), fq2_mul(t2, ninv))


def fq6_eq(a, b):
    r = fq2_eq(a[0], b[0])
    return r & fq2_eq(a[1], b[1]) & fq2_eq(a[2], b[2])


def fq6_select(cond, a, b):
    return tuple(fq2_select(cond, x, y) for x, y in zip(a, b))


def fq6_frobenius(a):
    return (fq2_conj(a[0]),
            fq2_mul(fq2_conj(a[1]), _bcast2(FROB6_C1, a[1])),
            fq2_mul(fq2_conj(a[2]), _bcast2(FROB6_C2, a[2])))


# --------------------------------------------------------------------------
# Fq12
# --------------------------------------------------------------------------

def fq12_ones(batch_shape=()):
    """FQ12 one broadcast to a batch shape."""
    one = _bcast2(FQ2_ONE_NP, (jnp.zeros(batch_shape + (fp.L,),
                                         dtype=jnp.int64),) * 2)
    zero2 = _bcast2(FQ2_ZERO_NP, one)
    z6 = (zero2, zero2, zero2)
    return ((one, zero2, zero2), z6)


def fq12_mul(a, b):
    a0, a1 = a
    b0, b1 = b
    t0 = fq6_mul(a0, b0)
    t1 = fq6_mul(a1, b1)
    c0 = fq6_add(t0, fq6_mul_by_v(t1))
    c1 = fq6_sub(fq6_sub(fq6_mul(fq6_add(a0, a1), fq6_add(b0, b1)), t0), t1)
    return (c0, c1)


def fq12_sqr(a):
    a0, a1 = a
    t = fq6_mul(a0, a1)
    c0 = fq6_sub(fq6_mul(fq6_add(a0, a1), fq6_add(a0, fq6_mul_by_v(a1))),
                 fq6_add(t, fq6_mul_by_v(t)))
    c1 = fq6_add(t, t)
    return (c0, c1)


def fq12_conj(a):
    return (a[0], fq6_neg(a[1]))


def _fp4_sqr(a, b):
    t = fq2_mul(a, b)
    return (fq2_add(fq2_sqr(a), fq2_mul_by_xi(fq2_sqr(b))), fq2_add(t, t))


def fq12_cyclo_sqr(a):
    """Granger-Scott squaring for cyclotomic-subgroup elements
    (mirrors oracle fields.fq12_cyclo_sqr; validated against fq12_sqr)."""
    (g0, g1, g2), (h0, h1, h2) = a
    a0, a1 = _fp4_sqr(g0, h1)
    b0, b1 = _fp4_sqr(h0, g2)
    c0, c1 = _fp4_sqr(g1, h2)
    sc0, sc1 = fq2_mul_by_xi(c1), c0

    def comb(s0, s1, o0, o1, sign):
        t0 = fq2_add(fq2_add(s0, s0), s0)
        t1 = fq2_add(fq2_add(s1, s1), s1)
        d0 = fq2_add(o0, o0)
        d1 = fq2_add(o1, o1)
        if sign > 0:
            return (fq2_add(t0, d0), fq2_sub(t1, d1))
        return (fq2_sub(t0, d0), fq2_add(t1, d1))

    B0 = comb(a0, a1, g0, h1, -1)
    B1 = comb(sc0, sc1, h0, g2, +1)
    B2 = comb(b0, b1, g1, h2, -1)
    return ((B0[0], B2[0], B1[1]), (B1[0], B0[1], B2[1]))


def fq12_inv(a):
    a0, a1 = a
    norm = fq6_sub(fq6_sqr(a0), fq6_mul_by_v(fq6_sqr(a1)))
    ninv = fq6_inv(norm)
    return (fq6_mul(a0, ninv), fq6_neg(fq6_mul(a1, ninv)))


def fq12_frobenius(a, power: int = 1):
    result = a
    for _ in range(power % 12):
        c0 = fq6_frobenius(result[0])
        c1 = fq6_frobenius(result[1])
        c1 = fq6_mul_by_fq2(c1, _bcast2(FROB12_C1, c1[0]))
        result = (c0, c1)
    return result


def fq12_eq(a, b):
    return fq6_eq(a[0], b[0]) & fq6_eq(a[1], b[1])


def fq12_is_one(a):
    return fq12_eq(a, fq12_ones(a[0][0][0].shape[:-1]))


def fq12_select(cond, a, b):
    return tuple(fq6_select(cond, x, y) for x, y in zip(a, b))


# --------------------------------------------------------------------------
# Host conversions (tests / boundaries)
# --------------------------------------------------------------------------

def fq2_to_device(c):
    """Oracle Fq2 (int pair) -> Montgomery limb arrays (unbatched)."""
    return (jnp.asarray(fp.int_to_mont(c[0])), jnp.asarray(fp.int_to_mont(c[1])))


def fq2_from_device(a, index=()) -> tuple:
    """Montgomery limb arrays -> oracle Fq2 int pair at a batch index."""
    return (fp.mont_to_int(np.asarray(a[0])[index]),
            fp.mont_to_int(np.asarray(a[1])[index]))


def fq6_to_device(c):
    return tuple(fq2_to_device(x) for x in c)


def fq6_from_device(a, index=()):
    return tuple(fq2_from_device(x, index) for x in a)


def fq12_to_device(c):
    return tuple(fq6_to_device(x) for x in c)


def fq12_from_device(a, index=()):
    return tuple(fq6_from_device(x, index) for x in a)
