"""JaxBls12381 — the TPU-backed BLS provider behind the node's SPI.

Plugs the batched verification kernel (teku_tpu/ops/verify.py) into the
same provider seam the reference exposes for blst (reference:
infrastructure/bls/src/main/java/tech/pegasys/teku/bls/impl/BLS12381.java:
34-157, installed via bls/BLS.java:51-62 setBlsImplementation).  The
pure-Python oracle remains the host-side fallback and supplies the rare
non-batch operations (key generation, signing), mirroring how the
reference keeps BlstLoader's graceful-degradation path.

Host/device split:
- host: wire-format parsing (flag bits, x < P), SHA-256 message
  expansion, pubkey cache bookkeeping, random multipliers — all
  marshaling vectorized with numpy (no per-lane Python bigint work on
  the hot path);
- device: pubkey decompression + subgroup checks for cache misses (one
  batched dispatch), and the whole verification pipeline — per-lane
  multi-key aggregation, hash-to-G2, scalar muls, Miller loops, final
  exponentiation — as a chain of staged jitted programs per padded
  batch-shape bucket.

DEDUP-AWARE: hash-to-G2 (the largest per-lane stage) runs over each
batch's UNIQUE messages, backed by a bounded device-resident H(m)
point cache (ops/h2c_cache.py — steady-state committee gossip pays h2c
once per distinct AttestationData, a fully-warm batch dispatches no
h2c at all), and the Miller loops fold to unique width via pairing
bilinearity (ops/verify.py:stage_group).  begin_batch_verify exposes
the async seam the batching service uses to overlap host_prep of the
next batch with the in-flight device execute.

MESH: constructed with mesh=..., dispatches shard GROUP-ALIGNED
across the chips (teku_tpu/parallel.GroupShardedVerifier): whole
message-group rows per shard, lanes permuted to follow their rows, so
the dedup pipeline (unique-message h2c, grouped Miller rows, the
Pippenger MSM) survives the mesh; one all_gather of per-device
partials crosses the ICI and the verdict contract is unchanged
(lane_ok un-permutes at the sync point).

Batch sizes (and the per-lane key-count axis) are padded to powers of
two so the jit cache stays small and shapes stay static (XLA recompiles
nothing after warm-up).
"""

import hashlib
import os
import secrets
import threading
import time
from typing import List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..crypto.bls import hash_to_curve as OH
from ..infra import (capacity, compilecache, dispatchledger, faults,
                     timeline, tracing)
from ..infra.collections import LimitedMap
from ..infra.env import env_int
from ..infra.metrics import GLOBAL_REGISTRY
from ..crypto.bls.constants import P, R
from ..crypto.bls.pure_impl import PureBls12381
from ..crypto.bls.spi import (BLS12381, BatchSemiAggregate,
                              ResolvedHandle)
from . import h2c_cache as HC
from . import limbs as fp
from . import msm
from . import mxu
from . import points as PT
from . import verify as V

# jax is imported by now (via ops/__init__): install the compile-cache
# hit/miss listener so dispatch outcomes below can be classified
compilecache.ensure_instrumented()

_G1_INF = bytes([0xC0] + [0] * 47)
_G2_INF = bytes([0xC0] + [0] * 95)

# Process-level dispatch observability (module-level because the staged
# verify jits in ops/verify.py are shared across provider instances).
# First dispatch of a (padded, kmax) bucket shape is the one that pays
# the XLA work — `compile` when it was a fresh compile, `cache_load`
# when the persistent compile cache served it from disk, `aot_load`
# when the serialized-executable store (infra/aotstore.py) skipped
# XLA entirely; everything after hits the in-memory jit cache
# (`cache_hit`).  `path` is the active mont_mul engine (vpu | mxu,
# ops/mxu.py).
_SEEN_SHAPES: set = set()
_SEEN_LOCK = threading.Lock()
_M_JIT = GLOBAL_REGISTRY.labeled_counter(
    "bls_jit_dispatch_total",
    "verify dispatches by padded bucket shape (lanes x keys), "
    "jit-cache outcome (compile|cache_load|aot_load|cache_hit) and "
    "mont_mul path (vpu|mxu)",
    labelnames=("shape", "outcome", "path"))
_M_LANES_REAL = GLOBAL_REGISTRY.counter(
    "bls_dispatch_lanes_real_total",
    "real (non-padding) lanes dispatched to the device")
_M_LANES_PADDED = GLOBAL_REGISTRY.counter(
    "bls_dispatch_lanes_padded_total",
    "total lanes dispatched including pow-2 padding")

# Dedup-aware h2c observability: hash-to-curve runs over each batch's
# UNIQUE messages (committee traffic signs the same AttestationData
# many times), so the lanes/unique gap is realized h2c savings and the
# dispatch counter proves a warm H(m) cache skips h2c entirely.
_M_H2C_LANES = GLOBAL_REGISTRY.counter(
    "bls_h2c_lanes_total",
    "real lanes entering unique-message h2c dedup")
_M_H2C_UNIQUE = GLOBAL_REGISTRY.counter(
    "bls_h2c_unique_total",
    "unique messages after dedup (h2c work actually owed)")
_M_H2C_DISPATCH = GLOBAL_REGISTRY.counter(
    "bls_h2c_dispatch_total",
    "hash-to-curve device dispatches (0 growth = H(m) cache warm)")

# MSM scalars-stage path observability: every verify dispatch resolves
# to the per-lane windowed ladder or the GLV+Pippenger bucketed MSM
# (ops/msm.py resolve(); `auto` is shape-aware), and capacity planning
# needs the lane split, not just the dispatch split — the closed
# {ladder, pippenger} vocabulary is linted in test_metrics_exposition
_M_MSM = GLOBAL_REGISTRY.labeled_counter(
    "bls_msm_dispatch_total",
    "verify dispatches by resolved scalars-stage path "
    "(ladder|pippenger, ops/msm.py)",
    labelnames=("path",))
_M_MSM_LANES = GLOBAL_REGISTRY.labeled_counter(
    "bls_msm_lanes_total",
    "real lanes dispatched by resolved scalars-stage path",
    labelnames=("path",))

# Mesh observability: sharded dispatches labeled by device count (a
# closed pow-2 vocabulary — the resolver only ever yields pow-2 mesh
# sizes, linted in test_metrics_exposition); the companion
# bls_mesh_devices gauge lives in teku_tpu/parallel.
_M_MESH_DISPATCH = GLOBAL_REGISTRY.labeled_counter(
    "bls_mesh_dispatch_total",
    "verify dispatches served by the group-aligned sharded mesh "
    "kernel, by mesh device count",
    labelnames=("devices",))


def _dedup_ratio() -> float:
    # read unique BEFORE lanes (writers inc lanes first): a dispatch
    # landing between the reads skews the ratio high, never negative
    uniq = _M_H2C_UNIQUE.value
    lanes = _M_H2C_LANES.value
    return (lanes - uniq) / lanes if lanes else 0.0


# duplication factor observable: 0.875 means 8 lanes/unique message —
# the fraction of h2c work the dedup pipeline did NOT have to do
GLOBAL_REGISTRY.gauge(
    "bls_h2c_dedup_ratio",
    "fraction of lanes whose H(m) was served by dedup instead of h2c",
    supplier=_dedup_ratio)

# the host-side wire caches share the H(m) arena's eviction family
_EVICT_PK = HC.evictions_counter("pk")
_EVICT_U = HC.evictions_counter("u")


# pow-2 padding trades jit-cache size for dead lanes; the dead
# fraction is a direct throughput observable (0.3 means 30% of device
# work verified nothing).  The gauge moved to the dispatch ledger
# (infra/dispatchledger.py) as bls_dispatch_padding_waste_ratio{stage}
# — SPLIT by stage bucket (lane vs unique-h2c row), fed from the same
# per-dispatch counts the records below carry.


# one shared definition of the padding rule (infra/pow2.py) — the
# admission planner and mesh shard planner pad with the same function
from ..infra.pow2 import next_pow2 as _next_pow2  # noqa: E402
# the bucket POLICY (floors, group split, shape labels) lives in
# ops/shapeset.py so `cli precompile` enumerates the exact programs
# this module dispatches — provider has no private copy of any rule
# (drift is structurally impossible; tests/test_shapeset.py pins it)
from . import shapeset as SS  # noqa: E402
from ..infra import aotstore  # noqa: E402


def bytes_to_limbs_np(b: np.ndarray) -> np.ndarray:
    """Vectorized big-endian byte matrix (N, nbytes) -> limb matrix
    (N, L), replacing per-lane Python bigint conversion on the dispatch
    hot path."""
    le = b[:, ::-1].astype(np.uint64)          # little-endian bytes
    n, nb = le.shape
    out = np.zeros((n, fp.L), dtype=np.int64)
    for i in range(fp.L):
        bit0 = fp.W * i
        byte0, shift = divmod(bit0, 8)
        acc = np.zeros(n, dtype=np.uint64)
        for k in range(5):                     # 26 + 7 bits span <= 5 bytes
            idx = byte0 + k
            if idx < nb:
                acc |= le[:, idx] << np.uint64(8 * k)
        out[:, i] = ((acc >> np.uint64(shift))
                     & np.uint64(fp.MASK)).astype(np.int64)
    return out


class _Semi(BatchSemiAggregate):
    """Parsed, host-validated triple awaiting the device dispatch."""

    __slots__ = ("pk_limbs", "message", "sig_x_bytes", "sig_large",
                 "sig_inf")

    def __init__(self, pk_limbs, message, sig_x_bytes, sig_large, sig_inf):
        self.pk_limbs = pk_limbs     # list of (x_mont, y_mont) np (L,)
        self.message = message
        self.sig_x_bytes = sig_x_bytes  # (2, 48) BE bytes of (x1, x0)
        self.sig_large = sig_large
        self.sig_inf = sig_inf


class _DispatchHandle:
    """An in-flight batch dispatch.

    The device work was enqueued via JAX async dispatch when this was
    created (the `device_enqueue` span, recorded by _begin_dispatch,
    covers the launch calls plus any XLA compile a first shape pays);
    result() forces the verdict arrays (the only host/device sync
    point) — callers may do arbitrary host work (e.g. host_prep of the
    NEXT batch) between the two.  result() records ONLY the blocking
    wait as `device_sync`, so under async overlap the span no longer
    absorbs host-prep time spent between enqueue and sync (the old
    combined `device_execute` span's documented caveat), and feeds the
    capacity model's per-shape device-latency/occupancy accounting
    with the overlap-corrected interval.  The traces bound at dispatch
    time are captured so both spans attribute to the right
    verifications even when result() runs under a different context.
    """

    __slots__ = ("_ok", "_lane_ok", "_n", "_traces", "_done",
                 "_verdict", "_shape", "_path", "_t_enq_end",
                 "_lane_sel", "_rec", "_recorded")

    def __init__(self, ok, lane_ok, n, traces, shape, path, t_enq_end,
                 lane_sel=None, rec=None):
        self._ok = ok
        self._lane_ok = lane_ok
        self._n = n
        self._traces = traces
        self._shape = shape
        self._path = path
        self._t_enq_end = t_enq_end
        # mesh dispatches PERMUTE lanes into group-aligned shard
        # blocks: lane_sel maps original lane i -> its slot in the
        # dispatched layout, so the verdict reads the right lanes
        self._lane_sel = lane_sel
        # the open dispatch-ledger record _begin_dispatch assembled:
        # result() completes it (sync duration, overlap-corrected
        # device time, verdict) and publishes it into the ring
        self._rec = rec
        self._done = False
        self._recorded = False
        self._verdict = False

    def result(self) -> bool:
        """Synchronize and return the batch verdict (idempotent)."""
        if self._done:
            return self._verdict
        t_sync0 = time.perf_counter()
        synced = False
        try:
            # np.asarray forces the device round-trip: this wait (and
            # nothing else) is the device_sync stage
            lane_ok = np.asarray(self._lane_ok)
            real = (lane_ok[self._lane_sel]
                    if self._lane_sel is not None
                    else lane_ok[:self._n])
            verdict = bool(np.asarray(self._ok)) and bool(real.all())
            synced = True
        finally:
            t_end = time.perf_counter()
            tracing.record_stage("device_sync", t_end - t_sync0,
                                 self._traces, t0=t_sync0)
            # the timeline's device-busy interval: enqueue-end →
            # sync-end, the numerator of overlap_efficiency (a raising
            # sync still occupied the device until it raised)
            timeline.interval(
                "device", "busy", t_end - self._t_enq_end,
                t_mono=self._t_enq_end,
                trace_id=(self._traces[0].trace_id if self._traces
                          else ""),
                shape=self._shape)
            if not synced and self._rec is not None:
                # a raising sync is still a decision worth its ledger
                # entry — the doctor wants to see the dispatch that
                # wedged, with its full decision context
                self._rec["device"] = {
                    "sync_s": round(t_end - t_sync0, 6),
                    "sync_error": True}
                self._rec["verdict"] = None
                if not self._recorded:
                    dispatchledger.record(self._rec)
                    self._recorded = True
        # true device time = enqueue-end → sync-end, clamped by the
        # tracker so overlapped dispatches never double-count.  Only a
        # SUCCESSFUL sync counts its lanes: a raising dispatch gets
        # bisected and re-dispatched, and crediting its lanes here
        # would inflate sustainable capacity during exactly the fault
        # incidents the capacity endpoint is meant to diagnose.
        busy = capacity.record_dispatch(self._shape, self._path,
                                        self._n, self._t_enq_end,
                                        t_end)
        self._done = True
        self._verdict = faults.transform("bls.dispatch", verdict)
        if self._rec is not None:
            self._rec["device"] = {
                "sync_s": round(t_end - t_sync0, 6),
                "busy_s": round(busy, 6)}
            self._rec["verdict"] = self._verdict
            # a retry after a raising sync already published this dict
            # into the ring: the in-place update above is enough — a
            # second record() would double-count its waste/decision
            # metrics and give one trace id two ring entries
            if not self._recorded:
                dispatchledger.record(self._rec)
                self._recorded = True
        return self._verdict


def _parse_g2_wire(sig: bytes):
    """Host wire checks for a compressed G2 signature.

    Returns (x_bytes (2, 48), large, is_inf) or None when malformed.
    On-curve and subgroup membership are checked on device."""
    if len(sig) != 96 or not sig[0] & 0x80:
        return None
    if sig[0] & 0x40:
        if any(sig[1:]) or (sig[0] & 0x3F):
            return None
        return (np.zeros((2, 48), dtype=np.uint8), False, True)
    x1 = int.from_bytes(bytes([sig[0] & 0x1F]) + sig[1:48], "big")
    x0 = int.from_bytes(sig[48:96], "big")
    if x0 >= P or x1 >= P:
        return None
    xb = np.frombuffer(sig, dtype=np.uint8).reshape(2, 48).copy()
    xb[0, 0] &= 0x1F
    return (xb, bool(sig[0] & 0x20), False)


def _parse_g1_wire(pk: bytes):
    """Host wire checks for a compressed G1 pubkey; same contract."""
    if len(pk) != 48 or not pk[0] & 0x80:
        return None
    if pk[0] & 0x40:
        if any(pk[1:]) or (pk[0] & 0x3F):
            return None
        return (0, False, True)
    x = int.from_bytes(bytes([pk[0] & 0x1F]) + pk[1:], "big")
    if x >= P:
        return None
    return (x, bool(pk[0] & 0x20), False)


class JaxBls12381(BLS12381):
    """TPU provider: batched pairing verification as single dispatches."""

    name = "jax-tpu"

    def __init__(self, max_batch: int = 4096, max_keys_per_lane: int = 2048,
                 min_bucket: int = 4, mesh=None):
        self._pure = PureBls12381()
        self.max_batch = max_batch
        # optional multi-chip dispatch: GROUP-ALIGNED sharding over the
        # mesh's dp axis — every shard owns whole message-group rows,
        # so the dedup pipeline (unique-message Miller grouping, the
        # Pippenger MSM) survives the mesh; partial products ride one
        # all_gather (teku_tpu/parallel.GroupShardedVerifier)
        self._sharded = None
        self.mesh_info = None
        if mesh is not None:
            from ..parallel import GroupShardedVerifier
            self._sharded = GroupShardedVerifier(mesh,
                                                 min_bucket=min_bucket)
            min_bucket = self._sharded.min_bucket
            self.mesh_info = self._sharded.describe()
        self.max_keys_per_lane = max_keys_per_lane
        # tiny batches pad up to one shared bucket: a couple of masked
        # lanes cost microseconds on device, a fresh XLA compile costs
        # minutes — fewer distinct shapes is strictly better
        self.min_bucket = min_bucket
        # pk bytes -> ("ok", x_mont (L,), y_mont (L,)) | ("bad",).
        # Bounded LRU, NOT a clear-at-bound dict: a wholesale clear
        # dumps every warm validator key at once and the next gossip
        # batches pay a re-validation storm; LRU evicts one cold entry
        # per insert and the shared eviction counter makes churn visible.
        self._pk_cache: LimitedMap = LimitedMap(
            200_000, on_evict=lambda _k, _v: _EVICT_PK.inc())
        self._u_cache: LimitedMap = LimitedMap(
            100_000, on_evict=lambda _k, _v: _EVICT_U.inc())
        # device-resident H(m) point cache: steady-state gossip pays
        # hash-to-curve once per distinct AttestationData
        self._h2c_cache = HC.H2cPointCache()
        # h2c dispatches pad the unique bucket to a pow-2 with this
        # floor so the h2c program keeps very few distinct shapes
        self._h2c_min_bucket = env_int("TEKU_TPU_H2C_MIN_BUCKET", 8,
                                       lo=1)
        # stage_group materializes a (U, G) lane matrix: cap G and
        # split oversized committees across rows (a message may own
        # several Miller rows — same verdict, bounded memory)
        self._group_cap = env_int("TEKU_TPU_H2C_GROUP_CAP", 32, lo=1)
        # staged dispatch: small programs instead of one monolith whose
        # TPU compile is unbounded (ops/verify.py staged_jits); h2c
        # runs separately over unique messages (see _begin_dispatch)
        self._pk_validate_jit = aotstore.wrap(
            f"pk_validate:{mxu.resolve()}",
            jax.jit(self._pk_validate_kernel))
        # observability: proof that node traffic actually reaches the
        # device path (mirrors the reference's signature_verifications_*
        # counters at AggregatingSignatureVerificationService.java:76-98)
        self.dispatch_count = 0
        self.lanes_dispatched = 0
        # h2c dispatches this provider issued: the warm-cache tests
        # assert a fully-warm batch leaves this untouched
        self.h2c_dispatch_count = 0
        # reshape generation stamp (parallel/selfheal.MeshHealer sets
        # it on the provider it installs): dispatch-ledger records and
        # doctor findings name WHICH live device set served a dispatch
        # across eject/readmit cycles
        self.mesh_epoch = 0
        # the mont_mul engine resolved when this provider was built —
        # jitted programs KEEP the engine they were traced with, so
        # the dispatch metric labels with this, not a re-resolution
        # (a mid-process set_path() affects only not-yet-traced shapes)
        self.mont_path = mxu.resolve()
        # per-provider MSM path evidence (the parity/auto tests read
        # this; the global bls_msm_* counters serve dashboards)
        self.msm_dispatches = {"ladder": 0, "pippenger": 0}

    # ------------------------------------------------------------------
    # Host-side SPI ops delegated to the oracle (rare, non-batch paths)
    # ------------------------------------------------------------------
    def secret_key_to_public_key(self, secret: int) -> bytes:
        return self._pure.secret_key_to_public_key(secret)

    def sign(self, secret: int, message: bytes) -> bytes:
        return self._pure.sign(secret, message)

    def aggregate_public_keys(self, public_keys: Sequence[bytes]) -> bytes:
        return self._pure.aggregate_public_keys(public_keys)

    def aggregate_signatures(self, signatures: Sequence[bytes]) -> bytes:
        return self._pure.aggregate_signatures(signatures)

    def signature_is_valid(self, signature: bytes) -> bool:
        return self._pure.signature_is_valid(signature)

    # ------------------------------------------------------------------
    # Pubkey cache with batched device validation
    # ------------------------------------------------------------------
    @staticmethod
    def _pk_validate_kernel(x_plain, large):
        ok, pt = PT.g1_recover_y(x_plain, large)
        ok = ok & PT.g1_in_subgroup(pt)
        # Z == 1 by construction: (X, Y) are already the affine coords
        return ok, fp.compress(pt[0]), fp.compress(pt[1])

    def _resolve_pks(self, all_pks: Sequence[bytes]) -> dict:
        """Resolve every requested pubkey (cache-filling, one device
        dispatch for the misses) and return {pk: entry}.

        The cache is a bounded LRU (pubkey bytes can be
        attacker-influenced, so an unbounded cache — including "bad"
        entries — is a slow memory-growth vector); eviction is one cold
        entry at a time, counted in bls_cache_evictions_total{cache="pk"}.
        Callers MUST read entries from the returned snapshot, never
        re-read the shared cache afterwards: at the bound, this batch's
        own inserts (or a concurrent worker's) may evict an entry
        resolved here, and a valid signature must not verify False
        because its pubkey went cold."""
        resolved = {}
        miss = {}
        for pk in all_pks:
            if pk in resolved or pk in miss:
                continue
            entry = self._pk_cache.get(pk)   # refreshes LRU recency
            if entry is not None:
                resolved[pk] = entry
                continue
            wire = _parse_g1_wire(pk)
            if wire is None or wire[2]:   # malformed or infinity
                resolved[pk] = ("bad",)
                self._pk_cache.put(pk, ("bad",))
            else:
                miss[pk] = wire
        miss = list(miss.items())
        if not miss:
            return resolved
        # floor of 16 keeps the validation program at very few distinct
        # shapes (same compile-cost argument as the verify min_bucket)
        n = SS.pk_validate_bucket(len(miss))
        xs = np.zeros((n, fp.L), dtype=np.int64)
        large = np.zeros(n, dtype=bool)
        for i, (_, (x, lg, _inf)) in enumerate(miss):
            xs[i] = fp.int_to_limbs(x)
            large[i] = lg
        ok, gx, gy = self._pk_validate_jit(xs, large)
        ok = np.asarray(ok)
        gx, gy = np.asarray(gx), np.asarray(gy)
        for i, (pk, _) in enumerate(miss):
            entry = ("ok", gx[i], gy[i]) if ok[i] else ("bad",)
            resolved[pk] = entry
            self._pk_cache.put(pk, entry)
        return resolved

    def public_key_is_valid(self, public_key: bytes) -> bool:
        return self._resolve_pks([public_key])[public_key][0] == "ok"

    # ------------------------------------------------------------------
    # Message hashing (host SHA-256 -> field draws, cached)
    # ------------------------------------------------------------------
    def _u_draws(self, message: bytes):
        hit = self._u_cache.get(message)
        if hit is None:
            (a, b), (c, d) = OH.hash_to_field_fq2(message, 2)
            hit = (fp.int_to_mont(a), fp.int_to_mont(b),
                   fp.int_to_mont(c), fp.int_to_mont(d))
            self._u_cache.put(message, hit)
        return hit

    # ------------------------------------------------------------------
    # Verification API — everything lands in the batched kernel
    # ------------------------------------------------------------------
    def prepare_batch_verify(
        self, triple: Tuple[Sequence[bytes], bytes, bytes]
    ) -> Optional[BatchSemiAggregate]:
        public_keys, message, signature = triple
        if not public_keys or len(public_keys) > self.max_keys_per_lane:
            return None
        resolved = self._resolve_pks(public_keys)
        points = []
        for pk in public_keys:
            entry = resolved[pk]
            if entry[0] != "ok":
                return None
            points.append((entry[1], entry[2]))
        sig = _parse_g2_wire(signature)
        if sig is None:
            return None
        return _Semi(points, message, *sig)

    def complete_batch_verify(
        self, semi_aggregates: Sequence[Optional[BatchSemiAggregate]]
    ) -> bool:
        if any(sa is None for sa in semi_aggregates):
            return False
        if not semi_aggregates:
            return True
        semis: List[_Semi] = list(semi_aggregates)
        if len(semis) > self.max_batch:
            # split oversized batches; all chunks must pass
            return all(
                self.complete_batch_verify(semis[i:i + self.max_batch])
                for i in range(0, len(semis), self.max_batch))
        return self._dispatch(semis, randomize=True)

    def batch_verify(
        self, triples: Sequence[Tuple[Sequence[bytes], bytes, bytes]],
    ) -> bool:
        # wire parse + pk-cache resolve is host work too: the trace's
        # host_prep stage sums this with _dispatch's array packing
        with tracing.span("host_prep"):
            semis = [self.prepare_batch_verify(t) for t in triples]
        return self.complete_batch_verify(semis)

    def verify(self, public_key: bytes, message: bytes,
               signature: bytes) -> bool:
        return self.fast_aggregate_verify([public_key], message, signature)

    def fast_aggregate_verify(self, public_keys: Sequence[bytes],
                              message: bytes, signature: bytes) -> bool:
        semi = self.prepare_batch_verify((public_keys, message, signature))
        if semi is None:
            return False
        return self._dispatch([semi], randomize=False)

    def aggregate_verify(self, public_keys: Sequence[bytes],
                         messages: Sequence[bytes], signature: bytes) -> bool:
        if not public_keys or len(public_keys) != len(messages):
            return False
        # prod_i e(pk_i, H(m_i)) == e(g1, sig): the r=1 batch with the
        # signature attached to lane 0 and infinity signatures elsewhere.
        semis = []
        for i, (pk, msg) in enumerate(zip(public_keys, messages)):
            sig = signature if i == 0 else _G2_INF
            semi = self.prepare_batch_verify(([pk], msg, sig))
            if semi is None:
                return False
            semis.append(semi)
        return self._dispatch(semis, randomize=False)

    # ------------------------------------------------------------------
    # Dedup-aware dispatch: h2c over unique messages + async handle
    # ------------------------------------------------------------------
    def begin_batch_verify(self, triples: Sequence[
            Tuple[Sequence[bytes], bytes, bytes]]):
        """Async-overlap entry: host_prep + device enqueue NOW (JAX
        async dispatch), verdict at handle.result().  The batching
        service uses this to overlap host_prep of batch N+1 with
        device execution of batch N.  Returns None for oversized
        batches
        (callers fall back to the splitting sync path)."""
        if len(triples) > self.max_batch:
            return None
        with tracing.span("host_prep"):
            semis = [self.prepare_batch_verify(t) for t in triples]
        if any(s is None for s in semis):
            return ResolvedHandle(False)
        if not semis:
            return ResolvedHandle(True)
        return self._begin_dispatch(semis, randomize=True)

    def _uniq_draws(self, msgs: List[bytes], bucket: int):
        """Host hash_to_field draws for `msgs`, padded to `bucket`."""
        u0c0 = np.zeros((bucket, fp.L), dtype=np.int64)
        u0c1 = np.zeros((bucket, fp.L), dtype=np.int64)
        u1c0 = np.zeros((bucket, fp.L), dtype=np.int64)
        u1c1 = np.zeros((bucket, fp.L), dtype=np.int64)
        for j, m in enumerate(msgs):
            u0c0[j], u0c1[j], u1c0[j], u1c1[j] = self._u_draws(m)
        return (u0c0, u0c1), (u1c0, u1c1)

    def _h2c_dispatch(self, draws):
        """ONE hash-to-curve device dispatch over precomputed draws."""
        u0, u1 = draws
        self.h2c_dispatch_count += 1
        _M_H2C_DISPATCH.inc()
        return V.staged_jits()["h2c"](u0, u1)

    def _hm_host_plan(self, uniq_msgs: List[bytes], u_bucket: int):
        """Host half of H(m) resolution — runs inside the host_prep
        span: message digests, arena lookups, and the hash_to_field
        draws for whatever still needs an h2c dispatch (so the SHA-256
        and draw cost never pollutes the device-span attribution).

        The cache is bypassed when the batch carries more unique
        messages than the whole arena holds: inserting more rows than
        capacity would recycle slots assigned earlier in the same call
        and serve the wrong point."""
        cache = self._h2c_cache
        if not cache.enabled or len(uniq_msgs) > cache.capacity:
            return None, None, None, self._uniq_draws(uniq_msgs,
                                                      u_bucket)
        digests = [hashlib.sha256(m).digest() for m in uniq_msgs]
        slots = np.zeros(u_bucket, dtype=np.int64)
        missing = []
        for j, dg in enumerate(digests):
            slot = cache.lookup(dg)
            if slot is None:
                missing.append(j)
            else:
                slots[j] = slot
        draws = None
        if missing:
            mb = SS.h2c_miss_bucket(len(missing),
                                    self._h2c_min_bucket)
            draws = self._uniq_draws([uniq_msgs[j] for j in missing],
                                     mb)
        return slots, missing, digests, draws

    def _hm_device(self, plan):
        """Device half of H(m) resolution for a deduped batch.

        Arena hits cost one gather; misses pay ONE h2c dispatch over
        the missing-message bucket and land in the arena; a fully-warm
        batch performs ZERO h2c dispatches.  Padding rows (>= the
        unique count) carry arbitrary points — group_present masks
        them downstream."""
        slots, missing, digests, draws = plan
        if slots is None:   # cache disabled/bypassed: plain unique h2c
            return self._h2c_dispatch(draws)
        if missing:
            hm_bucket = self._h2c_dispatch(draws)
            new_slots = self._h2c_cache.insert(
                [digests[j] for j in missing], hm_bucket)
            slots[np.asarray(missing)] = new_slots
        return self._h2c_cache.gather(slots)

    def _dispatch(self, semis: List[_Semi], randomize: bool) -> bool:
        return self._begin_dispatch(semis, randomize).result()

    def _begin_dispatch(self, semis: List[_Semi],
                        randomize: bool) -> "_DispatchHandle":
        # `bls.dispatch` fault site: the supervisor/breaker tests prove
        # hang/exception containment at the REAL device-dispatch seam
        faults.check("bls.dispatch")
        n = len(semis)
        self.dispatch_count += 1
        self.lanes_dispatched += n
        t_hp0 = time.perf_counter()
        with tracing.span("host_prep"):
            kmax = SS.kmax_bucket(max(len(s.pk_limbs) for s in semis))
            # unique-message index + per-message lane groups: h2c AND
            # the Miller loops run at unique width (stage_group folds a
            # message's lanes into one pairing input via bilinearity)
            uniq_index: dict = {}
            uniq_msgs: List[bytes] = []
            groups: List[List[int]] = []
            for i, s in enumerate(semis):
                u = uniq_index.get(s.message)
                if u is None:
                    u = uniq_index[s.message] = len(uniq_msgs)
                    uniq_msgs.append(s.message)
                    groups.append([])
                groups[u].append(i)
            # split committees larger than the group cap across rows:
            # G stays bounded (the grouped gather materializes a
            # (U, G) lane matrix) and a split message simply owns
            # several Miller rows backed by the SAME H(m) point
            rows: List[Tuple[int, List[int]]] = SS.group_rows(
                groups, self._group_cap)
            row_msgs = [uniq_msgs[u] for u, _ in rows]
            g_bucket = SS.group_bucket(rows)
            # canonical unique bucket: the h2c dispatch / H(m) arena
            # width.  Computed from the batch alone — IDENTICAL for
            # single-device and mesh dispatch of the same batch, so
            # the dedup counters and h2c dispatch count cannot depend
            # on the mesh (pinned in tests/test_mesh_grouped.py)
            u_hm = SS.unique_bucket(len(rows), self._h2c_min_bucket)
            if self._sharded is not None:
                # group-aligned shard layout: whole rows per shard,
                # lanes permuted into each shard's contiguous block
                plan = self._sharded.plan(
                    rows, n, min_rows_total=self._h2c_min_bucket)
                padded = plan.padded
                u_total = plan.rows_total
                lane_pos = plan.lane_pos
            else:
                plan = None
                padded = SS.lane_bucket(n, self.min_bucket)
                u_total = u_hm
                lane_pos = None
            pk_xs = np.zeros((padded, kmax, fp.L), dtype=np.int64)
            pk_ys = np.zeros((padded, kmax, fp.L), dtype=np.int64)
            pk_present = np.zeros((padded, kmax), dtype=bool)
            sig_bytes = np.zeros((padded, 2, 48), dtype=np.uint8)
            s_large = np.zeros(padded, dtype=bool)
            s_inf = np.zeros(padded, dtype=bool)
            lane_valid = np.zeros(padded, dtype=bool)
            for i, s in enumerate(semis):
                p = i if lane_pos is None else int(lane_pos[i])
                for j, (x, y) in enumerate(s.pk_limbs):
                    pk_xs[p, j] = x
                    pk_ys[p, j] = y
                    pk_present[p, j] = True
                sig_bytes[p] = s.sig_x_bytes
                s_large[p] = s.sig_large
                s_inf[p] = s.sig_inf
                lane_valid[p] = True
            group_idx = np.zeros((u_total, g_bucket), dtype=np.int32)
            group_present = np.zeros((u_total, g_bucket), dtype=bool)
            row_gather = None
            if plan is None:
                for r, (_, g) in enumerate(rows):
                    group_idx[r, :len(g)] = g
                    group_present[r, :len(g)] = True
            else:
                # group_idx carries SHARD-LOCAL lane indices (under
                # shard_map each shard sees only its own lane block);
                # row_gather scatters the canonical H(m) rows into the
                # shard layout (padding rows gather slot 0 — masked)
                row_gather = np.zeros(u_total, dtype=np.int32)
                for pos, r in enumerate(plan.row_layout):
                    if r < 0:
                        continue
                    g = rows[r][1]
                    base = ((pos // plan.rows_per_shard)
                            * plan.lanes_per_shard)
                    group_idx[pos, :len(g)] = \
                        lane_pos[np.asarray(g)] - base
                    group_present[pos, :len(g)] = True
                    row_gather[pos] = r
            sx1 = bytes_to_limbs_np(sig_bytes[:, 0])
            sx0 = bytes_to_limbs_np(sig_bytes[:, 1])
            # scalars-stage path: the per-lane windowed ladder (64-bit
            # multipliers) or the GLV+Pippenger bucketed MSM (32-bit
            # half-scalar pairs, ops/msm.py).  Resolved per dispatch —
            # `auto` keys on the duplication factor (lanes per Miller
            # row).  The GROUP-ALIGNED mesh kernel supports both
            # (groups never cross shards); msm.resolve(sharded=True)
            # remains the LEGACY lane-sharded kernel's always-ladder
            # contract and is not used here
            msm_path, msm_why = msm.explain(lanes=n, rows=len(rows))
            r_bits = glv_digits = None
            if randomize:
                # one os-entropy draw for the whole batch (the
                # reference uses SecureRandom per multiplier,
                # BlstBLS12381.java:191-195); zero multipliers are
                # nudged to 1 (2^-64 bias, negligible) — on the
                # pippenger path the same 64 bits split into the
                # (k1, k2) half-scalars whose effective multiplier
                # k1 + k2*lambda ranges over 2^64 - 1 values
                raw = np.frombuffer(secrets.token_bytes(8 * padded),
                                    dtype=np.uint64).copy()
                if msm_path == "pippenger":
                    glv_digits = msm.glv_digits_np(
                        *msm.glv_sample_from_uint64(raw))
                else:
                    raw[raw == 0] = 1
                    r_bits = np.asarray(PT.scalar_from_uint64(raw))
            elif msm_path == "pippenger":
                # r = 1 exactly: (k1, k2) = (1, 0)
                glv_digits = msm.glv_digits_np(
                    np.ones(padded, dtype=np.uint64),
                    np.zeros(padded, dtype=np.uint64))
            else:
                r_bits = np.asarray(PT.scalar_from_uint64(
                    np.ones(padded, dtype=np.uint64)))
            # H(m) host half (digests + cache lookups + field draws)
            # belongs to host_prep; only the dispatch/gather below is
            # device work
            hm_plan = self._hm_host_plan(row_msgs, u_hm)
            # per-dispatch H(m) arena accounting for the ledger: a
            # bypassed/disabled cache means every row pays h2c at the
            # canonical unique bucket; otherwise misses pay at the
            # missing-message bucket and hits cost one gather
            plan_slots, plan_missing, _, plan_draws = hm_plan
            # the bucket actually dispatched is read off the plan's
            # own padded draws (first dim) — never re-derived, so a
            # change to the plan's bucket rule can't skew the ledger
            h2c_bucket = (plan_draws[0][0].shape[0]
                          if plan_draws is not None else 0)
            if plan_slots is None:
                h2c_stats = {"cache_hits": 0,
                             "cache_misses": len(row_msgs),
                             "dispatch_bucket": h2c_bucket}
            else:
                misses = len(plan_missing)
                h2c_stats = {"cache_hits": len(row_msgs) - misses,
                             "cache_misses": misses,
                             "dispatch_bucket": h2c_bucket}
        # the timeline's host-prep interval: the serial host-side term
        # host_prep_serial_share is computed from (subtracting any
        # overlap with device-busy intervals)
        timeline.interval(
            "worker", "host_prep", time.perf_counter() - t_hp0,
            t_mono=t_hp0, trace_id=tracing.current_trace_id())
        mesh_n = (self._sharded.n_devices
                  if self._sharded is not None else 0)
        # mesh dispatches get their own shape family (the capacity
        # model's latency series must not blend an 8-chip program with
        # the single-device one; latency_for_lanes prefix-matches
        # "{lanes}x" so the admission planner still sees mesh-shaped
        # device latencies for its batch sizing)
        shape = SS.shape_label(padded, kmax, mesh_n)
        # the staged jits are module-level (shared across providers)
        # and the sharded kernels are process-memoized by (device set,
        # axis, msm path) — key the seen-set on the kernel identity
        # that will actually serve the dispatch, so a reshaped
        # provider over known devices reads cache_hit, not compile
        cache_key = (self._sharded.kernel_key(msm_path)
                     if self._sharded is not None else 0,
                     shape, msm_path)
        with _SEEN_LOCK:
            first = cache_key not in _SEEN_SHAPES
            _SEEN_SHAPES.add(cache_key)
        mont_path = self.mont_path
        # first dispatch of a shape pays the XLA work: diff the
        # persistent-cache counters around it to tell a fresh compile
        # from a disk cache load (racy under concurrent first
        # dispatches — the label may misattribute, the counts don't)
        cache_before = compilecache.stats() if first else None
        aot_before = aotstore.stats() if first else None
        # padded first: a scrape between the two incs must read the
        # ratio high, never negative
        _M_LANES_PADDED.inc(padded)
        _M_LANES_REAL.inc(n)
        _M_H2C_LANES.inc(n)
        _M_H2C_UNIQUE.inc(len(uniq_msgs))
        _M_MSM.labels(path=msm_path).inc()
        _M_MSM_LANES.labels(path=msm_path).inc(n)
        self.msm_dispatches[msm_path] += 1
        if mesh_n:
            _M_MESH_DISPATCH.labels(devices=str(mesh_n)).inc()
        # device section: every launch below is async (XLA compiles
        # synchronously on a first shape, then enqueues); the enqueue
        # span ends when the launches return, and the handle's
        # result() records the blocking wait as device_sync
        traces = tracing.current_traces()
        # the dispatch-ledger record: the full decision context of THIS
        # dispatch, completed by the handle's result().  open_record()
        # also merges the batching service's context annotations (plan
        # mode, brownout level, class mix) — asyncio.to_thread copied
        # them into this worker thread.
        if plan is not None:
            # `devices` + `epoch` stamp the LIVE device set serving
            # this dispatch: after a self-healing reshape the ledger
            # shows which records ran on the shrunken/regrown mesh
            mesh_block = {"devices": mesh_n,
                          "epoch": self.mesh_epoch,
                          "live": list(self._sharded.devices),
                          "shard_lanes": plan.shard_lanes,
                          "shard_rows": plan.shard_rows,
                          "lanes_per_shard": plan.lanes_per_shard,
                          "rows_per_shard": plan.rows_per_shard,
                          "makespan_ratio": round(
                              plan.makespan_ratio, 4)}
        else:
            mesh_block = {"devices": 0, "epoch": self.mesh_epoch}
        rec = dispatchledger.open_record(
            trace_ids=[t.trace_id for t in traces],
            shape=shape, mont_path=mont_path, randomized=randomize,
            lanes=n, kmax=kmax,
            unique_messages=len(uniq_msgs), rows=len(rows),
            group_bucket=g_bucket,
            dedup_ratio=round((n - len(uniq_msgs)) / n, 4),
            waste={"lane": {"real": n, "padded": padded},
                   "h2c": {"real": len(rows), "padded": u_total}},
            h2c=h2c_stats,
            msm={"path": msm_path, "why": msm_why},
            mesh=mesh_block)
        t_dev0 = time.perf_counter()
        outcome = "cache_hit"
        enqueued = False
        try:
            hm_uniq = self._hm_device(hm_plan)
            if self._sharded is not None:
                # `bls.mesh_shard` fault site: a wedged SHARD wedges
                # the whole mesh dispatch.  The LIVE device names ride
                # as keys so the chaos harness can wedge exactly one
                # chip: the keyed fault fires here (the collective
                # includes it) AND at that device's isolation probe
                # (parallel/selfheal.py), and stops firing once the
                # sick device is ejected from the live set
                faults.check("bls.mesh_shard",
                             keys=self._sharded.devices)
                # scatter the canonical H(m) rows into the shard
                # layout with one gather, then the group-aligned
                # kernel runs the full dedup pipeline per shard
                hm_rows = V.staged_jits()["gather"](
                    hm_uniq, jnp.asarray(row_gather))
                scalars = (glv_digits if msm_path == "pippenger"
                           else r_bits)
                ok, lane_ok = self._sharded.kernel(msm_path)(
                    pk_xs, pk_ys, pk_present, hm_rows, group_idx,
                    group_present, (sx0, sx1), s_large, s_inf,
                    scalars, lane_valid)
            elif msm_path == "pippenger":
                ok, lane_ok = V.verify_staged_pippenger(
                    pk_xs, pk_ys, pk_present, hm_uniq, group_idx,
                    group_present, (sx0, sx1), s_large, s_inf,
                    glv_digits, lane_valid)
            else:
                ok, lane_ok = V.verify_staged_grouped(
                    pk_xs, pk_ys, pk_present, hm_uniq, group_idx,
                    group_present, (sx0, sx1), s_large, s_inf,
                    r_bits, lane_valid)
            enqueued = True
        finally:
            if first:
                outcome = compilecache.classify_first_dispatch(
                    compilecache.delta(cache_before),
                    aot=aotstore.delta(aot_before))
            _M_JIT.labels(shape=shape, outcome=outcome,
                          path=mont_path).inc()
            t_enq_end = time.perf_counter()
            tracing.record_stage("device_enqueue", t_enq_end - t_dev0,
                                 traces, t0=t_dev0)
            # on a first shape the enqueue duration IS the XLA cost
            # this dispatch paid (fresh compile or disk cache load) —
            # the doctor's cold-compile findings cite it per record
            rec["compile"] = {"outcome": outcome,
                              "enqueue_s": round(
                                  t_enq_end - t_dev0, 6)}
            if not enqueued:
                # a raising enqueue (fault injection, XLA error) never
                # constructs the handle whose result() would publish
                # the record — and the dispatch that DIED is exactly
                # the one the doctor most needs to see
                rec["device"] = {"enqueue_error": True}
                rec["verdict"] = None
                dispatchledger.record(rec)
        # the capacity model's per-(shape, path) latency series must
        # distinguish the scalars engine: under msm auto, SAME-shape
        # dispatches can run ladder or pippenger (resolve() keys on
        # real lanes/rows), and blending two ~1.8x-apart programs into
        # one series would mis-model device time for the admission
        # controller's batch planner.  The jit metric above keeps the
        # plain mont vocabulary (its label contract is linted).
        lat_path = (mont_path if msm_path == "ladder"
                    else f"{mont_path}+pip")
        return _DispatchHandle(ok, lane_ok, n, traces, shape,
                               lat_path, t_enq_end,
                               lane_sel=lane_pos, rec=rec)
